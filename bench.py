"""Headline benchmark: full-goal proposal generation at LinkedIn scale.

BASELINE config 5 — 2,600 brokers / ~200k partitions / RF 3 — through the
complete default hard+soft goal stack. North star (BASELINE.md): < 10 s
wall-clock on a v5e-8 with goal-violation scores <= the stock greedy.

Output contract: stdout carries ONLY JSON lines of the form
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}
one per completed stage (configs run smallest-first, so a timeout still
leaves the largest *completed* config as the last line — parse the last
line). All diagnostics go to stderr, flushed, starting with backend/device
info so a hang is attributable.

`value` is the steady-state proposal-generation wall-clock (the production
regime: the proposal precompute loop reuses compiled kernels across model
generations, cc/analyzer/GoalOptimizer.java:129-179, so a warm-up pass
compiles and the timed pass measures). `vs_baseline` = 10 s target / value
(> 1 means faster than target).

Platform handling: the default backend (TPU) is probed in a subprocess with
a timeout first; if its init hangs (dead axon tunnel — the round-1 failure
mode), the run degrades to a labeled CPU number instead of dying silently.

Usage: python bench.py [--smoke]        # --smoke = config 1 only, fast
Env overrides: BENCH_CONFIG (single config 1-5), BENCH_SEED,
BENCH_PROBE_TIMEOUT_S, BENCH_STAGES (comma list, default "1,2,5").
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


TARGET_S = 10.0


def run_config(cfg_id: int, seed: int, platform: str) -> float:
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer, OptimizerSettings
    from cruise_control_tpu.models.generators import BASELINE_CONFIGS, random_cluster

    t_build = time.monotonic()
    model = random_cluster(seed, BASELINE_CONFIGS[cfg_id])
    log(
        f"[config {cfg_id}] model: {model.num_brokers} brokers / "
        f"{model.num_partitions} partitions / rf {model.assignment.shape[1]} "
        f"(built in {time.monotonic() - t_build:.1f}s)"
    )
    settings = OptimizerSettings(batch_k=256, max_rounds_per_goal=24, num_dst_candidates=16)
    optimizer = GoalOptimizer(settings=settings)

    def prog(tag):
        def cb(goal_name, seconds):
            log(f"[config {cfg_id}] {tag} {goal_name}: {seconds:.2f}s")
        return cb

    t0 = time.monotonic()
    optimizer.optimizations(model, raise_on_hard_failure=False, progress=prog("warmup"))
    log(f"[config {cfg_id}] warmup (compile) pass: {time.monotonic() - t0:.1f}s")

    t0 = time.monotonic()
    result = optimizer.optimizations(
        model, raise_on_hard_failure=False, progress=prog("timed")
    )
    wall = time.monotonic() - t0
    log(
        f"[config {cfg_id}] timed pass: {wall:.3f}s moves={result.num_replica_moves} "
        f"leadership={result.num_leadership_moves} "
        f"violated_before={result.violated_goals_before} "
        f"violated_after={result.violated_goals_after}"
    )
    emit(
        {
            "metric": (
                f"full-goal proposal generation, BASELINE config {cfg_id} "
                f"({model.num_brokers} brokers / {model.num_partitions} partitions, "
                f"{platform})"
            ),
            "value": round(wall, 3),
            "unit": "s",
            "vs_baseline": round(TARGET_S / wall, 3),
        }
    )
    return wall


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true", help="config 1 only (<60s)")
    args = parser.parse_args()

    log(f"bench.py starting: python {sys.version.split()[0]}, pid {os.getpid()}")
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "75"))

    from cruise_control_tpu.platform_probe import ensure_live_backend

    ensure_live_backend(timeout_s=probe_timeout, log=log)

    import jax

    platform = jax.default_backend()
    log(f"backend: {platform}, devices: {jax.devices()}")

    seed = int(os.environ.get("BENCH_SEED", "42"))
    if args.smoke:
        stages = [1]
    elif "BENCH_CONFIG" in os.environ:
        stages = [int(os.environ["BENCH_CONFIG"])]
    else:
        stages = [int(s) for s in os.environ.get("BENCH_STAGES", "1,2,5").split(",")]

    completed = 0
    for cfg_id in stages:
        try:
            run_config(cfg_id, seed, platform)
            completed += 1
        except Exception:
            log(f"[config {cfg_id}] FAILED:\n{traceback.format_exc()}")
            break
    if completed == 0:
        # still emit a parsable line so the driver records the failure mode
        emit(
            {
                "metric": f"bench failed before any config completed ({platform})",
                "value": -1.0,
                "unit": "s",
                "vs_baseline": 0.0,
            }
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
