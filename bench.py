"""Headline benchmark: full-goal proposal generation at LinkedIn scale.

BASELINE config 5 — 2,600 brokers / ~200k partitions / RF 3 — through the
complete default hard+soft goal stack. North star (BASELINE.md): < 10 s
wall-clock on a v5e-8 with goal-violation scores <= the stock greedy.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}
`value` is the steady-state proposal-generation wall-clock (the production
regime: the proposal precompute loop reuses compiled kernels across model
generations, cc/analyzer/GoalOptimizer.java:129-179, so a warm-up pass
compiles and the timed pass measures). `vs_baseline` = 10 s target / value
(> 1 means faster than target).

Env overrides: BENCH_CONFIG (1-5, default 5), BENCH_SEED.
"""

from __future__ import annotations

import json
import os
import time


def main() -> None:
    cfg_id = int(os.environ.get("BENCH_CONFIG", "5"))
    seed = int(os.environ.get("BENCH_SEED", "42"))

    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer, OptimizerSettings
    from cruise_control_tpu.models.generators import BASELINE_CONFIGS, random_cluster

    model = random_cluster(seed, BASELINE_CONFIGS[cfg_id])
    settings = OptimizerSettings(batch_k=256, max_rounds_per_goal=24, num_dst_candidates=16)
    optimizer = GoalOptimizer(settings=settings)

    # Warm-up pass: compiles every per-goal step for these dims (cached).
    optimizer.optimizations(model, raise_on_hard_failure=False)

    t0 = time.monotonic()
    result = optimizer.optimizations(model, raise_on_hard_failure=False)
    wall = time.monotonic() - t0

    target_s = 10.0
    print(
        json.dumps(
            {
                "metric": f"full-goal proposal generation, BASELINE config {cfg_id} "
                f"({model.num_brokers} brokers / {model.num_partitions} partitions)",
                "value": round(wall, 3),
                "unit": "s",
                "vs_baseline": round(target_s / wall, 3),
            }
        )
    )
    # secondary detail on stderr for humans; the driver reads stdout line 1
    import sys

    print(
        f"moves={result.num_replica_moves} leadership={result.num_leadership_moves} "
        f"violated_before={result.violated_goals_before} "
        f"violated_after={result.violated_goals_after}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
