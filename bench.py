"""Headline benchmark: full-goal proposal generation at LinkedIn scale.

All five BASELINE configs (BASELINE.md), largest last:
  1  RackAware+ReplicaCapacity only      20 brokers /   1k partitions
  2  full default hard+soft stack       100 brokers /  10k partitions
  3  skewed hot-partition model         100 brokers /  10k partitions
  4  add-broker + remove-broker drain   100 brokers /  10k partitions
  5  LinkedIn-scale snapshot          2,600 brokers / 200k partitions

Config 6 (slow lane only — BENCH_CONFIG=6, never in the default stage list)
is the north-star MESH run: the config-5 model sharded over every visible
device (requires >= 2; the virtual-8 CPU mesh via
XLA_FLAGS=--xla_force_host_platform_device_count=8 counts). Its record is
config 5's shape plus "meshDevices", and its decision contract is that its
provenanceDigest EQUALS a mesh-1 config-5 run's at the same seed — the
sharded round loop may not change a single move (docs/SHARDING.md).

North star (BASELINE.md): config 5 through the complete default hard+soft
goal stack in < 10 s wall-clock on a v5e-8 with goal-violation scores <= the
stock greedy. The greedy reference is produced here too: configs 1-4 run the
faithful-greedy parity mode (batch_k=1: one action per round through the
exhaustive [P, R, K] grid + full-destination scan, the reference's
AbstractGoal semantics made strictly stronger), and config 5 runs the same
parity contract on a downscaled model of the SAME family (exponential load,
52 racks) — the largest scale at which the 512-round greedy is a meaningful
baseline within the bench budget; the scale is stated in the JSON. Each
parity comparison applies the OptimizationVerifier post-condition
(cct/analyzer/OptimizationVerifier.java:48,:250): the batched engine may not
violate any goal the greedy satisfies, and per-goal cost-after may not
regress beyond epsilon. A parity failure zeroes vs_baseline — it IS a bench
failure.

Output contract: stdout carries ONLY compact JSON lines (<= ~1000 bytes) of
the form {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, ...}
— one per completed stage, configs smallest-first, so a timeout still leaves
the largest *completed* config as the last line (parse the last line). Each
line carries per-goal "goalRounds" and "goalDurS" maps (goal names
abbreviated by _short_goal) as top-level parsed fields so round/duration
regressions are visible without the detail file. The full per-goal and
parity tables go to BENCH_DETAIL.json next to this file and to stderr,
along with an `observability` block per config — per-goal tracer span
summaries (engine/rounds/converged), rounds by engine, recompile count, the
optimizer round-time histogram (p50/p95/p99), tracing overhead vs proposal
wall (<2% contract; the compact line carries `tracingOverheadPct`), and the
sensor-registry snapshot — so the perf trajectory records WHY a run was
fast or slow, not just totals. All diagnostics go to stderr, flushed,
starting with backend/device info so a hang is attributable.

`value` is the steady-state proposal-generation wall-clock (the production
regime: the proposal precompute loop reuses compiled kernels across model
generations, cc/analyzer/GoalOptimizer.java:129-179, so a warm-up pass
compiles and the timed pass measures). `vs_baseline`:
  config 5   = 10 s target / value       (> 1 means faster than the target;
               forced to 0.0 if the parity gate fails)
  configs1-4 = greedy wall / batched wall (> 1 means faster than the faithful
               greedy on the same hardware)

When more than one accelerator device is visible, the model's partition axis
is sharded over all of them (jax.sharding.Mesh via parallel.sharding); on a
single chip the mesh is skipped (a 1-device mesh only adds padding).

Platform handling: the default backend (TPU) is probed in a subprocess with
retries + backoff first; if its init hangs (dead axon tunnel — the round-1
failure mode), the run degrades to a labeled CPU number instead of dying
silently. Every compact line carries "platform" and "probeFallback" so a CPU
fallback is impossible to miss, and when a fallback happened the tunnel is
re-probed before each remaining config — on recovery the process re-execs
itself so the larger configs still produce TPU numbers.

Provenance: every record embeds an environment "fingerprint" block
(platform, device kind+count, jax/jaxlib versions, git sha, probeFallback —
common/telemetry.py) and emit() refuses to write a record whose metric label
contradicts it: a probe-fallback run claiming TPU exits with rc 3 before the
line reaches stdout (the BENCH_r05 artifact-drift class, BASELINE.md).
Detail records additionally carry the device-telemetry join (per-bucket
program flops/bytes from XLA cost analysis, memory watermark, host<->device
transfer totals) and telemetryOverheadPct (<2% contract, like tracing).
scripts/perf_gate.py diffs a fresh BENCH_DETAIL.json against the committed
baseline with per-metric tolerances and stable exit codes.

Each detail record also carries a "collectives" block — cross-device
collective op counts and bytes parsed from every compiled program's lowered
HLO (common/telemetry.collective_stats), cumulative at the moment the config
completed, with per-bucket rows and the per-round (while-body) sub-account.
scripts/perf_gate.py diffs it like wall time: per-round collective growth on
an unchanged config is a sharding regression even when the wall clock hides
it behind compile-cache noise.

Usage: python bench.py [--smoke]        # --smoke = config 1 only, fast
Env overrides: BENCH_CONFIG (single config 1-6), BENCH_SEED,
BENCH_PROBE_TIMEOUT_S, BENCH_PROBE_RETRIES (default 3), BENCH_REPROBE=0 to
disable mid-run re-probing, BENCH_STAGES (comma list, default "1,2,3,4,5"),
BENCH_PARITY=0 to skip the greedy passes, BENCH_PARITY5_BROKERS (parity
model size for config 5, default 520), BENCH_GREEDY_CEILING (greedy
cost-scaled round-cap ceiling, default 4096), BENCH_POLISH_ROUNDS (batched
full-table polish pass budget per goal, default 48; 0 disables),
BENCH_LEDGER_DIR (write every timed pass's decision-provenance RunLedger —
analyzer/provenance.py — as ledger_cfg<N>_<tag>.json there; feed a pair to
scripts/diff_runs.py to pinpoint the first divergent move between runs),
BENCH_INCREMENTAL=0 to skip the incremental-lane stage.

Incremental-lane stage (PR 20, non-config-4 stages): after the timed pass,
the bench arms the incremental lane (analyzer/incremental.py) on the solved
model, kills one seeded broker, and times the lane's in-place re-proposal —
typed deltas scattered into the warm device-resident context, goal-scoped
re-solve seeded from the surviving placement, no model rebuild and no
recompile. The compact line carries `incrementalReproposalS` (the lane's
wall) and `incrementalDigestOk` (the lane's proposal must be
provenance-digest-equal to a from-scratch solve of the SAME goal subset on
the SAME perturbed model); scripts/perf_gate.py fails a false flag with its
own exit code (6). The detail block records both walls, both digests, and
the lane's delta/sensitivity summary.

Each compact line also carries `provenanceDigest` — the 16-hex checksum of
the run's canonical move list + per-goal cost deltas (the MoveLedger
digest). Two runs with equal digests made the SAME decisions; a digest flip
at equal parity is silent decision drift, which scripts/perf_gate.py flags
as its own exit path (5).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

DETAIL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json")
_DETAIL: dict = {"configs": []}
if os.environ.get("BENCH_DETAIL_APPEND") == "1":
    # set by the mid-run re-exec (a recovered TPU tunnel): earlier configs'
    # detail records were written by the previous incarnation of this process
    try:
        with open(DETAIL_PATH) as _f:
            _DETAIL = json.load(_f)
        _DETAIL.setdefault("configs", [])
    except (OSError, ValueError):
        pass


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


#: environment fingerprint (set in main() after the platform probe); every
#: record embeds it and emit() refuses platform-contradicting labels
_FINGERPRINT: dict = {}


def _platform_guard(payload: dict) -> None:
    """Provenance gate: a record may not claim a platform its fingerprint
    contradicts. The BENCH_r05 artifact recorded a "TPU" result that actually
    ran `platform: cpu, probeFallback: true`; this exits nonzero (rc 3)
    before such a line can reach stdout or the detail file."""
    fp = payload.get("fingerprint") or _FINGERPRINT
    actual = (fp.get("platform") or payload.get("platform") or "").lower()
    metric = payload.get("metric", "").lower()
    claims_tpu = "tpu" in metric or str(payload.get("platform", "")).lower() == "tpu"
    if claims_tpu and (fp.get("probeFallback") or actual != "tpu"):
        log(
            "FATAL: metric claims TPU but the environment fingerprint says "
            f"platform={fp.get('platform')!r} probeFallback={fp.get('probeFallback')!r}"
            " — refusing to record a mislabeled result (see BASELINE.md r05 note)"
        )
        sys.exit(3)


def emit(payload: dict, detail: dict | None = None) -> None:
    """Compact line to stdout; full tables to BENCH_DETAIL.json + stderr.
    Every record embeds the environment fingerprint and passes the
    platform-contradiction guard (exit 3 on a mislabeled platform)."""
    if _FINGERPRINT:
        payload.setdefault("fingerprint", _FINGERPRINT)
    _platform_guard(payload)
    if detail:
        record = dict(payload)
        record.update(detail)
        _DETAIL["configs"].append(record)
        try:
            with open(DETAIL_PATH, "w") as f:
                json.dump(_DETAIL, f, indent=1)
        except OSError as e:  # detail is best-effort; the stdout line is the contract
            log(f"BENCH_DETAIL write failed: {e}")
        log("detail: " + json.dumps(record))
    line = json.dumps(payload)
    if len(line) > 1100:
        log(f"WARNING: compact line is {len(line)} bytes (contract ~1000)")
    print(line, flush=True)


TARGET_S = 10.0  # config-5 north star (BASELINE.md)
#: per-goal cost-after regression tolerance: relative to the greedy's final
#: cost, with a noise floor relative to the goal's starting cost (two
#: near-converged runs differ by path-dependent residuals that are noise at
#: the scale of the work done). The floor is calibrated at 1%: at the 520B
#: parity scale both engines improve LeaderReplicaDistributionGoal from 687
#: to within [2, 6] with EQUAL violated-broker counts, landing 0.58% of the
#: entry cost apart purely by path (measured round 5; the swap fallback and
#: the full-table polish pass both confirm no further legal action exists
#: from the batched end state)
PARITY_COST_REL = 0.05
PARITY_COST_FLOOR = 0.01
#: violated-broker-count tolerance per goal (BASELINE.md: counts within 3
#: brokers of greedy)
PARITY_COUNT_SLACK = 3


def _settings(batched: bool):
    from cruise_control_tpu.analyzer.optimizer import OptimizerSettings

    # chunked goal machine: bounds each device call's duration so the remote
    # TPU transport never kills a long-running fused call (the config-5
    # failure mode); 0 restores the single fused-stack call
    chunk = int(os.environ.get("BENCH_CHUNK_ROUNDS", "16"))
    if batched:
        rounds = int(os.environ.get("BENCH_BATCHED_ROUNDS", "128"))
        # polish pass: after the stack completes, stalled goals retry under
        # the FULL merged table set (an early goal can stall in a state a
        # later goal's moves unblock — the round-4 LeaderReplica parity
        # residual); greedy keeps the reference's single pass
        polish = int(os.environ.get("BENCH_POLISH_ROUNDS", "48"))
        return OptimizerSettings(batch_k=1024, max_rounds_per_goal=rounds,
                                 num_dst_candidates=16,
                                 num_swap_pairs=16, swap_candidates=16, swaps_per_broker=4,
                                 chunk_rounds=chunk, polish_rounds=polish)
    # faithful greedy: one action per round through the exhaustive [P, R, K]
    # grid + full-destination precision scan
    # (AbstractGoal.maybeApplyBalancingAction); resource-distribution goals
    # use the same reference-shaped drain/fill kernel in both modes but run
    # here to deeper convergence (4x the rounds), making the greedy
    # reference a STRICTLY stronger baseline on those goals. Count-family
    # goals run the bulk count-rebalance planner (analyzer.bulk): every
    # planner action is individually validated at application time, so the
    # baseline stays a sequence of reference-legal greedy
    # steps — it just CONVERGES now (the one-unit-per-round topic goal
    # needed ~14k rounds at the 520B parity scale and hit every affordable
    # ceiling cap-bound; `rounds` for count goals now counts planner
    # rounds, tens not thousands). The round cap scales with each goal's
    # entry cost (normalized by the violated set where the planner runs) so
    # large goals CONVERGE instead of comparing caps; goals the ceiling
    # still binds are reported as greedyCapBoundGoals.
    ceiling = int(os.environ.get("BENCH_GREEDY_CEILING", "4096"))
    return OptimizerSettings(batch_k=1, max_rounds_per_goal=512, num_dst_candidates=16,
                             num_swap_pairs=16, swap_candidates=16, swaps_per_broker=4,
                             chunk_rounds=chunk * 4 if chunk else 0,
                             cost_scaled_rounds=1.5, rounds_ceiling=ceiling)


def _short_goal(name: str) -> str:
    """Abbreviated goal name for the compact line's per-goal maps."""
    return (
        name.replace("UsageDistributionGoal", "Usage")
        .replace("DistributionGoal", "")
        .replace("CapacityGoal", "Cap")
        .replace("Goal", "")
    )


def _goal_payload_fields(result) -> dict:
    """Per-goal rounds + wall-clock as top-level parsed fields: the driver
    reads round regressions (e.g. a count goal falling off the bulk-planner
    path back to one-unit rounds) from the compact line directly."""
    return {
        "goalRounds": {_short_goal(g.name): g.rounds for g in result.goal_results},
        "goalDurS": {
            _short_goal(g.name): round(g.duration_s, 1) for g in result.goal_results
        },
    }


def _goal_table(result):
    return [
        {
            "goal": g.name,
            "violBefore": g.violated_brokers_before,
            "violAfter": g.violated_brokers_after,
            "costBefore": round(g.cost_before, 6),
            "costAfter": round(g.cost_after, 6),
            "rounds": g.rounds,
            "converged": g.converged,
            "durationS": round(g.duration_s, 4),
        }
        for g in result.goal_results
    ]


def _log_pass(cfg_id: int, tag: str, wall: float, result) -> None:
    log(
        f"[config {cfg_id}] {tag}: {wall:.3f}s moves={result.num_replica_moves} "
        f"leadership={result.num_leadership_moves} "
        f"violated_before={result.violated_goals_before} "
        f"violated_after={result.violated_goals_after}"
    )
    rounds = {g.name: g.rounds for g in result.goal_results}
    log(f"[config {cfg_id}] {tag} rounds/goal: {rounds}")


def _timed(optimizer, model, cfg_id, tag, **kw):
    """Warmup (compile) pass then timed pass; returns (wall, result).

    Chunked mode compiles with a single budget-1 call (GoalOptimizer.warmup)
    instead of a full optimization — the budget is a traced scalar, so the
    timed pass reuses the exact compiled program.

    The timed pass runs under a bench root span, and the result carries its
    trace id + recompile/tracer-overhead deltas so _observability_block can
    scope the span summaries to exactly this measurement."""
    from cruise_control_tpu.common.history import HISTORY
    from cruise_control_tpu.common.sensors import REGISTRY
    from cruise_control_tpu.common.telemetry import TELEMETRY
    from cruise_control_tpu.common.tracing import TRACER

    t0 = time.monotonic()
    optimizer.warmup(
        model, goal_names=kw.get("goal_names"),
        options=kw.get("options") or _default_options(),
    )
    log(f"[config {cfg_id}] {tag} warmup (compile) pass: {time.monotonic() - t0:.1f}s")
    recompiles0 = REGISTRY.meter("GoalOptimizer.program-cache-misses").snapshot()["count"]
    overhead0 = TRACER.overhead_s
    telemetry0 = TELEMETRY.overhead_s + HISTORY.overhead_s
    t0 = time.monotonic()
    with TRACER.span(f"bench.{tag}", kind="bench", config=cfg_id) as root:
        result = optimizer.optimizations(model, raise_on_hard_failure=False, **kw)
    wall = time.monotonic() - t0
    result._bench_trace_id = root.trace_id
    result._bench_recompiles = (
        REGISTRY.meter("GoalOptimizer.program-cache-misses").snapshot()["count"]
        - recompiles0
    )
    result._bench_tracing_overhead_s = TRACER.overhead_s - overhead0
    result._bench_telemetry_overhead_s = (
        TELEMETRY.overhead_s + HISTORY.overhead_s - telemetry0
    )
    _log_pass(cfg_id, f"{tag} timed", wall, result)
    _dump_ledger(cfg_id, tag, result)
    return wall, result


def _dump_ledger(cfg_id: int, tag: str, result) -> None:
    """BENCH_LEDGER_DIR: persist this pass's RunLedger for diff_runs.py
    (ledger_cfg<N>_<tag>.json; best-effort, the bench line is the contract)."""
    out_dir = os.environ.get("BENCH_LEDGER_DIR")
    if not out_dir or result.provenance is None:
        return
    safe = tag.replace(" ", "_").replace("/", "-")
    path = os.path.join(out_dir, f"ledger_cfg{cfg_id}_{safe}.json")
    try:
        os.makedirs(out_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump({"config": cfg_id, "tag": tag,
                       "ledger": result.provenance.to_dict()}, f)
        log(f"[config {cfg_id}] {tag} ledger: {path} "
            f"({len(result.provenance.moves)} moves)")
    except OSError as e:
        log(f"[config {cfg_id}] {tag} ledger write failed: {e}")


def _provenance_fields(result) -> tuple:
    """(compact checksum or None, detail digest block or None)."""
    led = result.provenance
    if led is None:
        return None, None
    digest = led.digest()
    return digest["checksum"], {"runId": led.run_id, **digest}


def _observability_block(result, wall: float) -> dict:
    """Why the run was fast or slow, not just totals (BENCH_DETAIL.json):
    per-goal spans (engine/rounds/converged), rounds by engine, recompile
    count, the round-time histogram (p50/p95/p99), tracer + telemetry/history
    overhead vs the proposal wall (acceptance gates: <2% each), the device
    telemetry join (per-bucket program cost, memory watermark, transfer
    totals), and the sensor-registry snapshot."""
    from cruise_control_tpu.common.sensors import REGISTRY
    from cruise_control_tpu.common.telemetry import TELEMETRY
    from cruise_control_tpu.common.tracing import TRACER

    tid = getattr(result, "_bench_trace_id", None)
    goal_spans = []
    rounds_by_engine: dict = {}
    # recent() is newest-first; reverse back into stack priority order
    for s in reversed(TRACER.recent(limit=512, kind="goal", trace_id=tid)):
        a = s["attributes"]
        goal_spans.append(
            {
                "goal": _short_goal(a.get("goal", s["name"])),
                "engine": a.get("engine"),
                "rounds": a.get("rounds"),
                "converged": a.get("converged"),
                "durationS": s["durationS"],
            }
        )
        eng = a.get("engine", "?")
        rounds_by_engine[eng] = rounds_by_engine.get(eng, 0) + int(a.get("rounds") or 0)
    snap = REGISTRY.snapshot()
    overhead = float(getattr(result, "_bench_tracing_overhead_s", 0.0))
    telemetry_overhead = float(getattr(result, "_bench_telemetry_overhead_s", 0.0))
    return {
        "goalSpans": goal_spans,
        "roundsByEngine": rounds_by_engine,
        "recompiles": getattr(result, "_bench_recompiles", None),
        "roundTimer": snap.get("GoalOptimizer.optimizer-round-timer"),
        "deviceCallTimer": snap.get("GoalOptimizer.device-call-timer"),
        "tracingOverheadS": round(overhead, 6),
        "tracingOverheadPct": round(100.0 * overhead / max(wall, 1e-9), 4),
        "telemetryOverheadS": round(telemetry_overhead, 6),
        "telemetryOverheadPct": round(
            100.0 * telemetry_overhead / max(wall, 1e-9), 4
        ),
        "telemetry": TELEMETRY.snapshot(),
        "spanSummary": TRACER.summarize(),
        "sensors": snap,
    }


def _collectives_block() -> dict:
    """Cross-device collective account at the moment this config completed.

    Totals are cumulative across the process (configs run smallest-first, so
    run-over-run diffs always compare equal prefixes); the per-bucket rows
    attribute growth to the program that pays it, and `perRound*` counts only
    instructions inside `lax.while_loop` bodies — the traffic multiplied by
    every round, which is what the <docs/SHARDING.md> budget bounds."""
    from cruise_control_tpu.common.telemetry import TELEMETRY

    totals = TELEMETRY.collective_totals()
    by_bucket: dict = {}
    for r in TELEMETRY.programs():
        b = by_bucket.setdefault(
            r.get("bucket", "?"), {"ops": 0, "bytes": 0, "perRoundOps": 0}
        )
        b["ops"] += r.get("collectiveOps", 0)
        b["bytes"] += r.get("collectiveBytes", 0)
        b["perRoundOps"] += (r.get("collectivesPerRound") or {}).get("ops", 0)
    totals["byBucket"] = by_bucket
    return totals


def _default_options():
    from cruise_control_tpu.analyzer.context import OptimizationOptions

    return OptimizationOptions()


def _incremental_block(optimizer, model, cfg_id, seed, result):
    """Incremental-lane measurement (analyzer/incremental.py): arm the lane
    on the model just solved, kill one seeded broker, and time the lane's
    in-place re-proposal (delta scatter into the warm device context +
    goal-scoped re-solve, no rebuild/recompile) against a from-scratch solve
    of the SAME goal subset on the SAME perturbed model. The two runs must
    be provenance-digest-equal — `incrementalDigestOk` rides the compact
    line and scripts/perf_gate.py fails it with its own exit code (6).
    Returns (payload_fields, detail_block); BENCH_INCREMENTAL=0 skips."""
    import numpy as np

    from cruise_control_tpu.analyzer.incremental import IncrementalLane
    from cruise_control_tpu.common.resources import BrokerState

    lane = IncrementalLane(optimizer)
    names = tuple(g.name for g in result.goal_results)
    if not lane.arm(model, _default_options(), names, generation=1):
        log(f"[config {cfg_id}] incremental: lane failed to arm (prep cache miss)")
        return {"incrementalDigestOk": False}, {"incremental": {"armed": False}}

    state = np.asarray(model.broker_state).copy()
    alive = np.nonzero(state == BrokerState.ALIVE)[0]
    victim = int(alive[seed % alive.size])
    state[victim] = BrokerState.DEAD
    perturbed = model._replace(broker_state=state)
    log(f"[config {cfg_id}] incremental: killing broker {victim}, re-proposing")

    t0 = time.monotonic()
    out = lane.propose(perturbed, generation=2)
    inc_wall = time.monotonic() - t0
    block = {"summary": out.summary(), "incrementalWallS": round(inc_wall, 3),
             "victimBroker": victim}
    if not out.ok:
        # a broker death must stay in-lane; a fallback here is a regression
        log(f"[config {cfg_id}] incremental: FELL BACK ({out.fallback_reason})")
        return (
            {"incrementalReproposalS": round(inc_wall, 3),
             "incrementalDigestOk": False},
            {"incremental": block},
        )

    t0 = time.monotonic()
    scratch = optimizer.optimizations(
        perturbed, goal_names=list(out.affected), options=_default_options(),
        raise_on_hard_failure=False,
    )
    scratch_wall = time.monotonic() - t0
    inc_digest = out.result.provenance.digest()["checksum"] \
        if out.result.provenance else None
    scr_digest = scratch.provenance.digest()["checksum"] \
        if scratch.provenance else None
    digest_ok = inc_digest is not None and inc_digest == scr_digest
    ratio = inc_wall / max(scratch_wall, 1e-9)
    log(
        f"[config {cfg_id}] incremental: {inc_wall:.3f}s vs scratch "
        f"{scratch_wall:.3f}s ({ratio:.1%}), digest "
        f"{inc_digest} vs {scr_digest} ok={digest_ok}"
    )
    block.update({
        "scratchWallS": round(scratch_wall, 3),
        "reproposalVsScratch": round(ratio, 4),
        "incrementalDigest": inc_digest,
        "scratchDigest": scr_digest,
        "digestOk": digest_ok,
    })
    return (
        {"incrementalReproposalS": round(inc_wall, 3),
         "incrementalDigestOk": digest_ok},
        {"incremental": block},
    )


def _compile_counters() -> dict:
    """Process-wide compile/program-cache counters (sensors from the
    optimizer's program cache): the raw material of the compile-amortization
    summary and each config's `bucketed` detail block."""
    from cruise_control_tpu.common.sensors import REGISTRY

    h = REGISTRY.histogram("GoalOptimizer.stack-compile-timer").snapshot()
    return {
        "programs": h["count"],
        "compileS": round(h["totalS"], 3),
        "misses": REGISTRY.meter("GoalOptimizer.program-cache-misses").snapshot()["count"],
        "hits": REGISTRY.meter("GoalOptimizer.program-cache-hits").snapshot()["count"],
    }


def _bucketed_block(result, before: dict) -> dict:
    """Shape-bucketing record for the detail file: exact vs padded dims and
    how many compiles this config actually paid vs reused warm."""
    after = _compile_counters()
    block = dict(result.bucketed or {})
    block["newPrograms"] = after["programs"] - before["programs"]
    block["compileS"] = round(after["compileS"] - before["compileS"], 3)
    block["warmReuses"] = after["hits"] - before["hits"]
    return block


def _parity_block(cfg_id, batched_result, greedy_wall, greedy_result):
    """Side-by-side scores: batched must not violate more than the greedy
    AND may not regress any goal's final cost beyond epsilon (the north
    star's 'scores <= stock greedy' contract = OptimizationVerifier's
    REGRESSION check)."""
    batched_after = set(batched_result.violated_goals_after)
    greedy_after = set(greedy_result.violated_goals_after)
    worse = sorted(batched_after - greedy_after)
    cost_delta = {}
    regressed = []
    count_worse = []
    for bg, gg in zip(batched_result.goal_results, greedy_result.goal_results):
        delta = bg.cost_after - gg.cost_after
        cost_delta[bg.name] = round(delta, 6)
        if delta > PARITY_COST_REL * max(abs(gg.cost_after), 1e-9) and (
            delta > PARITY_COST_FLOOR * max(gg.cost_before, 1.0)
        ):
            regressed.append(bg.name)
        if bg.violated_brokers_after > gg.violated_brokers_after + PARITY_COUNT_SLACK:
            count_worse.append(bg.name)
    ok = not worse and not regressed and not count_worse
    # goals where the greedy baseline ran out of rounds before stalling: its
    # scores there reflect the cap, not search quality (VERDICT r4 weak #3)
    cap_bound = [g.name for g in greedy_result.goal_results if not g.converged]
    block = {
        "greedyWallS": round(greedy_wall, 3),
        "greedyViolatedAfter": sorted(greedy_after),
        "batchedViolatedAfter": sorted(batched_after),
        "batchedWorseGoals": worse,  # must be []
        "costRegressedGoals": regressed,  # must be []
        "countRegressedGoals": count_worse,  # must be [] (> +3 brokers)
        "costAfterDeltaVsGreedy": cost_delta,  # negative = batched better
        "greedyCapBoundGoals": cap_bound,  # [] = greedy fully converged
        "parityOk": ok,
        "greedyGoals": _goal_table(greedy_result),
    }
    log(
        f"[config {cfg_id}] parity: batched_violated={len(batched_after)} "
        f"greedy_violated={len(greedy_after)} worse_goals={worse} "
        f"cost_regressed={regressed} count_regressed={count_worse} ok={ok}"
    )
    return block


def _parity5(seed: int, mesh, batched_settings) -> dict:
    """Config-5 parity at the largest greedy-convergent scale in budget:
    the same model family (exponential load, 52 racks, rf 3) downscaled so
    the 512-round-per-goal greedy is a meaningful baseline. Both modes run
    on THIS model; the gate result applies to config 5's line."""
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.models.generators import ClusterProperty, random_cluster

    brokers = int(os.environ.get("BENCH_PARITY5_BROKERS", "520"))
    prop = ClusterProperty(
        num_racks=52, num_brokers=brokers, num_topics=max(50, (brokers * 20) // 13),
        mean_partitions_per_topic=50.0, replication_factor=3,
        load_distribution="exponential",
    )
    model = random_cluster(seed + 5, prop)
    log(
        f"[config 5] parity model: {model.num_brokers} brokers / "
        f"{model.num_partitions} partitions (config-5 family, downscaled)"
    )
    batched = GoalOptimizer(settings=batched_settings, mesh=mesh)
    b_wall, b_result = _timed(batched, model, 5, "parity batched")
    # scope the observability block to the batched parity pass before the
    # greedy pass pollutes the registry/ring (the 520-broker acceptance
    # record: per-goal engine/round/recompile summaries + tracing overhead)
    obs = _observability_block(b_result, b_wall)
    greedy = GoalOptimizer(settings=_settings(batched=False))
    g_wall, g_result = _timed(greedy, model, 5, "parity greedy")
    block = _parity_block(5, b_result, g_wall, g_result)
    block["observability"] = obs
    block["parityScale"] = f"{model.num_brokers}B/{model.num_partitions}P"
    block["batchedWallS"] = round(b_wall, 3)
    return block


def run_config(cfg_id: int, seed: int, platform: str, parity: bool, mesh,
               probe_fallback: bool = False) -> None:
    import numpy as np

    from cruise_control_tpu.analyzer.context import OptimizationOptions
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.common.resources import BrokerState
    from cruise_control_tpu.models.generators import BASELINE_CONFIGS, random_cluster

    if cfg_id == 6 and (mesh is None or mesh.size < 2):
        # the whole point of config 6 is the sharded round loop; a 1-device
        # "mesh" run would just be config 5 with padding
        raise RuntimeError(
            "config 6 is the north-star MESH run: need >1 visible device "
            "(e.g. XLA_FLAGS=--xla_force_host_platform_device_count=8)"
        )
    compile0 = _compile_counters()
    t_build = time.monotonic()
    model = random_cluster(seed, BASELINE_CONFIGS[5 if cfg_id == 6 else cfg_id])
    log(
        f"[config {cfg_id}] model: {model.num_brokers} brokers / "
        f"{model.num_partitions} partitions / rf {model.assignment.shape[1]} "
        f"(built in {time.monotonic() - t_build:.1f}s)"
    )
    settings = _settings(batched=True)
    optimizer = GoalOptimizer(settings=settings, mesh=mesh)

    if cfg_id == 4:
        # add-broker: the 4 NEW brokers are the only eligible destinations
        # (KafkaCruiseControl.addBrokers :277 + requested_destination_brokers)
        new_mask = np.asarray(model.broker_state) == BrokerState.NEW
        add_opts = OptimizationOptions(requested_destination_brokers=new_mask)
        add_wall, add_result = _timed(
            optimizer, model, cfg_id, "add-broker", options=add_opts
        )
        # remove-broker: mark 4 brokers DEAD, immigrant-only drain
        # (KafkaCruiseControl.decommissionBrokers :187 self-healing mode)
        state = np.asarray(model.broker_state).copy()
        alive_idx = np.nonzero(state == BrokerState.ALIVE)[0]
        state[alive_idx[:4]] = BrokerState.DEAD
        drain_model = model._replace(broker_state=state)
        drain_opts = OptimizationOptions(only_move_immigrants=True)
        drain_wall, drain_result = _timed(
            optimizer, drain_model, cfg_id, "remove-broker", options=drain_opts
        )
        # evacuation check must inspect the FINAL placement: dead brokers can
        # never be destinations, and an un-moved replica emits no proposal
        dead_ids = alive_idx[:4]
        final = drain_result.final_assignment
        evacuated = not bool(np.isin(final[final >= 0], dead_ids).any())
        wall = add_wall + drain_wall
        payload = {
            "metric": (
                f"add-broker + remove-broker proposal generation, BASELINE config 4 "
                f"({model.num_brokers} brokers / {model.num_partitions} partitions, "
                f"{platform})"
            ),
            "value": round(wall, 3),
            "unit": "s",
            "platform": platform,
            "probeFallback": probe_fallback,
            "addWallS": round(add_wall, 3),
            "removeWallS": round(drain_wall, 3),
            "removeEvacuatedCleanly": evacuated,
        }
        payload.update(_goal_payload_fields(add_result))
        obs = _observability_block(add_result, add_wall)
        payload["tracingOverheadPct"] = obs["tracingOverheadPct"]
        payload["telemetryOverheadPct"] = obs["telemetryOverheadPct"]
        checksum, prov_block = _provenance_fields(add_result)
        if checksum:
            payload["provenanceDigest"] = checksum
        detail = {
            "goals": _goal_table(add_result),
            "observability": obs,
            "bucketed": _bucketed_block(add_result, compile0),
            "collectives": _collectives_block(),
            **({"provenance": prov_block} if prov_block else {}),
        }
        payload["collectiveOpsPerRound"] = detail["collectives"]["perRoundOps"]
        payload["programsCompiled"] = _compile_counters()["programs"]
        payload["compileSTotal"] = _compile_counters()["compileS"]
        if parity:
            greedy = GoalOptimizer(settings=_settings(batched=False))
            greedy_wall, greedy_result = _timed(
                greedy, model, cfg_id, "greedy add-broker", options=add_opts
            )
            detail["parity"] = _parity_block(cfg_id, add_result, greedy_wall, greedy_result)
            payload["parityOk"] = detail["parity"]["parityOk"]
            # the greedy reference covers the add pass only; scope the ratio
            # to the same measurement so value * vs_baseline stays meaningful.
            # A parity failure zeroes vs_baseline (the module contract: it IS
            # a bench failure); the raw ratio stays in speedupVsGreedy.
            ratio = round(greedy_wall / max(add_wall, 1e-9), 3)
            payload["speedupVsGreedy"] = ratio
            payload["vs_baseline"] = ratio if payload["parityOk"] else 0.0
            payload["vsBaselineScope"] = "add-broker pass (greedyWallS / addWallS)"
        else:
            payload["vs_baseline"] = 0.0
        emit(payload, detail)
        return

    goal_names = None
    if cfg_id == 1:
        goal_names = ["RackAwareGoal", "ReplicaCapacityGoal"]
    elif cfg_id == 3:
        # BASELINE.md: ResourceDistributionGoal x4 on the hot-partition model
        goal_names = [
            "DiskUsageDistributionGoal",
            "NetworkInboundUsageDistributionGoal",
            "NetworkOutboundUsageDistributionGoal",
            "CpuUsageDistributionGoal",
        ]
    wall, result = _timed(optimizer, model, cfg_id, "batched", goal_names=goal_names)
    inc_fields: dict = {}
    inc_detail: dict = {}
    if os.environ.get("BENCH_INCREMENTAL", "1") != "0":
        try:
            inc_fields, inc_detail = _incremental_block(
                optimizer, model, cfg_id, seed, result
            )
        except Exception:
            log(f"[config {cfg_id}] incremental stage FAILED:\n{traceback.format_exc()}")
            inc_fields = {"incrementalDigestOk": False}
    mesh_label = f"mesh-{mesh.size}, " if cfg_id == 6 else ""
    payload = {
        "metric": (
            f"full-goal proposal generation, BASELINE config {cfg_id} "
            f"({model.num_brokers} brokers / {model.num_partitions} partitions, "
            f"{mesh_label}{platform})"
        ),
        "value": round(wall, 3),
        "unit": "s",
        "platform": platform,
        "probeFallback": probe_fallback,
        "moves": result.num_replica_moves,
        "leadershipMoves": result.num_leadership_moves,
        "violatedAfterCount": len(result.violated_goals_after),
    }
    payload.update(_goal_payload_fields(result))
    obs = _observability_block(result, wall)
    payload["tracingOverheadPct"] = obs["tracingOverheadPct"]
    payload["telemetryOverheadPct"] = obs["telemetryOverheadPct"]
    checksum, prov_block = _provenance_fields(result)
    if checksum:
        payload["provenanceDigest"] = checksum
    detail = {
        "goals": _goal_table(result),
        "violatedAfter": result.violated_goals_after,
        "observability": obs,
        "bucketed": _bucketed_block(result, compile0),
        "collectives": _collectives_block(),
        **({"provenance": prov_block} if prov_block else {}),
    }
    payload.update(inc_fields)
    detail.update(inc_detail)
    payload["collectiveOpsPerRound"] = detail["collectives"]["perRoundOps"]
    payload["programsCompiled"] = _compile_counters()["programs"]
    payload["compileSTotal"] = _compile_counters()["compileS"]
    if cfg_id in (5, 6):
        payload["vs_baseline"] = round(TARGET_S / wall, 3)
        if cfg_id == 6:
            # the parity contract for the mesh run is DECISION IDENTITY, not
            # a greedy race: its provenanceDigest must equal a mesh-1
            # config-5 run's at the same seed (scripts/perf_gate.py exit 5)
            payload["meshDevices"] = mesh.size
        if parity and cfg_id == 5:
            # the parity gate runs on the downscaled config-5-family model;
            # a failure zeroes vs_baseline (the contract is time AND scores)
            block = _parity5(seed, mesh, settings)
            detail["parity"] = block
            payload["parityOk"] = block["parityOk"]
            payload["parityScale"] = block["parityScale"]
            if not block["parityOk"]:
                payload["vs_baseline"] = 0.0
    elif parity:
        greedy = GoalOptimizer(settings=_settings(batched=False))
        greedy_wall, greedy_result = _timed(
            greedy, model, cfg_id, "greedy", goal_names=goal_names
        )
        detail["parity"] = _parity_block(cfg_id, result, greedy_wall, greedy_result)
        payload["parityOk"] = detail["parity"]["parityOk"]
        # a parity failure zeroes vs_baseline on EVERY config (the module
        # contract); the raw speed ratio stays in speedupVsGreedy
        ratio = round(greedy_wall / max(wall, 1e-9), 3)
        payload["speedupVsGreedy"] = ratio
        payload["vs_baseline"] = ratio if payload["parityOk"] else 0.0
    else:
        payload["vs_baseline"] = 0.0
    emit(payload, detail)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true", help="config 1 only (<60s)")
    args = parser.parse_args()

    log(f"bench.py starting: python {sys.version.split()[0]}, pid {os.getpid()}")
    import logging

    logging.basicConfig(stream=sys.stderr, level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "75"))

    from cruise_control_tpu.platform_probe import ensure_live_backend

    probe = ensure_live_backend(
        timeout_s=probe_timeout, log=log,
        retries=int(os.environ.get("BENCH_PROBE_RETRIES", "3")),
    )

    from cruise_control_tpu.compile_cache import enable_persistent_cache

    cache_dir = enable_persistent_cache()
    log(f"persistent compile cache: {cache_dir or 'DISABLED (no writable dir)'}")

    import jax

    platform = jax.default_backend()
    devices = jax.devices()
    log(f"backend: {platform}, devices: {devices}")

    # environment fingerprint: the provenance block every record embeds
    # (platform, device kind+count, versions, git sha, probe outcome) — the
    # reason a CPU-fallback run can no longer record a TPU-labeled metric
    from cruise_control_tpu.common.telemetry import TELEMETRY

    global _FINGERPRINT
    _FINGERPRINT = TELEMETRY.fingerprint(probe_fallback=probe.fallback)
    _DETAIL["fingerprint"] = _FINGERPRINT
    log(f"fingerprint: {json.dumps(_FINGERPRINT)}")

    mesh = None
    if len(devices) > 1:
        from cruise_control_tpu.parallel.sharding import make_mesh

        mesh = make_mesh(len(devices))
        log(f"mesh: sharding partition axis over {len(devices)} devices")

    seed = int(os.environ.get("BENCH_SEED", "42"))
    parity = os.environ.get("BENCH_PARITY", "1") != "0"
    if args.smoke:
        stages = [1]
    elif "BENCH_CONFIG" in os.environ:
        stages = [int(os.environ["BENCH_CONFIG"])]
    else:
        stages = [int(s) for s in os.environ.get("BENCH_STAGES", "1,2,3,4,5").split(",")]

    # after a mid-run TPU-recovery re-exec, earlier configs' results are
    # already on stdout / in the detail file — the "failed before any config
    # completed" record must not contradict them
    completed = len(_DETAIL["configs"]) if os.environ.get("BENCH_DETAIL_APPEND") == "1" else 0
    for i, cfg_id in enumerate(stages):
        if probe.fallback and i > 0 and os.environ.get("BENCH_REPROBE", "1") != "0":
            # the run degraded to CPU at startup; a tunnel that recovers
            # mid-run should still produce TPU numbers for the remaining
            # (larger) configs. The in-process backend cannot be swapped
            # after init, so on a live re-probe the process re-execs itself
            # for the remaining stages (stdout fd survives exec; the detail
            # file is appended via BENCH_DETAIL_APPEND).
            from cruise_control_tpu.platform_probe import probe_only

            log(f"re-probing default backend before config {cfg_id}...")
            name = probe_only(timeout_s=min(probe_timeout, 60.0))
            if name is not None and name != "cpu":
                remaining = ",".join(str(s) for s in stages[i:])
                log(f"default backend recovered ({name}); re-exec for stages {remaining}")
                env = dict(os.environ)
                env.pop("JAX_PLATFORMS", None)  # drop our cpu pin
                env["BENCH_STAGES"] = remaining
                env["BENCH_DETAIL_APPEND"] = "1"
                env.pop("BENCH_CONFIG", None)
                sys.stderr.flush()
                sys.stdout.flush()
                os.execve(
                    sys.executable,
                    [sys.executable, os.path.abspath(__file__)], env,
                )
            log("default backend still dead; continuing on cpu")
        try:
            run_config(cfg_id, seed, platform, parity=parity, mesh=mesh,
                       probe_fallback=probe.fallback)
            completed += 1
        except Exception:
            log(f"[config {cfg_id}] FAILED:\n{traceback.format_exc()}")
            break
    # one-line compile-amortization summary: the shape-bucketed program
    # cache's whole point is FEWER programs than configs — record the win in
    # the trajectory without reading the detail JSON
    cc = _compile_counters()
    log(
        f"compile-amortization: {cc['programs']} programs compiled "
        f"({cc['compileS']:.1f}s total XLA) for {completed} configs run; "
        f"{cc['hits']} warm program reuses, {cc['misses']} cold misses"
    )
    if completed == 0:
        # still emit a parsable line so the driver records the failure mode
        emit(
            {
                "metric": f"bench failed before any config completed ({platform})",
                "value": -1.0,
                "unit": "s",
                "vs_baseline": 0.0,
                "platform": platform,
                "probeFallback": probe.fallback,
            }
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
