"""Focused parity experiment: batched vs greedy on the config-5 family at a
chosen scale, printing the per-goal cost table (scripts/ = dev tooling, not
shipped API). Usage:
  JAX_PLATFORMS=cpu python scripts/exp_parity.py [brokers] [goal-subset]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# env var alone is not enough under axon (site customization re-pins the
# platform); jax.config must be updated before any backend initializes
from cruise_control_tpu.platform_probe import pin_cpu  # noqa: E402

pin_cpu()

brokers = int(sys.argv[1]) if len(sys.argv) > 1 else 130
subset = sys.argv[2] if len(sys.argv) > 2 else None

from cruise_control_tpu.analyzer.optimizer import GoalOptimizer, OptimizerSettings
from cruise_control_tpu.models.generators import ClusterProperty, random_cluster

prop = ClusterProperty(
    num_racks=52, num_brokers=brokers, num_topics=max(50, (brokers * 20) // 13),
    mean_partitions_per_topic=50.0, replication_factor=3,
    load_distribution="exponential",
)
model = random_cluster(42 + 5, prop)
print(f"model: {model.num_brokers}B / {model.num_partitions}P", flush=True)

goal_names = None
if subset:
    goal_names = subset.split(",")

chunk = int(os.environ.get("BENCH_CHUNK_ROUNDS", "16"))
polish = int(os.environ.get("BENCH_POLISH_ROUNDS", "48"))
batched_s = OptimizerSettings(batch_k=1024, max_rounds_per_goal=128,
                              num_dst_candidates=16, num_swap_pairs=16,
                              swap_candidates=16, swaps_per_broker=4,
                              chunk_rounds=chunk, polish_rounds=polish)
ceiling = int(os.environ.get("BENCH_GREEDY_CEILING", "4096"))
greedy_s = OptimizerSettings(batch_k=1, max_rounds_per_goal=512,
                             num_dst_candidates=16, num_swap_pairs=16,
                             swap_candidates=16, swaps_per_broker=4,
                             chunk_rounds=chunk * 4,
                             cost_scaled_rounds=1.5, rounds_ceiling=ceiling)


def run(tag, settings):
    opt = GoalOptimizer(settings=settings)
    t0 = time.monotonic()
    opt.warmup(model, goal_names=goal_names)
    print(f"{tag} compile: {time.monotonic() - t0:.1f}s", flush=True)
    t0 = time.monotonic()
    res = opt.optimizations(model, goal_names=goal_names, raise_on_hard_failure=False)
    wall = time.monotonic() - t0
    print(f"{tag} wall: {wall:.2f}s moves={res.num_replica_moves} "
          f"lead={res.num_leadership_moves}", flush=True)
    for g in res.goal_results:
        cap = "" if g.converged else "  CAP-BOUND"
        print(f"  {tag} {g.name:38s} viol {g.violated_brokers_before:4d}->"
              f"{g.violated_brokers_after:4d} cost {g.cost_before:12.1f}->"
              f"{g.cost_after:10.1f} rounds {g.rounds:4d}{cap}", flush=True)
    return wall, res


b_wall, b_res = run("batched", batched_s)
g_wall, g_res = run("greedy ", greedy_s)

print("\nper-goal cost-after delta (batched - greedy; negative = batched better):")
for bg, gg in zip(b_res.goal_results, g_res.goal_results):
    delta = bg.cost_after - gg.cost_after
    flag = ""
    if delta > 0.05 * max(abs(gg.cost_after), 1e-9) and delta > 0.01 * max(gg.cost_before, 1.0):
        flag = "  <-- REGRESSED"
    print(f"  {bg.name:38s} {delta:+12.1f}  (viol {bg.violated_brokers_after} vs "
          f"{gg.violated_brokers_after}){flag}")
print(f"\nwalls: batched {b_wall:.2f}s greedy {g_wall:.2f}s "
      f"speedup {g_wall / max(b_wall, 1e-9):.2f}x")
