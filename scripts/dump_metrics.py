"""Dump a running instance's /metrics + /trace as a ranked latency table.

The live counterpart of scripts/parse_xplane.py: where parse_xplane ranks
XLA ops from a profiler capture, this ranks tracer span kinds and sensor
histograms from a serving process — no profiler, no restart, one curl each.

Usage:
  python scripts/dump_metrics.py [http://127.0.0.1:9090] [--limit N] [--raw]

Output (stdout):
  1. per-span-kind latency table from /trace's summary, ranked by total time
     (count, total, mean, p50/p95/p99, max),
  2. the slowest recent spans with their attributes (engine, rounds, goal),
  3. sensor histograms/timers from /metrics, ranked by total seconds,
  4. the resilience picture: self-healing circuit-breaker states and the
     retry/dead-task/dispatch-failure counters (docs/RESILIENCE.md),
  5. the proposal drift/validation picture: trimmed-by-reason counts, the
     generation-skew gauge, and the batch-abort counter,
  5b. the incremental-rebalancing picture: lane armings, deltas applied by
     kind, goals skipped by the sensitivity map, the re-proposal timer, and
     fallback-to-full counts by reason (docs/RESILIENCE.md),
  6. the perf observatory: device telemetry (per-bucket program flops/bytes
     from XLA cost analysis, device-memory watermark, host<->device transfer
     totals) and the top time-series movers from /timeseries
     (docs/OBSERVABILITY.md telemetry section),
  7. decision provenance from /explain: the latest recorded run's moves by
     goal/engine, its top cost-delta movers, and the MoveLedger counters
     (docs/OBSERVABILITY.md provenance section).

--raw additionally prints the raw Prometheus exposition text.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:8.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:6.2f}ms"
    return f"{v * 1e6:6.1f}us"


def _span_kind_table(summary: dict) -> None:
    print("== span kinds (ranked by total time) ==")
    header = f"{'kind':<14} {'count':>7} {'total':>9} {'mean':>9} {'p50':>9} {'p95':>9} {'p99':>9} {'max':>9}"
    print(header)
    print("-" * len(header))
    for kind, s in sorted(summary.items(), key=lambda kv: -kv[1]["totalS"]):
        print(
            f"{kind:<14} {s['count']:>7} {_fmt_s(s['totalS']):>9} "
            f"{_fmt_s(s['meanS']):>9} {_fmt_s(s['p50S']):>9} "
            f"{_fmt_s(s['p95S']):>9} {_fmt_s(s['p99S']):>9} {_fmt_s(s['maxS']):>9}"
        )


def _slow_spans(spans: list, top: int = 15) -> None:
    print(f"\n== slowest recent spans (top {top}) ==")
    timed = [s for s in spans if s.get("durationS") is not None]
    for s in sorted(timed, key=lambda s: -s["durationS"])[:top]:
        attrs = {
            k: v for k, v in (s.get("attributes") or {}).items() if k != "synthetic"
        }
        attr_str = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        print(
            f"{_fmt_s(s['durationS']):>9}  {s['kind']:<12} {s['name']:<34} "
            f"trace={s['traceId'][:8]} {attr_str}"
        )


def _parse_prometheus_latencies(text: str) -> dict:
    """{sensor: {"count": n, "sum": s}} from the latency/timer families."""
    out: dict = {}
    for line in text.splitlines():
        if line.startswith("#") or "{" not in line:
            continue
        name, rest = line.split("{", 1)
        labels_raw, value = rest.rsplit("} ", 1)
        if name not in (
            "cruise_control_latency_seconds_sum",
            "cruise_control_latency_seconds_count",
            "cruise_control_timer_seconds_sum",
            "cruise_control_timer_seconds_count",
        ):
            continue
        sensor = None
        for part in labels_raw.split('",'):
            k, _, v = part.partition('="')
            if k.strip(", ") == "sensor":
                sensor = v.rstrip('"')
        if sensor is None:
            continue
        entry = out.setdefault(sensor, {"count": 0, "sum": 0.0})
        if name.endswith("_sum"):
            entry["sum"] = float(value)
        else:
            entry["count"] = int(float(value))
    return out


def _parse_labels(labels_raw: str) -> dict:
    out = {}
    for part in labels_raw.split('",'):
        k, _, v = part.partition('="')
        out[k.strip(", ")] = v.rstrip('"')
    return out


#: CircuitBreaker.STATE_CODES, inverted (kept literal: this script must run
#: against a remote instance without importing the package)
_BREAKER_STATES = {0: "closed", 1: "half_open", 2: "open"}

#: meter-name markers that belong in the resilience section
_RESILIENCE_MARKERS = (
    "Retry.", "CircuitBreaker.", "Executor.task-", "Executor.dispatch-",
    "Executor.driver-", "Executor.execution-phase-failures",
    "AnomalyDetector.fix-failures",
)


def _resilience_section(text: str) -> None:
    breakers = {}
    meters = {}
    for line in text.splitlines():
        if line.startswith("#") or "{" not in line:
            continue
        name, rest = line.split("{", 1)
        labels_raw, value = rest.rsplit("} ", 1)
        labels = _parse_labels(labels_raw)
        sensor = labels.get("sensor", "")
        if name == "cruise_control_gauge" and sensor.endswith("breaker-state"):
            code = int(float(value))
            breakers[labels.get("field", sensor)] = _BREAKER_STATES.get(
                code, f"code={code}"
            )
        elif name == "cruise_control_meter_total" and any(
            m in sensor for m in _RESILIENCE_MARKERS
        ):
            meters[sensor] = int(float(value))
    print("\n== resilience (breakers + retry/failure counters) ==")
    if breakers:
        for anomaly_type, state in sorted(breakers.items()):
            marker = "!!" if state != "closed" else "  "
            print(f"{marker} breaker {anomaly_type:<20} {state}")
    else:
        print("   (no breaker gauge exported)")
    for sensor, count in sorted(meters.items(), key=lambda kv: -kv[1]):
        if count:
            print(f"   {sensor:<52} {count:>8}")


def _drift_section(text: str) -> None:
    """Proposal drift/validation picture (docs/RESILIENCE.md): trimmed-by-
    reason counts, the generation-skew gauge, batch aborts, and revalidation
    failures — rendered next to the PR-4 resilience section."""
    skew = None
    trimmed = {}
    counters = {}
    for line in text.splitlines():
        if line.startswith("#") or "{" not in line:
            continue
        name, rest = line.split("{", 1)
        labels_raw, value = rest.rsplit("} ", 1)
        labels = _parse_labels(labels_raw)
        sensor = labels.get("sensor", "")
        if name == "cruise_control_gauge" and sensor == "Executor.generation-skew":
            skew = int(float(value))
        elif name == "cruise_control_meter_total":
            if sensor.startswith("Executor.proposal-trimmed."):
                trimmed[sensor.rsplit(".", 1)[1]] = int(float(value))
            elif sensor in ("Executor.proposal-trimmed", "Executor.batch-aborts",
                            "Executor.revalidation-failures"):
                counters[sensor] = int(float(value))
    print("\n== proposal drift / validation ==")
    if skew is None and not trimmed and not counters:
        print("   (no drift sensors exported — executor has not validated a batch)")
        return
    if skew is not None:
        print(f"   generation skew (last observed)                      {skew:>8}")
    for sensor, count in sorted(counters.items(), key=lambda kv: -kv[1]):
        marker = "!!" if count and sensor == "Executor.batch-aborts" else "  "
        print(f"{marker} {sensor:<52} {count:>8}")
    for reason, count in sorted(trimmed.items(), key=lambda kv: -kv[1]):
        if count:
            print(f"   trimmed[{reason}]".ljust(55) + f"{count:>8}")


def _incremental_section(text: str) -> None:
    """Incremental-rebalancing picture (docs/RESILIENCE.md): how often the
    lane proposed in place vs fell back to a full re-solve, what deltas it
    absorbed, and what the re-proposal latency looks like."""
    meters = {}
    skipped = None
    timer = None
    for line in text.splitlines():
        if line.startswith("#") or "{" not in line:
            continue
        name, rest = line.split("{", 1)
        labels_raw, value = rest.rsplit("} ", 1)
        labels = _parse_labels(labels_raw)
        sensor = labels.get("sensor", "")
        if not sensor.startswith("Incremental."):
            continue
        if name == "cruise_control_meter_total":
            meters[sensor] = int(float(value))
        elif name == "cruise_control_gauge" and sensor == "Incremental.goals-skipped":
            skipped = int(float(value))
        elif name in ("cruise_control_latency_seconds_sum",
                      "cruise_control_latency_seconds_count",
                      "cruise_control_timer_seconds_sum",
                      "cruise_control_timer_seconds_count"):
            timer = timer or {"count": 0, "sum": 0.0}
            if name.endswith("_sum"):
                timer["sum"] = float(value)
            else:
                timer["count"] = int(float(value))
    print("\n== incremental rebalancing (in-place deltas) ==")
    if not meters and skipped is None and timer is None:
        print("   (no incremental sensors exported — lane never armed)")
        return
    armed = meters.get("Incremental.lane-armed", 0)
    fallbacks = meters.get("Incremental.fallback-to-full", 0)
    print(f"   lane armings                                         {armed:>8}")
    if skipped is not None:
        print(f"   goals skipped by sensitivity (last re-solve)         {skipped:>8}")
    if timer and timer["count"]:
        mean = timer["sum"] / timer["count"]
        print(f"   re-proposals: {timer['count']} in {_fmt_s(timer['sum'])}"
              f" (mean {_fmt_s(mean)})")
    for sensor, count in sorted(meters.items(), key=lambda kv: -kv[1]):
        if not count or sensor == "Incremental.lane-armed":
            continue
        marker = "!!" if sensor == "Incremental.fallback-to-full" and (
            fallbacks > armed // 2
        ) else "  "
        print(f"{marker} {sensor:<52} {count:>8}")


def _fmt_bytes(v: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024.0 or unit == "TiB":
            return f"{v:7.1f}{unit}"
        v /= 1024.0
    return f"{v:.1f}TiB"


def _fmt_count(v: float) -> str:
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(v) < 1000.0 or unit == "P":
            return f"{v:7.2f}{unit}"
        v /= 1000.0
    return f"{v:.2f}P"


def _perf_section(text: str) -> None:
    """Device telemetry (docs/OBSERVABILITY.md): per-bucket compiled-program
    cost, the memory watermark, and host<->device transfer totals."""
    buckets = {}
    memory = {}
    transfers = {}
    for line in text.splitlines():
        if line.startswith("#") or "{" not in line:
            continue
        name, rest = line.split("{", 1)
        labels_raw, value = rest.rsplit("} ", 1)
        labels = _parse_labels(labels_raw)
        sensor = labels.get("sensor", "")
        if name == "cruise_control_gauge":
            if sensor.startswith("DeviceTelemetry.program-cost."):
                bucket = sensor[len("DeviceTelemetry.program-cost."):]
                buckets.setdefault(bucket, {})[labels.get("field", "")] = float(value)
            elif sensor == "DeviceTelemetry.device-memory":
                memory[labels.get("field", "")] = float(value)
        elif name == "cruise_control_meter_total" and sensor.startswith(
            "DeviceTelemetry."
        ):
            transfers[sensor.rsplit(".", 1)[1]] = float(value)
    print("\n== device telemetry (per-bucket program cost) ==")
    if not buckets:
        print("   (no program-cost gauges exported — nothing compiled yet)")
    else:
        header = f"{'bucket':<28} {'programs':>8} {'flops':>9} {'bytesAccessed':>13}"
        print(header)
        print("-" * len(header))
        for bucket, fields in sorted(
            buckets.items(), key=lambda kv: -kv[1].get("flops", 0.0)
        ):
            print(
                f"{bucket:<28} {int(fields.get('programs', 0)):>8} "
                f"{_fmt_count(fields.get('flops', 0.0)):>9} "
                f"{_fmt_bytes(fields.get('bytesAccessed', 0.0)):>13}"
            )
    if memory:
        fb = " (process RSS fallback)" if memory.get("fallback") else ""
        print(
            f"   device memory: in use {_fmt_bytes(memory.get('bytesInUse', 0))}, "
            f"peak {_fmt_bytes(memory.get('peakBytesInUse', 0))}{fb}"
        )
    if transfers:
        print(
            f"   transfers: h2d {_fmt_bytes(transfers.get('host-to-device-bytes', 0))}"
            f" in {int(transfers.get('host-to-device-transfers', 0))} call(s), "
            f"d2h {_fmt_bytes(transfers.get('device-to-host-bytes', 0))}"
            f" in {int(transfers.get('device-to-host-transfers', 0))} call(s)"
        )


def _timeseries_movers(base: str, top: int = 10) -> None:
    """Top sensor movers over the /timeseries window (absent on servers
    predating the history store — degrade, don't die)."""
    print(f"\n== time-series movers (top {top} by |delta|) ==")
    try:
        doc = json.loads(_get(f"{base}/timeseries?limit={top}"))
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"   (no /timeseries endpoint: {e})")
        return
    query = doc.get("query") or {}
    movers = sorted(query.items(), key=lambda kv: -abs(kv[1]["delta"]))[:top]
    if not movers:
        print("   (history store is empty)")
        return
    h = doc.get("history") or {}
    for name, s in movers:
        if not s["delta"]:
            continue
        print(
            f"   {name:<58} {s['first']:>12.3f} -> {s['last']:>12.3f} "
            f"({s['delta']:+.3f}, {s['ratePerS']:+.4f}/s over {s['n']} pts)"
        )
    print(
        f"   history: {h.get('points', 0)}/{h.get('capacity', 0)} points, "
        f"sampler {'running' if h.get('samplerRunning') else 'off (scrape-driven)'}, "
        f"overhead {h.get('overheadS', 0.0)}s"
    )


def _provenance_section(base: str, text: str) -> None:
    """Decision provenance from /explain (absent on servers predating the
    MoveLedger — degrade, don't die): the latest run's moves by goal and
    engine plus its top cost-delta movers, next to the MoveLedger meters."""
    print("\n== decision provenance (latest recorded run) ==")
    counters = {}
    for line in text.splitlines():
        if line.startswith("#") or "{" not in line:
            continue
        name, rest = line.split("{", 1)
        labels_raw, value = rest.rsplit("} ", 1)
        sensor = _parse_labels(labels_raw).get("sensor", "")
        if sensor.startswith("MoveLedger."):
            counters[sensor] = float(value)
    try:
        doc = json.loads(_get(f"{base}/explain?limit=0"))
    except urllib.error.HTTPError as e:
        if e.code == 404:
            print("   (no optimization run recorded yet)")
        else:
            print(f"   (/explain error: {e})")
        doc = None
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"   (no /explain endpoint: {e})")
        doc = None
    if doc is not None:
        run = doc.get("run") or {}
        digest = run.get("digest") or {}
        print(
            f"   run {run.get('runId')}: {run.get('numMoves', 0)} moves + "
            f"{run.get('numLeadership', 0)} leadership, "
            f"checksum {digest.get('checksum')}"
        )
        segments = run.get("segments") or []
        by_goal = digest.get("byGoal") or {}
        if segments:
            header = (
                f"   {'goal':<38} {'engine':<14} {'phase':<7} {'moves':>6} "
                f"{'costDelta':>11}"
            )
            print(header)
            print("   " + "-" * (len(header) - 3))
            movers = sorted(
                segments, key=lambda s: -abs(s.get("costDelta", 0.0))
            )[:12]
            for s in movers:
                print(
                    f"   {s['goal']:<38} {s.get('engine', ''):<14} "
                    f"{s.get('phase', ''):<7} "
                    f"{s.get('numMoves', 0) + s.get('numLeadership', 0):>6} "
                    f"{s.get('costDelta', 0.0):>+11.4f}"
                )
        elif by_goal:
            for g, n in sorted(by_goal.items(), key=lambda kv: -kv[1]):
                print(f"   {g:<52} {n:>8}")
    for sensor, count in sorted(counters.items()):
        print(f"   {sensor:<52} {count:>8.0f}")


def _sensor_table(text: str) -> None:
    latencies = _parse_prometheus_latencies(text)
    print("\n== sensors (ranked by total seconds) ==")
    header = f"{'sensor':<52} {'count':>8} {'total':>10} {'mean':>9}"
    print(header)
    print("-" * len(header))
    for sensor, s in sorted(latencies.items(), key=lambda kv: -kv[1]["sum"]):
        mean = s["sum"] / s["count"] if s["count"] else 0.0
        print(f"{sensor:<52} {s['count']:>8} {_fmt_s(s['sum']):>10} {_fmt_s(mean):>9}")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("base", nargs="?", default="http://127.0.0.1:9090")
    parser.add_argument("--limit", type=int, default=512, help="spans to fetch")
    parser.add_argument("--raw", action="store_true", help="also dump raw /metrics text")
    args = parser.parse_args()
    base = args.base.rstrip("/")

    try:
        trace = json.loads(_get(f"{base}/trace?limit={args.limit}"))
        metrics_text = _get(f"{base}/metrics").decode()
    except (urllib.error.URLError, OSError) as e:
        print(f"cannot reach {base}: {e}", file=sys.stderr)
        return 1

    _span_kind_table(trace.get("summary", {}))
    _slow_spans(trace.get("spans", []))
    _sensor_table(metrics_text)
    _resilience_section(metrics_text)
    _drift_section(metrics_text)
    _incremental_section(metrics_text)
    _perf_section(metrics_text)
    _timeseries_movers(base)
    _provenance_section(base, metrics_text)
    print(f"\ntracer overhead: {trace.get('overheadS', 0.0):.6f}s")
    if args.raw:
        print("\n== raw /metrics ==")
        print(metrics_text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
