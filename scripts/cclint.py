#!/usr/bin/env python3
"""cclint CLI wrapper: lint the package without installing it.

    python scripts/cclint.py                 # full package, human output
    python scripts/cclint.py --json          # machine output (CI)
    python scripts/cclint.py --changed-only  # only files differing from main
    python scripts/cclint.py --list-rules    # rule catalog

Rule catalog and suppression policy: docs/LINTING.md. The same run gates
tier-1 through tests/test_static_guards.py.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from cruise_control_tpu.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
