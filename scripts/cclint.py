#!/usr/bin/env python3
"""cclint CLI wrapper: lint the package without installing it.

    python scripts/cclint.py                 # full package, both tiers
    python scripts/cclint.py --tier token    # ast/text rules only (fast loop)
    python scripts/cclint.py --tier trace    # jaxpr-level entry-point rules
    python scripts/cclint.py --json          # machine output, schema v2 (CI)
    python scripts/cclint.py --changed-only  # only files differing from main
    python scripts/cclint.py --list-rules    # rule catalog

This is the SAME CLI as `python -m cruise_control_tpu.lint` (pinned by
tests/test_lint_trace.py). Rule catalog and suppression policy:
docs/LINTING.md. The same run gates tier-1 through
tests/test_static_guards.py; the trace tier's verdicts are cached under
.cclint_cache/ keyed by source content hash.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from cruise_control_tpu.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
