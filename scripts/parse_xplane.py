"""Parse a JAX profiler xplane capture into a per-op time table (dev tool).

Usage: python scripts/parse_xplane.py /tmp/jaxtrace
Finds the newest *.xplane.pb under the trace dir and prints the op_profile /
framework_op_stats tool output as a ranked table (top self-time ops), so TPU
hot spots are readable without TensorBoard.
"""

import glob
import json
import os
import sys


def main() -> None:
    root = sys.argv[1] if len(sys.argv) > 1 else "/tmp/jaxtrace"
    paths = sorted(
        glob.glob(os.path.join(root, "**", "*.xplane.pb"), recursive=True),
        key=os.path.getmtime,
    )
    if not paths:
        sys.exit(f"no .xplane.pb under {root}")
    path = paths[-1]
    print(f"parsing {path} ({os.path.getsize(path)/1e6:.1f} MB)", flush=True)

    from xprof.convert import raw_to_tool_data as r2t

    params = {"tqx": "out:csv;"}
    for tool in ("framework_op_stats", "op_profile"):
        try:
            data, _ = r2t.xspace_to_tool_data([path], tool, params)
        except Exception as e:  # tool coverage varies by capture type
            print(f"-- {tool}: failed: {type(e).__name__}: {e}")
            continue
        out = os.path.join(root, f"{tool}.out")
        mode = "wb" if isinstance(data, bytes) else "w"
        with open(out, mode) as f:
            f.write(data)
        print(f"-- {tool}: wrote {out}")
        if tool == "framework_op_stats" and isinstance(data, (str, bytes)):
            text = data.decode() if isinstance(data, bytes) else data
            lines = text.splitlines()
            print("\n".join(lines[:40]))


if __name__ == "__main__":
    main()
