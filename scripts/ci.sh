#!/usr/bin/env bash
# One CI entrypoint: cclint (token + trace tiers) -> tier-1 tests -> perf gate.
#
# Usage:
#   scripts/ci.sh [CANDIDATE_BENCH_DETAIL.json]
#
# Artifacts: every run archives the cclint --json report (schema v2:
# per-rule family/tier/wall-time plus the trace-cache verdict) NEXT TO the
# tier-1 test log under $CI_ARTIFACTS (default /tmp/cruise_ci_artifacts):
#   cclint_report.json   machine-readable lint verdict
#   tier1.log            full tier-1 pytest output
#
# The perf gate only runs when a candidate BENCH_DETAIL.json is given (a
# fresh bench run is minutes of wall-clock; CI stages it separately and
# passes the artifact in). The gate diffs it against the committed
# BENCH_DETAIL.json baseline.
#
# Stable exit codes (documented in README; pipelines may match on them):
#   0  all stages passed
#   1  cclint findings (or lint usage error)
#   2  tier-1 test failure
#   3  perf regression           (perf_gate exit 1)
#   4  platform mismatch         (perf_gate exit 4)
#   5  provenance digest mismatch at equal parity — decision drift; run
#      scripts/diff_runs.py on the two runs' ledgers (perf_gate exit 5)
#   6  perf-gate usage / unreadable input (perf_gate exit 2)
#   7  incremental-vs-scratch digest mismatch — the delta-updated device
#      context diverged from the rebuild path (perf_gate exit 6)
set -u
cd "$(dirname "$0")/.."

ART="${CI_ARTIFACTS:-/tmp/cruise_ci_artifacts}"
mkdir -p "$ART"

echo "== cclint (token + trace tiers) =="
python scripts/cclint.py --tier all --json > "$ART/cclint_report.json"
lint_rc=$?
python - "$ART/cclint_report.json" <<'PY'
import json, sys
try:
    doc = json.load(open(sys.argv[1]))
except Exception as e:  # report unreadable: the exit code still gates
    print(f"cclint report unreadable: {e}")
    raise SystemExit(0)
s = doc.get("summary", {})
tr = doc.get("trace", {})
print(f"cclint: {s.get('unsuppressed', '?')} open / {s.get('suppressed', '?')} "
      f"suppressed over {doc.get('numFiles', '?')} files; trace tier: "
      f"{tr.get('entryPoints', 0)} entry points, "
      f"{'cache hit' if tr.get('cacheHit') else 'traced fresh'}")
for f in doc.get("findings", []):
    if not f.get("suppressed"):
        print(f"  {f['path']}:{f['line']}: {f['rule']}  {f['message']}")
PY
[ $lint_rc -eq 0 ] || exit 1

echo "== tier-1 tests (log: $ART/tier1.log) =="
env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider 2>&1 \
    | tee "$ART/tier1.log"
[ "${PIPESTATUS[0]}" -eq 0 ] || exit 2

if [ $# -ge 1 ]; then
    echo "== perf gate =="
    python scripts/perf_gate.py BENCH_DETAIL.json "$1"
    rc=$?
    case $rc in
        0) ;;
        1) exit 3 ;;
        4) exit 4 ;;
        5) exit 5 ;;
        6) exit 7 ;;
        *) exit 6 ;;
    esac
fi

echo "ci: all stages passed (artifacts: $ART)"
