#!/usr/bin/env bash
# One CI entrypoint: cclint -> tier-1 tests -> perf gate.
#
# Usage:
#   scripts/ci.sh [CANDIDATE_BENCH_DETAIL.json]
#
# The perf gate only runs when a candidate BENCH_DETAIL.json is given (a
# fresh bench run is minutes of wall-clock; CI stages it separately and
# passes the artifact in). The gate diffs it against the committed
# BENCH_DETAIL.json baseline.
#
# Stable exit codes (documented in README; pipelines may match on them):
#   0  all stages passed
#   1  cclint findings (or lint usage error)
#   2  tier-1 test failure
#   3  perf regression           (perf_gate exit 1)
#   4  platform mismatch         (perf_gate exit 4)
#   5  provenance digest mismatch at equal parity — decision drift; run
#      scripts/diff_runs.py on the two runs' ledgers (perf_gate exit 5)
#   6  perf-gate usage / unreadable input (perf_gate exit 2)
set -u
cd "$(dirname "$0")/.."

echo "== cclint =="
python scripts/cclint.py || exit 1

echo "== tier-1 tests =="
env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider || exit 2

if [ $# -ge 1 ]; then
    echo "== perf gate =="
    python scripts/perf_gate.py BENCH_DETAIL.json "$1"
    rc=$?
    case $rc in
        0) ;;
        1) exit 3 ;;
        4) exit 4 ;;
        5) exit 5 ;;
        *) exit 6 ;;
    esac
fi

echo "ci: all stages passed"
