"""Align two recorded provenance ledgers and pinpoint the first divergent move.

The decision-level counterpart of scripts/perf_gate.py: where perf_gate
diffs *outcomes* (wall, rounds, parity) between two bench runs, this diffs
the *decisions* — the per-move attribution ledgers two runs recorded
(analyzer/provenance.py RunLedger JSON, written by `bench.py` under
`BENCH_LEDGER_DIR` or dumped via GET /explain) — and reports the FIRST
move where they disagree, with both sides' full attribution (goal, engine,
phase, round, wave, src→dst). This is the tool that turns "config 3's
parity knife-edges by Δ0.193 on NW-in" from prose into a pinpointed
decision (BASELINE.md round-10 note).

Usage:
  python scripts/diff_runs.py LEDGER_A.json LEDGER_B.json [--json] [--moves N]

Inputs may be either a bare RunLedger dict or a file with a top-level
{"ledger": {...}} wrapper. Exit codes (stable):
  0  ledgers are decision-identical (same canonical move list)
  1  diverged (first divergence reported)
  2  usage / unreadable input
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# runnable as `python scripts/diff_runs.py` from anywhere: the ledger model
# lives in the package, which sits next to this script's parent dir
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXIT_IDENTICAL = 0
EXIT_DIVERGED = 1
EXIT_ERROR = 2


def _load(path: str):
    from cruise_control_tpu.analyzer.provenance import RunLedger

    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"diff_runs: cannot read {path}: {e}", file=sys.stderr)
        raise SystemExit(EXIT_ERROR)
    if isinstance(doc, dict) and "ledger" in doc:
        doc = doc["ledger"]
    if not isinstance(doc, dict) or "segments" not in doc:
        print(
            f"diff_runs: {path} is not a RunLedger dump "
            "(expected 'segments'/'moves' keys)",
            file=sys.stderr,
        )
        raise SystemExit(EXIT_ERROR)
    return RunLedger.from_dict(doc)


def _fmt_move(m: dict | None) -> str:
    if m is None:
        return "(no move — this side's stream ended here)"
    return (
        f"p{m['partition']}[slot {m['slot']}] {m['kind']} "
        f"{m['src']}->{m['dst']}  goal={m['goal']} engine={m['engine']} "
        f"phase={m['phase']} round={m['round']} wave={m['wave']}"
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="report the first divergent move between two recorded ledgers"
    )
    parser.add_argument("ledger_a", help="RunLedger JSON (e.g. the batched run)")
    parser.add_argument("ledger_b", help="RunLedger JSON (e.g. the greedy baseline)")
    parser.add_argument("--json", action="store_true", help="machine output")
    parser.add_argument("--moves", type=int, default=5,
                        help="context moves to print around the divergence")
    args = parser.parse_args(argv)

    from cruise_control_tpu.analyzer.provenance import MoveRecord, diff_ledgers

    a = _load(args.ledger_a)
    b = _load(args.ledger_b)
    report = diff_ledgers(a, b)

    if args.json:
        print(json.dumps(report, indent=1))
        return EXIT_IDENTICAL if report["identical"] else EXIT_DIVERGED

    print(f"run A: {report['runA']}  ({report['movesA']} moves, "
          f"checksum {report['digestA']['checksum']})")
    print(f"run B: {report['runB']}  ({report['movesB']} moves, "
          f"checksum {report['digestB']['checksum']})")
    print("\n== per-goal decision deltas (A - B) ==")
    header = (
        f"{'goal':<38} {'phase':<7} {'movesA':>7} {'movesB':>7} "
        f"{'costAfterA':>11} {'costAfterB':>11} {'delta':>10}"
    )
    print(header)
    print("-" * len(header))
    for s in report["segments"]:
        marker = "!!" if abs(s["costAfterDelta"]) > 1e-9 or s["movesA"] != s["movesB"] else "  "
        print(
            f"{marker}{s['goal']:<36} {s['phase']:<7} {s['movesA']:>7} "
            f"{s['movesB']:>7} {s['costAfterA']:>11.4f} {s['costAfterB']:>11.4f} "
            f"{s['costAfterDelta']:>+10.4f}"
        )

    if report["identical"]:
        print("\nledgers are decision-identical (same canonical move list)")
        return EXIT_IDENTICAL

    fd = report["firstDivergence"]
    print(
        f"\n== FIRST DIVERGENT MOVE (canonical index {fd['index']}; "
        f"goal {report['firstDivergenceGoal']}, "
        f"phase {report['firstDivergencePhase']}) =="
    )
    print(f"  A: {_fmt_move(fd['a'])}")
    print(f"  B: {_fmt_move(fd['b'])}")
    if args.moves > 0:
        sa = sorted(a.moves, key=MoveRecord.key)
        sb = sorted(b.moves, key=MoveRecord.key)
        i0 = max(0, fd["index"] - args.moves)
        i1 = fd["index"] + args.moves + 1
        print(f"\n  context (canonical order, moves {i0}..{i1 - 1}):")

        def _decision(d):
            # engine labels are presentation, not decisions (MoveRecord.decision)
            return {k: v for k, v in d.items() if k != "engine"} if d else None

        for i in range(i0, min(i1, max(len(sa), len(sb)))):
            ma = sa[i].to_dict() if i < len(sa) else None
            mb = sb[i].to_dict() if i < len(sb) else None
            same = _decision(ma) == _decision(mb)
            print(f"  {' ' if same else '>'} [{i:>5}] A {_fmt_move(ma)}")
            if not same:
                print(f"    [{i:>5}] B {_fmt_move(mb)}")
    return EXIT_DIVERGED


if __name__ == "__main__":
    raise SystemExit(main())
