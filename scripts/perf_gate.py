"""Machine-checkable bench regression gate.

Diffs a fresh BENCH_DETAIL.json against a committed baseline with per-metric
tolerances, so perf regressions fail a pipeline instead of hiding in prose
(BASELINE.md has twice drifted from the recorded artifacts — the round-5
verdict's open hinge). The inputs are the detail files bench.py writes;
records pair up by their BASELINE config (parsed from the metric string,
falling back to file order).

Checks per config pair (each individually tolerable):
  wall             candidate value <= baseline * (1 + --tol-wall)
  rounds           total goalRounds <= baseline * (1 + --tol-rounds)
  moves            |candidate - baseline| <= baseline * --tol-moves
  programsCompiled candidate <= baseline + --tol-programs  (compile-
                   amortization regressions are absolute, not relative)
  parityOk         may not flip true -> false
  collectiveOps    per-round collective op count (the `collectives` block
                   bench.py emits from the lowered HLO) may not grow beyond
                   --tol-collective-ops (absolute, default 0: an extra mesh
                   crossing per round is a sharding regression even when the
                   wall clock hides it); per-round collective BYTES get
                   relative slack (--tol-collective-bytes) since shape-bucket
                   padding legitimately moves them. Skipped when either
                   record predates the block or the platforms differ (each
                   backend lowers its own collectives).

Provenance checks (the r05 class):
  * candidate records missing a fingerprint block fail (bench.py now always
    embeds one; an unfingerprinted candidate is an untrusted artifact) —
    unless --allow-unfingerprinted (for gating historical baselines).
  * candidate platform must equal baseline platform (a cpu-vs-tpu wall diff
    is meaningless): exit 4, or pass --allow-platform-mismatch to compare
    anyway (wall/rounds checks are then skipped, provenance-only).

Decision-provenance check: when both records carry a `provenanceDigest`
(the MoveLedger checksum bench.py embeds) and every perf check passes at
equal parity, a digest mismatch means the runs made DIFFERENT decisions
while looking equally good — silent decision drift, not a perf regression.
It gets its own exit path (5) so pipelines can route it to
scripts/diff_runs.py instead of a perf triage.

Incremental-lane check (PR 20): when the candidate record carries an
`incrementalDigestOk` flag (bench.py emits it after timing a seeded
perturbation through the incremental lane, analyzer/incremental.py), the
flag must be True — False means an in-place delta re-solve and a
from-scratch solve of the SAME goal subset on the SAME perturbed model
produced different decisions, i.e. the scatter-updated device context has
diverged from the rebuild path. That is a correctness break in the
incremental kernel, not a perf regression, so it gets its own exit code
(6). The `incrementalReproposalS` wall rides the ordinary --tol-wall check
against the baseline when both records carry it.

Exit codes (stable; CI scripts may match on them):
  0  pass
  1  regression (any tolerance exceeded or parity flip)
  2  usage / unreadable input
  4  platform mismatch between candidate and baseline fingerprints
  5  provenance digest mismatch at equal parity (decision drift; run
     scripts/diff_runs.py on the two runs' ledgers)
  6  incremental-vs-scratch digest mismatch (candidate reports
     incrementalDigestOk=false: the delta-updated context diverged from
     the rebuild path on the re-solved goal subset)

Usage:
  python scripts/perf_gate.py BASELINE_DETAIL.json CANDIDATE_DETAIL.json \
      [--tol-wall 0.30] [--tol-rounds 0.25] [--tol-moves 0.25] \
      [--tol-programs 0] [--json]
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Dict, List, Optional

EXIT_PASS = 0
EXIT_REGRESSION = 1
EXIT_ERROR = 2
EXIT_PLATFORM_MISMATCH = 4
EXIT_DIGEST_MISMATCH = 5
EXIT_INCREMENTAL_DIGEST = 6

_CONFIG_RE = re.compile(r"BASELINE config (\d+)")


def _load(path: str) -> Dict:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"perf_gate: cannot read {path}: {e}", file=sys.stderr)
        raise SystemExit(EXIT_ERROR)
    if not isinstance(doc, dict) or not isinstance(doc.get("configs"), list):
        print(f"perf_gate: {path} is not a BENCH_DETAIL file "
              "(expected top-level {'configs': [...]})", file=sys.stderr)
        raise SystemExit(EXIT_ERROR)
    return doc


def _config_id(record: Dict, index: int) -> str:
    m = _CONFIG_RE.search(record.get("metric", ""))
    return m.group(1) if m else f"#{index}"


def _pair_records(base: Dict, cand: Dict) -> List:
    base_by_id = {
        _config_id(r, i): r for i, r in enumerate(base["configs"])
    }
    out = []
    for i, c in enumerate(cand["configs"]):
        cid = _config_id(c, i)
        b = base_by_id.get(cid)
        if b is not None:
            out.append((cid, b, c))
    return out


def _total_rounds(record: Dict) -> Optional[int]:
    rounds = record.get("goalRounds")
    if not isinstance(rounds, dict):
        return None
    return sum(int(v) for v in rounds.values())


def _fingerprint(doc: Dict, record: Dict) -> Dict:
    fp = record.get("fingerprint") or doc.get("fingerprint")
    return fp if isinstance(fp, dict) else {}


class Gate:
    def __init__(self, args):
        self.args = args
        self.checks: List[Dict] = []
        self.failed = False
        #: decision drift (digest mismatch at equal parity) — tracked apart
        #: from `failed` so it maps to its own exit code when it is the ONLY
        #: finding (a perf regression still exits 1 and dominates)
        self.digest_mismatch = False
        #: incremental-lane divergence (incrementalDigestOk=false): a
        #: correctness break in the delta kernel, own exit code (6)
        self.incremental_mismatch = False

    def check(self, cid: str, name: str, ok: bool, detail: str) -> None:
        self.checks.append(
            {"config": cid, "check": name, "ok": bool(ok), "detail": detail}
        )
        if not ok:
            if name == "provenanceDigest":
                self.digest_mismatch = True
            elif name == "incrementalDigestOk":
                self.incremental_mismatch = True
            else:
                self.failed = True

    def compare_pair(self, cid: str, b: Dict, c: Dict, walls: bool) -> None:
        a = self.args
        if walls:
            bw, cw = float(b.get("value", -1)), float(c.get("value", -1))
            if bw > 0 and cw > 0:
                limit = bw * (1.0 + a.tol_wall)
                self.check(
                    cid, "wall", cw <= limit,
                    f"wall {cw:.3f}s vs baseline {bw:.3f}s "
                    f"(limit {limit:.3f}s, tol {a.tol_wall:+.0%})",
                )
            br, cr = _total_rounds(b), _total_rounds(c)
            if br and cr is not None:
                limit_r = br * (1.0 + a.tol_rounds)
                self.check(
                    cid, "rounds", cr <= limit_r,
                    f"total rounds {cr} vs baseline {br} "
                    f"(limit {limit_r:.0f}, tol {a.tol_rounds:+.0%})",
                )
        bm, cm = b.get("moves"), c.get("moves")
        if isinstance(bm, int) and isinstance(cm, int) and bm > 0:
            slack = bm * a.tol_moves
            self.check(
                cid, "moves", abs(cm - bm) <= slack,
                f"moves {cm} vs baseline {bm} (slack +-{slack:.0f})",
            )
        b_coll, c_coll = b.get("collectives"), c.get("collectives")
        if walls and isinstance(b_coll, dict) and isinstance(c_coll, dict):
            bo, co = b_coll.get("perRoundOps"), c_coll.get("perRoundOps")
            if isinstance(bo, int) and isinstance(co, int):
                self.check(
                    cid, "collectiveOps", co <= bo + a.tol_collective_ops,
                    f"per-round collective ops {co} vs baseline {bo} "
                    f"(+{a.tol_collective_ops} allowed)",
                )
            bb, cb = b_coll.get("perRoundBytes"), c_coll.get("perRoundBytes")
            if isinstance(bb, (int, float)) and isinstance(cb, (int, float)) and bb > 0:
                limit_b = bb * (1.0 + a.tol_collective_bytes)
                self.check(
                    cid, "collectiveBytes", cb <= limit_b,
                    f"per-round collective bytes {cb} vs baseline {bb} "
                    f"(limit {limit_b:.0f}, tol {a.tol_collective_bytes:+.0%})",
                )
        bp, cp = b.get("programsCompiled"), c.get("programsCompiled")
        if isinstance(bp, int) and isinstance(cp, int):
            self.check(
                cid, "programsCompiled", cp <= bp + a.tol_programs,
                f"programs {cp} vs baseline {bp} (+{a.tol_programs} allowed)",
            )
        if b.get("parityOk") is True:
            self.check(
                cid, "parityOk", c.get("parityOk") is True,
                f"parityOk {c.get('parityOk')} vs baseline True",
            )
        ci = c.get("incrementalDigestOk")
        if ci is not None:
            self.check(
                cid, "incrementalDigestOk", ci is True,
                f"incremental-vs-scratch digest ok: {ci} (delta-updated "
                "context must reproduce the rebuild path's decisions)",
            )
        bi_s, ci_s = b.get("incrementalReproposalS"), c.get("incrementalReproposalS")
        if walls and isinstance(bi_s, (int, float)) and isinstance(ci_s, (int, float)) \
                and bi_s > 0 and ci_s > 0:
            limit_i = bi_s * (1.0 + a.tol_wall)
            self.check(
                cid, "incrementalWall", ci_s <= limit_i,
                f"incremental re-proposal {ci_s:.3f}s vs baseline {bi_s:.3f}s "
                f"(limit {limit_i:.3f}s, tol {a.tol_wall:+.0%})",
            )
        bd, cd = b.get("provenanceDigest"), c.get("provenanceDigest")
        if (
            isinstance(bd, str) and isinstance(cd, str)
            and b.get("parityOk") == c.get("parityOk")
        ):
            # equal parity + different decisions = silent decision drift
            # (exit 5 when nothing else failed; see module docstring)
            self.check(
                cid, "provenanceDigest", cd == bd,
                f"decision digest {cd} vs baseline {bd} at equal parity "
                "(run scripts/diff_runs.py on the two runs' ledgers)",
            )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="diff a fresh BENCH_DETAIL.json against a committed baseline"
    )
    parser.add_argument("baseline", help="committed BENCH_DETAIL.json")
    parser.add_argument("candidate", help="fresh BENCH_DETAIL.json to gate")
    parser.add_argument("--tol-wall", type=float, default=0.30,
                        help="relative wall-clock slack (default +30%%)")
    parser.add_argument("--tol-rounds", type=float, default=0.25,
                        help="relative total-goal-rounds slack (default +25%%)")
    parser.add_argument("--tol-moves", type=float, default=0.25,
                        help="relative replica-move-count slack (default +-25%%)")
    parser.add_argument("--tol-programs", type=int, default=0,
                        help="absolute extra compiled programs allowed (default 0)")
    parser.add_argument("--tol-collective-ops", type=int, default=0,
                        help="absolute extra per-round collective ops allowed "
                             "(default 0: no new mesh crossings per round)")
    parser.add_argument("--tol-collective-bytes", type=float, default=0.25,
                        help="relative per-round collective-bytes slack "
                             "(default +25%%; shape-bucket padding moves bytes)")
    parser.add_argument("--allow-platform-mismatch", action="store_true",
                        help="compare across platforms (wall/round checks skipped)")
    parser.add_argument("--allow-unfingerprinted", action="store_true",
                        help="accept candidate records with no fingerprint block")
    parser.add_argument("--json", action="store_true", help="machine output")
    args = parser.parse_args(argv)

    base = _load(args.baseline)
    cand = _load(args.candidate)
    pairs = _pair_records(base, cand)
    if not pairs:
        print("perf_gate: no overlapping configs between baseline and candidate",
              file=sys.stderr)
        return EXIT_ERROR

    gate = Gate(args)
    platform_mismatch = False
    for cid, b, c in pairs:
        bfp, cfp = _fingerprint(base, b), _fingerprint(cand, c)
        if not cfp and not args.allow_unfingerprinted:
            gate.check(
                cid, "fingerprint", False,
                "candidate record carries no environment fingerprint "
                "(re-run with the current bench.py, or --allow-unfingerprinted)",
            )
        walls = True
        b_platform = bfp.get("platform") or b.get("platform")
        c_platform = cfp.get("platform") or c.get("platform")
        if b_platform and c_platform and b_platform != c_platform:
            platform_mismatch = True
            walls = False  # cross-platform wall/round diffs are meaningless
            gate.check(
                cid, "platform", args.allow_platform_mismatch,
                f"candidate platform {c_platform!r} vs baseline {b_platform!r}",
            )
        if cfp.get("probeFallback") and c_platform != "cpu":
            gate.check(
                cid, "probeFallback", False,
                "candidate fingerprint has probeFallback=true but a "
                f"non-cpu platform label ({c_platform!r}) — mislabeled artifact",
            )
        gate.compare_pair(cid, b, c, walls=walls)

    if args.json:
        print(json.dumps(
            {"checks": gate.checks,
             "digestMismatch": gate.digest_mismatch,
             "incrementalMismatch": gate.incremental_mismatch,
             "pass": not gate.failed and not gate.digest_mismatch
             and not gate.incremental_mismatch and not (
                 platform_mismatch and not args.allow_platform_mismatch)},
            indent=1,
        ))
    else:
        for ch in gate.checks:
            marker = "ok  " if ch["ok"] else "FAIL"
            print(f"{marker} config {ch['config']:<3} {ch['check']:<16} {ch['detail']}")
        n_fail = sum(1 for ch in gate.checks if not ch["ok"])
        print(f"perf_gate: {len(gate.checks)} check(s), {n_fail} failure(s) "
              f"over {len(pairs)} config pair(s)")
    if platform_mismatch and not args.allow_platform_mismatch:
        return EXIT_PLATFORM_MISMATCH
    if gate.failed:
        return EXIT_REGRESSION
    if gate.digest_mismatch:
        return EXIT_DIGEST_MISMATCH
    return EXIT_INCREMENTAL_DIGEST if gate.incremental_mismatch else EXIT_PASS


if __name__ == "__main__":
    raise SystemExit(main())
