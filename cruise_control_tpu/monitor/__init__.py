"""Monitor subsystem: samples -> windows -> FlatClusterModel.

The analog of cc/monitor/ + the core aggregation engine
(core/monitor/sampling/aggregator/): a windowed metric aggregator re-expressed
as dense ring-buffer arrays over (entity, window, metric), pluggable samplers
and sample stores, the metric processor that derives per-partition CPU from
broker CPU and byte rates, and the LoadMonitor that assembles the flattened
cluster model the analyzer consumes.
"""

from cruise_control_tpu.monitor.aggregator import (
    AggregationOptions,
    Extrapolation,
    Granularity,
    WindowedAggregator,
)
from cruise_control_tpu.monitor.completeness import ModelCompletenessRequirements
from cruise_control_tpu.monitor.load_monitor import LoadMonitor, LoadMonitorConfig
from cruise_control_tpu.monitor.metricdef import AggregationFunction, KafkaMetricDef

__all__ = [
    "AggregationFunction",
    "AggregationOptions",
    "Extrapolation",
    "Granularity",
    "KafkaMetricDef",
    "LoadMonitor",
    "LoadMonitorConfig",
    "ModelCompletenessRequirements",
    "WindowedAggregator",
]
