"""Sampling scheduler: the LoadMonitorTaskRunner analog.

Mirrors cc/monitor/task/LoadMonitorTaskRunner.java:30 — a background scheduler
driving periodic sampling rounds against the LoadMonitor, with the reference's
state machine (NOT_STARTED/RUNNING/SAMPLING/PAUSED/BOOTSTRAPPING/...) living
on the monitor itself and pause/resume (:273-295) forwarded through here.
"""

from __future__ import annotations

import threading
from typing import Optional

from cruise_control_tpu.monitor.load_monitor import LoadMonitor
from cruise_control_tpu.monitor.sampler import Samples


class LoadMonitorTaskRunner:
    def __init__(self, monitor: LoadMonitor, sampling_interval_s: Optional[float] = None):
        self._monitor = monitor
        self._interval = (
            sampling_interval_s
            if sampling_interval_s is not None
            else monitor._config.sampling_interval_s
        )
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> None:
        """LoadMonitorTaskRunner.start (:225): replay store, begin sampling."""
        if self._thread is not None:
            raise RuntimeError("task runner already started")
        self._monitor.start_up()
        self._stop.clear()

        def run():
            while not self._stop.wait(self._interval):
                try:
                    self._monitor.sample_once()
                except Exception:
                    pass  # sampling errors must not kill the loop

        self._thread = threading.Thread(target=run, name="load-monitor-sampler", daemon=True)
        self._thread.start()

    def bootstrap(self, samples: Samples) -> int:
        """Backfill mode (BootstrapTask analog)."""
        return self._monitor.bootstrap(samples)

    def pause_sampling(self, reason: str = "") -> None:
        self._monitor.pause_metric_sampling(reason)

    def resume_sampling(self) -> None:
        self._monitor.resume_metric_sampling()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
