"""Sampling scheduler: the LoadMonitorTaskRunner analog.

Mirrors cc/monitor/task/LoadMonitorTaskRunner.java:30 — a background scheduler
driving periodic sampling rounds against the LoadMonitor, plus the bootstrap
and training tasks (BootstrapTask :21, TrainingTask :20). The state machine
(NOT_STARTED/LOADING/RUNNING/SAMPLING/PAUSED/BOOTSTRAPPING/TRAINING,
enum :52) lives on the monitor; the runner drives the transitions and
exposes the combined view for `/state`.

Sampling itself may be a single `MetricSampler` or an N-way
`MetricFetcherManager` (monitor.fetcher) — the monitor treats both
identically through the sampler signature.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from cruise_control_tpu.monitor.load_monitor import LoadMonitor
from cruise_control_tpu.monitor.sampler import Samples


class LoadMonitorTaskRunner:
    def __init__(self, monitor: LoadMonitor, sampling_interval_s: Optional[float] = None):
        self._monitor = monitor
        self._interval = (
            sampling_interval_s
            if sampling_interval_s is not None
            else monitor._config.sampling_interval_s
        )
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # exclusive-mode serialization (one bootstrap/training at a time,
        # :127) lives on the monitor's _task_lock so REST requests that reach
        # the monitor directly are covered by the same guard
        self.sensors: Dict[str, int] = {
            "sampling_rounds": 0,
            "sampling_failures": 0,
            "bootstrap_tasks": 0,
            "training_tasks": 0,
        }

    @property
    def state(self) -> str:
        """The reference's LoadMonitorTaskRunnerState, surfaced via /state."""
        return self._monitor.state

    def start(self) -> None:
        """LoadMonitorTaskRunner.start (:225): replay store, begin sampling."""
        if self._thread is not None:
            raise RuntimeError("task runner already started")
        self._monitor.start_up()
        self._stop.clear()

        def run():
            while not self._stop.wait(self._interval):
                try:
                    self._monitor.sample_once()
                    self.sensors["sampling_rounds"] += 1
                except Exception:
                    self.sensors["sampling_failures"] += 1

        self._thread = threading.Thread(target=run, name="load-monitor-sampler", daemon=True)
        self._thread.start()

    # -- bootstrap (BootstrapTask) --------------------------------------------

    def bootstrap(self, samples: Samples) -> int:
        """Backfill pre-built samples."""
        self.sensors["bootstrap_tasks"] += 1
        return self._monitor.bootstrap(samples)

    def bootstrap_range(self, start_ms: int, end_ms: Optional[int] = None) -> int:
        """Time-range backfill from the sample store (RANGE / SINCE modes of
        LoadMonitorTaskRunner.bootstrap :127-177)."""
        self.sensors["bootstrap_tasks"] += 1
        return self._monitor.bootstrap_range(start_ms, end_ms)

    # -- training (TrainingTask) ----------------------------------------------

    def train(self, start_ms: int, end_ms: Optional[int] = None) -> Dict:
        """Feed the linear-regression CPU model from the range's broker
        samples (LoadMonitorTaskRunner.train :205)."""
        self.sensors["training_tasks"] += 1
        return self._monitor.train_range(start_ms, end_ms)

    # -- pause / resume --------------------------------------------------------

    def pause_sampling(self, reason: str = "") -> None:
        self._monitor.pause_metric_sampling(reason)

    def resume_sampling(self) -> None:
        self._monitor.resume_metric_sampling()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
