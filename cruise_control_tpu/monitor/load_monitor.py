"""LoadMonitor: windows -> FlatClusterModel.

Analog of cc/monitor/LoadMonitor.java:68 — owns the partition and broker
aggregators, samples through the pluggable sampler, persists through the
sample store, and on demand assembles the flattened cluster model
(clusterModel :422-487: topology from metadata + capacities from the resolver
+ per-partition window loads). Model generation is guarded by a fairness
semaphore (`acquire_for_model_generation` :357) and the result summarizes into
BrokerStats for the /load endpoint.

The window->expected-utilization reduction (Load.expectedUtilizationFor) is
where windows collapse to the part_load matrix: CPU/NW are window-averaged,
DISK takes the latest window — computed as one numpy pass over the
aggregation result.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

import numpy as np

from cruise_control_tpu.common.resources import NUM_PART_METRICS, BrokerState, PartMetric
from cruise_control_tpu.models.flat_model import ClusterMetadata, FlatClusterModel
from cruise_control_tpu.models.model_utils import follower_cpu_util_from_leader_load
from cruise_control_tpu.monitor.aggregator import (
    AggregationOptions,
    Extrapolation,
    WindowedAggregator,
)
from cruise_control_tpu.monitor.completeness import (
    ModelCompletenessRequirements,
    NotEnoughValidPartitionsError,
    NotEnoughValidWindowsError,
)
from cruise_control_tpu.monitor.metadata import (
    BrokerCapacityConfigResolver,
    MetadataClient,
    StaticCapacityResolver,
)
from cruise_control_tpu.monitor.metricdef import (
    AGGREGATION_OF,
    NUM_BROKER_METRICS,
    NUM_COMMON_METRICS,
    COMMON_METRIC_DEFS,
    KafkaMetricDef,
)
from cruise_control_tpu.monitor.sample_store import NoopSampleStore, SampleStore
from cruise_control_tpu.monitor.sampler import MetricSampler, Samples
from cruise_control_tpu.monitor.samples import as_batch


@dataclasses.dataclass(frozen=True)
class LoadMonitorConfig:
    """Window knobs; key names mirror num.partition.metrics.windows etc."""

    window_ms: int = 60_000
    num_windows: int = 5
    min_samples_per_window: int = 3
    num_broker_windows: int = 20
    sampling_interval_s: float = 10.0


class LoadMonitorState:
    NOT_STARTED = "NOT_STARTED"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    SAMPLING = "SAMPLING"
    BOOTSTRAPPING = "BOOTSTRAPPING"
    TRAINING = "TRAINING"
    LOADING = "LOADING"


class IllegalMonitorStateError(RuntimeError):
    """An exclusive mode (bootstrap/training) was requested while another is
    in progress — the reference REJECTS the request rather than queueing it
    (LoadMonitorTaskRunner.bootstrap :127-177 throws IllegalStateException
    when the state machine is not in RUNNING)."""


class LoadMonitor:
    def __init__(
        self,
        metadata_client: MetadataClient,
        sampler: MetricSampler,
        sample_store: Optional[SampleStore] = None,
        capacity_resolver: Optional[BrokerCapacityConfigResolver] = None,
        config: LoadMonitorConfig = LoadMonitorConfig(),
        clock: Callable[[], float] = time.time,
    ):
        self._metadata = metadata_client
        self._sampler = sampler
        self._store = sample_store or NoopSampleStore()
        # bound the store to a multiple of the aggregation horizon: samples
        # past the horizon can't contribute to windows, but train_range /
        # bootstrap_range replay deeper history for the LR CPU model and
        # backfills, so keep several horizons (KafkaSampleStore's topic
        # retention is likewise operator-sized above the window horizon)
        self._store.configure_retention(8 * config.window_ms * config.num_windows)
        self._capacity = capacity_resolver or StaticCapacityResolver()
        self._config = config
        self._clock = clock
        self._state = LoadMonitorState.NOT_STARTED
        self._sampling_paused = False
        self._pause_reason: Optional[str] = None
        self._model_semaphore = threading.Semaphore(1)
        self._lock = threading.RLock()
        #: guards exclusive modes (one bootstrap/training at a time); entry
        #: is non-blocking — a concurrent request is REJECTED with
        #: IllegalMonitorStateError, matching the reference's behavior
        self._task_lock = threading.Lock()
        #: /state reporting of the active exclusive mode + progress
        #: (the reference surfaces bootstrap progress % via
        #: LoadMonitorTaskRunner's state)
        self._active_task: Optional[Dict] = None
        self._last_sample_ms = 0
        # sensor counters (cluster-model-creation-timer analog)
        self.sensors: Dict[str, float] = {"model_creations": 0, "model_creation_time_s": 0.0}
        #: trainable CPU-estimation model fed by train_range
        #: (cc/model/LinearRegressionModelParameters.java:26 analog)
        from cruise_control_tpu.models.model_utils import LinearRegressionModelParameters

        self.lr_params = LinearRegressionModelParameters()

        topo = metadata_client.refresh_metadata()
        common_fns = [AGGREGATION_OF[d] for d in COMMON_METRIC_DEFS]
        broker_fns = [AGGREGATION_OF[d] for d in KafkaMetricDef]
        self._partition_agg = WindowedAggregator(
            num_entities=topo.num_partitions,
            num_metrics=NUM_COMMON_METRICS,
            aggregation_functions=common_fns,
            window_ms=config.window_ms,
            num_windows=config.num_windows,
            min_samples_per_window=config.min_samples_per_window,
            entity_group=np.asarray(topo.topic_id, dtype=np.int64),
        )
        self._broker_agg = WindowedAggregator(
            num_entities=topo.num_brokers,
            num_metrics=NUM_BROKER_METRICS,
            aggregation_functions=broker_fns,
            window_ms=config.window_ms,
            num_windows=config.num_broker_windows,
            min_samples_per_window=1,
        )

    # -- lifecycle / state -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def start_up(self) -> None:
        """Replay the sample store (SampleLoadingTask analog), then run."""
        with self._lock:
            self._state = LoadMonitorState.LOADING
        part, brok = self._store.load_samples()
        if part or brok:
            self._add_samples(Samples(part, brok), persist=False)
        with self._lock:
            self._state = LoadMonitorState.RUNNING

    def pause_metric_sampling(self, reason: str = "") -> None:
        with self._lock:
            self._sampling_paused = True
            self._pause_reason = reason
            self._state = LoadMonitorState.PAUSED

    def resume_metric_sampling(self) -> None:
        with self._lock:
            self._sampling_paused = False
            self._pause_reason = None
            self._state = LoadMonitorState.RUNNING

    @property
    def sampling_paused(self) -> bool:
        with self._lock:
            return self._sampling_paused

    # -- sampling --------------------------------------------------------------

    def sample_once(self) -> int:
        """One sampling round (SamplingTask analog); returns samples ingested."""
        with self._lock:
            if self._sampling_paused:
                return 0
            self._state = LoadMonitorState.SAMPLING
        try:
            topo = self._metadata.refresh_metadata()
            self._ensure_universe(topo)
            now_ms = int(self._clock() * 1000)
            start_ms = self._last_sample_ms
            samples = self._sampler.get_samples(topo, start_ms, now_ms)
            self._last_sample_ms = now_ms
            return self._add_samples(samples, persist=True)
        finally:
            with self._lock:
                if not self._sampling_paused:
                    self._state = LoadMonitorState.RUNNING

    def _restore_state(self) -> None:
        """Leave an exclusive mode without clobbering an operator pause."""
        with self._lock:
            self._state = (
                LoadMonitorState.PAUSED
                if self._sampling_paused
                else LoadMonitorState.RUNNING
            )

    @contextmanager
    def _exclusive_mode(self, mode: str, description: str = ""):
        """Enter an exclusive mode (BOOTSTRAPPING/TRAINING) or REJECT.

        The reference refuses to start a bootstrap/training while another
        exclusive task is in progress (LoadMonitorTaskRunner.bootstrap
        :127-177); this non-blocking guard is the single authoritative gate
        for every entry point (REST and task runner both land here)."""
        if not self._task_lock.acquire(blocking=False):
            active = (self._active_task or {}).get("mode", "unknown")
            raise IllegalMonitorStateError(
                f"cannot start {mode}: {active} is in progress"
            )
        try:
            with self._lock:
                self._state = mode
                self._active_task = {
                    "mode": mode, "progress": 0.0, "description": description,
                }
            yield
        finally:
            with self._lock:
                self._active_task = None
            self._restore_state()
            self._task_lock.release()

    def _set_task_progress(self, fraction: float) -> None:
        with self._lock:
            if self._active_task is not None:
                self._active_task["progress"] = round(min(1.0, max(0.0, fraction)), 4)

    @property
    def active_task(self) -> Optional[Dict]:
        """{'mode', 'progress', 'description'} of the running exclusive task
        (None when idle) — surfaced through /state."""
        with self._lock:
            return dict(self._active_task) if self._active_task else None

    def bootstrap(self, samples: Samples) -> int:
        """Backfill historic samples (LoadMonitorTaskRunner.bootstrap :127)."""
        with self._exclusive_mode(
            LoadMonitorState.BOOTSTRAPPING,
            f"{len(samples.partition_samples)}+{len(samples.broker_samples)} samples",
        ):
            topo = self._metadata.refresh_metadata()
            self._ensure_universe(topo)
            # ingest in slices so /state reports bootstrap progress
            part = list(samples.partition_samples)
            brok = list(samples.broker_samples)
            total = max(1, len(part) + len(brok))
            step = max(1, total // 10)
            added = 0
            done = 0
            for lo in range(0, len(part), step):
                added += self._add_samples(
                    Samples(part[lo:lo + step], []), persist=False
                )
                done += len(part[lo:lo + step])
                self._set_task_progress(done / total)
            for lo in range(0, len(brok), step):
                added += self._add_samples(
                    Samples([], brok[lo:lo + step]), persist=False
                )
                done += len(brok[lo:lo + step])
                self._set_task_progress(done / total)
            return added

    def bootstrap_range(self, start_ms: int, end_ms: Optional[int] = None) -> int:
        """Time-range bootstrap (BootstrapTask :21, the RANGE/SINCE modes of
        LoadMonitorTaskRunner.bootstrap :127-177): replay the sample store's
        history inside [start_ms, end_ms) into the window aggregators. The
        store is this deployment's durable history — the analog of seeking a
        consumer back through the metrics topic."""
        part, brok = self._store.load_samples()
        hi = end_ms if end_ms is not None else int(self._clock() * 1000)
        picked = Samples(
            [s for s in part if start_ms <= s.time_ms < hi],
            [s for s in brok if start_ms <= s.time_ms < hi],
        )
        return self.bootstrap(picked)

    def _lr_observe(self, metrics) -> bool:
        """Feed one broker-metric vector into the LR model; False if skipped."""
        from cruise_control_tpu.monitor.metricdef import KafkaMetricDef

        cpu = float(metrics[KafkaMetricDef.CPU_USAGE])
        if cpu <= 0:
            return False
        self.lr_params.add_observation(
            cpu / 100.0,
            float(metrics[KafkaMetricDef.LEADER_BYTES_IN]),
            float(metrics[KafkaMetricDef.LEADER_BYTES_OUT]),
            float(metrics[KafkaMetricDef.REPLICATION_BYTES_IN_RATE]),
        )
        return True

    def train_range(self, start_ms: int, end_ms: Optional[int] = None) -> Dict:
        """Training mode (LoadMonitorTaskRunner.train :205 + TrainingTask/
        TrainingFetcher): feed broker samples from the range into the
        linear-regression CPU model (ModelParameters analog). Returns the fit
        summary; coefficients stay on `self.lr_params` for the estimator."""
        with self._exclusive_mode(
            LoadMonitorState.TRAINING, f"range [{start_ms}, {end_ms})"
        ):
            _, brok = self._store.load_samples()
            hi = end_ms if end_ms is not None else int(self._clock() * 1000)
            in_range = [s for s in brok if start_ms <= s.time_ms < hi]
            n = 0
            for i, s in enumerate(in_range):
                n += self._lr_observe(s.metrics)
                if i % 64 == 0:
                    self._set_task_progress(i / max(1, len(in_range)))
            if n == 0:
                # no durable history in range (e.g. Noop store): observe
                # the in-memory broker windows instead — the recent
                # history the TrainingFetcher would re-sample.
                try:
                    vals = self._broker_agg.aggregate().values  # [B, W, M]
                except ValueError:
                    vals = None
                if vals is not None:
                    n = sum(
                        self._lr_observe(vals[b, w])
                        for b in range(vals.shape[0])
                        for w in range(vals.shape[1])
                    )
            self._set_task_progress(1.0)
            coef = self.lr_params.train()
            return {
                "observations_added": int(n),
                "total_observations": self.lr_params.num_observations,
                "trained": coef is not None,
                "coefficients": None if coef is None else [float(c) for c in coef],
            }

    def _ensure_universe(self, topo) -> None:
        if topo.num_partitions > self._partition_agg.num_entities:
            self._partition_agg.resize(
                topo.num_partitions, np.asarray(topo.topic_id, dtype=np.int64)
            )
        if topo.num_brokers > self._broker_agg.num_entities:
            self._broker_agg.resize(topo.num_brokers)

    def _add_samples(self, samples: Samples, persist: bool) -> int:
        n = 0
        part = as_batch(samples.partition_samples, "partition")
        brok = as_batch(samples.broker_samples, "broker")
        if len(part):
            n += self._partition_agg.add_samples(part.ids, part.times, part.metrics)
        if len(brok):
            n += self._broker_agg.add_samples(brok.ids, brok.times, brok.metrics)
        if persist and (len(part) or len(brok)):
            self._store.store_samples(part, brok)
        return n

    # -- completeness ----------------------------------------------------------

    def meet_completeness_requirements(self, req: ModelCompletenessRequirements) -> bool:
        """LoadMonitor.meetCompletenessRequirements (:539)."""
        options = AggregationOptions(
            min_valid_entity_ratio=req.min_monitored_partitions_percentage,
            min_valid_windows=req.min_required_num_windows,
        )
        return self._partition_agg.meets(options)

    @property
    def generation(self) -> int:
        """Model generation: bumps when windows or topology change."""
        return self._partition_agg.generation + self._metadata.generation

    # -- model assembly --------------------------------------------------------

    def acquire_for_model_generation(self, timeout_s: float = 60.0):
        """Fairness semaphore around model builds (LoadMonitor:357)."""
        acquired = self._model_semaphore.acquire(timeout=timeout_s)
        if not acquired:
            raise TimeoutError("could not acquire model-generation semaphore")

        class _Release:
            def __enter__(inner):
                return inner

            def __exit__(inner, *exc):
                self._model_semaphore.release()
                return False

        return _Release()

    def cluster_model(
        self,
        requirements: ModelCompletenessRequirements = ModelCompletenessRequirements(),
        allow_capacity_estimation: bool = True,
    ) -> tuple:
        """Build (FlatClusterModel, ClusterMetadata) from current windows.

        The flattening pass of LoadMonitor.clusterModel (:422-487): topology
        arrays come straight from metadata; part_load comes from the window
        aggregation, leader/follower split via the CPU attribution model."""
        from cruise_control_tpu.common.tracing import TRACER

        with TRACER.span("cluster-model-creation", kind="monitor") as span:
            model, meta = self._build_cluster_model(requirements, span)
        return model, meta

    def _build_cluster_model(self, requirements: ModelCompletenessRequirements, span):
        t0 = self._clock()
        topo = self._metadata.refresh_metadata()
        self._ensure_universe(topo)

        try:
            agg = self._partition_agg.aggregate(
                options=AggregationOptions(
                    min_valid_entity_ratio=requirements.min_monitored_partitions_percentage,
                    min_valid_windows=requirements.min_required_num_windows,
                )
            )
        except ValueError as e:
            # a cold aggregator ("no samples added yet" / "no completed
            # windows yet") is a completeness condition, not an internal
            # error — surface it typed so the REST tier answers 503
            raise NotEnoughValidWindowsError(str(e), {
                "validPartitionRatio": 0.0,
                "requiredPartitionRatio": requirements.min_monitored_partitions_percentage,
                "validWindows": 0,
                "requiredWindows": requirements.min_required_num_windows,
            }) from e
        c = agg.completeness
        completeness = {
            "validPartitionRatio": round(float(c.valid_entity_ratio), 4),
            "requiredPartitionRatio": requirements.min_monitored_partitions_percentage,
            "validWindows": len(c.valid_windows),
            "requiredWindows": requirements.min_required_num_windows,
        }
        if c.valid_entity_ratio < requirements.min_monitored_partitions_percentage:
            raise NotEnoughValidPartitionsError(
                f"not enough valid partitions: {c.valid_entity_ratio:.3f} < "
                f"{requirements.min_monitored_partitions_percentage:.3f}",
                completeness,
            )
        if len(c.valid_windows) < requirements.min_required_num_windows:
            raise NotEnoughValidWindowsError(
                f"not enough valid windows: {len(c.valid_windows)} < "
                f"{requirements.min_required_num_windows}",
                completeness,
            )

        values = agg.values  # f32[P, W, M_common]
        # windows -> expected utilization (Load.expectedUtilizationFor):
        # AVG metrics average over windows; LATEST (disk) takes the newest.
        win_avg = values.mean(axis=1)  # [P, M]
        disk = values[:, -1, KafkaMetricDef.DISK_USAGE]
        cpu = win_avg[:, KafkaMetricDef.CPU_USAGE]
        l_in = win_avg[:, KafkaMetricDef.LEADER_BYTES_IN]
        l_out = win_avg[:, KafkaMetricDef.LEADER_BYTES_OUT]

        part_load = np.zeros((topo.num_partitions, NUM_PART_METRICS), dtype=np.float32)
        part_load[:, PartMetric.CPU_LEADER] = cpu
        part_load[:, PartMetric.CPU_FOLLOWER] = follower_cpu_util_from_leader_load(
            l_in, l_out, cpu
        )
        part_load[:, PartMetric.NW_IN_LEADER] = l_in
        part_load[:, PartMetric.NW_IN_FOLLOWER] = l_in  # replication pulls leader input
        part_load[:, PartMetric.NW_OUT_LEADER] = l_out
        part_load[:, PartMetric.DISK] = disk

        capacities = np.stack(
            [self._capacity.capacity_for_broker(int(bid)) for bid in topo.broker_ids]
        )

        model = FlatClusterModel(
            assignment=np.asarray(topo.assignment, dtype=np.int32),
            part_load=part_load,
            topic_id=np.asarray(topo.topic_id, dtype=np.int32),
            broker_capacity=capacities.astype(np.float32),
            broker_rack=np.asarray(topo.broker_rack, dtype=np.int32),
            broker_host=np.asarray(topo.broker_host, dtype=np.int32),
            broker_state=np.asarray(topo.broker_state, dtype=np.int32),
        )
        meta = ClusterMetadata(
            topic_names=tuple(topo.topic_names),
            partition_index=np.asarray(topo.partition_index, dtype=np.int32),
            broker_ids=np.asarray(topo.broker_ids, dtype=np.int32),
            topic_of_partition=np.asarray(topo.topic_id, dtype=np.int32),
        )
        self.sensors["model_creations"] += 1
        self.sensors["model_creation_time_s"] += self._clock() - t0
        from cruise_control_tpu.common.sensors import REGISTRY

        # hot timer -> histogram: /metrics serves p50/p95/p99 of model builds
        REGISTRY.histogram("LoadMonitor.cluster-model-creation-timer").record(
            self._clock() - t0
        )
        span.attributes.update(
            brokers=int(topo.num_brokers),
            partitions=int(topo.num_partitions),
            generation=int(self.generation),
        )
        return model, meta

