"""Cluster topology source + broker capacity resolution.

Analogs of MetadataClient (cc/common/MetadataClient.java — TTL-cached Kafka
metadata with a generation counter) and the BrokerCapacityConfigResolver SPI
(cc/config/BrokerCapacityConfigResolver.java:16 /
BrokerCapacityConfigFileResolver.java:69 reading config/capacity.json). The
topology is already in flat-array form so LoadMonitor can assemble a
FlatClusterModel without an object-graph intermediate.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, BrokerState, Resource


@dataclasses.dataclass(frozen=True)
class ClusterTopology:
    """Flat snapshot of cluster structure (no load)."""

    topic_names: Tuple[str, ...]
    topic_id: np.ndarray  # i32[P]
    partition_index: np.ndarray  # i32[P] partition number within topic
    assignment: np.ndarray  # i32[P, R]; slot 0 = leader, -1 pad
    broker_ids: np.ndarray  # i32[B] external ids (dense index -> external)
    broker_rack: np.ndarray  # i32[B]
    broker_host: np.ndarray  # i32[B]
    broker_state: np.ndarray  # i32[B]
    generation: int = 0

    @property
    def num_partitions(self) -> int:
        return self.topic_id.shape[0]

    @property
    def num_brokers(self) -> int:
        return self.broker_ids.shape[0]

    def broker_index_of(self) -> Dict[int, int]:
        return {int(b): i for i, b in enumerate(self.broker_ids)}

    def leader_topic_counts(self) -> np.ndarray:
        """i32[B, T]: leader partitions per (broker, topic) — the processor's
        leaderDistributionStats (CruiseControlMetricsProcessor.java:208)."""
        b, t = self.num_brokers, len(self.topic_names)
        leaders = self.assignment[:, 0]
        ok = leaders >= 0
        flat = leaders[ok] * t + self.topic_id[ok]
        counts = np.bincount(flat, minlength=b * t).astype(np.int32)
        return counts.reshape(b, t)


class MetadataClient:
    """TTL-cached topology provider. `fetch` is the pluggable backend (a Kafka
    admin client in production; a simulator in tests)."""

    def __init__(self, fetch: Callable[[], ClusterTopology], ttl_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self._fetch = fetch
        self._ttl = ttl_s
        self._clock = clock
        self._lock = threading.Lock()
        self._cached: Optional[ClusterTopology] = None
        self._fetched_at = -float("inf")
        self._generation = 0

    def refresh_metadata(self, force: bool = False) -> ClusterTopology:
        with self._lock:
            now = self._clock()
            if force or self._cached is None or now - self._fetched_at > self._ttl:
                topo = self._fetch()
                if self._cached is None or not _same_topology(self._cached, topo):
                    self._generation += 1
                self._cached = dataclasses.replace(topo, generation=self._generation)
                self._fetched_at = now
            return self._cached

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation


def _same_topology(a: ClusterTopology, b: ClusterTopology) -> bool:
    return (
        a.topic_names == b.topic_names
        and a.assignment.shape == b.assignment.shape
        and np.array_equal(a.assignment, b.assignment)
        and np.array_equal(a.broker_state, b.broker_state)
    )


# -- capacity resolution -------------------------------------------------------

DEFAULT_CAPACITY_BROKER_ID = -1


class BrokerCapacityConfigResolver:
    """SPI: external broker id -> f32[4] capacity vector
    (units: CPU in %, NW in KB/s, DISK in MB — same as capacity.json)."""

    def capacity_for_broker(self, broker_id: int) -> np.ndarray:
        raise NotImplementedError


class BrokerCapacityConfigFileResolver(BrokerCapacityConfigResolver):
    """Reads the reference's capacity.json format
    (cc/config/BrokerCapacityConfigFileResolver.java:69, config/capacity.json):
    a list of {brokerId, capacity: {DISK, CPU, NW_IN, NW_OUT}} entries with
    brokerId -1 as the default.

    Both disk variants are supported: the flat form (`"DISK": "100000"`) and
    the JBOD form (`"DISK": {"/logdir1": "250000", "/logdir2": "250000"}` —
    capacity.JBOD.json), where the broker's DISK capacity is the sum of its
    log dirs; the per-logdir map is kept on `logdirs_for_broker` for
    disk-level reporting."""

    def __init__(self, path: str):
        with open(path) as f:
            doc = json.load(f)
        self._by_broker: Dict[int, np.ndarray] = {}
        self._logdirs: Dict[int, Dict[str, float]] = {}
        for entry in doc["brokerCapacities"]:
            broker_id = int(entry["brokerId"])
            cap = np.zeros(NUM_RESOURCES, dtype=np.float32)
            for name, value in entry["capacity"].items():
                if isinstance(value, dict):  # JBOD per-logdir disks
                    if Resource[name] != Resource.DISK:
                        raise ValueError(
                            f"per-logdir capacities only apply to DISK, got {name}"
                        )
                    dirs = {d: float(v) for d, v in value.items()}
                    self._logdirs[broker_id] = dirs
                    cap[Resource.DISK] = sum(dirs.values())
                else:
                    cap[Resource[name]] = float(value)
            self._by_broker[broker_id] = cap
        if DEFAULT_CAPACITY_BROKER_ID not in self._by_broker:
            raise ValueError("capacity config must define the default (brokerId -1)")

    def capacity_for_broker(self, broker_id: int) -> np.ndarray:
        cap = self._by_broker.get(int(broker_id))
        return cap.copy() if cap is not None else self._by_broker[DEFAULT_CAPACITY_BROKER_ID].copy()

    def logdirs_for_broker(self, broker_id: int) -> Dict[str, float]:
        """Per-logdir DISK capacities (JBOD variant); {} for flat entries.
        Brokers without an explicit entry inherit the default's dirs."""
        bid = int(broker_id)
        if bid in self._by_broker:
            return dict(self._logdirs.get(bid, {}))
        return dict(self._logdirs.get(DEFAULT_CAPACITY_BROKER_ID, {}))


class StaticCapacityResolver(BrokerCapacityConfigResolver):
    """Uniform capacity for simulations/tests."""

    def __init__(self, cpu=100.0, nw_in=1e5, nw_out=1e5, disk=1e6):
        self._cap = np.zeros(NUM_RESOURCES, dtype=np.float32)
        self._cap[Resource.CPU] = cpu
        self._cap[Resource.NW_IN] = nw_in
        self._cap[Resource.NW_OUT] = nw_out
        self._cap[Resource.DISK] = disk

    def capacity_for_broker(self, broker_id: int) -> np.ndarray:
        return self._cap.copy()
