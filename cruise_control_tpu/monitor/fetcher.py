"""N-way parallel metric fetching with topic-sticky partition assignment.

The redesign of MetricFetcherManager (cc/monitor/sampling/MetricFetcherManager
.java:35, fetchPartitionMetricSamples :175) and
DefaultMetricSamplerPartitionAssignor (cc/monitor/sampling/
DefaultMetricSamplerPartitionAssignor.java): the cluster's partitions are
split across N fetcher workers — every partition of a topic stays on one
fetcher so per-topic derivations see complete data — and a sampling round
runs the workers concurrently under one deadline. A slow or failing fetcher
loses only its shard (counted in the per-fetcher failure meters), never the
round.

`MetricFetcherManager.get_samples` has the `MetricSampler` signature on
purpose: the LoadMonitor takes the manager wherever a single sampler fits,
so single-threaded setups keep the plain sampler and large clusters drop in
the manager without the monitor changing.
"""

from __future__ import annotations

import concurrent.futures
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from cruise_control_tpu.monitor.metadata import ClusterTopology
from cruise_control_tpu.monitor.sampler import MetricSampler, Samples


class MetricSamplerPartitionAssignor:
    """SPI: split partition indices across fetchers
    (cc/monitor/sampling/MetricSamplerPartitionAssignor.java)."""

    def assign(self, topology: ClusterTopology, num_fetchers: int) -> List[np.ndarray]:
        raise NotImplementedError


class DefaultMetricSamplerPartitionAssignor(MetricSamplerPartitionAssignor):
    """Topic-sticky greedy packing: topics (largest first) go to the fetcher
    with the fewest assigned partitions, so all partitions of one topic land
    on one fetcher (the reference's invariant) and shard sizes stay balanced.
    """

    def assign(self, topology: ClusterTopology, num_fetchers: int) -> List[np.ndarray]:
        topic_id = np.asarray(topology.topic_id)
        num_topics = int(topic_id.max()) + 1 if topic_id.size else 0
        counts = np.bincount(topic_id, minlength=num_topics)
        order = np.argsort(-counts, kind="stable")  # largest topics first
        loads = np.zeros(num_fetchers, dtype=np.int64)
        topic_owner = np.zeros(num_topics, dtype=np.int64)
        for t in order:
            f = int(np.argmin(loads))
            topic_owner[t] = f
            loads[f] += counts[t]
        owner_of_partition = topic_owner[topic_id]
        return [
            np.nonzero(owner_of_partition == f)[0].astype(np.int32)
            for f in range(num_fetchers)
        ]


class MetricFetcherManager:
    """Runs one sampler per fetcher thread over its assigned shard.

    Sensors mirror the reference's fetcher timers/meters
    (MetricFetcherManager's `partition-samples-fetcher-timer`,
    `*-fetcher-failure-rate`; docs/wiki "Sensors.md").
    """

    def __init__(
        self,
        samplers: Sequence[MetricSampler],
        assignor: Optional[MetricSamplerPartitionAssignor] = None,
        round_timeout_s: float = 30.0,
        clock=time.monotonic,
    ):
        if not samplers:
            raise ValueError("need at least one sampler")
        self._samplers = list(samplers)
        self._assignor = assignor or DefaultMetricSamplerPartitionAssignor()
        self._timeout = round_timeout_s
        self._clock = clock
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=len(self._samplers), thread_name_prefix="metric-fetcher"
        )
        self._lock = threading.Lock()
        n = len(self._samplers)
        self.sensors: Dict[str, object] = {
            "fetch_rounds": 0,
            "fetcher_time_s": [0.0] * n,
            "fetcher_rounds": [0] * n,
            "fetcher_failures": [0] * n,
            "fetcher_timeouts": [0] * n,
            "fetcher_skipped_busy": [0] * n,
        }
        #: round N's future per fetcher; a fetcher whose previous call is
        #: still running is skipped next round — two concurrent get_samples
        #: calls on one sampler would race its internal state
        self._inflight: List[Optional[concurrent.futures.Future]] = [None] * n

    @property
    def num_fetchers(self) -> int:
        return len(self._samplers)

    def get_samples(self, topology: ClusterTopology, start_ms: int, end_ms: int,
                    partitions=None) -> Samples:
        """One sampling round (fetchPartitionMetricSamples :175): fan out the
        shards, merge whatever returns before the deadline.

        A fetcher whose previous round is still running (it timed out — the
        thread cannot be killed) is skipped so one sampler never runs two
        concurrent calls; its shard is lost for this round and counted in
        `fetcher_skipped_busy`. `partitions` narrows the round to a subset
        (the manager itself satisfies the MetricSampler SPI)."""
        assignment = self._assignor.assign(topology, len(self._samplers))
        if partitions is not None:
            wanted = np.asarray(partitions)
            assignment = [
                shard[np.isin(shard, wanted)] for shard in assignment
            ]
        deadline = self._clock() + self._timeout
        futures = {}
        for i, (sampler, shard) in enumerate(zip(self._samplers, assignment)):
            prev = self._inflight[i]
            if prev is not None and not prev.done():
                with self._lock:
                    self.sensors["fetcher_skipped_busy"][i] += 1
                continue
            futures[i] = self._pool.submit(
                self._fetch_one, i, sampler, topology, shard, start_ms, end_ms
            )
            self._inflight[i] = futures[i]
        part, brok = [], []
        for i, fut in futures.items():
            remaining = max(0.0, deadline - self._clock())
            try:
                samples = fut.result(timeout=remaining)
            except concurrent.futures.TimeoutError:
                with self._lock:
                    self.sensors["fetcher_timeouts"][i] += 1
                continue
            except Exception:
                with self._lock:
                    self.sensors["fetcher_failures"][i] += 1
                continue
            part.extend(samples.partition_samples)
            brok.extend(samples.broker_samples)
        with self._lock:
            self.sensors["fetch_rounds"] += 1
        return Samples(part, brok)

    def _fetch_one(self, i, sampler, topology, shard, start_ms, end_ms) -> Samples:
        t0 = self._clock()
        try:
            return sampler.get_samples(topology, start_ms, end_ms, partitions=shard)
        finally:
            with self._lock:
                self.sensors["fetcher_time_s"][i] += self._clock() - t0
                self.sensors["fetcher_rounds"][i] += 1

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        for s in self._samplers:
            s.close()
