"""Metric definitions: raw types -> aggregation strategy -> Resource.

The analog of KafkaMetricDef (cc/monitor/metricdefinition/KafkaMetricDef.java:41-51)
and the core MetricDef/MetricInfo registry (core/metricdef/): each defined
metric has a dense integer id (its array column), a value-computing strategy
(AVG / MAX / LATEST, core/metricdef/ValueComputingStrategy.java:10), and an
optional Resource it contributes to.

COMMON defs exist for both partitions and brokers (the partition sample
columns); BROKER_ONLY defs extend the broker sample with queue/latency/flush
telemetry used by the metric-anomaly detector.
"""

from __future__ import annotations

import enum
from typing import Dict, List, Optional

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.reporter.metrics import RawMetricType


class AggregationFunction(enum.IntEnum):
    AVG = 0
    MAX = 1
    LATEST = 2


class DefScope(enum.IntEnum):
    COMMON = 0
    BROKER_ONLY = 1


class KafkaMetricDef(enum.IntEnum):
    """Dense metric ids; COMMON block first so partition samples are a prefix."""

    CPU_USAGE = 0
    DISK_USAGE = 1
    LEADER_BYTES_IN = 2
    LEADER_BYTES_OUT = 3
    PRODUCE_RATE = 4
    FETCH_RATE = 5
    MESSAGE_IN_RATE = 6
    REPLICATION_BYTES_IN_RATE = 7
    REPLICATION_BYTES_OUT_RATE = 8
    # broker-only telemetry
    BROKER_PRODUCE_REQUEST_RATE = 9
    BROKER_CONSUMER_FETCH_REQUEST_RATE = 10
    BROKER_FOLLOWER_FETCH_REQUEST_RATE = 11
    BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT = 12
    BROKER_REQUEST_QUEUE_SIZE = 13
    BROKER_RESPONSE_QUEUE_SIZE = 14
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX = 15
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN = 16
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = 17
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = 18
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = 19
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = 20
    BROKER_PRODUCE_TOTAL_TIME_MS_MAX = 21
    BROKER_PRODUCE_TOTAL_TIME_MS_MEAN = 22
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MAX = 23
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MEAN = 24
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MAX = 25
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MEAN = 26
    BROKER_PRODUCE_LOCAL_TIME_MS_MAX = 27
    BROKER_PRODUCE_LOCAL_TIME_MS_MEAN = 28
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MAX = 29
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN = 30
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MAX = 31
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN = 32
    BROKER_LOG_FLUSH_RATE = 33
    BROKER_LOG_FLUSH_TIME_MS_MAX = 34
    BROKER_LOG_FLUSH_TIME_MS_MEAN = 35
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_50TH = 36
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_999TH = 37
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_50TH = 38
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_999TH = 39
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_50TH = 40
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_999TH = 41
    BROKER_PRODUCE_TOTAL_TIME_MS_50TH = 42
    BROKER_PRODUCE_TOTAL_TIME_MS_999TH = 43
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_50TH = 44
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_999TH = 45
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_50TH = 46
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_999TH = 47
    BROKER_PRODUCE_LOCAL_TIME_MS_50TH = 48
    BROKER_PRODUCE_LOCAL_TIME_MS_999TH = 49
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_50TH = 50
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_999TH = 51
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_50TH = 52
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_999TH = 53
    BROKER_LOG_FLUSH_TIME_MS_50TH = 54
    BROKER_LOG_FLUSH_TIME_MS_999TH = 55


NUM_COMMON_METRICS = 9  # the COMMON block above
NUM_BROKER_METRICS = len(KafkaMetricDef)

#: CPU_USAGE aggregates as AVG; DISK_USAGE as LATEST (a gauge, the reference
#: keeps the most recent size); everything else rate-like is AVG.
AGGREGATION_OF: Dict[KafkaMetricDef, AggregationFunction] = {
    d: (AggregationFunction.LATEST if d == KafkaMetricDef.DISK_USAGE else AggregationFunction.AVG)
    for d in KafkaMetricDef
}

#: Resource each def contributes to (None for telemetry-only defs), matching
#: KafkaMetricDef's resource column.
RESOURCE_OF: Dict[KafkaMetricDef, Optional[Resource]] = {
    KafkaMetricDef.CPU_USAGE: Resource.CPU,
    KafkaMetricDef.DISK_USAGE: Resource.DISK,
    KafkaMetricDef.LEADER_BYTES_IN: Resource.NW_IN,
    KafkaMetricDef.LEADER_BYTES_OUT: Resource.NW_OUT,
    KafkaMetricDef.REPLICATION_BYTES_IN_RATE: Resource.NW_IN,
    KafkaMetricDef.REPLICATION_BYTES_OUT_RATE: Resource.NW_OUT,
}

#: RawMetricType -> KafkaMetricDef, matching KafkaMetricDef.TYPE_TO_DEF (:125).
TYPE_TO_DEF: Dict[RawMetricType, KafkaMetricDef] = {
    # topic raw metrics -> common defs
    RawMetricType.TOPIC_BYTES_IN: KafkaMetricDef.LEADER_BYTES_IN,
    RawMetricType.TOPIC_BYTES_OUT: KafkaMetricDef.LEADER_BYTES_OUT,
    RawMetricType.TOPIC_REPLICATION_BYTES_IN: KafkaMetricDef.REPLICATION_BYTES_IN_RATE,
    RawMetricType.TOPIC_REPLICATION_BYTES_OUT: KafkaMetricDef.REPLICATION_BYTES_OUT_RATE,
    RawMetricType.TOPIC_PRODUCE_REQUEST_RATE: KafkaMetricDef.PRODUCE_RATE,
    RawMetricType.TOPIC_FETCH_REQUEST_RATE: KafkaMetricDef.FETCH_RATE,
    RawMetricType.TOPIC_MESSAGES_IN_PER_SEC: KafkaMetricDef.MESSAGE_IN_RATE,
    # partition raw metrics
    RawMetricType.PARTITION_SIZE: KafkaMetricDef.DISK_USAGE,
    # broker raw metrics
    RawMetricType.BROKER_CPU_UTIL: KafkaMetricDef.CPU_USAGE,
    RawMetricType.ALL_TOPIC_BYTES_IN: KafkaMetricDef.LEADER_BYTES_IN,
    RawMetricType.ALL_TOPIC_BYTES_OUT: KafkaMetricDef.LEADER_BYTES_OUT,
    RawMetricType.ALL_TOPIC_REPLICATION_BYTES_IN: KafkaMetricDef.REPLICATION_BYTES_IN_RATE,
    RawMetricType.ALL_TOPIC_REPLICATION_BYTES_OUT: KafkaMetricDef.REPLICATION_BYTES_OUT_RATE,
    RawMetricType.ALL_TOPIC_PRODUCE_REQUEST_RATE: KafkaMetricDef.PRODUCE_RATE,
    RawMetricType.ALL_TOPIC_FETCH_REQUEST_RATE: KafkaMetricDef.FETCH_RATE,
    RawMetricType.ALL_TOPIC_MESSAGES_IN_PER_SEC: KafkaMetricDef.MESSAGE_IN_RATE,
    RawMetricType.BROKER_PRODUCE_REQUEST_RATE: KafkaMetricDef.BROKER_PRODUCE_REQUEST_RATE,
    RawMetricType.BROKER_CONSUMER_FETCH_REQUEST_RATE: KafkaMetricDef.BROKER_CONSUMER_FETCH_REQUEST_RATE,
    RawMetricType.BROKER_FOLLOWER_FETCH_REQUEST_RATE: KafkaMetricDef.BROKER_FOLLOWER_FETCH_REQUEST_RATE,
    RawMetricType.BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT: KafkaMetricDef.BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT,
    RawMetricType.BROKER_REQUEST_QUEUE_SIZE: KafkaMetricDef.BROKER_REQUEST_QUEUE_SIZE,
    RawMetricType.BROKER_RESPONSE_QUEUE_SIZE: KafkaMetricDef.BROKER_RESPONSE_QUEUE_SIZE,
}

# remaining broker raw types map 1:1 by name
for _t in RawMetricType:
    if _t not in TYPE_TO_DEF and _t.name.startswith("BROKER_"):
        try:
            TYPE_TO_DEF[_t] = KafkaMetricDef[_t.name]
        except KeyError:
            pass

COMMON_METRIC_DEFS: List[KafkaMetricDef] = [d for d in KafkaMetricDef if d < NUM_COMMON_METRICS]
