"""Raw metric -> sample derivation.

Analog of CruiseControlMetricsProcessor (cc/monitor/sampling/
CruiseControlMetricsProcessor.java:38): groups one reporting interval's raw
metrics by broker, derives per-partition samples from topic-level IO (split
evenly across the topic's leader partitions on that broker,
buildPartitionMetricSample :220-267) and attributes per-partition CPU from the
broker's measured CPU and byte rates (ModelUtils.estimateLeaderCpuUtil), with
the reference's skip rules when inputs are missing. Vectorized over the whole
batch with numpy grouping instead of per-partition object walks.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from cruise_control_tpu.models.model_utils import estimate_leader_cpu_util
from cruise_control_tpu.monitor.metadata import ClusterTopology
from cruise_control_tpu.monitor.metricdef import (
    NUM_BROKER_METRICS,
    NUM_COMMON_METRICS,
    TYPE_TO_DEF,
    KafkaMetricDef,
)
from cruise_control_tpu.monitor.samples import (
    BrokerMetricSample,
    PartitionMetricSample,
    SampleBatch,
)
from cruise_control_tpu.reporter.metrics import CruiseControlMetric, MetricScope, RawMetricType

BYTES_IN_KB = 1024.0
BYTES_IN_MB = 1024.0 * 1024.0

_BYTE_RATE_TYPES = {
    RawMetricType.ALL_TOPIC_BYTES_IN,
    RawMetricType.ALL_TOPIC_BYTES_OUT,
    RawMetricType.ALL_TOPIC_REPLICATION_BYTES_IN,
    RawMetricType.ALL_TOPIC_REPLICATION_BYTES_OUT,
    RawMetricType.TOPIC_BYTES_IN,
    RawMetricType.TOPIC_BYTES_OUT,
    RawMetricType.TOPIC_REPLICATION_BYTES_IN,
    RawMetricType.TOPIC_REPLICATION_BYTES_OUT,
}


def _convert_unit(metric_type: RawMetricType, value: float) -> float:
    """CruiseControlMetricsProcessor.convertUnit: byte rates -> KB/s,
    partition size -> MB."""
    if metric_type in _BYTE_RATE_TYPES:
        return value / BYTES_IN_KB
    if metric_type == RawMetricType.PARTITION_SIZE:
        return value / BYTES_IN_MB
    return value


@dataclasses.dataclass
class ProcessorResult:
    partition_samples: "SampleBatch"  # array-native; iterable as records
    broker_samples: List[BrokerMetricSample]
    skipped_partitions: int
    skipped_brokers: int


class MetricsProcessor:
    """One reporting interval in, derived samples out."""

    def __init__(self):
        # (topology generation, id) -> sorted partition key table so repeated
        # rounds against an unchanged topology skip the O(P) rebuild
        self._key_cache: Optional[tuple] = None

    def process(
        self,
        metrics: Iterable[CruiseControlMetric],
        topology: ClusterTopology,
    ) -> ProcessorResult:
        broker_index = topology.broker_index_of()
        topic_index = {name: i for i, name in enumerate(topology.topic_names)}
        b, t = topology.num_brokers, len(topology.topic_names)

        # -- bucket the batch --------------------------------------------------
        broker_vals: Dict[int, Dict[RawMetricType, float]] = {}
        broker_time: Dict[int, int] = {}
        topic_vals = np.zeros((b, t, 7), dtype=np.float64)  # 7 topic metric types
        topic_seen = np.zeros((b, t), dtype=bool)
        size_seen = np.zeros((b, t), dtype=bool)

        topic_slot = {
            RawMetricType.TOPIC_BYTES_IN: 0,
            RawMetricType.TOPIC_BYTES_OUT: 1,
            RawMetricType.TOPIC_REPLICATION_BYTES_IN: 2,
            RawMetricType.TOPIC_REPLICATION_BYTES_OUT: 3,
            RawMetricType.TOPIC_PRODUCE_REQUEST_RATE: 4,
            RawMetricType.TOPIC_FETCH_REQUEST_RATE: 5,
            RawMetricType.TOPIC_MESSAGES_IN_PER_SEC: 6,
        }
        size_b: List[int] = []
        size_t: List[int] = []
        size_p: List[int] = []
        size_v: List[float] = []

        for m in metrics:
            bi = broker_index.get(m.broker_id)
            if bi is None:
                continue
            value = _convert_unit(m.metric_type, m.value)
            scope = m.metric_type.scope
            if scope == MetricScope.BROKER:
                broker_vals.setdefault(bi, {})[m.metric_type] = value
                broker_time[bi] = max(broker_time.get(bi, 0), m.time_ms)
            elif scope == MetricScope.TOPIC:
                ti = topic_index.get(m.topic)
                if ti is not None:
                    topic_vals[bi, ti, topic_slot[m.metric_type]] = value
                    topic_seen[bi, ti] = True
            else:  # PARTITION (only PARTITION_SIZE exists)
                ti = topic_index.get(m.topic)
                if ti is not None:
                    size_b.append(bi)
                    size_t.append(ti)
                    size_p.append(m.partition)
                    size_v.append(value)
                    size_seen[bi, ti] = True

        # topics with sizes reported but no IO metrics had zero traffic
        # (BrokerLoad._dotHandledTopicsWithPartitionSizeReported comment)
        topic_ok = topic_seen | size_seen

        # -- broker samples ----------------------------------------------------
        broker_samples: List[BrokerMetricSample] = []
        skipped_brokers = 0
        broker_ok = np.zeros(b, dtype=bool)
        broker_cpu = np.zeros(b)
        broker_l_in = np.zeros(b)
        broker_total_out = np.zeros(b)
        broker_f_in = np.zeros(b)
        for bi, vals in broker_vals.items():
            if RawMetricType.BROKER_CPU_UTIL not in vals:
                skipped_brokers += 1
                continue
            vec = np.zeros(NUM_BROKER_METRICS, dtype=np.float32)
            for raw_type, value in vals.items():
                d = TYPE_TO_DEF.get(raw_type)
                if d is not None:
                    vec[d] = value
            broker_samples.append(BrokerMetricSample(bi, broker_time.get(bi, 0), vec))
            broker_ok[bi] = True
            broker_cpu[bi] = vals[RawMetricType.BROKER_CPU_UTIL]
            broker_l_in[bi] = vals.get(RawMetricType.ALL_TOPIC_BYTES_IN, 0.0)
            broker_total_out[bi] = vals.get(RawMetricType.ALL_TOPIC_BYTES_OUT, 0.0) + vals.get(
                RawMetricType.ALL_TOPIC_REPLICATION_BYTES_OUT, 0.0
            )
            broker_f_in[bi] = vals.get(RawMetricType.ALL_TOPIC_REPLICATION_BYTES_IN, 0.0)

        # -- partition samples (vectorized over P) -----------------------------
        leaders = np.asarray(topology.assignment[:, 0])
        topics = np.asarray(topology.topic_id)
        p = topology.num_partitions
        valid = (leaders >= 0) & broker_ok[np.clip(leaders, 0, b - 1)]
        lt_ok = topic_ok[np.clip(leaders, 0, b - 1), topics]
        valid &= lt_ok

        sizes = np.full(p, np.nan)
        if size_b:
            # map (broker, topic, partition-index) keys onto dense partition
            # ids via a sorted int64 key table, cached per topology generation
            pmax = int(np.asarray(topology.partition_index).max()) + 1
            cache_tag = (topology.generation, p, b, t, pmax)
            if self._key_cache is None or self._key_cache[0] != cache_tag:
                table = (
                    (leaders.astype(np.int64) * t + topics) * pmax
                    + np.asarray(topology.partition_index, dtype=np.int64)
                )
                order = np.argsort(table, kind="stable")
                self._key_cache = (cache_tag, table[order], order)
            _, sorted_keys, order = self._key_cache
            query = (
                (np.asarray(size_b, dtype=np.int64) * t + np.asarray(size_t, dtype=np.int64)) * pmax
                + np.asarray(size_p, dtype=np.int64)
            )
            pos = np.searchsorted(sorted_keys, query)
            pos_ok = (pos < p) & (sorted_keys[np.clip(pos, 0, p - 1)] == query)
            pid_hit = order[pos[pos_ok]]
            sizes[pid_hit] = np.asarray(size_v)[pos_ok]
        valid &= ~np.isnan(sizes)

        n_leaders = topology.leader_topic_counts()  # [B, T]
        safe_leaders = np.clip(leaders, 0, b - 1)
        denom = np.maximum(n_leaders[safe_leaders, topics], 1)
        rates = topic_vals[safe_leaders, topics] / denom[:, None]  # [P, 7]

        part_in = rates[:, 0]
        part_out = rates[:, 1]
        part_rep_out = rates[:, 3]
        cpu = estimate_leader_cpu_util(
            broker_cpu[safe_leaders],
            broker_l_in[safe_leaders],
            broker_total_out[safe_leaders],
            broker_f_in[safe_leaders],
            part_in,
            part_out + part_rep_out,
        )
        valid &= ~np.isnan(cpu)

        # assemble the whole [N_valid, M] matrix with column writes — no
        # per-partition Python objects on the hot path
        time_ms = max(broker_time.values(), default=0)
        pids = np.nonzero(valid)[0]
        mat = np.zeros((pids.shape[0], NUM_COMMON_METRICS), dtype=np.float32)
        mat[:, KafkaMetricDef.CPU_USAGE] = cpu[pids]
        mat[:, KafkaMetricDef.DISK_USAGE] = sizes[pids]
        mat[:, KafkaMetricDef.LEADER_BYTES_IN] = rates[pids, 0]
        mat[:, KafkaMetricDef.LEADER_BYTES_OUT] = rates[pids, 1]
        mat[:, KafkaMetricDef.REPLICATION_BYTES_IN_RATE] = rates[pids, 2]
        mat[:, KafkaMetricDef.REPLICATION_BYTES_OUT_RATE] = rates[pids, 3]
        mat[:, KafkaMetricDef.PRODUCE_RATE] = rates[pids, 4]
        mat[:, KafkaMetricDef.FETCH_RATE] = rates[pids, 5]
        mat[:, KafkaMetricDef.MESSAGE_IN_RATE] = rates[pids, 6]
        partition_samples = SampleBatch(
            ids=pids.astype(np.int64),
            times=np.full(pids.shape[0], time_ms, dtype=np.int64),
            metrics=mat,
            kind="partition",
        )

        return ProcessorResult(
            partition_samples=partition_samples,
            broker_samples=broker_samples,
            skipped_partitions=int(p - valid.sum()),
            skipped_brokers=skipped_brokers,
        )
