"""Sample records + binary serde.

Analogs of PartitionMetricSample (cc/monitor/sampling/PartitionMetricSample.java)
and BrokerMetricSample (cc/monitor/sampling/BrokerMetricSample.java): one
timestamped dense metric vector per entity, with a versioned binary wire form
for the sample store."""

from __future__ import annotations

import dataclasses
import struct
from typing import List

import numpy as np

from cruise_control_tpu.monitor.metricdef import NUM_BROKER_METRICS, NUM_COMMON_METRICS

SAMPLE_SERDE_VERSION = 1

# header: version u8, kind u8, entity i64, time i64, metric count u16
_HEADER = struct.Struct(">BBqqH")
_KIND_PARTITION = 0
_KIND_BROKER = 1


@dataclasses.dataclass(frozen=True)
class PartitionMetricSample:
    """Dense COMMON-metric vector for one partition at one time."""

    partition_id: int  # dense partition index
    time_ms: int
    metrics: np.ndarray  # f32[NUM_COMMON_METRICS]

    def __post_init__(self):
        if np.asarray(self.metrics).shape != (NUM_COMMON_METRICS,):
            raise ValueError(f"expected {NUM_COMMON_METRICS} common metrics")


@dataclasses.dataclass(frozen=True)
class BrokerMetricSample:
    """Dense full-metric vector for one broker at one time."""

    broker_id: int
    time_ms: int
    metrics: np.ndarray  # f32[NUM_BROKER_METRICS]

    def __post_init__(self):
        if np.asarray(self.metrics).shape != (NUM_BROKER_METRICS,):
            raise ValueError(f"expected {NUM_BROKER_METRICS} broker metrics")


def serialize_sample(s) -> bytes:
    kind = _KIND_PARTITION if isinstance(s, PartitionMetricSample) else _KIND_BROKER
    entity = s.partition_id if kind == _KIND_PARTITION else s.broker_id
    m = np.asarray(s.metrics, dtype=np.float32)
    return _HEADER.pack(SAMPLE_SERDE_VERSION, kind, entity, s.time_ms, m.shape[0]) + m.tobytes()


def deserialize_sample(data: bytes):
    version, kind, entity, time_ms, n = _HEADER.unpack_from(data, 0)
    if version > SAMPLE_SERDE_VERSION:
        raise ValueError(f"unsupported sample serde version {version}")
    metrics = np.frombuffer(data, dtype=np.float32, count=n, offset=_HEADER.size).copy()
    if kind == _KIND_PARTITION:
        return PartitionMetricSample(entity, time_ms, metrics)
    return BrokerMetricSample(entity, time_ms, metrics)


@dataclasses.dataclass
class SampleBatch:
    """Array-native batch of samples — the hot-path form.

    The processor emits these directly so a 200k-partition sampling round
    never materializes per-sample objects; `__iter__` lazily yields
    PartitionMetricSample/BrokerMetricSample only where an SPI needs records
    (file persistence, tests).
    """

    ids: np.ndarray  # i64[N]
    times: np.ndarray  # i64[N]
    metrics: np.ndarray  # f32[N, M]
    kind: str = "partition"  # "partition" | "broker"

    def __len__(self) -> int:
        return int(self.ids.shape[0])

    def __iter__(self):
        cls = PartitionMetricSample if self.kind == "partition" else BrokerMetricSample
        for i in range(len(self)):
            yield cls(int(self.ids[i]), int(self.times[i]), self.metrics[i])

    @classmethod
    def empty(cls, num_metrics: int, kind: str = "partition") -> "SampleBatch":
        return cls(
            np.zeros(0, np.int64), np.zeros(0, np.int64),
            np.zeros((0, num_metrics), np.float32), kind,
        )

    @classmethod
    def from_samples(cls, samples: List, kind: str = "partition") -> "SampleBatch":
        if not samples:
            m = NUM_COMMON_METRICS if kind == "partition" else NUM_BROKER_METRICS
            return cls.empty(m, kind)
        ids, times, metrics = batch_arrays(samples)
        return cls(ids, times, metrics, kind)


def as_batch(samples, kind: str = "partition") -> SampleBatch:
    """Normalize a list of sample records or a SampleBatch to a SampleBatch."""
    if isinstance(samples, SampleBatch):
        return samples
    return SampleBatch.from_samples(list(samples), kind)


def batch_arrays(samples: List) -> tuple:
    """(entity_ids i64[N], times i64[N], metrics f32[N, M]) for the aggregator."""
    if not samples:
        return (
            np.zeros(0, np.int64),
            np.zeros(0, np.int64),
            np.zeros((0, NUM_COMMON_METRICS), np.float32),
        )
    ids = np.asarray(
        [s.partition_id if isinstance(s, PartitionMetricSample) else s.broker_id for s in samples],
        dtype=np.int64,
    )
    times = np.asarray([s.time_ms for s in samples], dtype=np.int64)
    metrics = np.stack([np.asarray(s.metrics, dtype=np.float32) for s in samples])
    return ids, times, metrics
