"""Model completeness requirements.

Analog of ModelCompletenessRequirements (cc/monitor/ModelCompletenessRequirements.java:33)
with the weaker()/stronger() combinators used when merging per-goal
requirements (MonitorUtils.combineLoadRequirementOptions)."""

from __future__ import annotations

import dataclasses


class ModelCompletenessError(ValueError):
    """The monitor's windows cannot satisfy the requested completeness.

    A ValueError subclass so existing handlers keep working; the REST layer
    maps it to a typed 503 (`errorClass` + `completeness` detail) instead of
    a generic 500 — "not enough data yet" is a retryable service condition,
    not an internal failure. `completeness` carries the observed-vs-required
    numbers for the caller's backoff decision."""

    def __init__(self, message: str, completeness: dict):
        super().__init__(message)
        self.completeness = dict(completeness)


class NotEnoughValidWindowsError(ModelCompletenessError):
    """Fewer valid aggregation windows than min_required_num_windows."""


class NotEnoughValidPartitionsError(ModelCompletenessError):
    """Monitored-partition ratio below min_monitored_partitions_percentage."""


@dataclasses.dataclass(frozen=True)
class ModelCompletenessRequirements:
    min_required_num_windows: int = 1
    min_monitored_partitions_percentage: float = 0.995
    include_all_topics: bool = False

    def weaker(self, other: "ModelCompletenessRequirements") -> "ModelCompletenessRequirements":
        """The less demanding combination (satisfied if either would be)."""
        return ModelCompletenessRequirements(
            min_required_num_windows=min(
                self.min_required_num_windows, other.min_required_num_windows
            ),
            min_monitored_partitions_percentage=min(
                self.min_monitored_partitions_percentage,
                other.min_monitored_partitions_percentage,
            ),
            include_all_topics=self.include_all_topics and other.include_all_topics,
        )

    def stronger(self, other: "ModelCompletenessRequirements") -> "ModelCompletenessRequirements":
        """The more demanding combination (satisfies both)."""
        return ModelCompletenessRequirements(
            min_required_num_windows=max(
                self.min_required_num_windows, other.min_required_num_windows
            ),
            min_monitored_partitions_percentage=max(
                self.min_monitored_partitions_percentage,
                other.min_monitored_partitions_percentage,
            ),
            include_all_topics=self.include_all_topics or other.include_all_topics,
        )
