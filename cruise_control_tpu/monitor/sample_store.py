"""Sample persistence SPI — the checkpoint/resume mechanism.

Analog of SampleStore (cc/monitor/sampling/SampleStore.java:17) and
KafkaSampleStore (cc/monitor/sampling/KafkaSampleStore.java:79): metric
samples are the ONLY durable state; windows are rebuilt by replaying them on
startup (SampleLoadingTask). The default here is an append-only local file
pair; a Kafka/object-store impl plugs in through the same SPI.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Iterable, List, Tuple

from cruise_control_tpu.monitor.samples import (
    BrokerMetricSample,
    PartitionMetricSample,
    deserialize_sample,
    serialize_sample,
)


class SampleStore:
    def store_samples(
        self,
        partition_samples: Iterable[PartitionMetricSample],
        broker_samples: Iterable[BrokerMetricSample],
    ) -> None:
        raise NotImplementedError

    def load_samples(self) -> Tuple[List[PartitionMetricSample], List[BrokerMetricSample]]:
        """Replay everything retained (KafkaSampleStore.loadSamples :332)."""
        raise NotImplementedError

    def configure_retention(self, retention_ms: int) -> None:
        """Hint the aggregation horizon (window_ms * num_windows); stores
        that persist history may drop anything older. The LoadMonitor calls
        this at construction — the analog of KafkaSampleStore configuring
        its sample topics' retention to the horizon
        (cc/monitor/sampling/KafkaSampleStore.java:79)."""

    def close(self) -> None:
        pass


class NoopSampleStore(SampleStore):
    def store_samples(self, partition_samples, broker_samples) -> None:
        pass

    def load_samples(self):
        return [], []


class FileSampleStore(SampleStore):
    """Length-prefixed binary records in time-segmented append files with
    retention.

    KafkaSampleStore leans on topic retention to bound both storage and the
    startup replay (cc/monitor/sampling/KafkaSampleStore.java:79 configures
    the sample topics' retention to the aggregation horizon; loadSamples :332
    then replays whatever the broker kept). The file analog: records land in
    segment files named `<kind>-<segment_start_ms>.bin` (segment id = sample
    time // segment_ms), and segments that end before
    `newest sample time - retention_ms` are deleted on write and skipped —
    then deleted — on load. Replay cost is therefore bounded by
    retention_ms/segment_ms segments regardless of process uptime.

    `retention_ms=None` defers to `configure_retention`, which the
    LoadMonitor calls with its window_ms * num_windows horizon — samples
    older than the aggregation horizon can never contribute to a window, so
    dropping them loses nothing (same argument the reference makes for topic
    retention). An explicit constructor value wins over the monitor's hint.
    Legacy unsegmented `<kind>-samples.bin` files from older processes are
    still read (and counted as one always-retained segment)."""

    SEGMENT_DEFAULT_MS = 3_600_000  # 1h segments unless retention is tighter

    def __init__(self, directory: str, retention_ms: int | None = None,
                 segment_ms: int | None = None):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._retention = retention_ms
        self._retention_pinned = retention_ms is not None
        self._segment_ms_arg = segment_ms
        self._segment_ms = self._derive_segment_ms()
        self._max_time_ms = 0
        self._legacy = {
            "partition": os.path.join(directory, "partition-samples.bin"),
            "broker": os.path.join(directory, "broker-samples.bin"),
        }

    def _derive_segment_ms(self) -> int:
        if self._segment_ms_arg is not None:
            return self._segment_ms_arg
        segment_ms = self.SEGMENT_DEFAULT_MS
        if self._retention is not None:
            # >= 8 segments per horizon so expiry is reasonably granular
            segment_ms = min(segment_ms, max(1, self._retention // 8))
        return segment_ms

    def configure_retention(self, retention_ms: int) -> None:
        """Adopt the monitor's aggregation horizon unless the constructor
        pinned an explicit retention."""
        with self._lock:
            if self._retention_pinned:
                return
            self._retention = int(retention_ms)
            self._segment_ms = self._derive_segment_ms()

    def _segment_path(self, kind: str, time_ms: int) -> str:
        # the width is PERSISTED in the name: expiry must judge a segment by
        # the width it was WRITTEN with, not the current one — reopening a
        # directory after the retention hint (and hence the derived width)
        # shrinks would otherwise treat a wide old segment as expired while
        # it still holds in-retention samples
        start = (time_ms // self._segment_ms) * self._segment_ms
        return os.path.join(self._dir, f"{kind}-{start}w{self._segment_ms}.bin")

    def _segments(self, kind: str) -> List[Tuple[int, int, str]]:
        """[(segment_start_ms, width_ms, path)] for this kind, oldest first.

        Width-less names come from processes predating width persistence;
        their span is bounded conservatively by max(default, current width)
        (the derivation never exceeded the default unless explicitly
        constructed wider), which can only over-retain one segment."""
        out = []
        prefix = f"{kind}-"
        fallback = max(self.SEGMENT_DEFAULT_MS, self._segment_ms)
        for name in os.listdir(self._dir):
            if name.startswith(prefix) and name.endswith(".bin"):
                stem = name[len(prefix):-4]
                if stem.isdigit():
                    out.append((int(stem), fallback, os.path.join(self._dir, name)))
                elif "w" in stem:
                    start, _, width = stem.partition("w")
                    if start.isdigit() and width.isdigit():
                        out.append((int(start), int(width), os.path.join(self._dir, name)))
        return sorted(out)

    def _append(self, kind: str, samples) -> None:
        by_path: dict = {}
        for s in samples:
            payload = serialize_sample(s)
            by_path.setdefault(self._segment_path(kind, s.time_ms), []).append(payload)
            if s.time_ms > self._max_time_ms:
                self._max_time_ms = s.time_ms
        for path, payloads in by_path.items():
            with open(path, "ab") as f:
                for payload in payloads:
                    f.write(len(payload).to_bytes(4, "big") + payload)

    def _cutoff_ms(self) -> int | None:
        if self._retention is None:
            return None
        return self._max_time_ms - self._retention

    def _expire(self, kind: str) -> None:
        cutoff = self._cutoff_ms()
        if cutoff is None:
            return
        for start, width, path in self._segments(kind):
            if start + width <= cutoff:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def store_samples(self, partition_samples, broker_samples) -> None:
        with self._lock:
            self._append("partition", partition_samples)
            self._append("broker", broker_samples)
            self._expire("partition")
            self._expire("broker")

    def _read(self, path: str) -> List:
        out = []
        try:
            with open(path, "rb") as f:
                while True:
                    head = f.read(4)
                    if len(head) < 4:
                        break
                    size = int.from_bytes(head, "big")
                    payload = f.read(size)
                    if len(payload) < size:
                        break  # torn tail from a crash mid-append: stop here
                    try:
                        out.append(deserialize_sample(payload))
                    except (ValueError, struct.error):
                        break  # corrupt tail record; keep what was readable
        except FileNotFoundError:
            pass
        return out

    def _load_kind(self, kind: str) -> List:
        out = self._read(self._legacy[kind])
        segments = self._segments(kind)
        if out or segments:
            # estimate the newest sample time from segment STARTS — an
            # underestimate. Using segment ends would inflate the cutoff by
            # up to one segment and delete still-in-retention history at
            # restart; an underestimate only ever keeps one extra segment.
            newest = max(
                [s.time_ms for s in out] + [start for start, _, _ in segments]
                or [0]
            )
            if newest > self._max_time_ms:
                self._max_time_ms = newest
        cutoff = self._cutoff_ms()
        for start, width, path in segments:
            if cutoff is not None and start + width <= cutoff:
                try:
                    os.unlink(path)  # truncate on load: bounded restart replay
                except OSError:
                    pass
                continue
            out.extend(self._read(path))
        return out

    def load_samples(self):
        with self._lock:
            return self._load_kind("partition"), self._load_kind("broker")
