"""Sample persistence SPI — the checkpoint/resume mechanism.

Analog of SampleStore (cc/monitor/sampling/SampleStore.java:17) and
KafkaSampleStore (cc/monitor/sampling/KafkaSampleStore.java:79): metric
samples are the ONLY durable state; windows are rebuilt by replaying them on
startup (SampleLoadingTask). The default here is an append-only local file
pair; a Kafka/object-store impl plugs in through the same SPI.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Iterable, List, Tuple

from cruise_control_tpu.monitor.samples import (
    BrokerMetricSample,
    PartitionMetricSample,
    deserialize_sample,
    serialize_sample,
)


class SampleStore:
    def store_samples(
        self,
        partition_samples: Iterable[PartitionMetricSample],
        broker_samples: Iterable[BrokerMetricSample],
    ) -> None:
        raise NotImplementedError

    def load_samples(self) -> Tuple[List[PartitionMetricSample], List[BrokerMetricSample]]:
        """Replay everything retained (KafkaSampleStore.loadSamples :332)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class NoopSampleStore(SampleStore):
    def store_samples(self, partition_samples, broker_samples) -> None:
        pass

    def load_samples(self):
        return [], []


class FileSampleStore(SampleStore):
    """Length-prefixed binary records in two append-only files."""

    def __init__(self, directory: str):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._paths = {
            "partition": os.path.join(directory, "partition-samples.bin"),
            "broker": os.path.join(directory, "broker-samples.bin"),
        }

    def _append(self, path: str, samples) -> None:
        with open(path, "ab") as f:
            for s in samples:
                payload = serialize_sample(s)
                f.write(len(payload).to_bytes(4, "big") + payload)

    def store_samples(self, partition_samples, broker_samples) -> None:
        with self._lock:
            self._append(self._paths["partition"], partition_samples)
            self._append(self._paths["broker"], broker_samples)

    def _read(self, path: str) -> List:
        out = []
        try:
            with open(path, "rb") as f:
                while True:
                    head = f.read(4)
                    if len(head) < 4:
                        break
                    size = int.from_bytes(head, "big")
                    payload = f.read(size)
                    if len(payload) < size:
                        break  # torn tail from a crash mid-append: stop here
                    try:
                        out.append(deserialize_sample(payload))
                    except (ValueError, struct.error):
                        break  # corrupt tail record; keep what was readable
        except FileNotFoundError:
            pass
        return out

    def load_samples(self):
        with self._lock:
            return self._read(self._paths["partition"]), self._read(self._paths["broker"])
