"""Metric sampler SPI + default transport-backed implementation.

Analogs of MetricSampler (cc/monitor/sampling/MetricSampler.java:24, the
pluggable sample source) and CruiseControlMetricsReporterSampler
(cc/monitor/sampling/CruiseControlMetricsReporterSampler.java:37, which polls
the metrics topic and runs the processor)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from cruise_control_tpu.monitor.metadata import ClusterTopology
from cruise_control_tpu.monitor.processor import MetricsProcessor
from cruise_control_tpu.monitor.samples import BrokerMetricSample, PartitionMetricSample
from cruise_control_tpu.reporter.transport import MetricsTransport


@dataclasses.dataclass
class Samples:
    """MetricSampler.Samples analog."""

    partition_samples: List[PartitionMetricSample]
    broker_samples: List[BrokerMetricSample]


class MetricSampler:
    """SPI: fetch one round of samples for (a shard of) the cluster.

    `partitions` (optional i32[...] dense partition indices) is the shard
    assigned by the fetcher manager's partition assignor; None means the
    whole cluster. Samplers that pull from a self-distributing source (e.g.
    a consumer group over the metrics topic) may ignore it."""

    def get_samples(self, topology: ClusterTopology, start_ms: int, end_ms: int,
                    partitions=None) -> Samples:
        raise NotImplementedError

    def close(self) -> None:
        pass


class NoopSampler(MetricSampler):
    def get_samples(self, topology, start_ms, end_ms, partitions=None) -> Samples:
        return Samples([], [])


class TransportMetricSampler(MetricSampler):
    """Polls raw metrics off a MetricsTransport and derives samples — the
    default sampler, mirroring CruiseControlMetricsReporterSampler's
    consumer-poll + processor flow."""

    def __init__(self, transport: MetricsTransport, processor: Optional[MetricsProcessor] = None,
                 max_records_per_round: int = 5_000_000):
        self._transport = transport
        self._processor = processor or MetricsProcessor()
        self._max_records = max_records_per_round
        #: records polled off the at-most-once transport whose timestamp is
        #: ahead of the round's range; carried to the next round instead of
        #: being lost (publish can race the round boundary)
        self._carry: list = []

    def get_samples(self, topology: ClusterTopology, start_ms: int, end_ms: int,
                    partitions=None) -> Samples:
        # `partitions` is ignored: transport consumers self-distribute records
        # (the consumer-group semantics of the reference's default sampler),
        # so post-poll filtering would drop other shards' records for good.
        raw = self._carry + self._transport.poll(self._max_records)
        in_range = [m for m in raw if start_ms <= m.time_ms < end_ms]
        self._carry = [m for m in raw if m.time_ms >= end_ms]
        if not in_range:
            return Samples([], [])
        result = self._processor.process(in_range, topology)
        return Samples(result.partition_samples, result.broker_samples)
