"""Windowed metric aggregation as dense ring-buffer arrays.

The TPU-native re-expression of the core aggregation engine:
`MetricSampleAggregator` (core/monitor/sampling/aggregator/
MetricSampleAggregator.java:84 — samples to fixed-width windows per entity,
completeness accounting, generation counters) and `RawMetricValues`
(.../RawMetricValues.java:29 — per-entity ring buffer with extrapolation).

Instead of one ring-buffer object per entity, the whole aggregator is three
arrays over (entity, window, metric):

  sum    f32[E, W, M]   running sum per window (AVG strategy)
  peak   f32[E, W, M]   running max per window (MAX strategy)
  latest f32[E, W, M]   last-by-time value per window (LATEST strategy)
  count  i32[E, W]      samples per window per entity

`add_samples` is one vectorized scatter; `aggregate` applies the reference's
exact extrapolation ladder (RawMetricValues.aggregate:263-345) as masked
array selects:

  count >= min_samples          -> value, NONE
  count >= max(1, min//2)       -> value, AVG_AVAILABLE
  interior & both neighbors full-> 3-window average, AVG_ADJACENT
  count > 0                     -> value, FORCED_INSUFFICIENT
  else                          -> 0, NO_VALID_EXTRAPOLATION

Window indexing matches the reference: window index = time_ms // window_ms;
the aggregator keeps the newest `num_windows` *completed* windows plus the
in-flight current window; adding a sample to a completed window bumps the
generation (cache invalidation for the proposal precompute loop).
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cruise_control_tpu.monitor.metricdef import AggregationFunction


class Extrapolation(enum.IntEnum):
    """Same ladder as core/monitor/sampling/aggregator/Extrapolation.java:32."""

    NONE = 0
    AVG_AVAILABLE = 1
    AVG_ADJACENT = 2
    FORCED_INSUFFICIENT = 3
    NO_VALID_EXTRAPOLATION = 4


class Granularity(enum.IntEnum):
    """AggregationOptions.Granularity: how strict completeness is."""

    ENTITY = 0  # an entity must be valid in EVERY window
    ENTITY_GROUP = 1  # an invalid entity invalidates its whole group


@dataclasses.dataclass(frozen=True)
class AggregationOptions:
    """Analog of core AggregationOptions: completeness requirements."""

    min_valid_entity_ratio: float = 0.5
    min_valid_entity_group_ratio: float = 0.0
    min_valid_windows: int = 1
    granularity: Granularity = Granularity.ENTITY


@dataclasses.dataclass
class CompletenessSummary:
    """MetricSampleCompleteness analog."""

    valid_entity_ratio: float
    valid_entity_group_ratio: float
    valid_windows: List[int]
    generation: int


class AggregationResult(dict):
    """aggregate() output bundle (MetricSampleAggregationResult analog)."""

    def __init__(self, values, extrapolations, valid_entities, windows, completeness):
        super().__init__()
        self.values: np.ndarray = values  # f32[E, Wq, M]
        self.extrapolations: np.ndarray = extrapolations  # i8[E, Wq]
        self.valid_entities: np.ndarray = valid_entities  # bool[E]
        self.windows: List[int] = windows
        self.completeness: CompletenessSummary = completeness


class WindowedAggregator:
    """Thread-safe dense aggregator over a fixed entity universe.

    Entities are dense ints [0, E). Callers that track dynamic universes
    (partition churn) map external ids -> dense ids and `resize` on growth.
    """

    def __init__(
        self,
        num_entities: int,
        num_metrics: int,
        aggregation_functions: Sequence[AggregationFunction],
        window_ms: int = 60_000,
        num_windows: int = 5,
        min_samples_per_window: int = 3,
        entity_group: Optional[np.ndarray] = None,
    ):
        if len(aggregation_functions) != num_metrics:
            raise ValueError("need one aggregation function per metric")
        self._window_ms = int(window_ms)
        self._num_windows = int(num_windows)
        self._min_samples = int(min_samples_per_window)
        self._half_min = max(1, self._min_samples // 2)
        self._agg_fn = np.asarray(aggregation_functions, dtype=np.int8)
        self._lock = threading.RLock()
        self._generation = 0
        # ring storage: slot w stores window index (oldest + w); rebased on roll
        self._oldest_window: Optional[int] = None  # oldest *retained* window index
        self._first_window: Optional[int] = None  # first window ever observed
        e, w, m = num_entities, self._num_windows + 1, num_metrics
        self._sum = np.zeros((e, w, m), dtype=np.float64)
        self._peak = np.zeros((e, w, m), dtype=np.float32)
        self._latest = np.zeros((e, w, m), dtype=np.float32)
        self._latest_time = np.full((e, w), -1, dtype=np.int64)
        self._count = np.zeros((e, w), dtype=np.int32)
        self._group = (
            np.asarray(entity_group, dtype=np.int64)
            if entity_group is not None
            else np.zeros(e, dtype=np.int64)
        )

    # -- properties ------------------------------------------------------------

    @property
    def num_entities(self) -> int:
        return self._sum.shape[0]

    @property
    def num_metrics(self) -> int:
        return self._sum.shape[2]

    @property
    def window_ms(self) -> int:
        return self._window_ms

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def current_window(self) -> Optional[int]:
        with self._lock:
            if self._oldest_window is None:
                return None
            return self._oldest_window + self._num_windows

    def completed_windows(self) -> List[int]:
        """Newest-first completed window indices (allWindows analog).

        Windows predating the first observed sample are not reported — early
        in an aggregator's life the completed-window set grows from zero, as
        in the reference, rather than including phantom pre-history."""
        with self._lock:
            if self._oldest_window is None:
                return []
            lo = max(self._oldest_window, self._first_window)
            return list(range(self._oldest_window + self._num_windows - 1, lo - 1, -1))

    # -- ingestion -------------------------------------------------------------

    def resize(self, num_entities: int, entity_group: Optional[np.ndarray] = None) -> None:
        """Grow the entity universe (cluster expansion); keeps history."""
        with self._lock:
            e_old = self.num_entities
            if num_entities < e_old:
                raise ValueError("aggregator cannot shrink")
            if num_entities == e_old:
                return
            pad = num_entities - e_old
            w, m = self._sum.shape[1], self._sum.shape[2]
            self._sum = np.concatenate([self._sum, np.zeros((pad, w, m))], axis=0)
            self._peak = np.concatenate([self._peak, np.zeros((pad, w, m), np.float32)], axis=0)
            self._latest = np.concatenate([self._latest, np.zeros((pad, w, m), np.float32)], axis=0)
            self._latest_time = np.concatenate(
                [self._latest_time, np.full((pad, w), -1, np.int64)], axis=0
            )
            self._count = np.concatenate([self._count, np.zeros((pad, w), np.int32)], axis=0)
            if entity_group is not None:
                self._group = np.asarray(entity_group, dtype=np.int64)
            else:
                self._group = np.concatenate([self._group, np.zeros(pad, np.int64)])
            self._generation += 1

    def _roll_to(self, window_index: int) -> None:
        """Advance the ring so `window_index` is the current (in-flight) window."""
        cur = self._oldest_window
        if cur is None:
            self._oldest_window = window_index - self._num_windows
            self._first_window = window_index
            return
        shift = window_index - (cur + self._num_windows)
        if shift <= 0:
            return
        w = self._sum.shape[1]
        if shift >= w:
            self._sum[:] = 0.0
            self._peak[:] = 0.0
            self._latest[:] = 0.0
            self._latest_time[:] = -1
            self._count[:] = 0
        else:
            self._sum = np.roll(self._sum, -shift, axis=1)
            self._peak = np.roll(self._peak, -shift, axis=1)
            self._latest = np.roll(self._latest, -shift, axis=1)
            self._latest_time = np.roll(self._latest_time, -shift, axis=1)
            self._count = np.roll(self._count, -shift, axis=1)
            self._sum[:, -shift:] = 0.0
            self._peak[:, -shift:] = 0.0
            self._latest[:, -shift:] = 0.0
            self._latest_time[:, -shift:] = -1
            self._count[:, -shift:] = 0
        self._oldest_window = cur + shift
        self._generation += 1  # completed-window set changed

    def add_samples(
        self,
        entity_ids: np.ndarray,
        times_ms: np.ndarray,
        values: np.ndarray,  # f32[N, M]
    ) -> int:
        """Vectorized RawMetricValues.addSample (:121). Returns accepted count.

        Samples older than the retained span are dropped (the reference
        rejects samples outside the window range)."""
        entity_ids = np.asarray(entity_ids, dtype=np.int64)
        times_ms = np.asarray(times_ms, dtype=np.int64)
        values = np.asarray(values, dtype=np.float32)
        if entity_ids.size == 0:
            return 0
        with self._lock:
            win = times_ms // self._window_ms
            self._roll_to(int(win.max()))
            # a batch (e.g. a sample-store replay) may span windows older than
            # its max; the first-observed watermark must cover them
            self._first_window = min(self._first_window, int(win.min()))
            slot = win - self._oldest_window
            ok = (slot >= 0) & (slot < self._sum.shape[1]) & (entity_ids >= 0) & (
                entity_ids < self.num_entities
            )
            if not ok.any():
                return 0
            e, s, t, v = entity_ids[ok], slot[ok].astype(np.int64), times_ms[ok], values[ok]
            np.add.at(self._sum, (e, s), v.astype(np.float64))
            np.maximum.at(self._peak, (e, s), v)
            # LATEST: keep the value with the greatest timestamp per (e, s).
            order = np.argsort(t, kind="stable")
            eo, so, to, vo = e[order], s[order], t[order], v[order]
            newer = to >= self._latest_time[eo, so]
            # later duplicates win because assignment happens in time order
            self._latest[eo[newer], so[newer]] = vo[newer]
            self._latest_time[eo[newer], so[newer]] = to[newer]
            np.add.at(self._count, (e, s), 1)
            # bumping a completed (non-current) window invalidates caches
            if (s < self._num_windows).any():
                self._generation += 1
            return int(ok.sum())

    # -- aggregation -----------------------------------------------------------

    def _values_by_strategy(self) -> np.ndarray:
        """f32[E, W, M]: per-strategy window value (sum/avg handled later)."""
        cnt = np.maximum(self._count[:, :, None], 1)
        avg = (self._sum / cnt).astype(np.float32)
        per_metric = np.where(
            self._agg_fn[None, None, :] == AggregationFunction.AVG,
            avg,
            np.where(self._agg_fn[None, None, :] == AggregationFunction.MAX, self._peak, self._latest),
        )
        return per_metric

    def aggregate(
        self,
        windows: Optional[Sequence[int]] = None,
        options: AggregationOptions = AggregationOptions(),
        include_current: bool = False,
    ) -> AggregationResult:
        """Windowed values + extrapolations + completeness, oldest window first.

        The vectorized equivalent of MetricSampleAggregator.aggregate (:193)
        over RawMetricValues.aggregate (:263-345)."""
        with self._lock:
            if self._oldest_window is None:
                raise ValueError("no samples added yet")
            if windows is None:
                lo = max(self._oldest_window, self._first_window)
                hi = self._oldest_window + self._num_windows + (1 if include_current else 0)
                windows = list(range(lo, hi))
            windows = sorted(int(w) for w in windows)
            if not windows:
                raise ValueError("no completed windows yet")
            slots = np.asarray([w - self._oldest_window for w in windows], dtype=np.int64)
            if (slots < 0).any() or (slots >= self._sum.shape[1]).any():
                raise ValueError(f"window out of retained range: {windows}")

            vals_all = self._values_by_strategy()  # [E, W, M], computed once
            vals = vals_all[:, slots]  # [E, Wq, M]
            cnt = self._count[:, slots]  # [E, Wq]

            # AVG_ADJACENT inputs: neighbors in *retained ring* space
            w_total = self._sum.shape[1]
            prev_s = np.clip(slots - 1, 0, w_total - 1)
            next_s = np.clip(slots + 1, 0, w_total - 1)
            interior = (slots > 0) & (slots < w_total - 1)
            prev_cnt = self._count[:, prev_s]
            next_cnt = self._count[:, next_s]
            neighbors_full = (
                interior[None, :]
                & (prev_cnt >= self._min_samples)
                & (next_cnt >= self._min_samples)
            )
            # adjacent value: AVG -> total sum / total count; MAX/LATEST ->
            # mean of the 2-3 retained window values (RawMetricValues:316-330)
            sum3 = self._sum[:, prev_s] + self._sum[:, slots] + self._sum[:, next_s]
            cnt3 = np.maximum((prev_cnt + cnt + next_cnt)[:, :, None], 1)
            adj_avg = (sum3 / cnt3).astype(np.float32)
            vals_prev = vals_all[:, prev_s]
            vals_next = vals_all[:, next_s]
            three = np.where((cnt > 0)[:, :, None], 3.0, 2.0)
            adj_other = (vals_prev + np.where((cnt > 0)[:, :, None], vals, 0.0) + vals_next) / three
            adj = np.where(
                self._agg_fn[None, None, :] == AggregationFunction.AVG, adj_avg, adj_other
            )

            sufficient = cnt >= self._min_samples
            available = cnt >= self._half_min
            some = cnt > 0

            extrap = np.full(cnt.shape, Extrapolation.NO_VALID_EXTRAPOLATION, dtype=np.int8)
            out = np.zeros(vals.shape, dtype=np.float32)
            # ladder, highest priority last so earlier writes win via masking
            use_forced = some & ~available & ~neighbors_full
            out[use_forced] = vals[use_forced]
            extrap[use_forced] = Extrapolation.FORCED_INSUFFICIENT
            use_adj = ~available & neighbors_full
            out[use_adj] = adj[use_adj]
            extrap[use_adj] = Extrapolation.AVG_ADJACENT
            use_avail = available & ~sufficient
            out[use_avail] = vals[use_avail]
            extrap[use_avail] = Extrapolation.AVG_AVAILABLE
            out[sufficient] = vals[sufficient]
            extrap[sufficient] = Extrapolation.NONE

            valid_window = extrap < Extrapolation.FORCED_INSUFFICIENT  # [E, Wq]
            valid_entity = valid_window.all(axis=1)  # [E]
            # a window counts as valid only when enough entities are valid in
            # it (MetricSampleCompleteness' per-window valid-entity-ratio)
            window_ratio = valid_window.mean(axis=0) if valid_window.size else np.zeros(len(windows))
            valid_window_list = [
                int(w)
                for w, r in zip(windows, window_ratio)
                if r >= options.min_valid_entity_ratio
            ]

            # completeness over entity groups
            groups = self._group
            num_groups = int(groups.max()) + 1 if groups.size else 0
            if num_groups:
                group_valid = np.ones(num_groups, dtype=bool)
                np.logical_and.at(group_valid, groups, valid_entity)
                valid_group_ratio = float(group_valid.mean())
            else:
                valid_group_ratio = 0.0
            valid_ratio = float(valid_entity.mean()) if valid_entity.size else 0.0

            if options.granularity == Granularity.ENTITY_GROUP and num_groups:
                valid_entity = valid_entity & group_valid[groups]
                valid_ratio = float(valid_entity.mean())

            completeness = CompletenessSummary(
                valid_entity_ratio=valid_ratio,
                valid_entity_group_ratio=valid_group_ratio,
                valid_windows=valid_window_list,
                generation=self._generation,
            )
            return AggregationResult(out, extrap, valid_entity, list(windows), completeness)

    def meets(self, options: AggregationOptions) -> bool:
        """meetCompletenessRequirements analog."""
        try:
            result = self.aggregate(options=options)
        except ValueError:
            return False
        c = result.completeness
        if c.valid_entity_ratio < options.min_valid_entity_ratio:
            return False
        if c.valid_entity_group_ratio < options.min_valid_entity_group_ratio:
            return False
        return len(c.valid_windows) >= options.min_valid_windows
