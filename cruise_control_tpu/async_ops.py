"""Async operation framework.

Analog of cc/async/ (AsyncKafkaCruiseControl.java:60 + progress/): long
operations run on worker threads and return an OperationFuture carrying
progress steps (OperationProgress: GeneratingClusterModel,
OptimizationForGoal...); the REST layer polls futures by User-Task-ID. Also
hosts the background proposal-precompute loop (GoalOptimizer.run :129-179)
that keeps the facade's proposal cache warm.
"""

from __future__ import annotations

import threading
import time
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, List, Optional


class OperationProgress:
    """Step log for one async operation (cc/async/progress/OperationProgress.java)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._steps: List[Dict] = []

    def add_step(self, description: str) -> None:
        with self._lock:
            now = time.time()
            if self._steps:
                self._steps[-1].setdefault("endMs", now * 1000)
            self._steps.append({"step": description, "startMs": now * 1000})

    def to_list(self) -> List[Dict]:
        with self._lock:
            return [dict(s) for s in self._steps]


class OperationFuture:
    """A Future with progress + a stable operation name."""

    def __init__(self, operation: str):
        self.operation = operation
        self.progress = OperationProgress()
        self._future: Future = Future()

    def set_result(self, value) -> None:
        self._future.set_result(value)

    def set_exception(self, exc: BaseException) -> None:
        self._future.set_exception(exc)

    def done(self) -> bool:
        return self._future.done()

    def result(self, timeout: Optional[float] = None):
        return self._future.result(timeout)

    def exception(self, timeout: Optional[float] = 0):
        if not self._future.done():
            return None
        return self._future.exception(timeout)

    def describe(self) -> Dict:
        out = {"operation": self.operation, "done": self.done(),
               "progress": self.progress.to_list()}
        if self.done() and self._future.exception() is not None:
            out["error"] = str(self._future.exception())
        return out


class AsyncCruiseControl:
    """Submits facade operations to a session pool, returning OperationFutures.

    The analog of AsyncKafkaCruiseControl's session executor; one pool for
    user ops, one thread for proposal precompute."""

    def __init__(self, facade, max_workers: int = 4):
        self.facade = facade
        self._pool = ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="cc-op")
        self._precompute_stop = threading.Event()
        self._precompute_thread: Optional[threading.Thread] = None

    def submit(self, operation: str, fn: Callable, *args, **kwargs) -> OperationFuture:
        of = OperationFuture(operation)
        of.progress.add_step(f"Queued {operation}")

        import inspect

        try:
            takes_progress = "progress" in inspect.signature(fn).parameters
        except (TypeError, ValueError):
            takes_progress = False

        def run():
            of.progress.add_step(f"Running {operation}")
            try:
                if takes_progress:
                    of.set_result(fn(*args, progress=of.progress, **kwargs))
                else:
                    of.set_result(fn(*args, **kwargs))
            except BaseException as e:  # surface any failure through the future
                of.set_exception(e)

        self._pool.submit(run)
        return of

    # convenience wrappers mirroring AsyncKafkaCruiseControl's op methods
    def rebalance(self, **kwargs) -> OperationFuture:
        return self.submit("REBALANCE", self.facade.rebalance, **kwargs)

    def decommission_brokers(self, broker_indices, **kwargs) -> OperationFuture:
        return self.submit("REMOVE_BROKER", self.facade.decommission_brokers, broker_indices, **kwargs)

    def add_brokers(self, broker_indices, **kwargs) -> OperationFuture:
        return self.submit("ADD_BROKER", self.facade.add_brokers, broker_indices, **kwargs)

    def demote_brokers(self, broker_indices, **kwargs) -> OperationFuture:
        return self.submit("DEMOTE_BROKER", self.facade.demote_brokers, broker_indices, **kwargs)

    def get_proposals(self, **kwargs) -> OperationFuture:
        return self.submit("PROPOSALS", self.facade.get_proposals, **kwargs)

    # -- proposal precompute (GoalOptimizer.run :129) --------------------------

    def start_proposal_precompute(self, interval_s: float = 30.0) -> None:
        if self._precompute_thread is not None:
            return
        self._precompute_stop.clear()

        def loop():
            while not self._precompute_stop.wait(interval_s):
                try:
                    self.facade.get_proposals()
                except Exception:
                    pass  # cache stays cold; next tick retries

        self._precompute_thread = threading.Thread(
            target=loop, name="proposal-precompute", daemon=True
        )
        self._precompute_thread.start()

    def shutdown(self) -> None:
        self._precompute_stop.set()
        if self._precompute_thread is not None:
            self._precompute_thread.join(timeout=5)
            self._precompute_thread = None
        self._pool.shutdown(wait=False)
