"""cccli: command-line client for the REST API.

Analog of cruise-control-client (cruisecontrolclient/client/cccli.py +
Endpoint.py/Responder.py/Display.py, SURVEY.md §2i): one subcommand per
endpoint, typed CCParameter validation client-side (client.endpoint), table
rendering for the well-known payloads (client.display, `--json` for raw), and
User-Task-ID polling for long operations — stdlib urllib only, so the CLI
works anywhere the service does."""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.parse
import urllib.request
from typing import Dict, Optional

GET_ENDPOINTS = {
    "state", "load", "partition_load", "proposals", "kafka_cluster_state",
    "user_tasks", "review_board", "bootstrap", "train",
}
POST_ENDPOINTS = {
    "rebalance", "add_broker", "remove_broker", "demote_broker",
    "stop_proposal_execution", "pause_sampling", "resume_sampling",
    "topic_configuration", "admin", "review",
}


class CruiseControlClient:
    """Responder.py analog: HTTP + User-Task-ID polling."""

    def __init__(self, base_url: str, poll_interval_s: float = 1.0, timeout_s: float = 600.0):
        self._base = base_url.rstrip("/")
        self._poll = poll_interval_s
        self._timeout = timeout_s

    def request(self, endpoint: str, params: Optional[Dict] = None, wait: bool = True) -> Dict:
        method = "GET" if endpoint in GET_ENDPOINTS else "POST"
        query = urllib.parse.urlencode(params or {})
        url = f"{self._base}/kafkacruisecontrol/{endpoint}"
        if query:
            url += f"?{query}"
        task_id = None
        deadline = time.monotonic() + self._timeout
        while True:
            req = urllib.request.Request(url, method=method)
            if task_id:
                req.add_header("User-Task-ID", task_id)
            try:
                with urllib.request.urlopen(req) as resp:
                    body = json.loads(resp.read().decode())
                    status = resp.status
                    task_id = resp.headers.get("User-Task-ID", task_id)
            except urllib.error.HTTPError as e:
                return {"errorMessage": e.read().decode(), "status": e.code}
            if status != 202 or not wait:
                return body
            if time.monotonic() > deadline:
                return {"errorMessage": "timed out waiting for task", "userTaskId": task_id}
            time.sleep(self._poll)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="cccli", description="cruise_control_tpu REST client"
    )
    parser.add_argument("-a", "--address", default="http://127.0.0.1:9090",
                        help="server base URL")
    parser.add_argument("--no-wait", action="store_true",
                        help="do not poll async operations to completion")
    parser.add_argument("--json", action="store_true", dest="raw_json",
                        help="print raw JSON instead of tables")
    sub = parser.add_subparsers(dest="endpoint", required=True)

    def add(name, *flags):
        p = sub.add_parser(name)
        for flag, kw in flags:
            p.add_argument(flag, **kw)
        return p

    bools = {"action": "store_true"}
    add("state", ("--substates", {}))
    add("load")
    add("partition_load", ("--resource", {"default": "DISK"}), ("--entries", {"type": int, "default": 20}))
    add("proposals", ("--goals", {}), ("--ignore-proposal-cache", bools),
        ("--excluded-topics", {}), ("--destination-broker-ids", {}))
    add("kafka_cluster_state", ("--verbose", bools))
    add("user_tasks")
    add("review_board")
    add("bootstrap", ("--start", {"type": int}), ("--end", {"type": int}))
    add("train", ("--start", {"type": int}), ("--end", {"type": int}))
    add("rebalance", ("--goals", {}), ("--dryrun", {"default": "true"}),
        ("--skip-hard-goal-check", bools), ("--review-id", {}),
        ("--excluded-topics", {}), ("--destination-broker-ids", {}))
    add("add_broker", ("brokerid", {}), ("--dryrun", {"default": "true"}), ("--review-id", {}))
    add("remove_broker", ("brokerid", {}), ("--dryrun", {"default": "true"}), ("--review-id", {}),
        ("--excluded-topics", {}), ("--destination-broker-ids", {}))
    add("demote_broker", ("brokerid", {}), ("--dryrun", {"default": "true"}), ("--review-id", {}))
    add("stop_proposal_execution")
    add("pause_sampling", ("--reason", {"default": "cccli"}))
    add("resume_sampling")
    add("topic_configuration", ("--topic", {"required": True}),
        ("--replication-factor", {"type": int, "required": True}),
        ("--dryrun", {"default": "true"}), ("--review-id", {}))
    add("admin", ("--concurrent-partition-movements-per-broker", {"type": int}),
        ("--concurrent-leader-movements", {"type": int}),
        ("--enable-self-healing-for", {}), ("--disable-self-healing-for", {}))
    add("review", ("--approve", {}), ("--discard", {}), ("--reason", {"default": ""}))
    return parser


def main(argv=None) -> int:
    from cruise_control_tpu.client.display import render
    from cruise_control_tpu.client.endpoint import validate_params

    args = build_parser().parse_args(argv)
    params = {
        k: v
        for k, v in vars(args).items()
        if k not in ("address", "endpoint", "no_wait", "raw_json")
        # `is` comparisons: 0 is a legitimate value (e.g. --start 0) and
        # compares equal to False under `in`
        and v is not None and v is not False
    }
    params = {k: ("true" if v is True else str(v)) for k, v in params.items()}
    try:
        params = validate_params(args.endpoint, params)
    except ValueError as e:
        print(f"invalid parameter: {e}", file=sys.stderr)
        return 2
    client = CruiseControlClient(args.address)
    out = client.request(args.endpoint, params, wait=not args.no_wait)
    if args.raw_json or not isinstance(out, dict):
        json.dump(out, sys.stdout, indent=2, default=str)
        print()
    else:
        print(render(args.endpoint, out))
    return 0 if "errorMessage" not in out else 1


if __name__ == "__main__":
    sys.exit(main())
