"""Python REST client + CLI (the cruise-control-client analog)."""

from cruise_control_tpu.client.cccli import CruiseControlClient, main

__all__ = ["CruiseControlClient", "main"]
