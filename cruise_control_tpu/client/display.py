"""Human-readable rendering of REST responses.

Analog of cruise-control-client's Display.py / util/print.py: well-known
payload shapes (broker load, proposals, state, user tasks) render as aligned
tables; everything else falls back to pretty JSON. `--json` on the CLI forces
raw JSON.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence


def _table(headers: Sequence[str], rows: List[Sequence]) -> str:
    cols = [[str(h)] + [str(r[i]) for r in rows] for i, h in enumerate(headers)]
    widths = [max(len(v) for v in col) for col in cols]
    def fmt(row):
        return "  ".join(str(v).ljust(w) for v, w in zip(row, widths))
    lines = [fmt(headers), fmt(["-" * w for w in widths])]
    lines.extend(fmt(r) for r in rows)
    return "\n".join(lines)


def render(endpoint: str, payload: Dict) -> str:
    if not isinstance(payload, dict):
        return json.dumps(payload, indent=2, default=str)
    if "errorMessage" in payload:
        return f"ERROR: {payload['errorMessage']}"
    if endpoint == "load" and "brokers" in payload:
        headers = ["Broker", "Host", "State", "DiskMB", "DiskPct", "CpuPct",
                   "LeaderNwIn", "FollowerNwIn", "NwOut", "PnwOut", "Replicas", "Leaders"]
        rows = [
            [b["Broker"], b["Host"], b["BrokerState"], b["DiskMB"], b["DiskPct"],
             b["CpuPct"], b["LeaderNwInRate"], b["FollowerNwInRate"],
             b["NwOutRate"], b["PnwOutRate"], b["Replicas"], b["Leaders"]]
            for b in payload["brokers"]
        ]
        return _table(headers, rows)
    if "summary" in payload and "goalSummary" in payload:  # OptimizationResult
        out = [json.dumps(payload["summary"], indent=2, default=str), ""]
        rows = [
            [g["goal"], g["status"],
             g["clusterModelStats"]["violatedBrokersBefore"],
             g["clusterModelStats"]["violatedBrokersAfter"]]
            for g in payload["goalSummary"]
        ]
        out.append(_table(["Goal", "Status", "ViolatedBefore", "ViolatedAfter"], rows))
        n = len(payload.get("proposals", []))
        out.append(f"\n{n} proposal(s)")
        return "\n".join(out)
    if endpoint == "user_tasks" and "userTasks" in payload:
        rows = [
            [t["UserTaskId"], t["RequestURL"], t["Status"], t["StartMs"],
             t.get("ClientIdentity", "")]
            for t in payload["userTasks"]
        ]
        return _table(["UserTaskId", "RequestURL", "Status", "StartMs", "Client"], rows)
    if endpoint == "partition_load" and "records" in payload:
        if not payload["records"]:
            return "(no records)"
        keys = list(payload["records"][0].keys())
        rows = [[r.get(k, "") for k in keys] for r in payload["records"]]
        return _table(keys, rows)
    return json.dumps(payload, indent=2, default=str)
