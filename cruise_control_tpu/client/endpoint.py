"""Typed endpoint parameters for the CLI.

Analog of cruise-control-client's Endpoint.py `CCParameter` hierarchy
(cruisecontrolclient/client/Endpoint.py): every endpoint declares its
parameters with a type, and values are validated CLIENT-side at parse time —
a bad flag fails fast with a message instead of a server round-trip.
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Sequence


class CCParameter:
    """One request parameter: name + validation to its canonical wire form."""

    def __init__(self, name: str, doc: str = ""):
        self.name = name
        self.doc = doc

    def validate(self, value: str) -> str:
        """Return the canonical string value or raise ValueError."""
        return value


class BooleanParameter(CCParameter):
    _TRUE = {"true", "t", "yes", "1"}
    _FALSE = {"false", "f", "no", "0"}

    def validate(self, value: str) -> str:
        v = str(value).strip().lower()
        if v in self._TRUE:
            return "true"
        if v in self._FALSE:
            return "false"
        raise ValueError(f"{self.name}: expected a boolean, got {value!r}")


class NonNegativeIntegerParameter(CCParameter):
    def validate(self, value: str) -> str:
        try:
            i = int(value)
        except (TypeError, ValueError):
            raise ValueError(f"{self.name}: expected an integer, got {value!r}")
        if i < 0:
            raise ValueError(f"{self.name}: must be >= 0, got {i}")
        return str(i)


class TimestampParameter(NonNegativeIntegerParameter):
    """Epoch milliseconds (the reference also accepts ISO dates; ms only here)."""


class RegexParameter(CCParameter):
    def validate(self, value: str) -> str:
        try:
            re.compile(value)
        except re.error as e:
            raise ValueError(f"{self.name}: invalid regular expression: {e}")
        return value


class SetOfChoicesParameter(CCParameter):
    def __init__(self, name: str, choices: Sequence[str], doc: str = ""):
        super().__init__(name, doc)
        self.choices = set(choices)

    def validate(self, value: str) -> str:
        parts = [p.strip() for p in str(value).split(",") if p.strip()]
        bad = [p for p in parts if p not in self.choices]
        if bad:
            raise ValueError(
                f"{self.name}: invalid value(s) {bad}; choices: {sorted(self.choices)}"
            )
        return ",".join(parts)


class SingleChoiceParameter(CCParameter):
    """Exactly one value from a choice set, canonicalized to upper case."""

    def __init__(self, name: str, choices: Sequence[str], doc: str = ""):
        super().__init__(name, doc)
        self.choices = {c.upper() for c in choices}

    def validate(self, value: str) -> str:
        v = str(value).strip().upper()
        if v not in self.choices:
            raise ValueError(
                f"{self.name}: invalid value {value!r}; choices: {sorted(self.choices)}"
            )
        return v


class CSVIntListParameter(CCParameter):
    def validate(self, value: str) -> str:
        try:
            ids = [int(p) for p in str(value).split(",") if p.strip()]
        except ValueError:
            raise ValueError(f"{self.name}: expected comma-separated broker ids, got {value!r}")
        if not ids:
            raise ValueError(f"{self.name}: at least one broker id is required")
        return ",".join(str(i) for i in ids)


_RESOURCES = ("CPU", "NW_IN", "NW_OUT", "DISK")
_ANOMALY_TYPES = ("goal_violation", "broker_failure", "metric_anomaly")

#: endpoint -> {wire parameter name: CCParameter}
ENDPOINT_PARAMETERS: Dict[str, Dict[str, CCParameter]] = {
    "partition_load": {
        # the server resolves ONE Resource per request
        "resource": SingleChoiceParameter("resource", _RESOURCES),
        "entries": NonNegativeIntegerParameter("entries"),
    },
    "state": {"substates": CCParameter("substates")},
    "proposals": {
        "goals": CCParameter("goals"),
        "ignore_proposal_cache": BooleanParameter("ignore_proposal_cache"),
        "excluded_topics": RegexParameter("excluded_topics"),
        "destination_broker_ids": CSVIntListParameter("destination_broker_ids"),
    },
    "kafka_cluster_state": {"verbose": BooleanParameter("verbose")},
    "bootstrap": {
        "start": TimestampParameter("start"),
        "end": TimestampParameter("end"),
    },
    "train": {
        "start": TimestampParameter("start"),
        "end": TimestampParameter("end"),
    },
    "rebalance": {
        "goals": CCParameter("goals"),
        "dryrun": BooleanParameter("dryrun"),
        "skip_hard_goal_check": BooleanParameter("skip_hard_goal_check"),
        "excluded_topics": RegexParameter("excluded_topics"),
        "destination_broker_ids": CSVIntListParameter("destination_broker_ids"),
        "review_id": NonNegativeIntegerParameter("review_id"),
        "ignore_proposal_cache": BooleanParameter("ignore_proposal_cache"),
    },
    "add_broker": {
        "brokerid": CSVIntListParameter("brokerid"),
        "dryrun": BooleanParameter("dryrun"),
        "review_id": NonNegativeIntegerParameter("review_id"),
    },
    "remove_broker": {
        "brokerid": CSVIntListParameter("brokerid"),
        "dryrun": BooleanParameter("dryrun"),
        "excluded_topics": RegexParameter("excluded_topics"),
        "destination_broker_ids": CSVIntListParameter("destination_broker_ids"),
        "review_id": NonNegativeIntegerParameter("review_id"),
    },
    "demote_broker": {
        "brokerid": CSVIntListParameter("brokerid"),
        "dryrun": BooleanParameter("dryrun"),
        "review_id": NonNegativeIntegerParameter("review_id"),
    },
    "pause_sampling": {"reason": CCParameter("reason")},
    "topic_configuration": {
        "topic": RegexParameter("topic"),
        "replication_factor": NonNegativeIntegerParameter("replication_factor"),
        "dryrun": BooleanParameter("dryrun"),
        "review_id": NonNegativeIntegerParameter("review_id"),
    },
    "admin": {
        "concurrent_partition_movements_per_broker": NonNegativeIntegerParameter(
            "concurrent_partition_movements_per_broker"
        ),
        "concurrent_leader_movements": NonNegativeIntegerParameter(
            "concurrent_leader_movements"
        ),
        "enable_self_healing_for": SetOfChoicesParameter(
            "enable_self_healing_for", _ANOMALY_TYPES
        ),
        "disable_self_healing_for": SetOfChoicesParameter(
            "disable_self_healing_for", _ANOMALY_TYPES
        ),
    },
    "review": {
        # the server accepts CSV lists of review ids (server.py review handler)
        "approve": CSVIntListParameter("approve"),
        "discard": CSVIntListParameter("discard"),
        "reason": CCParameter("reason"),
    },
}


def validate_params(endpoint: str, params: Dict[str, str]) -> Dict[str, str]:
    """Canonicalize/validate; raises ValueError on any bad name or value."""
    spec: Optional[Dict[str, CCParameter]] = ENDPOINT_PARAMETERS.get(endpoint)
    out = {}
    for name, value in params.items():
        if spec is None or name not in spec:
            known = sorted(spec) if spec else []
            raise ValueError(
                f"{endpoint}: unknown parameter {name!r}"
                + (f"; known: {known}" if known else " (endpoint takes no parameters)")
            )
        out[name] = spec[name].validate(value)
    return out
