"""CruiseControl facade: wires monitor + analyzer + executor (+ detector).

Analog of KafkaCruiseControl (cc/KafkaCruiseControl.java:70): the operation
surface the REST layer and detectors call — rebalance (:375),
decommission_brokers (:187), add_brokers (:277), demote_brokers (:434) — plus
the proposal cache with expiration and the cache-bypass rules
(ignoreProposalCache :675-691) and hard-goal presence check
(sanityCheckHardGoalPresence :1238)."""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from cruise_control_tpu.analyzer.context import OptimizationOptions
from cruise_control_tpu.analyzer.goals import DEFAULT_GOAL_ORDER, GOAL_REGISTRY, HARD_GOAL_NAMES
from cruise_control_tpu.analyzer.incremental import IncrementalConfig, IncrementalLane
from cruise_control_tpu.analyzer.optimizer import (
    GoalOptimizer,
    OptimizerResult,
    OptimizerSettings,
)
from cruise_control_tpu.common.resources import BrokerState
from cruise_control_tpu.executor.executor import Executor
from cruise_control_tpu.models.flat_model import FlatClusterModel
from cruise_control_tpu.monitor.completeness import ModelCompletenessRequirements
from cruise_control_tpu.monitor.load_monitor import LoadMonitor


class IllegalRequestException(Exception):
    """Bad operator input (missing hard goals, unknown goal names...)."""


@dataclasses.dataclass
class _CachedProposals:
    result: OptimizerResult
    generation: int
    computed_at: float
    requirements: ModelCompletenessRequirements


@dataclasses.dataclass(frozen=True)
class FacadeConfig:
    proposal_expiration_s: float = 60.0  # proposal.expiration.ms
    default_requirements: ModelCompletenessRequirements = ModelCompletenessRequirements(
        min_required_num_windows=1, min_monitored_partitions_percentage=0.5
    )
    #: goals used when a request names none — the reference's `default.goals`
    #: key (operators commonly trim the stack); None = the full priority order
    default_goal_names: Optional[Tuple[str, ...]] = None
    #: incremental re-proposal lane knobs (`optimizer.incremental.*` keys,
    #: analyzer/incremental.py)
    incremental: IncrementalConfig = IncrementalConfig()


class CruiseControl:
    def __init__(
        self,
        load_monitor: LoadMonitor,
        executor: Executor,
        optimizer: Optional[GoalOptimizer] = None,
        config: FacadeConfig = FacadeConfig(),
        clock=time.monotonic,
    ):
        self._monitor = load_monitor
        self._executor = executor
        self._optimizer = optimizer or GoalOptimizer()
        self._config = config
        self._clock = clock
        self._cache_lock = threading.Lock()
        self._cached: Optional[_CachedProposals] = None
        #: the incremental re-proposal lane, armed after every stamped full
        #: solve and consulted by incremental_reproposal() (the detector's
        #: ProposalDriftAnomaly recovery path)
        self._incremental = IncrementalLane(self._optimizer, config.incremental)

    # -- goal resolution -------------------------------------------------------

    @staticmethod
    def goals_by_priority(goal_names: Optional[Sequence[str]]) -> List[str]:
        """Resolve requested names in default priority order
        (KafkaCruiseControl.goalsByPriority :1218). Validation lives here;
        the ordering is the analyzer registry's, so the two cannot drift."""
        from cruise_control_tpu.analyzer.goals import goals_by_priority as resolve

        if goal_names:
            unknown = [n for n in goal_names if n not in GOAL_REGISTRY]
            if unknown:
                raise IllegalRequestException(f"unknown goals: {unknown}")
        return [g.name for g in resolve(goal_names)]

    @staticmethod
    def sanity_check_hard_goal_presence(goal_names: Optional[Sequence[str]],
                                        skip_hard_goal_check: bool = False) -> None:
        """All hard goals must be included unless explicitly skipped
        (sanityCheckHardGoalPresence :1238)."""
        if skip_hard_goal_check or not goal_names:
            return
        missing = [h for h in HARD_GOAL_NAMES if h not in set(goal_names)]
        if missing:
            raise IllegalRequestException(
                f"missing hard goals {missing}; pass skip_hard_goal_check=True to override"
            )

    # -- proposal cache --------------------------------------------------------

    def _ignore_proposal_cache(
        self,
        goal_names,
        options: OptimizationOptions,
        ignore_proposal_cache: bool,
    ) -> bool:
        """The bypass rules of KafkaCruiseControl.ignoreProposalCache (:675)."""
        return (
            ignore_proposal_cache
            or self._executor.has_ongoing_execution
            or bool(goal_names)
            or options.excluded_partitions is not None
            or options.excluded_brokers_for_leadership is not None
            or options.excluded_brokers_for_replica_move is not None
            or options.requested_destination_brokers is not None
            or options.excluded_topic_pattern is not None
            or options.destination_broker_ids is not None
            or options.only_move_immigrants
            or options.is_triggered_by_goal_violation
        )

    @staticmethod
    def _stamp_result(result: OptimizerResult, generation: int, topo) -> OptimizerResult:
        """Drift-safety stamps (executor/validation.py): the monitor
        generation and the topology fingerprint at model-build time ride the
        result so the executor can revalidate the batch against fresh
        metadata before (and while) dispatching."""
        from cruise_control_tpu.executor.validation import TopologyFingerprint

        result.generation = generation
        result.fingerprint = TopologyFingerprint.from_topology(topo)
        return result

    def _execute_result(self, result: OptimizerResult, **kwargs) -> Dict:
        """Dispatch an optimizer result with its drift stamps and decision
        provenance attached (tasks carry `<run>/p<partition>` ids into
        terminal events and trim records — GET /explain's execution join)."""
        return self._executor.execute_proposals(
            result.proposals,
            generation=result.generation,
            fingerprint=result.fingerprint,
            provenance_run=(
                result.provenance.run_id if result.provenance is not None else None
            ),
            **kwargs,
        )

    @staticmethod
    def _attach_topic_names(result: OptimizerResult, meta) -> OptimizerResult:
        """Fill each proposal's topicPartition from the model metadata: the
        reference's proposals are topic-partition keyed (ExecutionProposal),
        and clients match on names, not dense partition indices."""
        import dataclasses as _dc

        result.proposals = [
            _dc.replace(p, topic_partition=meta.topic_partition(p.partition))
            for p in result.proposals
        ]
        return result

    def _effective_goals(self, goal_names: Optional[Sequence[str]]):
        """Requested goals in priority order; falls back to the configured
        default.goals list, then to the full stack (None)."""
        if goal_names:
            return self.goals_by_priority(goal_names)
        if self._config.default_goal_names:
            # the configured default goes through the same validation +
            # priority ordering as any request (a verbatim list would run in
            # operator order, changing acceptance-table semantics)
            return self.goals_by_priority(self._config.default_goal_names)
        return None

    def get_proposals(
        self,
        goal_names: Optional[Sequence[str]] = None,
        requirements: Optional[ModelCompletenessRequirements] = None,
        options: OptimizationOptions = OptimizationOptions(),
        ignore_proposal_cache: bool = False,
        model: Optional[FlatClusterModel] = None,
    ) -> OptimizerResult:
        """Cached default-goal proposals, or a fresh optimization
        (KafkaCruiseControl.getProposals :710)."""
        from cruise_control_tpu.common.tracing import TRACER

        with TRACER.span("get-proposals", kind="facade", cache="miss") as span:
            return self._get_proposals(
                goal_names, requirements, options, ignore_proposal_cache, model, span
            )

    def _get_proposals(
        self, goal_names, requirements, options, ignore_proposal_cache, model, span
    ) -> OptimizerResult:
        req = requirements or self._config.default_requirements
        use_cache = not self._ignore_proposal_cache(goal_names, options, ignore_proposal_cache)
        if use_cache and model is None:
            with self._cache_lock:
                c = self._cached
                # the cached result is reusable only if it was computed under
                # requirements at least as strong as the caller's
                # (ignoreProposalCache's hasWeakerRequirement, :682-686)
                strong_enough = c is not None and (
                    c.requirements.min_required_num_windows >= req.min_required_num_windows
                    and c.requirements.min_monitored_partitions_percentage
                    >= req.min_monitored_partitions_percentage
                    and (c.requirements.include_all_topics or not req.include_all_topics)
                )
                fresh = (
                    strong_enough
                    and c.generation == self._monitor.generation
                    and self._clock() - c.computed_at < self._config.proposal_expiration_s
                )
                if fresh:
                    span.attributes["cache"] = "hit"
                    return c.result

        if model is None:
            with self._monitor.acquire_for_model_generation():
                generation = self._monitor.generation
                model, _meta = self._monitor.cluster_model(req)
                _topo = self._monitor._metadata.refresh_metadata()
            from cruise_control_tpu.analyzer.context import resolve_options

            options = resolve_options(options, model, _meta.topic_names)
        else:
            generation = -1
        result = self._optimizer.optimizations(
            model,
            goal_names=self._effective_goals(goal_names),
            options=options,
            raise_on_hard_failure=not options.is_triggered_by_goal_violation,
        )
        if generation >= 0:
            result = self._attach_topic_names(result, _meta)
            result = self._stamp_result(result, generation, _topo)
            # arm the incremental lane on the SAME (model, options) objects
            # this solve prepared — the prep-cache seam keys by identity, so
            # the lane captures the device-resident padded context of the
            # solve that just ran (analyzer/incremental.py)
            self._incremental.arm(
                model, options,
                tuple(g.name for g in result.goal_results),
                generation=generation,
            )
        if use_cache and generation >= 0:
            with self._cache_lock:
                self._cached = _CachedProposals(result, generation, self._clock(), req)
        return result

    # -- operations ------------------------------------------------------------

    def rebalance(
        self,
        goal_names: Optional[Sequence[str]] = None,
        dryrun: bool = True,
        requirements: Optional[ModelCompletenessRequirements] = None,
        options: OptimizationOptions = OptimizationOptions(),
        skip_hard_goal_check: bool = False,
        ignore_proposal_cache: bool = False,
    ) -> OptimizerResult:
        """KafkaCruiseControl.rebalance (:375)."""
        self.sanity_check_hard_goal_presence(goal_names, skip_hard_goal_check)
        self._sanity_check_dry_run(dryrun)
        result = self.get_proposals(goal_names, requirements, options, ignore_proposal_cache)
        if not dryrun:
            self._execute_result(result)
        return result

    def incremental_reproposal(
        self,
        dryrun: bool = True,
        requirements: Optional[ModelCompletenessRequirements] = None,
    ) -> OptimizerResult:
        """The recovery lane: fresh monitor model → typed delta stream →
        in-place scatter into the device-resident padded context →
        goal-scoped re-solve seeded from the surviving placement
        (analyzer/incremental.py).

        The detector's `ProposalDriftAnomaly` recompute (which the executor's
        batch-abort path also queues) routes here instead of the full
        rebalance. Any lane ineligibility — unarmed, stale generation, delta
        out of the shape bucket, sensitivity map says all — falls back to
        the full goal-violation re-solve when
        `optimizer.incremental.fallback.full` is on, and raises otherwise
        (the operator asked for incremental-or-nothing)."""
        if not dryrun:
            self._sanity_check_dry_run(dryrun)
        req = requirements or self._config.default_requirements
        with self._monitor.acquire_for_model_generation():
            generation = self._monitor.generation
            model, _meta = self._monitor.cluster_model(req)
            _topo = self._monitor._metadata.refresh_metadata()
        outcome = self._incremental.propose(model, generation=generation)
        if outcome.ok:
            result = outcome.result
            result = self._attach_topic_names(result, _meta)
            result = self._stamp_result(result, generation, _topo)
            if not dryrun:
                self._execute_result(result)
            return result
        if self._incremental.config.fallback_full:
            return self.rebalance(
                dryrun=dryrun,
                options=OptimizationOptions(is_triggered_by_goal_violation=True),
                ignore_proposal_cache=True,
            )
        raise RuntimeError(
            f"incremental re-proposal unavailable: {outcome.fallback_reason} "
            "(optimizer.incremental.fallback.full is off)"
        )

    def decommission_brokers(
        self,
        broker_indices: Set[int],
        goal_names: Optional[Sequence[str]] = None,
        dryrun: bool = True,
        skip_hard_goal_check: bool = False,
        options: OptimizationOptions = OptimizationOptions(),
    ) -> OptimizerResult:
        """Drain brokers: mark DEAD then optimize so replicas move off them
        (KafkaCruiseControl.decommissionBrokers :187)."""
        from cruise_control_tpu.analyzer.context import resolve_options

        self.sanity_check_hard_goal_presence(goal_names, skip_hard_goal_check)
        self._sanity_check_dry_run(dryrun)
        with self._monitor.acquire_for_model_generation():
            generation = self._monitor.generation
            model, _meta = self._monitor.cluster_model(
                self._config.default_requirements
            )
            _topo = self._monitor._metadata.refresh_metadata()
        state = np.array(model.broker_state)
        state[list(broker_indices)] = BrokerState.DEAD
        model = model._replace(broker_state=state)
        result = self._optimizer.optimizations(
            model,
            goal_names=self._effective_goals(goal_names),
            options=resolve_options(options, model, _meta.topic_names),
        )
        result = self._attach_topic_names(result, _meta)
        result = self._stamp_result(result, generation, _topo)
        if not dryrun:
            self._execute_result(result, removed_brokers=broker_indices)
        return result

    def add_brokers(
        self,
        broker_indices: Set[int],
        goal_names: Optional[Sequence[str]] = None,
        dryrun: bool = True,
        skip_hard_goal_check: bool = False,
    ) -> OptimizerResult:
        """Move load onto NEW brokers (KafkaCruiseControl.addBrokers :277)."""
        self.sanity_check_hard_goal_presence(goal_names, skip_hard_goal_check)
        self._sanity_check_dry_run(dryrun)
        with self._monitor.acquire_for_model_generation():
            generation = self._monitor.generation
            model, _meta = self._monitor.cluster_model(self._config.default_requirements)
            _topo = self._monitor._metadata.refresh_metadata()
        state = np.array(model.broker_state)
        state[list(broker_indices)] = BrokerState.NEW
        model = model._replace(broker_state=state)
        result = self._optimizer.optimizations(
            model, goal_names=self._effective_goals(goal_names)
        )
        result = self._attach_topic_names(result, _meta)
        result = self._stamp_result(result, generation, _topo)
        if not dryrun:
            self._execute_result(result)
        return result

    def demote_brokers(self, broker_indices: Set[int], dryrun: bool = True) -> OptimizerResult:
        """Move leadership (and preferred position) off brokers
        (KafkaCruiseControl.demoteBrokers :434): mark DEMOTED, then run the
        preferred-leader-election pass with demoted brokers excluded from
        leadership."""
        self._sanity_check_dry_run(dryrun)
        with self._monitor.acquire_for_model_generation():
            generation = self._monitor.generation
            model, _meta = self._monitor.cluster_model(self._config.default_requirements)
            _topo = self._monitor._metadata.refresh_metadata()
        state = np.array(model.broker_state)
        state[list(broker_indices)] = BrokerState.DEMOTED
        model = model._replace(broker_state=state)
        mask = np.zeros(model.num_brokers, dtype=bool)
        mask[list(broker_indices)] = True
        result = self._optimizer.optimizations(
            model,
            goal_names=["LeaderReplicaDistributionGoal"],
            options=OptimizationOptions(excluded_brokers_for_leadership=mask),
        )
        result = self._attach_topic_names(result, _meta)
        result = self._stamp_result(result, generation, _topo)
        if not dryrun:
            self._execute_result(result, demoted_brokers=broker_indices)
        return result

    def update_topic_replication_factor(
        self, topic_pattern: str, replication_factor: int, dryrun: bool = True
    ) -> Dict:
        """Change RF for topics matching the pattern
        (KafkaCruiseControl.updateTopicConfiguration :949): new replicas go to
        alive brokers on under-represented racks with the fewest replicas;
        RF reduction drops trailing followers (never the leader)."""
        import re as _re

        from cruise_control_tpu.analyzer.proposals import ExecutionProposal
        from cruise_control_tpu.models.flat_model import replica_counts

        if replication_factor < 1:
            raise IllegalRequestException("replication_factor must be >= 1")
        self._sanity_check_dry_run(dryrun)
        with self._monitor.acquire_for_model_generation():
            generation = self._monitor.generation
            model, meta = self._monitor.cluster_model(self._config.default_requirements)
            _topo = self._monitor._metadata.refresh_metadata()
        pattern = _re.compile(topic_pattern)
        topic_ids = {
            t for t, name in enumerate(meta.topic_names) if pattern.fullmatch(name)
        }
        if not topic_ids:
            raise IllegalRequestException(f"no topics match {topic_pattern!r}")
        a = np.asarray(model.assignment)
        state = np.asarray(model.broker_state)
        rack = np.asarray(model.broker_rack)
        counts = np.asarray(replica_counts(model)).copy()
        proposals: List[ExecutionProposal] = []
        for p in np.nonzero(np.isin(np.asarray(model.topic_id), list(topic_ids)))[0]:
            old = [int(b) for b in a[p] if b >= 0]
            new = list(old)
            while len(new) > replication_factor:
                new.pop()  # drop trailing followers, keep the leader
            while len(new) < replication_factor:
                used_racks = {int(rack[b]) for b in new}
                eligible = [
                    b
                    for b in range(model.num_brokers)
                    if state[b] != BrokerState.DEAD and b not in new
                ]
                if not eligible:
                    raise IllegalRequestException(
                        f"not enough alive brokers for RF {replication_factor}"
                    )
                fresh_rack = [b for b in eligible if int(rack[b]) not in used_racks]
                pool = fresh_rack or eligible
                pick = min(pool, key=lambda b: counts[b])
                counts[pick] += 1
                new.append(pick)
            if new != old:
                proposals.append(
                    ExecutionProposal(
                        partition=int(p),
                        old_replicas=tuple(old),
                        new_replicas=tuple(new),
                        topic_partition=meta.topic_partition(int(p)),
                    )
                )
        if not dryrun and proposals:
            from cruise_control_tpu.executor.validation import TopologyFingerprint

            self._executor.execute_proposals(
                proposals,
                generation=generation,
                fingerprint=TopologyFingerprint.from_topology(_topo),
            )
        return {
            "topics": sorted(meta.topic_names[t] for t in topic_ids),
            "replicationFactor": replication_factor,
            "numProposals": len(proposals),
            "proposals": [pr.to_dict() for pr in proposals[:1000]],
            "dryrun": dryrun,
        }

    def _sanity_check_dry_run(self, dryrun: bool) -> None:
        """No non-dryrun op may start over an ongoing execution
        (sanityCheckDryRun :337)."""
        if not dryrun and self._executor.has_ongoing_execution:
            raise RuntimeError("cannot start execution: another execution is in progress")

    # -- state -----------------------------------------------------------------

    def state(self) -> Dict:
        """Aggregated sub-states (/state endpoint; KafkaCruiseControl :1148)."""
        from cruise_control_tpu.common.sensors import REGISTRY

        monitor_state = {
            "state": self._monitor.state,
            # active exclusive mode (BOOTSTRAPPING/TRAINING) + progress, the
            # reference's LoadMonitorTaskRunner state reporting
            "activeTask": self._monitor.active_task,
            "generation": self._monitor.generation,
            "sensors": dict(self._monitor.sensors),
        }
        fetcher = getattr(self._monitor._sampler, "sensors", None)
        if fetcher is not None:  # N-way MetricFetcherManager in place
            monitor_state["fetchers"] = {
                k: (list(v) if isinstance(v, list) else v) for k, v in fetcher.items()
            }
        return {
            "MonitorState": monitor_state,
            "ExecutorState": self._executor.state_summary(),
            "AnalyzerState": {
                "goals": [g.name for g in DEFAULT_GOAL_ORDER],
                "cachedProposals": self._cached is not None,
            },
            "IncrementalState": self._incremental.state(),
            # named timers/meters (Sensors.md; JMX domain kafka.cruisecontrol)
            "Sensors": REGISTRY.snapshot(),
        }
