"""Replica movement ordering strategies.

Analog of cc/executor/strategy/: a strategy orders each broker's pending
inter-broker movement tasks; strategies chain, with the base
execution-id order as the final tie-breaker
(ExecutionTaskPlanner ctor chains BaseReplicaMovementStrategy last).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from cruise_control_tpu.executor.task import ExecutionTask


class ReplicaMovementStrategy:
    """SPI (cc/executor/strategy/ReplicaMovementStrategy.java:15)."""

    def sort_key(self, task: ExecutionTask, urp: Optional[set] = None):
        """Smaller sorts first. `urp` is the set of currently
        under-replicated partition ids (for the URP strategy)."""
        raise NotImplementedError

    def chain(self, next_strategy: "ReplicaMovementStrategy") -> "ReplicaMovementStrategy":
        return _ChainedStrategy(self, next_strategy)

    def apply(self, tasks: Sequence[ExecutionTask], urp: Optional[set] = None) -> List[ExecutionTask]:
        base_chained = self.chain(BaseReplicaMovementStrategy())
        return sorted(tasks, key=lambda t: base_chained.sort_key(t, urp))


class _ChainedStrategy(ReplicaMovementStrategy):
    def __init__(self, first: ReplicaMovementStrategy, second: ReplicaMovementStrategy):
        self._first = first
        self._second = second

    def sort_key(self, task, urp=None):
        k1 = self._first.sort_key(task, urp)
        k2 = self._second.sort_key(task, urp)
        k1 = k1 if isinstance(k1, tuple) else (k1,)
        k2 = k2 if isinstance(k2, tuple) else (k2,)
        return k1 + k2


class BaseReplicaMovementStrategy(ReplicaMovementStrategy):
    """Execution-id order (cc/executor/strategy/BaseReplicaMovementStrategy.java:15)."""

    def sort_key(self, task, urp=None):
        return (task.execution_id,)


class PrioritizeLargeReplicaMovementStrategy(ReplicaMovementStrategy):
    """Biggest data first, so the long pole starts immediately."""

    def sort_key(self, task, urp=None):
        return (-task.proposal.data_to_move_mb,)


class PrioritizeSmallReplicaMovementStrategy(ReplicaMovementStrategy):
    """Smallest data first, so many moves finish early."""

    def sort_key(self, task, urp=None):
        return (task.proposal.data_to_move_mb,)


class PostponeUrpReplicaMovementStrategy(ReplicaMovementStrategy):
    """Move replicas of currently under-replicated partitions first (their
    data is at risk), postponing healthy partitions — the semantics of
    cc/executor/strategy/PostponeUrpReplicaMovementStrategy (healthy sorts
    after URP)."""

    def sort_key(self, task, urp=None):
        is_urp = urp is not None and task.proposal.partition in urp
        return (0 if is_urp else 1,)
