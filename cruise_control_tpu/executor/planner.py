"""Execution task planner.

Analog of ExecutionTaskPlanner (cc/executor/ExecutionTaskPlanner.java:48):
turns proposals into tasks (skipping no-ops against the current cluster
state), orders each broker's replica movements through the strategy chain,
and hands out executable batches respecting per-broker in-flight limits.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.strategy import (
    BaseReplicaMovementStrategy,
    ReplicaMovementStrategy,
)
from cruise_control_tpu.executor.task import ExecutionTask, TaskState, TaskType


class ExecutionTaskPlanner:
    def __init__(self, default_strategy: Optional[ReplicaMovementStrategy] = None):
        import time

        self._strategy = default_strategy or BaseReplicaMovementStrategy()
        # ids are epoch-seeded so they are unique ACROSS process restarts:
        # external drivers (ReassignmentJournalDriver) key completion acks by
        # execution id on shared storage, and a restarted process reusing id
        # 0 could be spuriously "completed" by an ack written for its
        # predecessor. Microsecond granularity: supervisors restart within
        # the same second, which a seconds-based seed would collide on.
        self._execution_id = time.time_ns() // 1_000
        self._remaining_moves: List[ExecutionTask] = []
        self._remaining_leaderships: List[ExecutionTask] = []

    def add_execution_proposals(
        self,
        proposals: Sequence[ExecutionProposal],
        current_assignment=None,
        strategy: Optional[ReplicaMovementStrategy] = None,
        urp: Optional[Set[int]] = None,
        provenance_run: Optional[str] = None,
    ) -> None:
        """Register proposals, dropping no-ops against `current_assignment`
        (a dict partition -> tuple of current replicas, or None to trust the
        proposals' old state). `provenance_run` is the MoveLedger run id the
        batch was computed under; each task is stamped with its proposal's
        provenance id (`<run>/p<partition>`) so terminal events and drift
        trims join back to GET /explain."""
        def pid(p: ExecutionProposal) -> str:
            return f"{provenance_run}/p{p.partition}" if provenance_run else ""

        for p in proposals:
            current = (
                tuple(current_assignment[p.partition])
                if current_assignment is not None and p.partition in current_assignment
                else p.old_replicas
            )
            if p.has_replica_action and not p.is_completed(current):
                self._remaining_moves.append(
                    ExecutionTask(
                        self._next_id(), p, TaskType.INTER_BROKER_REPLICA_ACTION,
                        provenance_id=pid(p),
                    )
                )
            elif p.has_leader_action and (not current or current[0] != p.new_leader):
                self._remaining_leaderships.append(
                    ExecutionTask(
                        self._next_id(), p, TaskType.LEADER_ACTION,
                        provenance_id=pid(p),
                    )
                )
        use = strategy or self._strategy
        self._remaining_moves = use.apply(self._remaining_moves, urp)

    def _next_id(self) -> int:
        i = self._execution_id
        self._execution_id += 1
        return i

    @property
    def remaining_inter_broker_replica_movements(self) -> List[ExecutionTask]:
        return [t for t in self._remaining_moves if t.state == TaskState.PENDING]

    @property
    def remaining_leadership_movements(self) -> List[ExecutionTask]:
        return [t for t in self._remaining_leaderships if t.state == TaskState.PENDING]

    def get_inter_broker_replica_movement_tasks(
        self, available_slots_by_broker: Dict[int, int], max_tasks: int = 1 << 30
    ) -> List[ExecutionTask]:
        """Drain pending movement tasks whose involved brokers all have
        in-flight budget (ExecutionTaskPlanner.getInterBrokerReplicaMovementTasks).
        Mutates the passed availability map as it assigns."""
        out: List[ExecutionTask] = []
        for task in self._remaining_moves:
            if len(out) >= max_tasks:
                break
            if task.state != TaskState.PENDING:
                continue
            brokers = task.involved_brokers
            if all(available_slots_by_broker.get(b, 0) > 0 for b in brokers):
                for b in brokers:
                    available_slots_by_broker[b] -= 1
                out.append(task)
        return out

    def get_leadership_movement_tasks(self, max_tasks: int) -> List[ExecutionTask]:
        out = []
        for task in self._remaining_leaderships:
            if len(out) >= max_tasks:
                break
            if task.state == TaskState.PENDING:
                out.append(task)
        return out

    def clear(self) -> None:
        self._remaining_moves.clear()
        self._remaining_leaderships.clear()
