"""In-flight task bookkeeping with per-broker concurrency caps.

Analog of ExecutionTaskManager (cc/executor/ExecutionTaskManager.java):
enforces `num.concurrent.partition.movements.per.broker` and the global
leadership-movement batch size, and feeds state counts to the tracker.
"""

from __future__ import annotations

from typing import Dict, List, Set

from cruise_control_tpu.executor.task import ExecutionTask, TaskState, TaskType
from cruise_control_tpu.executor.tracker import ExecutionTaskTracker


class ExecutionTaskManager:
    def __init__(
        self,
        concurrent_partition_movements_per_broker: int = 10,
        max_leadership_movements: int = 1000,
    ):
        self._per_broker_cap = concurrent_partition_movements_per_broker
        self._leadership_cap = max_leadership_movements
        self._in_flight_by_broker: Dict[int, int] = {}
        self._in_flight: List[ExecutionTask] = []
        self.tracker = ExecutionTaskTracker()

    def set_concurrency(self, per_broker: int = None, leadership: int = None) -> None:
        """Dynamic throttle adjustment (Executor setters :356-372)."""
        if per_broker is not None:
            self._per_broker_cap = per_broker
        if leadership is not None:
            self._leadership_cap = leadership

    @property
    def leadership_cap(self) -> int:
        return self._leadership_cap

    def available_slots(self, brokers) -> Dict[int, int]:
        return {
            b: max(0, self._per_broker_cap - self._in_flight_by_broker.get(b, 0))
            for b in brokers
        }

    def mark_in_progress(self, tasks: List[ExecutionTask], now_ms: int = 0) -> None:
        for t in tasks:
            t.in_progress(now_ms)
            self._in_flight.append(t)
            if t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION:
                for b in t.involved_brokers:
                    self._in_flight_by_broker[b] = self._in_flight_by_broker.get(b, 0) + 1
            self.tracker.observe(t)

    def mark_done(self, task: ExecutionTask) -> None:
        """Call after the task reached a terminal state."""
        if task in self._in_flight:
            self._in_flight.remove(task)
            if task.task_type == TaskType.INTER_BROKER_REPLICA_ACTION:
                for b in task.involved_brokers:
                    self._in_flight_by_broker[b] = max(0, self._in_flight_by_broker.get(b, 0) - 1)
        self.tracker.observe(task)

    @property
    def in_flight_tasks(self) -> List[ExecutionTask]:
        return list(self._in_flight)
