"""Executor notification SPI.

Analog of ExecutorNotifier (cc/executor/ExecutorNotifier.java) and the
OPERATION_LOG audit logger (cc/executor/Executor.java): execution lifecycle
events (started / finished / stopped / task state changes) flow to a
pluggable sink. The Executor accepts any callable(event, info); these classes
are the config-instantiable implementations
(`executor.notifier.class`)."""

from __future__ import annotations

import logging
from typing import Dict

OPERATION_LOG = logging.getLogger("cruise_control_tpu.operation")


class ExecutorNotifier:
    """SPI: receives (event name, detail dict) per execution event."""

    def __call__(self, event: str, info: Dict) -> None:
        raise NotImplementedError

    def configure(self, configs: Dict) -> None:  # pluggable-component hook
        pass


class LoggingExecutorNotifier(ExecutorNotifier):
    """Default sink: the operation audit log."""

    def __call__(self, event: str, info: Dict) -> None:
        OPERATION_LOG.info("executor %s: %s", event, info)


class NoopExecutorNotifier(ExecutorNotifier):
    def __call__(self, event: str, info: Dict) -> None:
        pass
