"""Execution task state machine.

Analog of ExecutionTask (cc/executor/ExecutionTask.java:41):

    PENDING --> IN_PROGRESS --> COMPLETED
                     |--> ABORTING --> ABORTED
                     |--> ABORTING --> DEAD
                     |--> DEAD

with the same valid-transition table (:55-60).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Optional

from cruise_control_tpu.analyzer.proposals import ExecutionProposal


class TaskType(enum.IntEnum):
    INTER_BROKER_REPLICA_ACTION = 0
    LEADER_ACTION = 1


class TaskState(enum.IntEnum):
    PENDING = 0
    IN_PROGRESS = 1
    ABORTING = 2
    ABORTED = 3
    DEAD = 4
    COMPLETED = 5


_VALID_TRANSFER = {
    TaskState.PENDING: {TaskState.IN_PROGRESS},
    TaskState.IN_PROGRESS: {TaskState.ABORTING, TaskState.DEAD, TaskState.COMPLETED},
    TaskState.ABORTING: {TaskState.ABORTED, TaskState.DEAD},
    TaskState.COMPLETED: set(),
    TaskState.DEAD: set(),
    TaskState.ABORTED: set(),
}


#: terminal states always carry an end_time_ms and fire the task's listener
TERMINAL_STATES = frozenset({TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD})


@dataclasses.dataclass
class ExecutionTask:
    execution_id: int
    proposal: ExecutionProposal
    task_type: TaskType
    state: TaskState = TaskState.PENDING
    start_time_ms: Optional[int] = None
    end_time_ms: Optional[int] = None
    #: why the task reached a terminal state ("", "deadline", "dispatch
    #: failure: ...", "driver unreachable", ...) — failure attribution for
    #: the execution summary and op_log
    terminal_reason: str = ""
    #: decision-provenance join key (`<ledger run id>/p<partition>`): which
    #: recorded optimization decision this task executes — carried into
    #: terminal events and drift-trim records so GET /explain answers both
    #: "why was this proposed" and "what happened to it". Empty when the
    #: batch had no recorded ledger.
    provenance_id: str = ""
    #: invoked once, with the task, when it enters a terminal state; the
    #: executor wires this to its ExecutorNotifier + tracker
    listener: Optional[Callable[["ExecutionTask"], None]] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def _transfer(self, target: TaskState) -> None:
        if target not in _VALID_TRANSFER[self.state]:
            raise ValueError(f"illegal transition {self.state.name} -> {target.name}")
        self.state = target
        if target in TERMINAL_STATES and self.listener is not None:
            self.listener(self)

    def in_progress(self, now_ms: int = 0) -> None:
        self.start_time_ms = now_ms
        self._transfer(TaskState.IN_PROGRESS)

    def completed(self, now_ms: int = 0) -> None:
        self.end_time_ms = now_ms
        self._transfer(TaskState.COMPLETED)

    def abort(self, reason: str = "") -> None:
        if reason:
            self.terminal_reason = reason
        self._transfer(TaskState.ABORTING)

    def aborted(self, now_ms: int = 0, reason: str = "") -> None:
        self.end_time_ms = now_ms
        if reason:
            self.terminal_reason = reason
        self._transfer(TaskState.ABORTED)

    def kill(self, now_ms: int = 0, reason: str = "") -> None:
        self.end_time_ms = now_ms
        if reason:
            self.terminal_reason = reason
        self._transfer(TaskState.DEAD)

    @property
    def done(self) -> bool:
        return self.state in (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD)

    #: brokers whose in-flight budget this task consumes (source + destination)
    @property
    def involved_brokers(self):
        p = self.proposal
        if self.task_type == TaskType.LEADER_ACTION:
            return {p.old_leader, p.new_leader}
        return set(p.replicas_to_add) | set(p.replicas_to_remove)
