"""Execution task state machine.

Analog of ExecutionTask (cc/executor/ExecutionTask.java:41):

    PENDING --> IN_PROGRESS --> COMPLETED
                     |--> ABORTING --> ABORTED
                     |--> ABORTING --> DEAD
                     |--> DEAD

with the same valid-transition table (:55-60).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from cruise_control_tpu.analyzer.proposals import ExecutionProposal


class TaskType(enum.IntEnum):
    INTER_BROKER_REPLICA_ACTION = 0
    LEADER_ACTION = 1


class TaskState(enum.IntEnum):
    PENDING = 0
    IN_PROGRESS = 1
    ABORTING = 2
    ABORTED = 3
    DEAD = 4
    COMPLETED = 5


_VALID_TRANSFER = {
    TaskState.PENDING: {TaskState.IN_PROGRESS},
    TaskState.IN_PROGRESS: {TaskState.ABORTING, TaskState.DEAD, TaskState.COMPLETED},
    TaskState.ABORTING: {TaskState.ABORTED, TaskState.DEAD},
    TaskState.COMPLETED: set(),
    TaskState.DEAD: set(),
    TaskState.ABORTED: set(),
}


@dataclasses.dataclass
class ExecutionTask:
    execution_id: int
    proposal: ExecutionProposal
    task_type: TaskType
    state: TaskState = TaskState.PENDING
    start_time_ms: Optional[int] = None
    end_time_ms: Optional[int] = None

    def _transfer(self, target: TaskState) -> None:
        if target not in _VALID_TRANSFER[self.state]:
            raise ValueError(f"illegal transition {self.state.name} -> {target.name}")
        self.state = target

    def in_progress(self, now_ms: int = 0) -> None:
        self._transfer(TaskState.IN_PROGRESS)
        self.start_time_ms = now_ms

    def completed(self, now_ms: int = 0) -> None:
        self._transfer(TaskState.COMPLETED)
        self.end_time_ms = now_ms

    def abort(self) -> None:
        self._transfer(TaskState.ABORTING)

    def aborted(self, now_ms: int = 0) -> None:
        self._transfer(TaskState.ABORTED)
        self.end_time_ms = now_ms

    def kill(self, now_ms: int = 0) -> None:
        self._transfer(TaskState.DEAD)
        self.end_time_ms = now_ms

    @property
    def done(self) -> bool:
        return self.state in (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD)

    #: brokers whose in-flight budget this task consumes (source + destination)
    @property
    def involved_brokers(self):
        p = self.proposal
        if self.task_type == TaskType.LEADER_ACTION:
            return {p.old_leader, p.new_leader}
        return set(p.replicas_to_add) | set(p.replicas_to_remove)
