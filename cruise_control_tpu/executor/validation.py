"""Proposal drift safety: generation stamps, topology fingerprints, and
pre-dispatch revalidation.

A proposal batch is computed against monitor generation N and a topology
snapshot; nothing in the reference protects the window between model build
and dispatch — brokers can die, topics can vanish, replicas can move at
generation N+k and the executor would actuate the stale plan blindly.
Stream-reconfiguration work treats reconfiguration as continuous rather than
episodic (PAPERS.md, arxiv 1602.03770); this module gives the executor the
tools to treat every batch boundary as a revalidation point:

  * `TopologyFingerprint` — a compact structural digest (broker set + alive
    mask + per-topic partition counts) stamped onto every `OptimizerResult`
    at model-build time by the facade;
  * `validate_proposal` / `validate_proposals` — per-proposal checks of a
    stamped plan against FRESH `ClusterTopology`: the partition must still
    exist and still mean the same topic-partition, destinations must be
    alive and in range, the replica set must still match the plan's view,
    and the replication factor must be unchanged. Invalid proposals are
    *trimmed* with a reason code, never dispatched and never raised
    (docs/RESILIENCE.md never-raise contract).

Reason codes (the `trimmedByReason` vocabulary in the execution summary,
`/state`, and the `Executor.proposal-trimmed.*` meters):

  TOPIC_GONE          the proposal's topic no longer has any partitions
  PARTITION_GONE      the dense partition index is out of range / the
                      topic's partition index vanished
  PARTITION_REMAPPED  the dense index now addresses a DIFFERENT
                      topic-partition (rows shifted under the plan)
  DEST_INVALID        a destination broker index is out of range
  DEST_DEAD           a destination broker (added replica or new leader)
                      is dead
  RF_CHANGED          the partition's replication factor changed since the
                      plan was built
  REPLICA_MOVED       the current replica set no longer matches the plan's
                      old set (a concurrent reassignment won)
  GENERATION_SKEW     batch-level: monitor generation drifted past
                      `executor.proposal.max.generation.skew`; the whole
                      batch aborts and the detector is asked to recompute
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.common.resources import BrokerState

# -- reason codes --------------------------------------------------------------

TOPIC_GONE = "TOPIC_GONE"
PARTITION_GONE = "PARTITION_GONE"
PARTITION_REMAPPED = "PARTITION_REMAPPED"
DEST_INVALID = "DEST_INVALID"
DEST_DEAD = "DEST_DEAD"
RF_CHANGED = "RF_CHANGED"
REPLICA_MOVED = "REPLICA_MOVED"
GENERATION_SKEW = "GENERATION_SKEW"

REASON_CODES = (
    TOPIC_GONE, PARTITION_GONE, PARTITION_REMAPPED, DEST_INVALID,
    DEST_DEAD, RF_CHANGED, REPLICA_MOVED, GENERATION_SKEW,
)


# -- topology fingerprint ------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TopologyFingerprint:
    """Compact structural snapshot of the cluster at model-build time.

    Deliberately load-free: a fingerprint changes exactly when something a
    proposal references can have changed meaning — the broker set, broker
    liveness, or the per-topic partition layout. Load drift is the
    optimizer's business, not admission's."""

    num_brokers: int
    #: per-broker liveness (True = not DEAD); index-aligned with the model
    alive: Tuple[bool, ...]
    #: (topic name, partition count), sorted by name; topics with zero
    #: partitions are absent (a deleted topic drops out)
    topic_partitions: Tuple[Tuple[str, int], ...]

    @classmethod
    def from_topology(cls, topo) -> "TopologyFingerprint":
        """Build from a monitor.metadata.ClusterTopology."""
        state = np.asarray(topo.broker_state)
        tids, counts = np.unique(np.asarray(topo.topic_id), return_counts=True)
        tp = tuple(sorted(
            (topo.topic_names[int(t)], int(c)) for t, c in zip(tids, counts)
        ))
        return cls(
            num_brokers=int(state.shape[0]),
            alive=tuple((state != BrokerState.DEAD).tolist()),
            topic_partitions=tp,
        )

    @property
    def num_alive(self) -> int:
        return sum(self.alive)

    @property
    def num_partitions(self) -> int:
        return sum(c for _, c in self.topic_partitions)

    @property
    def digest(self) -> str:
        """Stable short hex digest for logs/summaries."""
        h = hashlib.sha1(repr(
            (self.num_brokers, self.alive, self.topic_partitions)
        ).encode())
        return h.hexdigest()[:12]

    def diff(self, other: "TopologyFingerprint") -> Dict:
        """Human-attributable drift summary (self = at build, other = now)."""
        before = dict(self.topic_partitions)
        after = dict(other.topic_partitions)
        died = [
            i for i in range(min(self.num_brokers, other.num_brokers))
            if self.alive[i] and not other.alive[i]
        ]
        revived = [
            i for i in range(min(self.num_brokers, other.num_brokers))
            if not self.alive[i] and other.alive[i]
        ]
        return {
            "brokerCountDelta": other.num_brokers - self.num_brokers,
            "brokersDied": died,
            "brokersRevived": revived,
            "topicsGone": sorted(set(before) - set(after)),
            "topicsAdded": sorted(set(after) - set(before)),
            "partitionCountChanged": sorted(
                t for t in set(before) & set(after) if before[t] != after[t]
            ),
        }

    def to_dict(self) -> Dict:
        return {
            "digest": self.digest,
            "numBrokers": self.num_brokers,
            "numAlive": self.num_alive,
            "numPartitions": self.num_partitions,
            "numTopics": len(self.topic_partitions),
        }


# -- fresh-topology view -------------------------------------------------------


class TopologyView:
    """Lookup-friendly wrapper over one fresh ClusterTopology snapshot.

    Built once per revalidation round and consulted per proposal. The fast
    path is O(T) to build (per-topic partition counts via one bincount) and
    O(1) per proposal; the O(P) name scan runs only on the error path (a
    proposal whose dense row shifted), so revalidating a batch stays a
    rounding error next to one driver dispatch even at 200k partitions."""

    def __init__(self, topo):
        self._topo = topo
        self._assignment = np.asarray(topo.assignment)
        self._state = np.asarray(topo.broker_state)
        self.num_brokers = int(self._state.shape[0])
        self.num_partitions = int(self._assignment.shape[0])
        self._topic_id = np.asarray(topo.topic_id)
        self._pindex = np.asarray(topo.partition_index)
        self._names = topo.topic_names
        self._topic_index: Dict[str, int] = {
            n: i for i, n in enumerate(self._names)
        }
        counts = (
            np.bincount(self._topic_id, minlength=len(self._names))
            if self.num_partitions else np.zeros(len(self._names), dtype=np.int64)
        )
        #: topic name -> partition count; topics with zero partitions absent
        self.partitions_of_topic: Dict[str, int] = {
            n: int(counts[i]) for i, n in enumerate(self._names) if counts[i]
        }

    def replicas(self, row: int) -> Tuple[int, ...]:
        return tuple(int(b) for b in self._assignment[row] if b >= 0)

    def broker_dead(self, b: int) -> bool:
        return bool(self._state[b] == BrokerState.DEAD)

    def name_of(self, row: int) -> str:
        """'topic-partitionIndex' rendering of a dense row."""
        return f"{self._names[int(self._topic_id[row])]}-{int(self._pindex[row])}"

    def row_of(self, name: str) -> Optional[int]:
        """Dense row of a topic-partition name in THIS snapshot, or None.
        Vectorized O(P) scan — error/remap path only, never the batch loop."""
        topic, _, pi = name.rpartition("-")
        t = self._topic_index.get(topic)
        if t is None or not pi.isdigit():
            return None
        hits = np.nonzero((self._topic_id == t) & (self._pindex == int(pi)))[0]
        return int(hits[0]) if hits.size else None

    def items(self):
        """Iterate (topic-partition name, dense row) pairs of this snapshot."""
        return ((self.name_of(r), r) for r in range(self.num_partitions))

    def resolve(self, p: ExecutionProposal) -> Tuple[Optional[int], Optional[str]]:
        """-> (dense row the DRIVER would address, reason code or None).

        Drivers address partitions by the proposal's dense index, so the
        check anchors there; the topic-partition name (when stamped) is the
        identity cross-check that catches rows shifting underneath the plan
        (e.g. a topic deleted mid-batch renumbers everything after it)."""
        if p.topic_partition is not None:
            topic, _, _ = p.topic_partition.rpartition("-")
            if topic and topic not in self.partitions_of_topic:
                return None, TOPIC_GONE
            if (
                p.partition >= self.num_partitions
                or self.name_of(p.partition) != p.topic_partition
            ):
                # the named partition may survive at another row, but the
                # executor's dense addressing is stale either way
                if self.row_of(p.topic_partition) is None:
                    return None, PARTITION_GONE
                return None, PARTITION_REMAPPED
            return p.partition, None
        if p.partition >= self.num_partitions:
            return None, PARTITION_GONE
        return p.partition, None


def validate_proposal(p: ExecutionProposal, view: TopologyView) -> Optional[str]:
    """Reason code if the proposal must be trimmed, None when still valid."""
    row, err = view.resolve(p)
    if err is not None:
        return err
    for b in p.replicas_to_add:
        if b < 0 or b >= view.num_brokers:
            return DEST_INVALID
        if view.broker_dead(b):
            return DEST_DEAD
    current = view.replicas(row)
    if p.has_replica_action:
        if len(current) != len(p.old_replicas):
            return RF_CHANGED
        if set(current) != set(p.old_replicas):
            return REPLICA_MOVED
    else:  # leadership-only movement
        if p.new_leader not in current:
            return REPLICA_MOVED
    if p.new_leader >= view.num_brokers:
        return DEST_INVALID
    if p.new_leader >= 0 and view.broker_dead(p.new_leader):
        return DEST_DEAD
    return None


def validate_proposals(
    proposals, topo
) -> Tuple[List[ExecutionProposal], List[Tuple[ExecutionProposal, str]]]:
    """Split proposals into (still valid, [(stale, reason), ...]) against a
    fresh topology snapshot."""
    view = TopologyView(topo)
    valid: List[ExecutionProposal] = []
    trimmed: List[Tuple[ExecutionProposal, str]] = []
    for p in proposals:
        reason = validate_proposal(p, view)
        if reason is None:
            valid.append(p)
        else:
            trimmed.append((p, reason))
    return valid, trimmed
