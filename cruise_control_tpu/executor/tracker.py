"""Task-state aggregation for observability.

Analog of ExecutionTaskTracker (cc/executor/ExecutionTaskTracker.java):
counts by (type, state) for the /state endpoint and sensors, plus a
per-execution terminal-event log (executionId, state, start/end times,
reason) so the summary and op_log can attribute WHICH tasks died and why.

Thread-safety: the executor's poll loop mutates this tracker while REST
server threads render `/state` from it, so all aggregate state is guarded
by the tracker's own lock (the `#: guarded_by(_lock)` contract is enforced
by cclint's `conc-guarded-by` rule — docs/LINTING.md)."""

from __future__ import annotations

import threading
from typing import Dict, List

from cruise_control_tpu.executor.task import ExecutionTask, TaskState, TaskType

#: terminal events kept per execution (ABORTED/DEAD first, so failures are
#: never truncated away by a large completed count)
_MAX_TERMINAL_EVENTS = 200


class ExecutionTaskTracker:
    def __init__(self):
        self._lock = threading.Lock()
        self._latest: Dict[int, ExecutionTask] = {}  #: guarded_by(_lock)
        self._terminal_events: List[Dict] = []  #: guarded_by(_lock)

    def observe(self, task: ExecutionTask) -> None:
        with self._lock:
            self._latest[task.execution_id] = task

    def record_terminal(self, task: ExecutionTask) -> None:
        """One terminal transition (COMPLETED/ABORTED/DEAD), with timing and
        reason — wired from the ExecutionTask listener."""
        with self._lock:
            self._latest[task.execution_id] = task
            if len(self._terminal_events) < _MAX_TERMINAL_EVENTS:
                self._terminal_events.append({
                    "executionId": task.execution_id,
                    "type": task.task_type.name,
                    "state": task.state.name,
                    "startTimeMs": task.start_time_ms,
                    "endTimeMs": task.end_time_ms,
                    "reason": task.terminal_reason,
                    # GET /explain join key (empty when the batch carried no
                    # recorded decision ledger)
                    "provenanceId": task.provenance_id,
                })

    def terminal_events(self, only_failures: bool = False) -> List[Dict]:
        with self._lock:
            events = list(self._terminal_events)
        if only_failures:
            return [e for e in events if e["state"] != TaskState.COMPLETED.name]
        return events

    def reset(self) -> None:
        """Drop prior-execution tasks (summaries are per execution; without
        this, a long-lived service accumulates every task ever run)."""
        with self._lock:
            self._latest.clear()
            self._terminal_events.clear()

    def counts(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            tasks = list(self._latest.values())
        out = {
            t.name: {s.name: 0 for s in TaskState} for t in TaskType
        }
        for task in tasks:
            out[task.task_type.name][task.state.name] += 1
        return out

    def summary(self) -> Dict:
        c = self.counts()
        by_state = {
            s.name: sum(v[s.name] for v in c.values()) for s in TaskState
        }
        return {
            "numTotalMovements": sum(sum(v.values()) for v in c.values()),
            "numFinishedMovements": sum(
                v[TaskState.COMPLETED.name] + v[TaskState.ABORTED.name] + v[TaskState.DEAD.name]
                for v in c.values()
            ),
            "numInProgressMovements": sum(v[TaskState.IN_PROGRESS.name] for v in c.values()),
            "numAbortedOrDead": sum(
                v[TaskState.ABORTED.name] + v[TaskState.DEAD.name] for v in c.values()
            ),
            "byState": by_state,
        }
