"""Task-state aggregation for observability.

Analog of ExecutionTaskTracker (cc/executor/ExecutionTaskTracker.java):
counts by (type, state) for the /state endpoint and sensors."""

from __future__ import annotations

from typing import Dict

from cruise_control_tpu.executor.task import ExecutionTask, TaskState, TaskType


class ExecutionTaskTracker:
    def __init__(self):
        self._latest: Dict[int, ExecutionTask] = {}

    def observe(self, task: ExecutionTask) -> None:
        self._latest[task.execution_id] = task

    def reset(self) -> None:
        """Drop prior-execution tasks (summaries are per execution; without
        this, a long-lived service accumulates every task ever run)."""
        self._latest.clear()

    def counts(self) -> Dict[str, Dict[str, int]]:
        out = {
            t.name: {s.name: 0 for s in TaskState} for t in TaskType
        }
        for task in self._latest.values():
            out[task.task_type.name][task.state.name] += 1
        return out

    def summary(self) -> Dict[str, int]:
        c = self.counts()
        return {
            "numTotalMovements": sum(sum(v.values()) for v in c.values()),
            "numFinishedMovements": sum(
                v[TaskState.COMPLETED.name] + v[TaskState.ABORTED.name] + v[TaskState.DEAD.name]
                for v in c.values()
            ),
            "numInProgressMovements": sum(v[TaskState.IN_PROGRESS.name] for v in c.values()),
            "numAbortedOrDead": sum(
                v[TaskState.ABORTED.name] + v[TaskState.DEAD.name] for v in c.values()
            ),
        }
