"""The Executor: proposal execution lifecycle.

Analog of cc/executor/Executor.java:58. `execute_proposals` (:288) registers
tasks and runs the execution loop (ProposalExecutionRunnable.execute
:546-626): pause metric sampling, drive inter-broker replica movements in
throttled batches through the ClusterDriver, then leadership movements, poll
until finished, resume sampling. Supports dynamic concurrency changes,
user-triggered graceful stop (:433), an ExecutorNotifier hook, and the
recently-removed/demoted broker history (:234-267).

Resilience contract (docs/RESILIENCE.md): once an execution has started,
`execute_proposals` never raises and never leaves a task in a non-terminal
state. A dispatch failure kills only the failed task (the already-dispatched
remainder keeps draining); a task that outlives `task_deadline_s` is aborted
through the real state machine (IN_PROGRESS → ABORTING → ABORTED) and the
batch continues; a driver that fails `max_consecutive_driver_failures` poll
rounds in a row is declared unreachable and every in-flight task dies. The
returned summary carries per-state counts plus the terminal-event log for
failure attribution.

Drift safety (executor/validation.py): a proposal batch stamped with the
monitor generation and a topology fingerprint is revalidated against FRESH
metadata at admission and again before every dispatch batch. Stale proposals
are trimmed with per-proposal reason codes into the summary's
`proposalValidation` block instead of being dispatched (or raising); when
the monitor generation has drifted past `executor.proposal.max.generation.skew`
the whole batch aborts through the same never-raise contract and the drift
listener (wired by the anomaly detector) is asked to recompute."""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.driver import ClusterDriver
from cruise_control_tpu.executor.manager import ExecutionTaskManager
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.strategy import ReplicaMovementStrategy
from cruise_control_tpu.executor.task import ExecutionTask, TaskState, TaskType
from cruise_control_tpu.executor.validation import (
    GENERATION_SKEW,
    TopologyFingerprint,
    TopologyView,
    validate_proposal,
)


@dataclasses.dataclass(frozen=True)
class ExecutorConfig:
    """Defaults mirror config/cruisecontrol.properties."""

    num_concurrent_partition_movements_per_broker: int = 10
    num_concurrent_leader_movements: int = 1000
    execution_progress_check_interval_s: float = 0.01
    max_execution_polls: int = 100_000
    #: how long removed/demoted broker ids stay in history
    removal_history_retention_s: float = 3600.0
    #: per-task wall-clock deadline (`executor.task.deadline.s`): a task
    #: IN_PROGRESS longer than this is aborted (→ ABORTING → ABORTED) and
    #: its broker slots released; 0 disables (the poll cap still bounds the
    #: whole phase)
    task_deadline_s: float = 0.0
    #: consecutive failed driver poll rounds before the driver is declared
    #: unreachable and every in-flight task is killed DEAD
    max_consecutive_driver_failures: int = 10
    #: `executor.proposal.revalidate`: revalidate stamped proposals against
    #: fresh metadata at admission and before every dispatch batch, trimming
    #: stale ones with reason codes instead of dispatching them
    proposal_revalidate: bool = True
    #: `executor.proposal.max.generation.skew`: abort the whole batch (and
    #: ask the detector to recompute) when the monitor generation has moved
    #: more than this past the batch's stamp; 0 disables the abort
    max_generation_skew: int = 8

    @classmethod
    def from_config(cls, config) -> "ExecutorConfig":
        """Map `executor.*` / `num.concurrent.*` keys (config/cruise_config.py)."""
        return cls(
            num_concurrent_partition_movements_per_broker=config.get_int(
                "num.concurrent.partition.movements.per.broker"
            ),
            num_concurrent_leader_movements=config.get_int(
                "num.concurrent.leader.movements"
            ),
            execution_progress_check_interval_s=config.get_long(
                "execution.progress.check.interval.ms"
            ) / 1000.0,
            removal_history_retention_s=config.get_long(
                "removed.broker.history.retention.ms"
            ) / 1000.0,
            task_deadline_s=config.get_double("executor.task.deadline.s"),
            proposal_revalidate=config.get_boolean("executor.proposal.revalidate"),
            max_generation_skew=config.get_int(
                "executor.proposal.max.generation.skew"
            ),
        )


class ExecutorState:
    NO_TASK_IN_PROGRESS = "NO_TASK_IN_PROGRESS"
    STARTING_EXECUTION = "STARTING_EXECUTION"
    INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = "INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    LEADER_MOVEMENT_TASK_IN_PROGRESS = "LEADER_MOVEMENT_TASK_IN_PROGRESS"
    STOPPING_EXECUTION = "STOPPING_EXECUTION"


class ExecutionStoppedException(Exception):
    pass


class Executor:
    def __init__(
        self,
        driver: ClusterDriver,
        config: ExecutorConfig = ExecutorConfig(),
        load_monitor=None,
        notifier: Optional[Callable[[str, Dict], None]] = None,
        clock: Callable[[], float] = time.time,
        topology_source: Optional[Callable[[], object]] = None,
        generation_source: Optional[Callable[[], int]] = None,
    ):
        """`topology_source`: returns a FRESH monitor.metadata.ClusterTopology
        for proposal revalidation (defaults to a forced metadata refresh
        through `load_monitor` when one is given); `generation_source`:
        returns the current monitor generation for the skew check (defaults
        to `load_monitor.generation`)."""
        self._driver = driver
        self._config = config
        self._monitor = load_monitor
        self._notifier = notifier or (lambda event, info: None)
        self._clock = clock
        self._lock = threading.RLock()
        self._state = ExecutorState.NO_TASK_IN_PROGRESS
        self._stop_requested = threading.Event()
        self._manager = ExecutionTaskManager(
            config.num_concurrent_partition_movements_per_broker,
            config.num_concurrent_leader_movements,
        )
        self._planner = ExecutionTaskPlanner()
        self._removed_brokers: Dict[int, float] = {}
        self._demoted_brokers: Dict[int, float] = {}
        #: consecutive failed driver poll rounds (reset on success)
        self._driver_failures = 0
        if topology_source is None and load_monitor is not None:
            metadata = getattr(load_monitor, "_metadata", None)
            if metadata is not None:
                topology_source = lambda: metadata.refresh_metadata(force=True)
                if generation_source is None:
                    # sampling is paused during execution, so nothing else
                    # refreshes metadata: the generation probe must force a
                    # refresh or drift would go unseen until resume
                    def generation_source(_metadata=metadata, _mon=load_monitor):
                        _metadata.refresh_metadata(force=True)
                        return _mon.generation
        self._topology_source = topology_source
        self._generation_source = generation_source
        #: generation of the last FULL per-proposal validation pass; while it
        #: matches the current generation, batch boundaries can skip the
        #: per-task rechecks (unchanged generation ⟹ unchanged topology ⟹
        #: identical validation outcome) — the <2% overhead contract
        self._validated_gen: Optional[int] = None
        #: skew accounting across one execution (see _skew_exceeded)
        self._skew_base = 0
        self._structural_steps = 0
        self._last_structural_fp: Optional[TopologyFingerprint] = None
        #: called with a drift-abort info dict when a batch aborts for
        #: generation skew; the anomaly detector wires itself here so a
        #: recompute rides the normal self-healing path
        self._drift_listener: Optional[Callable[[Dict], None]] = None
        #: the current/last execution's proposalValidation record (/state)
        self._validation: Dict = {}
        #: (generation, TopologyView) from the last revalidation round
        self._reval_cache: Optional[tuple] = None
        self._register_skew_gauge()

    def _register_skew_gauge(self) -> None:
        """`Executor.generation-skew` gauge: last observed build-vs-now
        generation distance (weakref-guarded like the breaker gauge)."""
        import weakref

        from cruise_control_tpu.common.sensors import REGISTRY

        ref = weakref.ref(self)

        def skew():
            ex = ref()
            if ex is None:
                return {}
            v = ex._validation.get("generationSkew")
            return v if v is not None else 0

        REGISTRY.gauge("Executor.generation-skew", skew)

    def set_drift_listener(self, listener: Callable[[Dict], None]) -> None:
        self._drift_listener = listener

    # -- state -----------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def has_ongoing_execution(self) -> bool:
        with self._lock:
            return self._state not in (ExecutorState.NO_TASK_IN_PROGRESS,)

    def state_summary(self) -> Dict:
        return {
            "state": self.state,
            **self._manager.tracker.summary(),
            "recentlyRemovedBrokers": sorted(self.recently_removed_brokers),
            "recentlyDemotedBrokers": sorted(self.recently_demoted_brokers),
            "proposalValidation": dict(self._validation),
        }

    def user_triggered_stop_execution(self) -> None:
        """Graceful stop (Executor.userTriggeredStopExecution :433)."""
        from cruise_control_tpu.common.oplog import op_log

        with self._lock:
            stopping = self._state != ExecutorState.NO_TASK_IN_PROGRESS
            if stopping:
                self._state = ExecutorState.STOPPING_EXECUTION
        if stopping:
            op_log("User requested execution stop")
        self._stop_requested.set()

    def set_concurrency(self, per_broker: int = None, leadership: int = None) -> None:
        self._manager.set_concurrency(per_broker, leadership)

    # -- broker history --------------------------------------------------------

    def _gc_history(self, history: Dict[int, float]) -> None:
        cutoff = self._clock() - self._config.removal_history_retention_s
        for b in [b for b, t in history.items() if t < cutoff]:
            del history[b]

    @property
    def recently_removed_brokers(self) -> Set[int]:
        with self._lock:
            self._gc_history(self._removed_brokers)
            return set(self._removed_brokers)

    @property
    def recently_demoted_brokers(self) -> Set[int]:
        with self._lock:
            self._gc_history(self._demoted_brokers)
            return set(self._demoted_brokers)

    # -- execution -------------------------------------------------------------

    def execute_proposals(
        self,
        proposals: Sequence[ExecutionProposal],
        strategy: Optional[ReplicaMovementStrategy] = None,
        urp: Optional[Set[int]] = None,
        removed_brokers: Optional[Set[int]] = None,
        demoted_brokers: Optional[Set[int]] = None,
        generation: Optional[int] = None,
        fingerprint: Optional[TopologyFingerprint] = None,
        provenance_run: Optional[str] = None,
    ) -> Dict:
        """Synchronous execution loop; the async layer wraps this in an
        OperationFuture thread. Returns the execution summary.

        `generation`/`fingerprint` are the batch's model-build stamps (the
        facade fills them from the OptimizerResult); when given, admission
        and every batch boundary revalidate against them. `provenance_run`
        is the MoveLedger run id the proposals were computed under
        (OptimizerResult.provenance): every task carries its proposal's
        provenance id into terminal events and drift-trim records, so a
        failed or trimmed task joins back to the decision that proposed it
        (GET /explain)."""
        from cruise_control_tpu.common.oplog import op_log as _op_log

        with self._lock:
            if self._state != ExecutorState.NO_TASK_IN_PROGRESS:
                raise RuntimeError("an execution is already in progress")
            try:
                ongoing = self._driver.has_ongoing_reassignment()
            except Exception as e:
                # an unreachable driver cannot veto the start; the dispatch
                # path has its own failure handling (tasks die DEAD there)
                _op_log("Ongoing-reassignment check failed (%r); proceeding", e)
                ongoing = False
            if ongoing:
                raise RuntimeError("ongoing partition reassignment detected; refusing to start")
            self._state = ExecutorState.STARTING_EXECUTION
            self._stop_requested.clear()
            self._driver_failures = 0
            now = self._clock()
            for b in removed_brokers or ():
                self._removed_brokers[b] = now
            for b in demoted_brokers or ():
                self._demoted_brokers[b] = now

        from cruise_control_tpu.common.oplog import op_log
        from cruise_control_tpu.common.sensors import REGISTRY
        from cruise_control_tpu.common.tracing import TRACER

        with TRACER.span(
            "proposal-execution", kind="executor", numProposals=len(proposals)
        ) as span, REGISTRY.histogram("Executor.execution-timer"):
            self._notifier("execution_started", {"numProposals": len(proposals)})
            op_log(
                "Execution started: %d proposal(s), removed=%s demoted=%s",
                len(proposals), sorted(removed_brokers or ()), sorted(demoted_brokers or ()),
            )
            if self._monitor is not None:
                self._monitor.pause_metric_sampling("proposal execution")
            exec_t0 = time.monotonic()
            try:
                self._manager.tracker.reset()  # summaries are per execution
                self._planner.clear()
                self._provenance_run = provenance_run
                try:
                    admitted = self._admit_proposals(proposals, generation, fingerprint)
                    self._planner.add_execution_proposals(
                        admitted, strategy=strategy, urp=urp,
                        provenance_run=provenance_run,
                    )
                    if not self._validation.get("aborted"):
                        self._run_replica_movements()
                        self._run_leadership_movements()
                except Exception as e:
                    # resilience contract: once started, execution never
                    # raises — anything that slipped past the per-task
                    # handling kills the in-flight remainder and falls
                    # through to the summary
                    span.attributes["error"] = f"{type(e).__name__}: {e}"
                    op_log("Execution phase FAILED unexpectedly: %r", e)
                    REGISTRY.meter("Executor.execution-phase-failures").mark()
                    now_ms = int(self._clock() * 1000)
                    for t in self._manager.in_flight_tasks:
                        self._kill_task(t, now_ms, f"execution failure: {e}")
                summary = self._manager.tracker.summary()
                stopped = self._stop_requested.is_set()
                span.attributes["stopped"] = stopped
                span.attributes["byState"] = dict(summary["byState"])
                wall = max(time.monotonic() - exec_t0, 1e-9)
                self._validation["overheadPct"] = round(
                    100.0 * self._validation.get("overheadS", 0.0) / wall, 4
                )
                if self._validation.get("numTrimmed") or self._validation.get("aborted"):
                    span.attributes["proposalValidation"] = {
                        "numTrimmed": self._validation.get("numTrimmed", 0),
                        "aborted": self._validation.get("aborted", False),
                    }
                self._notifier(
                    "execution_stopped" if stopped else "execution_finished", summary
                )
                op_log(
                    "Execution %s: %s",
                    "stopped by user" if stopped else "finished", summary,
                )
                return {
                    **summary,
                    "stopped": stopped,
                    "failedTasks": self._manager.tracker.terminal_events(
                        only_failures=True
                    ),
                    "proposalValidation": dict(self._validation),
                }
            finally:
                if self._monitor is not None:
                    self._monitor.resume_metric_sampling()
                with self._lock:
                    self._state = ExecutorState.NO_TASK_IN_PROGRESS
                # sensor time-series point at the execution boundary
                # (rate-limited; docs/OBSERVABILITY.md history section)
                from cruise_control_tpu.common.history import HISTORY

                HISTORY.record_boundary("execution")

    # -- proposal drift validation ---------------------------------------------

    def _current_generation(self) -> Optional[int]:
        try:
            if self._generation_source is not None:
                return int(self._generation_source())
            if self._monitor is not None:
                return int(self._monitor.generation)
        except Exception:
            return None
        return None

    def _fresh_topology(self):
        """Fresh ClusterTopology for revalidation, or None (a metadata outage
        must never block execution — the batch passes unvalidated and the
        failure is metered)."""
        if self._topology_source is None:
            return None
        try:
            return self._topology_source()
        except Exception as e:
            from cruise_control_tpu.common.oplog import op_log
            from cruise_control_tpu.common.sensors import REGISTRY

            REGISTRY.meter("Executor.revalidation-failures").mark()
            op_log("Revalidation topology fetch FAILED (%r); batch passes unvalidated", e)
            return None

    def _record_trim(self, proposal: ExecutionProposal, reason: str, phase: str) -> None:
        from cruise_control_tpu.common.sensors import REGISTRY

        REGISTRY.meter("Executor.proposal-trimmed").mark()
        REGISTRY.meter(f"Executor.proposal-trimmed.{reason}").mark()
        v = self._validation
        v["numTrimmed"] = v.get("numTrimmed", 0) + 1
        v["trimmedByReason"][reason] = v["trimmedByReason"].get(reason, 0) + 1
        if len(v["trimmed"]) < 200:  # failures are never truncated silently:
            # numTrimmed/trimmedByReason always carry the full tally
            run = getattr(self, "_provenance_run", None)
            v["trimmed"].append({
                "partition": proposal.partition,
                "topicPartition": proposal.topic_partition,
                "reason": reason,
                "phase": phase,
                # GET /explain join key ("" when the batch carried no ledger)
                "provenanceId": f"{run}/p{proposal.partition}" if run else "",
            })

    def _trim_task(self, task: ExecutionTask, reason: str, now_ms: int) -> None:
        """Retire a stale (not yet dispatched) task through the real state
        machine: PENDING → IN_PROGRESS → ABORTING → ABORTED, listener fired,
        tracker/notifier informed — drift trims are attributable terminal
        events, not silently vanished tasks."""
        task.listener = self._on_task_terminal
        try:
            if task.state == TaskState.PENDING:
                task.in_progress(now_ms)
            if task.state == TaskState.IN_PROGRESS:
                task.abort(reason=reason)
            if task.state == TaskState.ABORTING:
                task.aborted(now_ms)
        except ValueError:
            pass  # already terminal (a racing completion won)
        self._manager.mark_done(task)

    def _abort_for_skew(self, skew: int, pending: List[ExecutionTask]) -> None:
        """Generation drifted too far: abort the whole remaining batch (the
        in-flight tasks keep draining — they were validly dispatched) and
        hand the drift listener the recompute request."""
        from cruise_control_tpu.common.oplog import op_log
        from cruise_control_tpu.common.sensors import REGISTRY
        from cruise_control_tpu.common.tracing import TRACER

        v = self._validation
        v["aborted"] = True
        v["abortReason"] = (
            f"generation skew {skew} > {self._config.max_generation_skew}"
        )
        REGISTRY.meter("Executor.batch-aborts").mark()
        now_ms = int(self._clock() * 1000)
        seen = set()
        for t in pending:
            if id(t) in seen:
                continue
            seen.add(id(t))
            self._record_trim(t.proposal, GENERATION_SKEW, phase="batch")
            self._trim_task(t, f"stale proposal: {GENERATION_SKEW}", now_ms)
        info = {
            "reason": GENERATION_SKEW,
            "generationSkew": skew,
            "maxGenerationSkew": self._config.max_generation_skew,
            "generationAtBuild": v.get("generationAtBuild"),
            "fingerprintDrift": v.get("fingerprintDrift"),
            "numAborted": len(seen),
        }
        with TRACER.span("proposal-drift-abort", kind="drift", **{
            k: info[k] for k in ("generationSkew", "numAborted")
        }):
            op_log("Proposal batch ABORTED for drift: %s", info)
            self._notifier("proposal_batch_aborted", info)
            if self._drift_listener is not None:
                try:
                    self._drift_listener(info)
                except Exception as e:
                    op_log("Drift listener failed: %r", e)

    def _skew_exceeded(self, skew: Optional[int]) -> Optional[int]:
        """`skew` back when it exceeds the configured threshold (updating the
        record either way); None when within bounds or unknowable.

        Skew accounting: at admission it is the raw monitor-generation delta
        between model build and execution start — the window the drift layer
        exists for. During execution the executor's OWN movements churn the
        metadata generation (every applied reassignment is a topology
        change), so raw deltas would self-inflate; batch boundaries instead
        add one step per observed STRUCTURAL change (broker liveness,
        per-topic partition layout — `_structural_steps`), which the
        execution never causes itself."""
        v = self._validation
        if v.get("generationAtBuild") is None or skew is None:
            return None
        v["generationSkew"] = skew
        if 0 < self._config.max_generation_skew < skew:
            return skew
        return None

    def _topology_view(self, now_gen: Optional[int]) -> Optional[TopologyView]:
        """Fresh-topology view for one revalidation round. Cached keyed on
        the monitor generation: an unchanged generation guarantees unchanged
        topology, so back-to-back batch boundaries in a quiet cluster pay
        one metadata fetch, not one per batch (the <2% overhead contract)."""
        if now_gen is not None and self._reval_cache is not None:
            cached_gen, cached_view = self._reval_cache
            if cached_gen == now_gen:
                return cached_view
        topo = self._fresh_topology()
        if topo is None:
            return None
        view = TopologyView(topo)
        if now_gen is not None:
            self._reval_cache = (now_gen, view)
        return view

    def _admit_proposals(
        self,
        proposals: Sequence[ExecutionProposal],
        generation: Optional[int],
        fingerprint: Optional[TopologyFingerprint],
    ) -> List[ExecutionProposal]:
        """Admission: stamp bookkeeping + the first revalidation pass, before
        any task exists. Returns the proposals that may become tasks."""
        from cruise_control_tpu.common.sensors import REGISTRY
        from cruise_control_tpu.common.tracing import TRACER

        self._validation = v = {
            "enabled": bool(self._config.proposal_revalidate),
            "provenanceRun": getattr(self, "_provenance_run", None),
            "generationAtBuild": generation,
            "generationAtStart": None,
            "generationSkew": None,
            "maxGenerationSkew": self._config.max_generation_skew,
            "fingerprintAtBuild": fingerprint.to_dict() if fingerprint else None,
            "fingerprintDrift": None,
            "admitted": len(proposals),
            "numTrimmed": 0,
            "trimmed": [],
            "trimmedByReason": {},
            "batchRevalidations": 0,
            "aborted": False,
            "abortReason": None,
            "overheadS": 0.0,
        }
        if not self._config.proposal_revalidate:
            return list(proposals)
        # never carry validation state across executions
        self._reval_cache = None
        self._validated_gen = None
        self._skew_base = 0
        self._structural_steps = 0
        self._last_structural_fp = None
        t0 = time.monotonic()
        with TRACER.span(
            "proposal-admission", kind="validation", numProposals=len(proposals)
        ) as vspan:
            now_gen = self._current_generation()
            v["generationAtStart"] = now_gen
            if generation is not None and now_gen is not None:
                self._skew_base = max(0, now_gen - generation)
            skew = self._skew_exceeded(
                self._skew_base if generation is not None and now_gen is not None
                else None
            )
            if skew is not None:
                v["admitted"] = 0
                for p in proposals:
                    self._record_trim(p, GENERATION_SKEW, phase="admission")
                self._abort_for_skew(skew, [])
                vspan.attributes["aborted"] = True
                v["overheadS"] += time.monotonic() - t0
                return []
            view = self._topology_view(now_gen)
            if view is None:
                v["overheadS"] += time.monotonic() - t0
                return list(proposals)
            now_fp = TopologyFingerprint.from_topology(view._topo)
            self._last_structural_fp = now_fp
            if fingerprint is not None and now_fp != fingerprint:
                v["fingerprintDrift"] = fingerprint.diff(now_fp)
            valid: List[ExecutionProposal] = []
            for p in proposals:
                reason = validate_proposal(p, view)
                if reason is None:
                    valid.append(p)
                else:
                    self._record_trim(p, reason, phase="admission")
            self._validated_gen = now_gen
            v["admitted"] = len(valid)
            vspan.attributes.update(
                admitted=len(valid), trimmed=len(proposals) - len(valid)
            )
            dt = time.monotonic() - t0
            v["overheadS"] += dt
            REGISTRY.histogram("Executor.revalidation-timer").record(dt)
            return valid

    def _revalidate_batch(
        self, batch: List[ExecutionTask], phase: str
    ) -> List[ExecutionTask]:
        """Batch-boundary revalidation. While the monitor generation matches
        the last full pass, the batch is provably still valid (unchanged
        generation ⟹ unchanged topology ⟹ identical validation outcome) and
        the boundary costs one generation probe. On a generation change,
        EVERY pending task — this batch and the planner's remainder — is
        re-checked against fresh topology, so the skip stays sound for the
        batches drawn later at the same generation; stale tasks are trimmed
        (ABORTED with a reason code), and excessive skew aborts everything
        pending."""
        if not batch or not self._config.proposal_revalidate:
            return batch
        from cruise_control_tpu.common.sensors import REGISTRY
        from cruise_control_tpu.common.tracing import TRACER

        v = self._validation
        t0 = time.monotonic()
        now_gen = self._current_generation()
        if now_gen is not None and now_gen == self._validated_gen:
            # the generation probe above still forced a metadata refresh, so
            # real drift cannot hide behind this fast path
            v["overheadS"] += time.monotonic() - t0
            return batch
        pending = list(batch)
        batch_ids = {id(t) for t in batch}
        seen = set(batch_ids)
        for t in (
            self._planner.remaining_inter_broker_replica_movements
            + self._planner.remaining_leadership_movements
        ):
            if id(t) not in seen:
                pending.append(t)
                seen.add(id(t))
        with TRACER.span(
            "batch-revalidation", kind="validation", tasks=len(pending), phase=phase
        ) as vspan:
            view = self._topology_view(now_gen)
            if view is None:
                v["overheadS"] += time.monotonic() - t0
                return batch
            now_fp = TopologyFingerprint.from_topology(view._topo)
            if (
                self._last_structural_fp is not None
                and now_fp != self._last_structural_fp
            ):
                self._structural_steps += 1
            self._last_structural_fp = now_fp
            skew = self._skew_exceeded(self._skew_base + self._structural_steps)
            if skew is not None:
                self._abort_for_skew(skew, pending)
                vspan.attributes["aborted"] = True
                v["overheadS"] += time.monotonic() - t0
                return []
            now_ms = int(self._clock() * 1000)
            live: List[ExecutionTask] = []
            trimmed = 0
            for t in pending:
                reason = validate_proposal(t.proposal, view)
                if reason is None:
                    if id(t) in batch_ids:
                        live.append(t)
                else:
                    trimmed += 1
                    self._record_trim(t.proposal, reason, phase=phase)
                    self._trim_task(t, f"stale proposal: {reason}", now_ms)
            self._validated_gen = now_gen
            v["batchRevalidations"] += 1
            vspan.attributes.update(live=len(live), trimmed=trimmed)
            dt = time.monotonic() - t0
            v["overheadS"] += dt
            REGISTRY.histogram("Executor.revalidation-timer").record(dt)
            return live

    # -- per-task terminal handling --------------------------------------------

    def _on_task_terminal(self, task: ExecutionTask) -> None:
        """ExecutionTask listener: every terminal transition lands in the
        tracker's terminal log, the sensors, and the ExecutorNotifier
        (`task_completed` / `task_aborted` / `task_dead`)."""
        from cruise_control_tpu.common.oplog import op_log
        from cruise_control_tpu.common.sensors import REGISTRY

        state = task.state.name.lower()
        REGISTRY.meter(f"Executor.task-{state}").mark()
        self._manager.tracker.record_terminal(task)
        info = {
            "executionId": task.execution_id,
            "type": task.task_type.name,
            "startTimeMs": task.start_time_ms,
            "endTimeMs": task.end_time_ms,
            "reason": task.terminal_reason,
            "provenanceId": task.provenance_id,
        }
        self._notifier(f"task_{state}", info)
        if task.state != TaskState.COMPLETED:
            op_log(
                "Task %d %s: %s", task.execution_id, task.state.name,
                task.terminal_reason or "unattributed",
            )

    def _kill_task(self, task: ExecutionTask, now_ms: int, reason: str) -> None:
        """Force a task to DEAD through the state machine and free its slots."""
        try:
            if task.state == TaskState.PENDING:
                task.in_progress(now_ms)
            if task.state == TaskState.IN_PROGRESS or task.state == TaskState.ABORTING:
                task.kill(now_ms, reason=reason)
        except ValueError:
            pass  # already terminal (a racing completion won)
        self._manager.mark_done(task)

    def _expire_deadlines(
        self, pending: List[ExecutionTask], now_ms: int
    ) -> List[ExecutionTask]:
        """Abort tasks whose wall-clock deadline expired (IN_PROGRESS →
        ABORTING → ABORTED); the agent may still finish the movement later —
        the executor just stops holding broker slots for it."""
        deadline_ms = self._config.task_deadline_s * 1000.0
        if deadline_ms <= 0:
            return pending
        from cruise_control_tpu.common.sensors import REGISTRY

        still = []
        for t in pending:
            if now_ms - (t.start_time_ms or 0) >= deadline_ms:
                REGISTRY.meter("Executor.task-deadline-expired").mark()
                t.abort(reason=f"deadline ({self._config.task_deadline_s:g}s) expired")
                t.aborted(now_ms)
                self._manager.mark_done(t)
            else:
                still.append(t)
        return still

    def _reap_finished(self, pending: List[ExecutionTask]) -> List[ExecutionTask]:
        """Poll the driver once: complete finished tasks, expire deadlines,
        and — after `max_consecutive_driver_failures` failed poll rounds —
        declare the driver unreachable and kill everything in flight."""
        from cruise_control_tpu.common.oplog import op_log
        from cruise_control_tpu.common.sensors import REGISTRY

        now_ms = int(self._clock() * 1000)
        try:
            self._driver.poll()
            self._driver_failures = 0
        except Exception as e:
            self._driver_failures += 1
            REGISTRY.meter("Executor.driver-poll-failures").mark()
            if self._driver_failures >= self._config.max_consecutive_driver_failures:
                op_log(
                    "Cluster driver unreachable after %d consecutive poll "
                    "failures (%r); killing %d in-flight task(s)",
                    self._driver_failures, e, len(pending),
                )
                for t in pending:
                    self._kill_task(t, now_ms, f"driver unreachable: {e}")
                return []
            return self._expire_deadlines(list(pending), now_ms)
        still = []
        for t in pending:
            try:
                finished = self._driver.is_finished(t)
            except Exception:
                finished = False
            if finished:
                t.completed(now_ms)
                self._manager.mark_done(t)
            else:
                still.append(t)
        return self._expire_deadlines(still, now_ms)

    def _dispatch_batch(
        self,
        batch: List[ExecutionTask],
        start_fn: Callable[[ExecutionTask], None],
    ) -> List[ExecutionTask]:
        """Mark a batch IN_PROGRESS and dispatch each task, isolating
        per-task dispatch failures: a task whose dispatch raises dies DEAD
        and releases its slots; the rest of the batch proceeds."""
        from cruise_control_tpu.common.oplog import op_log
        from cruise_control_tpu.common.sensors import REGISTRY

        now_ms = int(self._clock() * 1000)
        for t in batch:
            t.listener = self._on_task_terminal
        self._manager.mark_in_progress(batch, now_ms)
        live = []
        for t in batch:
            try:
                start_fn(t)
                live.append(t)
            except Exception as e:
                REGISTRY.meter("Executor.dispatch-failures").mark()
                op_log("Dispatch FAILED for task %d: %r", t.execution_id, e)
                self._kill_task(t, now_ms, f"dispatch failure: {e}")
        return live

    def _wait_for_tasks(self, tasks: List[ExecutionTask]) -> None:
        polls = 0
        pending = [t for t in tasks if not t.done]
        while pending:
            pending = self._reap_finished(pending)
            if not pending:
                break
            polls += 1
            if polls > self._config.max_execution_polls:
                now_ms = int(self._clock() * 1000)
                for t in pending:
                    self._kill_task(
                        t, now_ms,
                        f"poll cap ({self._config.max_execution_polls}) exhausted",
                    )
                break
            # graceful stop still waits for in-flight work — at normal pace,
            # not a busy spin
            time.sleep(self._config.execution_progress_check_interval_s)

    def _run_replica_movements(self) -> None:
        """Pipelined execution: broker slots refill as individual tasks
        finish, so one slow movement never stalls unrelated brokers
        (the reference refills per poll round the same way)."""
        from cruise_control_tpu.common.oplog import op_log
        from cruise_control_tpu.common.sensors import REGISTRY
        from cruise_control_tpu.common.tracing import TRACER

        with self._lock:
            self._state = ExecutorState.INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
        op_log(
            "Execution phase: inter-broker replica movement (%d task(s))",
            len(self._planner.remaining_inter_broker_replica_movements),
        )
        with TRACER.span(
            "executor.replica-movement-phase", kind="executor",
            tasks=len(self._planner.remaining_inter_broker_replica_movements),
        ) as span:
            batches = 0
            in_flight: List[ExecutionTask] = []
            polls = 0
            while True:
                in_flight = self._reap_finished(in_flight)
                remaining = self._planner.remaining_inter_broker_replica_movements
                if self._stop_requested.is_set():
                    if not in_flight:
                        break  # graceful: nothing new once stop is requested
                elif remaining:
                    brokers = set()
                    for t in remaining:
                        brokers |= t.involved_brokers
                    slots = self._manager.available_slots(brokers)
                    batch = self._planner.get_inter_broker_replica_movement_tasks(slots)
                    batch = self._revalidate_batch(batch, "replica")
                    if self._validation.get("aborted"):
                        batch = []
                    if batch:
                        # per-batch dispatch span: batch sizes and dispatch
                        # latency are where throttling problems show first
                        with TRACER.span(
                            "executor.batch-dispatch", kind="executor",
                            tasks=len(batch), type="replica",
                        ), REGISTRY.histogram("Executor.batch-dispatch-timer"):
                            live = self._dispatch_batch(
                                batch, self._driver.start_replica_movement
                            )
                        batches += 1
                        in_flight.extend(live)
                elif not in_flight:
                    break
                if in_flight:
                    polls += 1
                    if polls > self._config.max_execution_polls:
                        now_ms = int(self._clock() * 1000)
                        for t in in_flight:
                            self._kill_task(
                                t, now_ms,
                                f"poll cap ({self._config.max_execution_polls}) exhausted",
                            )
                        in_flight = []
                        continue
                    time.sleep(self._config.execution_progress_check_interval_s)
            span.attributes["batches"] = batches

    def _run_leadership_movements(self) -> None:
        from cruise_control_tpu.common.oplog import op_log
        from cruise_control_tpu.common.sensors import REGISTRY
        from cruise_control_tpu.common.tracing import TRACER

        with self._lock:
            self._state = ExecutorState.LEADER_MOVEMENT_TASK_IN_PROGRESS
        op_log(
            "Execution phase: leadership movement (%d task(s))",
            len(self._planner.remaining_leadership_movements),
        )
        with TRACER.span(
            "executor.leadership-movement-phase", kind="executor",
            tasks=len(self._planner.remaining_leadership_movements),
        ):
            while not self._stop_requested.is_set():
                batch = self._planner.get_leadership_movement_tasks(self._manager.leadership_cap)
                if not batch:
                    break
                batch = self._revalidate_batch(batch, "leadership")
                if self._validation.get("aborted"):
                    break
                if not batch:
                    continue
                with TRACER.span(
                    "executor.batch-dispatch", kind="executor",
                    tasks=len(batch), type="leadership",
                ), REGISTRY.histogram("Executor.batch-dispatch-timer"):
                    live = self._dispatch_batch(
                        batch, self._driver.start_leadership_movement
                    )
                self._wait_for_tasks(live)
