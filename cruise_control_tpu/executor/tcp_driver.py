"""TCP cluster-agent driver: a live-cluster binding for the executor.

The reference executes movements by writing reassignment JSON into ZooKeeper
for the Kafka controller to act on and polling the znode until it clears
(scala/executor/ExecutorUtils.scala:32, cc/executor/Executor.java poll loop).
This driver speaks to a controller-side agent over a socket instead — the
deployment story for clusters where the controller surface is an agent/proxy
rather than direct ZK access. `testing.fake_agent` implements the agent side
of the protocol against a simulated cluster (the protocol-level fake the
integration tests run against); a production agent implements the same five
ops against the real admin API.

## Wire protocol (the adapter contract)

JSON objects, one per line (UTF-8, '\\n'-terminated), strict request/response
over a persistent connection. Requests carry `op`; responses carry
`ok: true` or `ok: false, error: str`.

  {"op": "reassign", "executionId": int, "topic": str, "partition": int,
   "replicas": [int, ...]}
      -> {"ok": true}
      Begin moving the partition to the given replica list (first entry =
      target leader if the proposal carries a leader action). Asynchronous:
      completion is observed via "finished".

  {"op": "leader", "executionId": int, "topic": str, "partition": int,
   "leader": int}
      -> {"ok": true}
      Trigger preferred-leader election to the given broker.

  {"op": "finished", "executionIds": [int, ...]}
      -> {"ok": true, "finished": [int, ...]}
      Which of the given executions have completed. Completion is sticky
      until consumed ONCE (the driver deletes its record after reading, the
      ZK-node contract); agents must tolerate ids they never saw (restarted
      driver) by reporting them unfinished.

  {"op": "ongoing"}
      -> {"ok": true, "ongoing": bool}
      Whether any reassignment is in flight agent-side — the executor
      refuses to start over one (cc/executor/Executor.java:494).

  {"op": "ping"} -> {"ok": true}
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Callable, Dict, List, Optional, Set

from cruise_control_tpu.common.retry import RetryPolicy
from cruise_control_tpu.executor.driver import ClusterDriver
from cruise_control_tpu.executor.task import ExecutionTask


class AgentProtocolError(RuntimeError):
    """The agent rejected a request or broke the line protocol.

    Deliberately NOT in the retryable set: the agent parsed the request and
    refused it, so re-sending the same bytes cannot change the answer."""


class _LineClient:
    """Blocking JSON-lines client over one persistent socket.

    `ssl_context` wraps the connection in TLS (the SslTest analog for the
    agent path, mr/CruiseControlMetricsReporter.java:110-128 configures
    producer SSL); `server_hostname` is what the certificate is verified
    against when the context checks hostnames (cert pinning: build the
    context with load_verify_locations on the agent's own cert)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0,
                 ssl_context=None, server_hostname: Optional[str] = None,
                 fault_hook: Optional[Callable[[Dict], None]] = None):
        self._addr = (host, port)
        self._timeout = timeout_s
        self._ssl_context = ssl_context
        self._server_hostname = server_hostname or host
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._lock = threading.Lock()
        #: test-only client-side fault injection (testing/faults.py): called
        #: with the payload before each send; may raise ConnectionError/delay
        self._fault_hook = fault_hook

    def _connect(self) -> None:
        sock = socket.create_connection(self._addr, timeout=self._timeout)
        sock.settimeout(self._timeout)
        if self._ssl_context is not None:
            sock = self._ssl_context.wrap_socket(
                sock, server_hostname=self._server_hostname
            )
        self._sock = sock
        self._rfile = sock.makefile("rb")

    def request(self, payload: Dict, idempotent: bool = True) -> Dict:
        """One request/response exchange. A mid-exchange connection drop is
        retried ONCE only for `idempotent` requests — after a send, the agent
        may have processed the request even though the response was lost, so
        re-sending a non-idempotent payload (e.g. metrics_publish) would
        duplicate its effect; those surface the error to the caller instead."""
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._fault_hook is not None:
                        self._fault_hook(payload)
                    if self._sock is None:
                        self._connect()
                    self._sock.sendall(json.dumps(payload).encode() + b"\n")
                    line = self._rfile.readline()
                    if not line:
                        raise ConnectionError("agent closed the connection")
                    break
                except (OSError, ConnectionError):
                    self.close()
                    if attempt or not idempotent:
                        raise
        resp = json.loads(line)
        if not resp.get("ok"):
            raise AgentProtocolError(resp.get("error", "agent rejected request"))
        return resp

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._rfile = None


class TcpClusterDriver(ClusterDriver):
    """Executor binding over the cluster-agent wire protocol above.

    Every op runs under `retry_policy` with reconnect-on-failure: the
    _LineClient drops its socket on any transport error, so the next attempt
    re-dials from scratch. ALL five ops are safely retryable — `finished`/
    `ongoing`/`ping` are pure reads, and `reassign`/`leader` are idempotent
    by protocol because they are keyed on executionId (re-sending the same
    executionId overwrites the agent's pending entry for it, it does not
    start a second movement)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0,
                 ssl_context=None, server_hostname: Optional[str] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 fault_hook: Optional[Callable[[Dict], None]] = None):
        self._client = _LineClient(host, port, timeout_s, ssl_context=ssl_context,
                                   server_hostname=server_hostname,
                                   fault_hook=fault_hook)
        self._retry = retry_policy or RetryPolicy()
        self._finished: Set[int] = set()
        self._in_flight: Dict[int, ExecutionTask] = {}
        self._lock = threading.Lock()

    def _request(self, payload: Dict) -> Dict:
        op = payload.get("op", "op")
        return self._retry.call(
            lambda: self._client.request(payload), name=f"TcpDriver.{op}"
        )

    def _entry(self, task: ExecutionTask) -> Dict:
        p = task.proposal
        topic, _, part = (p.topic_partition or f"p-{p.partition}").rpartition("-")
        return {
            "executionId": task.execution_id,
            "topic": topic or f"p{p.partition}",
            "partition": int(part) if part.isdigit() else p.partition,
        }

    def start_replica_movement(self, task: ExecutionTask) -> None:
        req = {
            "op": "reassign",
            **self._entry(task),
            "replicas": list(task.proposal.new_replicas),
        }
        self._request(req)
        with self._lock:
            self._in_flight[task.execution_id] = task

    def start_leadership_movement(self, task: ExecutionTask) -> None:
        req = {
            "op": "leader",
            **self._entry(task),
            "leader": task.proposal.new_leader,
        }
        self._request(req)
        with self._lock:
            self._in_flight[task.execution_id] = task

    def poll(self) -> None:
        """One agent round-trip covering every in-flight task (the executor
        calls this once per progress-check interval; batching keeps it one
        RPC regardless of in-flight count)."""
        with self._lock:
            ids = list(self._in_flight)
        if not ids:
            return
        resp = self._request({"op": "finished", "executionIds": ids})
        done = set(resp.get("finished", ()))
        with self._lock:
            self._finished |= done
            for eid in done:
                self._in_flight.pop(eid, None)

    def is_finished(self, task: ExecutionTask) -> bool:
        with self._lock:
            if task.execution_id in self._finished:
                self._finished.discard(task.execution_id)  # consume once
                return True
        return False

    def has_ongoing_reassignment(self) -> bool:
        resp = self._request({"op": "ongoing"})
        return bool(resp.get("ongoing"))

    def close(self) -> None:
        self._client.close()
