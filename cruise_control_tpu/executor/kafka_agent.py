"""Production cluster agent: the wire protocol served against a REAL Kafka.

The executor's live-cluster binding ends at the JSON-lines agent protocol
(executor/tcp_driver.py module docstring: reassign / leader / finished /
ongoing / ping, plus the metrics transport's metrics_publish / metrics_poll).
`testing.fake_agent.FakeClusterAgent` implements that protocol against the
in-process simulator for tests; THIS module is the reference production
implementation, mapping the same ops onto a Kafka admin client — the analog
of the reference's ZK bridge and Kafka-backed sample store:

  reassign   -> AdminClient.alter_partition_reassignments, the KIP-455
                successor of writing reassignment JSON into ZooKeeper
                (scala/executor/ExecutorUtils.scala:32)
  leader     -> preferred-leader election
                (scala PreferredReplicaLeaderElectionCommand wrapper)
  finished   -> list_partition_reassignments: a topic-partition absent from
                the in-flight set has completed (the reference polls the
                reassignment znode until it clears, cc/executor/Executor.java)
  ongoing    -> list_partition_reassignments non-empty
                (cc/executor/Executor.java:494 refuses to start over one)
  metrics_*  -> produce/consume on a metrics topic, the deployment shape of
                CruiseControlMetricsReporter + KafkaSampleStore
                (mr/CruiseControlMetricsReporter.java:128,
                cc/monitor/sampling/KafkaSampleStore.java:294)

Layering: `ClusterAgentServer` owns the protocol bookkeeping (executionId
tracking, sticky-until-consumed completion, unknown-id tolerance) against an
`AdminAdapter` SPI; `KafkaAdminAdapter` is the kafka-python binding. The
split keeps the protocol logic unit-testable without a broker (the sandbox
has none), while the adapter stays a thin, auditable mapping. kafka-python
is imported lazily and guarded — constructing `KafkaAdminAdapter` without it
raises a clear error, and nothing in this module runs at package import.

Run standalone:
  python -m cruise_control_tpu.executor.kafka_agent \
      --bootstrap localhost:9092 --port 9500 [--metrics-topic __CCMetrics]
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class AdminAdapter:
    """What the agent needs from a cluster admin client.

    Implementations must be thread-safe (the agent server handles each
    connection on its own thread)."""

    def begin_reassignment(self, topic: str, partition: int, replicas: List[int]) -> None:
        """Start moving the partition to `replicas` (async)."""
        raise NotImplementedError

    def elect_leader(self, topic: str, partition: int, leader: int) -> None:
        """Make `leader` the partition's leader (preferred election)."""
        raise NotImplementedError

    def reassignment_done(self, topic: str, partition: int) -> bool:
        """True when no reassignment is in flight for the partition."""
        raise NotImplementedError

    def pending_reassignments(self) -> Optional[set]:
        """The set of (topic, partition) still moving, or None when the
        client has no bulk listing — the agent then falls back to per-
        partition reassignment_done probes. Implementations with a bulk API
        should override: a 'finished' request probes every in-flight
        executionId, and one listing answers all of them in one round-trip."""
        return None

    def any_ongoing(self) -> bool:
        """True when ANY reassignment is in flight cluster-wide."""
        raise NotImplementedError

    def publish_metrics(self, records: List[str]) -> None:
        """Durably accept reporter records (hex-encoded serde payloads)."""
        raise NotImplementedError

    def poll_metrics(self, max_records: int) -> List[str]:
        """Return up to max_records pending records, consuming them."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class KafkaAdminAdapter(AdminAdapter):
    """kafka-python binding of the AdminAdapter SPI.

    Requires kafka-python >= 2.0 (KIP-455 reassignment APIs). The import is
    deferred to construction so the module stays importable in environments
    without a Kafka client (this sandbox); integration tests run against the
    protocol-level fake instead (tests/test_cluster_binding.py).
    """

    def __init__(self, bootstrap_servers: str, metrics_topic: str = "__CruiseControlMetrics",
                 client_id: str = "cruise-control-tpu-agent"):
        try:
            from kafka import KafkaConsumer, KafkaProducer, TopicPartition
            from kafka.admin import KafkaAdminClient
        except ImportError as e:  # pragma: no cover - no broker in CI
            raise RuntimeError(
                "KafkaAdminAdapter requires kafka-python (pip install kafka-python); "
                "use testing.fake_agent.FakeClusterAgent for tests"
            ) from e
        # the admin APIs take TYPED arguments (TopicPartition keys,
        # NewPartitionReassignment values, an ElectionType member) — plain
        # tuples/strings raise AttributeError inside the client's encoder.
        # Resolved here, guarded, so an older client fails at construction
        # with a clear message instead of mid-rebalance.
        self._TopicPartition = TopicPartition
        try:  # pragma: no cover - needs kafka-python
            from kafka.admin import NewPartitionReassignment

            self._NewPartitionReassignment = NewPartitionReassignment
        except ImportError:
            self._NewPartitionReassignment = None
        try:  # pragma: no cover - needs kafka-python
            from kafka.admin import ElectionType

            self._preferred_election = ElectionType.PREFERRED
        except ImportError:
            self._preferred_election = None
        self._admin = KafkaAdminClient(
            bootstrap_servers=bootstrap_servers, client_id=client_id
        )
        self._producer = KafkaProducer(bootstrap_servers=bootstrap_servers)
        self._consumer = KafkaConsumer(
            metrics_topic,
            bootstrap_servers=bootstrap_servers,
            group_id=client_id,
            enable_auto_commit=True,
            consumer_timeout_ms=500,
        )
        self._metrics_topic = metrics_topic
        # per-client locks: the admin and consumer clients each need
        # serialization against THEMSELVES only (KafkaConsumer forbids
        # concurrent use; admin ops share one connection), while
        # KafkaProducer is documented thread-safe — one shared lock would
        # make every status RPC queue behind a 500 ms consumer poll window
        self._admin_lock = threading.Lock()
        self._consumer_lock = threading.Lock()

    def begin_reassignment(self, topic: str, partition: int, replicas: List[int]) -> None:
        # KIP-455 AlterPartitionReassignments — the post-ZK form of
        # ExecutorUtils.executeReplicaReassignmentTasks (scala :32). Newer
        # kafka-python exposes it as alter_partition_reassignments; guard so
        # an older client fails loudly rather than silently no-oping.
        alter = getattr(self._admin, "alter_partition_reassignments", None)
        if alter is None or self._NewPartitionReassignment is None:  # pragma: no cover
            raise RuntimeError(
                "kafka-python too old: alter_partition_reassignments / "
                "NewPartitionReassignment missing (need the KIP-455 admin API)"
            )
        with self._admin_lock:
            alter({
                self._TopicPartition(topic, partition):
                    self._NewPartitionReassignment(list(replicas))
            })

    def elect_leader(self, topic: str, partition: int, leader: int) -> None:
        # Preferred-leader election: KIP-460 ElectLeaders
        # (PreferredReplicaLeaderElectionCommand semantics). Requires a
        # client that exposes it — re-ordering the replica list via a
        # reassignment does NOT elect by itself (the leader only changes on
        # an unrelated auto.leader.rebalance cycle), so faking it here would
        # let the agent report leadership movements complete that never
        # happened. Fail loudly instead.
        elect = getattr(self._admin, "perform_leader_election", None)
        if elect is None or self._preferred_election is None:  # pragma: no cover
            raise RuntimeError(
                "kafka-python does not expose perform_leader_election / "
                "ElectionType (KIP-460); upgrade the client — leadership "
                "movements cannot be executed correctly without it"
            )
        with self._admin_lock:
            elect(
                self._preferred_election,
                [self._TopicPartition(topic, partition)],
            )

    def _in_flight(self) -> Dict[Tuple[str, int], List[int]]:
        lister = getattr(self._admin, "list_partition_reassignments", None)
        if lister is None:  # pragma: no cover - version-dependent
            raise RuntimeError(
                "kafka-python too old: list_partition_reassignments missing"
            )
        with self._admin_lock:
            return dict(lister() or {})

    def reassignment_done(self, topic: str, partition: int) -> bool:
        return (topic, partition) not in self._in_flight()

    def pending_reassignments(self) -> Optional[set]:
        # one list_partition_reassignments round-trip answers every
        # executionId in a 'finished' request
        return set(self._in_flight())

    def any_ongoing(self) -> bool:
        return bool(self._in_flight())

    def publish_metrics(self, records: List[str]) -> None:
        # KafkaProducer is thread-safe; no lock needed
        for rec in records:
            self._producer.send(self._metrics_topic, bytes.fromhex(rec))
        self._producer.flush()

    def poll_metrics(self, max_records: int) -> List[str]:
        # KafkaConsumer forbids concurrent use (a reconnecting transport
        # plus its stale connection would otherwise interleave on it)
        out: List[str] = []
        with self._consumer_lock:
            for msg in self._consumer:
                out.append(bytes(msg.value).hex())
                if len(out) >= max_records:
                    break
        return out

    def close(self) -> None:
        for c in (self._consumer, self._producer, self._admin):
            try:
                c.close()
            except Exception:
                pass


class ClusterAgentServer:
    """JSON-lines TCP server speaking the cluster-agent protocol against any
    AdminAdapter.

    Protocol bookkeeping matches the contract in executor/tcp_driver.py:
    completion is sticky until consumed once via "finished"; executionIds the
    agent never saw (a restarted driver) report unfinished; `leader` ops
    complete on their next "finished" probe (elections are synchronous at the
    admin API). `ssl_context` wraps accepted connections in TLS (the
    metrics-path security story; see reporter/transport.py).
    """

    #: completed executionIds remembered for late probes; bounded — the
    #: driver consumes completion exactly once (tcp_driver.is_finished), so
    #: old entries only serve duplicate probes and a production agent that
    #: rebalances continuously must not leak one entry per movement forever
    FINISHED_CAP = 65536

    def __init__(self, adapter: AdminAdapter, host: str = "127.0.0.1",
                 port: int = 0, ssl_context=None):
        import collections

        from cruise_control_tpu.common.lineserver import JsonLinesServer

        self._adapter = adapter
        self._lock = threading.Lock()
        #: executionId -> (topic, partition) still moving; None = leader op
        self._pending: Dict[int, Optional[Tuple[str, int]]] = {}
        self._finished: "collections.OrderedDict" = collections.OrderedDict()
        # transport is the SAME JsonLinesServer the protocol-level test fake
        # serves on (testing.fake_agent) — framing/TLS changes land once
        self._server = JsonLinesServer(
            self._dispatch, host=host, port=port, ssl_context=ssl_context,
            name="cluster-agent",
        )

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.address

    def start(self) -> "ClusterAgentServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop()
        self._adapter.close()

    def _dispatch(self, req: Dict) -> Dict:
        op = req.get("op")
        if op == "ping":
            return {"ok": True}
        if op == "reassign":
            topic, part = str(req["topic"]), int(req["partition"])
            self._adapter.begin_reassignment(
                topic, part, [int(b) for b in req["replicas"]]
            )
            with self._lock:
                self._pending[int(req["executionId"])] = (topic, part)
            return {"ok": True}
        if op == "leader":
            self._adapter.elect_leader(
                str(req["topic"]), int(req["partition"]), int(req["leader"])
            )
            with self._lock:
                # elections are synchronous at the admin API: done on the
                # next probe
                self._pending[int(req["executionId"])] = None
            return {"ok": True}
        if op == "finished":
            done = []
            with self._lock:
                pending = dict(self._pending)
                finished = set(self._finished)
            # one bulk listing when the adapter has one (the driver batches
            # every in-flight id into one request — tcp_driver.poll — so the
            # per-id fallback would cost one cluster RPC per id); fetched
            # lazily so requests probing only leader ops / stale ids cost
            # zero admin round-trips
            moving: Optional[set] = None
            moving_fetched = False
            for eid in req.get("executionIds", ()):
                eid = int(eid)
                if eid in finished:
                    done.append(eid)
                    continue
                if eid not in pending:
                    continue  # unknown id (restarted driver): unfinished
                tp = pending[eid]
                if tp is not None and not moving_fetched:
                    moving = self._adapter.pending_reassignments()
                    moving_fetched = True
                if tp is None or (
                    tp not in moving
                    if moving is not None
                    else self._adapter.reassignment_done(*tp)
                ):
                    done.append(eid)
            with self._lock:
                for eid in done:
                    self._pending.pop(eid, None)
                    self._finished[eid] = True
                    self._finished.move_to_end(eid)
                while len(self._finished) > self.FINISHED_CAP:
                    self._finished.popitem(last=False)
            return {"ok": True, "finished": done}
        if op == "ongoing":
            return {"ok": True, "ongoing": self._adapter.any_ongoing()}
        if op == "metrics_publish":
            self._adapter.publish_metrics(list(req.get("records", ())))
            return {"ok": True}
        if op == "metrics_poll":
            records = self._adapter.poll_metrics(int(req.get("max", 10000)))
            return {"ok": True, "records": records}
        return {"ok": False, "error": f"unknown op {op!r}"}


def main(argv: Optional[List[str]] = None) -> None:  # pragma: no cover - needs a broker
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--bootstrap", required=True, help="Kafka bootstrap servers")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=9500)
    parser.add_argument("--metrics-topic", default="__CruiseControlMetrics")
    parser.add_argument("--tls-cert", help="PEM cert; enables TLS with --tls-key")
    parser.add_argument("--tls-key", help="PEM private key")
    args = parser.parse_args(argv)
    ssl_context = None
    if args.tls_cert:
        import ssl

        ssl_context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ssl_context.load_cert_chain(args.tls_cert, args.tls_key)
    adapter = KafkaAdminAdapter(args.bootstrap, metrics_topic=args.metrics_topic)
    server = ClusterAgentServer(
        adapter, host=args.host, port=args.port, ssl_context=ssl_context
    )
    server.start()
    print(f"cluster agent serving on {server.address}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
