"""Production cluster agent: the wire protocol served against a REAL Kafka.

The executor's live-cluster binding ends at the JSON-lines agent protocol
(executor/tcp_driver.py module docstring: reassign / leader / finished /
ongoing / ping, plus the metrics transport's metrics_publish / metrics_poll).
`testing.fake_agent.FakeClusterAgent` implements that protocol against the
in-process simulator for tests; THIS module is the reference production
implementation, mapping the same ops onto a Kafka admin client — the analog
of the reference's ZK bridge and Kafka-backed sample store:

  reassign   -> AdminClient.alter_partition_reassignments, the KIP-455
                successor of writing reassignment JSON into ZooKeeper
                (scala/executor/ExecutorUtils.scala:32)
  leader     -> preferred-leader election
                (scala PreferredReplicaLeaderElectionCommand wrapper)
  finished   -> list_partition_reassignments: a topic-partition absent from
                the in-flight set has completed (the reference polls the
                reassignment znode until it clears, cc/executor/Executor.java)
  ongoing    -> list_partition_reassignments non-empty
                (cc/executor/Executor.java:494 refuses to start over one)
  metrics_*  -> produce/consume on a metrics topic, the deployment shape of
                CruiseControlMetricsReporter + KafkaSampleStore
                (mr/CruiseControlMetricsReporter.java:128,
                cc/monitor/sampling/KafkaSampleStore.java:294)

Layering: `ClusterAgentServer` owns the protocol bookkeeping (executionId
tracking, sticky-until-consumed completion, unknown-id tolerance) against an
`AdminAdapter` SPI; `KafkaAdminAdapter` is the kafka-python binding. The
split keeps the protocol logic unit-testable without a broker (the sandbox
has none), while the adapter stays a thin, auditable mapping. kafka-python
is imported lazily and guarded — constructing `KafkaAdminAdapter` without it
raises a clear error, and nothing in this module runs at package import.

Run standalone:
  python -m cruise_control_tpu.executor.kafka_agent \
      --bootstrap localhost:9092 --port 9500 [--metrics-topic __CCMetrics]
"""

from __future__ import annotations

import json
import socketserver
import threading
from typing import Dict, List, Optional, Tuple


class AdminAdapter:
    """What the agent needs from a cluster admin client.

    Implementations must be thread-safe (the agent server handles each
    connection on its own thread)."""

    def begin_reassignment(self, topic: str, partition: int, replicas: List[int]) -> None:
        """Start moving the partition to `replicas` (async)."""
        raise NotImplementedError

    def elect_leader(self, topic: str, partition: int, leader: int) -> None:
        """Make `leader` the partition's leader (preferred election)."""
        raise NotImplementedError

    def reassignment_done(self, topic: str, partition: int) -> bool:
        """True when no reassignment is in flight for the partition."""
        raise NotImplementedError

    def any_ongoing(self) -> bool:
        """True when ANY reassignment is in flight cluster-wide."""
        raise NotImplementedError

    def publish_metrics(self, records: List[str]) -> None:
        """Durably accept reporter records (hex-encoded serde payloads)."""
        raise NotImplementedError

    def poll_metrics(self, max_records: int) -> List[str]:
        """Return up to max_records pending records, consuming them."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class KafkaAdminAdapter(AdminAdapter):
    """kafka-python binding of the AdminAdapter SPI.

    Requires kafka-python >= 2.0 (KIP-455 reassignment APIs). The import is
    deferred to construction so the module stays importable in environments
    without a Kafka client (this sandbox); integration tests run against the
    protocol-level fake instead (tests/test_cluster_binding.py).
    """

    def __init__(self, bootstrap_servers: str, metrics_topic: str = "__CruiseControlMetrics",
                 client_id: str = "cruise-control-tpu-agent"):
        try:
            from kafka import KafkaConsumer, KafkaProducer
            from kafka.admin import KafkaAdminClient
        except ImportError as e:  # pragma: no cover - no broker in CI
            raise RuntimeError(
                "KafkaAdminAdapter requires kafka-python (pip install kafka-python); "
                "use testing.fake_agent.FakeClusterAgent for tests"
            ) from e
        self._admin = KafkaAdminClient(
            bootstrap_servers=bootstrap_servers, client_id=client_id
        )
        self._producer = KafkaProducer(bootstrap_servers=bootstrap_servers)
        self._consumer = KafkaConsumer(
            metrics_topic,
            bootstrap_servers=bootstrap_servers,
            group_id=client_id,
            enable_auto_commit=True,
            consumer_timeout_ms=500,
        )
        self._metrics_topic = metrics_topic
        self._lock = threading.Lock()

    def begin_reassignment(self, topic: str, partition: int, replicas: List[int]) -> None:
        # KIP-455 AlterPartitionReassignments — the post-ZK form of
        # ExecutorUtils.executeReplicaReassignmentTasks (scala :32). Newer
        # kafka-python exposes it as alter_partition_reassignments; guard so
        # an older client fails loudly rather than silently no-oping.
        alter = getattr(self._admin, "alter_partition_reassignments", None)
        if alter is None:  # pragma: no cover - version-dependent
            raise RuntimeError(
                "kafka-python too old: alter_partition_reassignments missing "
                "(need the KIP-455 admin API)"
            )
        with self._lock:
            alter({(topic, partition): replicas})

    def elect_leader(self, topic: str, partition: int, leader: int) -> None:
        # Preferred-leader election: KIP-460 ElectLeaders
        # (PreferredReplicaLeaderElectionCommand semantics). Requires a
        # client that exposes it — re-ordering the replica list via a
        # reassignment does NOT elect by itself (the leader only changes on
        # an unrelated auto.leader.rebalance cycle), so faking it here would
        # let the agent report leadership movements complete that never
        # happened. Fail loudly instead.
        elect = getattr(self._admin, "perform_leader_election", None)
        if elect is None:  # pragma: no cover - version-dependent
            raise RuntimeError(
                "kafka-python does not expose perform_leader_election "
                "(KIP-460); upgrade the client — leadership movements "
                "cannot be executed correctly without it"
            )
        with self._lock:
            elect("PREFERRED", [(topic, partition)])

    def _in_flight(self) -> Dict[Tuple[str, int], List[int]]:
        lister = getattr(self._admin, "list_partition_reassignments", None)
        if lister is None:  # pragma: no cover - version-dependent
            raise RuntimeError(
                "kafka-python too old: list_partition_reassignments missing"
            )
        with self._lock:
            return dict(lister() or {})

    def reassignment_done(self, topic: str, partition: int) -> bool:
        return (topic, partition) not in self._in_flight()

    def any_ongoing(self) -> bool:
        return bool(self._in_flight())

    def publish_metrics(self, records: List[str]) -> None:
        for rec in records:
            self._producer.send(self._metrics_topic, bytes.fromhex(rec))
        self._producer.flush()

    def poll_metrics(self, max_records: int) -> List[str]:
        out: List[str] = []
        for msg in self._consumer:
            out.append(bytes(msg.value).hex())
            if len(out) >= max_records:
                break
        return out

    def close(self) -> None:
        for c in (self._consumer, self._producer, self._admin):
            try:
                c.close()
            except Exception:
                pass


class ClusterAgentServer:
    """JSON-lines TCP server speaking the cluster-agent protocol against any
    AdminAdapter.

    Protocol bookkeeping matches the contract in executor/tcp_driver.py:
    completion is sticky until consumed once via "finished"; executionIds the
    agent never saw (a restarted driver) report unfinished; `leader` ops
    complete on their next "finished" probe (elections are synchronous at the
    admin API). `ssl_context` wraps accepted connections in TLS (the
    metrics-path security story; see reporter/transport.py).
    """

    #: completed executionIds remembered for late probes; bounded — the
    #: driver consumes completion exactly once (tcp_driver.is_finished), so
    #: old entries only serve duplicate probes and a production agent that
    #: rebalances continuously must not leak one entry per movement forever
    FINISHED_CAP = 65536

    def __init__(self, adapter: AdminAdapter, host: str = "127.0.0.1",
                 port: int = 0, ssl_context=None):
        import collections

        self._adapter = adapter
        self._lock = threading.Lock()
        #: executionId -> (topic, partition) still moving; None = leader op
        self._pending: Dict[int, Optional[Tuple[str, int]]] = {}
        self._finished: "collections.OrderedDict" = collections.OrderedDict()
        agent = self

        class Handler(socketserver.StreamRequestHandler):
            def setup(self):
                if ssl_context is not None:
                    self.request = ssl_context.wrap_socket(
                        self.request, server_side=True
                    )
                super().setup()

            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    try:
                        req = json.loads(line)
                        resp = agent._dispatch(req)
                    except Exception as e:
                        resp = {"ok": False, "error": repr(e)}
                    self.wfile.write(json.dumps(resp).encode() + b"\n")
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def start(self) -> "ClusterAgentServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="cluster-agent", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._adapter.close()

    def _dispatch(self, req: Dict) -> Dict:
        op = req.get("op")
        if op == "ping":
            return {"ok": True}
        if op == "reassign":
            topic, part = str(req["topic"]), int(req["partition"])
            self._adapter.begin_reassignment(
                topic, part, [int(b) for b in req["replicas"]]
            )
            with self._lock:
                self._pending[int(req["executionId"])] = (topic, part)
            return {"ok": True}
        if op == "leader":
            self._adapter.elect_leader(
                str(req["topic"]), int(req["partition"]), int(req["leader"])
            )
            with self._lock:
                # elections are synchronous at the admin API: done on the
                # next probe
                self._pending[int(req["executionId"])] = None
            return {"ok": True}
        if op == "finished":
            done = []
            with self._lock:
                pending = dict(self._pending)
                finished = set(self._finished)
            for eid in req.get("executionIds", ()):
                eid = int(eid)
                if eid in finished:
                    done.append(eid)
                    continue
                if eid not in pending:
                    continue  # unknown id (restarted driver): unfinished
                tp = pending[eid]
                if tp is None or self._adapter.reassignment_done(*tp):
                    done.append(eid)
            with self._lock:
                for eid in done:
                    self._pending.pop(eid, None)
                    self._finished[eid] = True
                    self._finished.move_to_end(eid)
                while len(self._finished) > self.FINISHED_CAP:
                    self._finished.popitem(last=False)
            return {"ok": True, "finished": done}
        if op == "ongoing":
            return {"ok": True, "ongoing": self._adapter.any_ongoing()}
        if op == "metrics_publish":
            self._adapter.publish_metrics(list(req.get("records", ())))
            return {"ok": True}
        if op == "metrics_poll":
            records = self._adapter.poll_metrics(int(req.get("max", 10000)))
            return {"ok": True, "records": records}
        return {"ok": False, "error": f"unknown op {op!r}"}


def main(argv: Optional[List[str]] = None) -> None:  # pragma: no cover - needs a broker
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--bootstrap", required=True, help="Kafka bootstrap servers")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=9500)
    parser.add_argument("--metrics-topic", default="__CruiseControlMetrics")
    parser.add_argument("--tls-cert", help="PEM cert; enables TLS with --tls-key")
    parser.add_argument("--tls-key", help="PEM private key")
    args = parser.parse_args(argv)
    ssl_context = None
    if args.tls_cert:
        import ssl

        ssl_context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ssl_context.load_cert_chain(args.tls_cert, args.tls_key)
    adapter = KafkaAdminAdapter(args.bootstrap, metrics_topic=args.metrics_topic)
    server = ClusterAgentServer(
        adapter, host=args.host, port=args.port, ssl_context=ssl_context
    )
    server.start()
    print(f"cluster agent serving on {server.address}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
