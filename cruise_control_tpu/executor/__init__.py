"""Executor subsystem: apply proposals to the live cluster.

Analog of cc/executor/ (SURVEY.md §2f): the Executor drives proposals through
a ClusterDriver (the ZK/admin bridge SPI) in throttled batches — replica
movements first, then leadership — with per-broker concurrency caps, a task
state machine, pluggable movement-ordering strategies, and graceful
user-triggered stop. Metric sampling pauses during execution, exactly as
ProposalExecutionRunnable does (cc/executor/Executor.java:546-626).
"""

from cruise_control_tpu.executor.task import ExecutionTask, TaskState, TaskType
from cruise_control_tpu.executor.strategy import (
    BaseReplicaMovementStrategy,
    PostponeUrpReplicaMovementStrategy,
    PrioritizeLargeReplicaMovementStrategy,
    PrioritizeSmallReplicaMovementStrategy,
    ReplicaMovementStrategy,
)
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.manager import ExecutionTaskManager
from cruise_control_tpu.executor.tracker import ExecutionTaskTracker
from cruise_control_tpu.executor.driver import ClusterDriver, SimulatorClusterDriver
from cruise_control_tpu.executor.executor import Executor, ExecutorConfig, ExecutorState
from cruise_control_tpu.executor.tcp_driver import TcpClusterDriver
from cruise_control_tpu.executor.validation import (
    TopologyFingerprint,
    TopologyView,
    validate_proposal,
    validate_proposals,
)

__all__ = [
    "BaseReplicaMovementStrategy",
    "ClusterDriver",
    "ExecutionTask",
    "ExecutionTaskManager",
    "ExecutionTaskPlanner",
    "ExecutionTaskTracker",
    "Executor",
    "ExecutorConfig",
    "ExecutorState",
    "PostponeUrpReplicaMovementStrategy",
    "PrioritizeLargeReplicaMovementStrategy",
    "PrioritizeSmallReplicaMovementStrategy",
    "ReplicaMovementStrategy",
    "SimulatorClusterDriver",
    "TaskState",
    "TaskType",
    "TcpClusterDriver",
    "TopologyFingerprint",
    "TopologyView",
    "validate_proposal",
    "validate_proposals",
]
