"""Cluster driver SPI — the ZK/admin bridge boundary.

Analog of the Scala ExecutorUtils shim (scala/executor/ExecutorUtils.scala:22:
write reassignment JSON to ZK, trigger preferred leader election, poll
progress). Anything that can start a replica movement and report its
completion can drive the executor; the simulator-backed driver closes the
loop in-process, with configurable completion latency to exercise the
executor's polling."""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

from cruise_control_tpu.executor.task import ExecutionTask, TaskType


class ClusterDriver:
    def start_replica_movement(self, task: ExecutionTask) -> None:
        """Begin moving replicas for the task's proposal (async)."""
        raise NotImplementedError

    def start_leadership_movement(self, task: ExecutionTask) -> None:
        raise NotImplementedError

    def poll(self) -> None:
        """Advance/refresh driver state (one reassignment-znode poll round)."""

    def is_finished(self, task: ExecutionTask) -> bool:
        raise NotImplementedError

    def has_ongoing_reassignment(self) -> bool:
        """Executor refuses to start over an in-progress external
        reassignment (cc/executor/Executor.java:494)."""
        return False


class SimulatorClusterDriver(ClusterDriver):
    """Drives a cruise_control_tpu.testing.SimulatedCluster.

    `latency_polls` simulates data-movement time: a movement completes only
    after that many poll() rounds, forcing the executor through its
    wait-for-finish loop."""

    def __init__(self, sim, latency_polls: int = 0):
        self._sim = sim
        self._latency = latency_polls
        self._pending: Dict[int, Tuple[ExecutionTask, int]] = {}
        self._lock = threading.Lock()

    def start_replica_movement(self, task: ExecutionTask) -> None:
        with self._lock:
            self._pending[task.execution_id] = (task, self._latency)

    def start_leadership_movement(self, task: ExecutionTask) -> None:
        with self._lock:
            self._pending[task.execution_id] = (task, self._latency)

    def poll(self) -> None:
        with self._lock:
            for eid, (task, remaining) in list(self._pending.items()):
                if remaining > 0:
                    self._pending[eid] = (task, remaining - 1)
                    continue
                self._apply(task)
                del self._pending[eid]

    def _apply(self, task: ExecutionTask) -> None:
        p = task.proposal
        if task.task_type == TaskType.INTER_BROKER_REPLICA_ACTION:
            removed = list(p.replicas_to_remove)
            adds = list(p.replicas_to_add)
            for i, dst in enumerate(adds):
                if i < len(removed):
                    self._sim.apply_movement(p.partition, removed[i], dst)
                else:
                    self._sim.add_replica(p.partition, dst)  # RF increase
            for src in removed[len(adds):]:  # RF decrease
                self._sim.remove_replica(p.partition, src)
            if p.has_leader_action:
                self._sim.apply_leadership(p.partition, p.new_leader)
        else:
            self._sim.apply_leadership(p.partition, p.new_leader)

    def is_finished(self, task: ExecutionTask) -> bool:
        with self._lock:
            if task.execution_id in self._pending:
                return False
        p = task.proposal
        if task.task_type == TaskType.LEADER_ACTION:
            return self._sim.leader_of(p.partition) == p.new_leader
        return all(self._sim.has_partition(p.partition, b) for b in p.replicas_to_add) and not any(
            self._sim.has_partition(p.partition, b) for b in p.replicas_to_remove
        )

    def has_ongoing_reassignment(self) -> bool:
        with self._lock:
            return bool(self._pending)


class ReassignmentJournalDriver(ClusterDriver):
    """File-journal driver: the direct analog of the reference's Scala shim
    writing reassignment JSON for the Kafka controller to act on
    (scala/executor/ExecutorUtils.scala:32 writes
    /admin/reassign_partitions; controller performs the movement and deletes
    the node).

    `journal_dir/reassign_partitions.json` holds the in-flight reassignment
    in the controller wire format
    ({"version": 1, "partitions": [{"topic", "partition", "replicas"}]});
    an external controller-side agent applies it and writes per-task acks
    into `journal_dir/completed/<execution_id>.json`. `poll()` merges new
    tasks into the journal (the reference merges with in-progress
    reassignments) and `is_finished` CONSUMES the ack file (reads and
    deletes) — the same write-then-watch contract as the ZK node, over a
    shared filesystem.

    Execution ids are epoch-seeded (ExecutionTaskPlanner starts at
    time_ns//1000 and counts up), so ids never recur across processes and an
    ack file is unambiguous evidence that its journal entry completed.
    Construction RECONCILES rather than sweeps: journal entries whose ack
    already exists are removed (their ack is consumed — the movement finished
    while no driver was watching); journal entries without an ack are KEPT as
    ongoing — `has_ongoing_reassignment` reports them and the executor
    refuses to start over them, mirroring the reference's
    ongoing-reassignment guard (cc/executor/Executor.java:494). Ack files
    matching no journal entry are orphans (their task was already consumed)
    and are deleted."""

    def __init__(self, journal_dir: str):
        import os

        self._dir = journal_dir
        self._completed_dir = os.path.join(journal_dir, "completed")
        os.makedirs(self._completed_dir, exist_ok=True)
        self._journal = os.path.join(journal_dir, "reassign_partitions.json")
        self._lock = threading.Lock()
        acked = set()
        for name in os.listdir(self._completed_dir):
            if name.endswith(".json") and name[:-5].isdigit():
                acked.add(int(name[:-5]))
        entries = self._read_journal()
        remaining = [e for e in entries if e.get("executionId") not in acked]
        if len(remaining) != len(entries):
            self._write_journal(remaining)
        live_ids = {e.get("executionId") for e in remaining}
        for eid in acked:
            if eid not in live_ids:
                try:
                    os.unlink(os.path.join(self._completed_dir, f"{eid}.json"))
                except OSError:
                    pass

    def _read_journal(self) -> List[Dict]:
        import json
        import os

        if not os.path.exists(self._journal):
            return []
        try:
            with open(self._journal) as f:
                return json.load(f).get("partitions", [])
        except (OSError, ValueError):
            return []

    def _write_journal(self, partitions: List[Dict]) -> None:
        import json
        import os

        tmp = self._journal + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": 1, "partitions": partitions}, f)
        os.replace(tmp, self._journal)  # atomic, like a ZK setData

    def _entry(self, task: ExecutionTask) -> Dict:
        p = task.proposal
        topic, _, part = (p.topic_partition or f"p-{p.partition}").rpartition("-")
        return {
            "topic": topic or f"p{p.partition}",
            "partition": int(part) if part.isdigit() else p.partition,
            "replicas": list(p.new_replicas),
            "executionId": task.execution_id,
        }

    def start_replica_movement(self, task: ExecutionTask) -> None:
        with self._lock:
            entries = self._read_journal()
            # merge with in-progress reassignments (ExecutorUtils :32 merges
            # into the existing znode content rather than replacing it)
            entries = [
                e for e in entries if e.get("executionId") != task.execution_id
            ] + [self._entry(task)]
            self._write_journal(entries)

    def start_leadership_movement(self, task: ExecutionTask) -> None:
        self.start_replica_movement(task)

    def is_finished(self, task: ExecutionTask) -> bool:
        import os

        ack = os.path.join(self._completed_dir, f"{task.execution_id}.json")
        if not os.path.exists(ack):
            return False
        with self._lock:
            remaining = [
                e
                for e in self._read_journal()
                if e.get("executionId") != task.execution_id
            ]
            self._write_journal(remaining)
            # consume the ack: the journal entry is gone, so the ack has
            # served its purpose and would otherwise accumulate forever
            try:
                os.unlink(ack)
            except OSError:
                pass
        return True

    def has_ongoing_reassignment(self) -> bool:
        return bool(self._read_journal())
