"""Cluster driver SPI — the ZK/admin bridge boundary.

Analog of the Scala ExecutorUtils shim (scala/executor/ExecutorUtils.scala:22:
write reassignment JSON to ZK, trigger preferred leader election, poll
progress). Anything that can start a replica movement and report its
completion can drive the executor; the simulator-backed driver closes the
loop in-process, with configurable completion latency to exercise the
executor's polling."""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

from cruise_control_tpu.executor.task import ExecutionTask, TaskType


class ClusterDriver:
    def start_replica_movement(self, task: ExecutionTask) -> None:
        """Begin moving replicas for the task's proposal (async)."""
        raise NotImplementedError

    def start_leadership_movement(self, task: ExecutionTask) -> None:
        raise NotImplementedError

    def poll(self) -> None:
        """Advance/refresh driver state (one reassignment-znode poll round)."""

    def is_finished(self, task: ExecutionTask) -> bool:
        raise NotImplementedError

    def has_ongoing_reassignment(self) -> bool:
        """Executor refuses to start over an in-progress external
        reassignment (cc/executor/Executor.java:494)."""
        return False


class SimulatorClusterDriver(ClusterDriver):
    """Drives a cruise_control_tpu.testing.SimulatedCluster.

    `latency_polls` simulates data-movement time: a movement completes only
    after that many poll() rounds, forcing the executor through its
    wait-for-finish loop."""

    def __init__(self, sim, latency_polls: int = 0):
        self._sim = sim
        self._latency = latency_polls
        self._pending: Dict[int, Tuple[ExecutionTask, int]] = {}
        self._lock = threading.Lock()

    def start_replica_movement(self, task: ExecutionTask) -> None:
        with self._lock:
            self._pending[task.execution_id] = (task, self._latency)

    def start_leadership_movement(self, task: ExecutionTask) -> None:
        with self._lock:
            self._pending[task.execution_id] = (task, self._latency)

    def poll(self) -> None:
        with self._lock:
            for eid, (task, remaining) in list(self._pending.items()):
                if remaining > 0:
                    self._pending[eid] = (task, remaining - 1)
                    continue
                self._apply(task)
                del self._pending[eid]

    def _apply(self, task: ExecutionTask) -> None:
        p = task.proposal
        if task.task_type == TaskType.INTER_BROKER_REPLICA_ACTION:
            removed = list(p.replicas_to_remove)
            adds = list(p.replicas_to_add)
            for i, dst in enumerate(adds):
                if i < len(removed):
                    self._sim.apply_movement(p.partition, removed[i], dst)
                else:
                    self._sim.add_replica(p.partition, dst)  # RF increase
            for src in removed[len(adds):]:  # RF decrease
                self._sim.remove_replica(p.partition, src)
            if p.has_leader_action:
                self._sim.apply_leadership(p.partition, p.new_leader)
        else:
            self._sim.apply_leadership(p.partition, p.new_leader)

    def is_finished(self, task: ExecutionTask) -> bool:
        with self._lock:
            if task.execution_id in self._pending:
                return False
        p = task.proposal
        if task.task_type == TaskType.LEADER_ACTION:
            return self._sim.leader_of(p.partition) == p.new_leader
        return all(self._sim.has_partition(p.partition, b) for b in p.replicas_to_add) and not any(
            self._sim.has_partition(p.partition, b) for b in p.replicas_to_remove
        )

    def has_ongoing_reassignment(self) -> bool:
        with self._lock:
            return bool(self._pending)
