"""The framework's main configuration.

Re-creates the reference's `KafkaCruiseControlConfig`
(cc/config/KafkaCruiseControlConfig.java, ~100 keys) with the same key names
and defaults for everything this framework supports, so an operator's
cruisecontrol.properties carries over. Goal class names accept both the
reference's Java class paths (mapped onto our goal registry by simple name) and
native `cruise_control_tpu...` paths.

Waived reference keys (present there, deliberately absent here): the eight
Kafka-client plumbing keys the reference passes straight into its embedded
NetworkClient/consumers — bootstrap.servers, client.id, connections.max.idle.ms,
metadata.max.age.ms, receive.buffer.bytes, send.buffer.bytes,
reconnect.backoff.ms, request.timeout.ms (KafkaCruiseControlConfig.java:724-806).
The TPU build has no in-process Kafka client: cluster I/O rides the agent wire
protocol (executor/tcp_driver.py, docs/CLUSTER_AGENT.md), whose transport knobs
live on the agent command line / driver constructor instead. Every other
reference key exists here under the identical name.
"""

from __future__ import annotations

from typing import Any, List, Mapping

from cruise_control_tpu.config.configdef import (
    AbstractConfig,
    ConfigDef,
    Importance,
    Type,
    at_least,
    between,
    load_properties,
)

# Default goal stack, same order as the reference's default.goals
# (config/cruisecontrol.properties, cc/config/KafkaCruiseControlConfig.java:1287-1322).
DEFAULT_GOALS = [
    "RackAwareGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
    "ReplicaDistributionGoal",
    "PotentialNwOutGoal",
    "DiskUsageDistributionGoal",
    "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal",
    "TopicReplicaDistributionGoal",
    "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal",
]

HARD_GOALS = [
    "RackAwareGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
]

ANOMALY_DETECTION_GOALS = HARD_GOALS


def _config_def() -> ConfigDef:
    d = ConfigDef()
    # --- analyzer thresholds (reference defaults at KafkaCruiseControlConfig.java:1100-1250)
    for res in ("cpu", "disk", "network.inbound", "network.outbound"):
        d.define(f"{res}.balance.threshold", Type.DOUBLE, 1.10, at_least(1.0), Importance.HIGH,
                 f"Upper/lower margin around the average {res} utilization that counts as balanced.")
        d.define(f"{res}.capacity.threshold", Type.DOUBLE, 0.80, between(0.0, 1.0), Importance.HIGH,
                 f"Maximum fraction of {res} capacity usable before the capacity goal acts.")
        d.define(f"{res}.low.utilization.threshold", Type.DOUBLE, 0.0, between(0.0, 1.0), Importance.LOW,
                 f"Below this fraction of capacity a broker is considered idle for {res} balancing.")
    d.define("replica.count.balance.threshold", Type.DOUBLE, 1.10, at_least(1.0), Importance.MEDIUM,
             "Margin around the average replica count per broker that counts as balanced.")
    d.define("leader.replica.count.balance.threshold", Type.DOUBLE, 1.10, at_least(1.0), Importance.MEDIUM,
             "Margin around the average leader count per broker that counts as balanced.")
    d.define("topic.replica.count.balance.threshold", Type.DOUBLE, 3.00, at_least(1.0), Importance.LOW,
             "Margin around the average per-topic replica count per broker.")
    d.define("goal.violation.distribution.threshold.multiplier", Type.DOUBLE, 1.00, at_least(1.0), Importance.MEDIUM,
             "Relaxation multiplier applied to distribution-goal thresholds during self-healing.")
    d.define("max.replicas.per.broker", Type.LONG, 10000, at_least(0), Importance.MEDIUM,
             "Hard cap on replicas per broker (ReplicaCapacityGoal).")
    d.define("proposal.expiration.ms", Type.LONG, 900000, at_least(0), Importance.MEDIUM,
             "Precomputed proposals older than this are discarded and recomputed.")
    d.define("max.proposal.candidates", Type.INT, 10, at_least(1), Importance.LOW,
             "Precomputed proposal candidates kept per computation round.")
    d.define("num.proposal.precompute.threads", Type.INT, 1, at_least(1), Importance.LOW,
             "Worker threads for background proposal precomputation.")
    d.define("default.goals", Type.LIST, ",".join(DEFAULT_GOALS), None, Importance.HIGH,
             "Goals used (in priority order) when a request does not name goals.")
    d.define("goals", Type.LIST, ",".join(DEFAULT_GOALS), None, Importance.HIGH,
             "All goals this instance may use.")
    d.define("hard.goals", Type.LIST, ",".join(HARD_GOALS), None, Importance.HIGH,
             "Goals that must be satisfied by every proposal.")
    d.define("anomaly.detection.goals", Type.LIST, ",".join(ANOMALY_DETECTION_GOALS), None, Importance.MEDIUM,
             "Goals the goal-violation detector dry-runs.")
    # --- optimizer (TPU-native keys; no reference equivalent)
    d.define("optimizer.batch.actions.per.round", Type.INT, 16, at_least(1), Importance.MEDIUM,
             "Max non-conflicting actions applied per batched-greedy round (1 = faithful greedy).")
    d.define("optimizer.max.rounds.per.goal", Type.INT, 64, at_least(1), Importance.MEDIUM,
             "Upper bound on batched-greedy rounds per goal.")
    d.define("optimizer.candidate.replicas.per.broker", Type.INT, 8, at_least(1), Importance.MEDIUM,
             "Top-k replicas per overloaded broker considered as move sources each round.")
    d.define("optimizer.swap.broker.pairs", Type.INT, 8, at_least(1), Importance.MEDIUM,
             "Hot/cold broker pairs examined per swap round when moves stall.")
    d.define("optimizer.swap.candidate.replicas", Type.INT, 8, at_least(1), Importance.MEDIUM,
             "Candidate replicas per broker in the swap search grid.")
    d.define("optimizer.chunk.rounds", Type.INT, 32, at_least(0), Importance.MEDIUM,
             "Max optimizer rounds per device call (chunked goal machine); bounds device-call "
             "duration for remote-TPU transports. 0 = single fused-stack call.")
    d.define("optimizer.apply.waves", Type.INT, 8, at_least(1), Importance.MEDIUM,
             "Conflict-free apply waves per round (sequential depth of the shortlist apply).")
    d.define("optimizer.drain.source.brokers", Type.INT, 512, at_least(1), Importance.MEDIUM,
             "Top-V source brokers per drain/fill round (batched mode).")
    d.define("optimizer.drain.candidates.per.broker", Type.INT, 8, at_least(1), Importance.MEDIUM,
             "Drain candidates pulled from each source broker's sorted run per round.")
    d.define("optimizer.drain.destination.brokers", Type.INT, 64, at_least(1), Importance.MEDIUM,
             "Destination candidates per drained replica (goal-aware lists).")
    d.define("optimizer.bulk.count.waves", Type.INT, 16, at_least(0), Importance.MEDIUM,
             "Max conflict-free waves per bulk count-rebalance round: count-distribution goals "
             "drain their whole surplus/deficit grid per round instead of searching "
             "round-by-round. 0 disables the bulk planner.")
    d.define("optimizer.bulk.min.brokers", Type.INT, 32, at_least(0), Importance.LOW,
             "Bulk count planner size floor: clusters smaller than this keep the per-round "
             "engines only (they already nominate every broker per round at that scale).")
    d.define("optimizer.polish.rounds", Type.INT, 0, at_least(0), Importance.MEDIUM,
             "After the priority stack completes, re-run every goal up to this many rounds "
             "under the FULL merged acceptance tables (retries goals an earlier lexicographic "
             "pass stalled). 0 disables the polish pass.")
    d.define("optimizer.bucket.partitions", Type.BOOLEAN, True, None, Importance.MEDIUM,
             "Pad the partition/topic axes to coarse shape buckets so partition-count and "
             "topic-count churn reuses compiled programs instead of recompiling the stack.")
    d.define("optimizer.bucket.brokers", Type.BOOLEAN, True, None, Importance.MEDIUM,
             "Pad the broker/host/rack axes up the geometric bucket ladder so broker churn "
             "(add/remove, count drift) reuses the warm compiled program of the shared "
             "bucket. Padding brokers are invalid: never destinations, never in any goal "
             "window — bucketed runs are result-identical to the exact shape.")
    d.define("optimizer.bucket.ratio", Type.DOUBLE, 1.25, between(1.01, 2.0), Importance.LOW,
             "Geometric step of the broker bucket ladder (1.25 = quarter-octave rungs, "
             "worst-case 25% padding).")
    d.define("optimizer.bucket.floor", Type.INT, 64, at_least(1), Importance.LOW,
             "Broker counts at or below this stay exact (no padding); tiny clusters "
             "recompile per shape but pay zero padding overhead.")
    d.define("optimizer.incremental.enabled", Type.BOOLEAN, True, None, Importance.MEDIUM,
             "Arm the incremental rebalancing lane after each proposal: model drift is "
             "applied to the device-resident prepared context as in-place typed deltas "
             "and only the sensitivity-affected goal subset is re-solved "
             "(analyzer/incremental.py, docs/RESILIENCE.md).")
    d.define("optimizer.incremental.max.deltas", Type.INT, 64, at_least(1), Importance.MEDIUM,
             "Max typed deltas absorbed in one incremental re-proposal; larger drifts "
             "fall back to a full from-scratch solve (the delta batch is padded to this "
             "size, so it is also the scatter kernel's compiled batch shape).")
    d.define("optimizer.incremental.fallback.full", Type.BOOLEAN, True, None, Importance.MEDIUM,
             "When the incremental lane declines (shape bucket overflow, stale "
             "generation, sensitivity says all goals, ...), transparently run the full "
             "goal-violation rebalance instead of raising.")
    # --- monitor (windows/sampling; reference defaults in cruisecontrol.properties)
    d.define("partition.metrics.window.ms", Type.LONG, 300000, at_least(1), Importance.HIGH,
             "Width of one partition-metric aggregation window.")
    d.define("num.partition.metrics.windows", Type.INT, 1, at_least(1), Importance.HIGH,
             "Number of partition-metric windows retained.")
    d.define("min.samples.per.partition.metrics.window", Type.INT, 1, at_least(1), Importance.MEDIUM,
             "Minimum samples for a partition window to be valid without extrapolation.")
    d.define("broker.metrics.window.ms", Type.LONG, 300000, at_least(1), Importance.HIGH,
             "Width of one broker-metric aggregation window.")
    d.define("num.broker.metrics.windows", Type.INT, 20, at_least(1), Importance.HIGH,
             "Number of broker-metric windows retained.")
    d.define("min.samples.per.broker.metrics.window", Type.INT, 1, at_least(1), Importance.MEDIUM,
             "Minimum samples for a broker window to be valid without extrapolation.")
    d.define("metric.sampling.interval.ms", Type.LONG, 120000, at_least(1), Importance.MEDIUM,
             "Period of the sampling loop.")
    d.define("num.metric.fetchers", Type.INT, 1, at_least(1), Importance.LOW,
             "Parallel sampling fetchers; partitions are assigned across them.")
    d.define("metric.sampler.class", Type.CLASS,
             "cruise_control_tpu.monitor.sampler.NoopSampler", None, Importance.MEDIUM,
             "MetricSampler implementation (pluggable).")
    d.define("sample.store.class", Type.CLASS,
             "cruise_control_tpu.monitor.sample_store.NoopSampleStore", None, Importance.MEDIUM,
             "SampleStore implementation (pluggable); replayed on startup.")
    d.define("broker.capacity.config.resolver.class", Type.CLASS,
             "cruise_control_tpu.monitor.metadata.BrokerCapacityConfigFileResolver", None, Importance.MEDIUM,
             "BrokerCapacityConfigResolver implementation.")
    d.define("capacity.config.file", Type.STRING, "config/capacity.json", None, Importance.MEDIUM,
             "JSON file of per-broker capacities for the file resolver.")
    d.define("min.valid.partition.ratio", Type.DOUBLE, 0.995, between(0.0, 1.0), Importance.MEDIUM,
             "Minimum monitored-partition fraction for a model to be considered complete.")
    d.define("leader.network.inbound.weight.for.cpu.util", Type.DOUBLE, 0.6, at_least(0.0), Importance.LOW,
             "Fixed-coefficient CPU attribution: weight of leader bytes-in (ModelUtils).")
    d.define("follower.network.inbound.weight.for.cpu.util", Type.DOUBLE, 0.3, at_least(0.0), Importance.LOW,
             "Fixed-coefficient CPU attribution: weight of follower bytes-in (ModelUtils).")
    d.define("leader.network.outbound.weight.for.cpu.util", Type.DOUBLE, 0.1, at_least(0.0), Importance.LOW,
             "Fixed-coefficient CPU attribution: weight of leader bytes-out (ModelUtils).")
    d.define("use.linear.regression.model", Type.BOOLEAN, False, None, Importance.LOW,
             "Use the trained linear-regression CPU model instead of fixed coefficients.")
    # --- executor (reference defaults in cruisecontrol.properties)
    d.define("num.concurrent.partition.movements.per.broker", Type.INT, 10, at_least(1), Importance.HIGH,
             "In-flight inter-broker replica moves allowed per broker.")
    d.define("num.concurrent.leader.movements", Type.INT, 1000, at_least(1), Importance.HIGH,
             "In-flight leadership moves allowed cluster-wide.")
    d.define("execution.progress.check.interval.ms", Type.LONG, 10000, at_least(1), Importance.MEDIUM,
             "Poll period for task completion during execution.")
    d.define("default.replica.movement.strategies", Type.LIST,
             "cruise_control_tpu.executor.strategy.BaseReplicaMovementStrategy", None, Importance.LOW,
             "Strategy chain ordering replica movements.")
    d.define("removed.broker.history.retention.ms", Type.LONG, 43200000, at_least(0), Importance.LOW,
             "How long removed-broker history is kept.")
    d.define("demoted.broker.history.retention.ms", Type.LONG, 43200000, at_least(0), Importance.LOW,
             "How long demoted-broker history is kept.")
    # --- anomaly detection (reference defaults at KafkaCruiseControlConfig.java)
    d.define("anomaly.detection.interval.ms", Type.LONG, 300000, at_least(1), Importance.MEDIUM,
             "Period of the anomaly detectors.")
    d.define("anomaly.notifier.class", Type.CLASS,
             "cruise_control_tpu.detector.notifier.NoopNotifier", None, Importance.MEDIUM,
             "AnomalyNotifier implementation.")
    d.define("metric.anomaly.finder.class", Type.CLASS,
             "cruise_control_tpu.detector.metric_anomaly.NoopMetricAnomalyFinder", None, Importance.LOW,
             "MetricAnomalyFinder implementation.")
    d.define("metric.anomaly.percentile.upper.threshold", Type.DOUBLE, 90.0, between(0.0, 100.0), Importance.LOW,
             "Percentile above which a current metric is anomalous.")
    d.define("metric.anomaly.percentile.lower.threshold", Type.DOUBLE, 10.0, between(0.0, 100.0), Importance.LOW,
             "Percentile below which a current metric is anomalous.")
    d.define("self.healing.enabled", Type.BOOLEAN, False, None, Importance.HIGH,
             "Master switch for self-healing on detected anomalies.")
    d.define("broker.failure.alert.threshold.ms", Type.LONG, 900000, at_least(0), Importance.MEDIUM,
             "Grace period before a broker failure raises an alert.")
    d.define("broker.failure.self.healing.threshold.ms", Type.LONG, 1800000, at_least(0), Importance.MEDIUM,
             "Grace period before a broker failure triggers self-healing.")
    d.define("failed.brokers.file.path", Type.STRING, "failed_brokers.json", None, Importance.LOW,
             "Where failed-broker times are persisted across restarts.")
    # --- webserver / user tasks (reference defaults at KafkaCruiseControlConfig.java:861+)
    d.define("webserver.http.port", Type.INT, 9090, at_least(0), Importance.HIGH, "REST port.")
    d.define("webserver.http.address", Type.STRING, "127.0.0.1", None, Importance.HIGH, "REST bind address.")
    d.define("webserver.api.urlprefix", Type.STRING, "/kafkacruisecontrol/*", None, Importance.LOW, "API prefix.")
    d.define("webserver.http.cors.enabled", Type.BOOLEAN, False, None, Importance.LOW, "Enable CORS headers.")
    d.define("max.active.user.tasks", Type.INT, 5, at_least(1), Importance.MEDIUM,
             "Concurrent async user tasks allowed.")
    d.define("max.cached.completed.user.tasks", Type.INT, 25, at_least(0), Importance.LOW,
             "Completed user tasks kept for result retrieval.")
    d.define("completed.user.task.retention.time.ms", Type.LONG, 86400000, at_least(0), Importance.LOW,
             "How long completed user tasks are retained.")
    d.define("two.step.verification.enabled", Type.BOOLEAN, False, None, Importance.LOW,
             "Require review/approval of POST requests via the purgatory.")
    d.define("two.step.purgatory.max.requests", Type.INT, 25, at_least(1), Importance.LOW,
             "Max requests parked in the purgatory.")
    d.define("two.step.purgatory.retention.time.ms", Type.LONG, 1209600000, at_least(0), Importance.LOW,
             "Retention of reviewed requests in the purgatory.")
    # --- remaining reference keys (KafkaCruiseControlConfig.java), same names
    # and defaults so an operator's cruisecontrol.properties parses unchanged
    d.define("self.healing.goals", Type.LIST, "", None, Importance.MEDIUM,
             "Goals used for self-healing; empty = the anomaly-detection goals.")
    d.define("intra.broker.goals", Type.LIST, "", None, Importance.LOW,
             "Intra-broker (disk-to-disk) goals; empty = disabled.")
    d.define("topics.excluded.from.partition.movement", Type.STRING, "", None, Importance.MEDIUM,
             "Regex of topics whose replicas must never move.")
    d.define("replica.movement.strategies", Type.LIST,
             "cruise_control_tpu.executor.strategy.PostponeUrpReplicaMovementStrategy,"
             "cruise_control_tpu.executor.strategy.PrioritizeLargeReplicaMovementStrategy,"
             "cruise_control_tpu.executor.strategy.PrioritizeSmallReplicaMovementStrategy,"
             "cruise_control_tpu.executor.strategy.BaseReplicaMovementStrategy",
             None, Importance.LOW,
             "Replica-movement strategies available for chaining.")
    d.define("executor.notifier.class", Type.CLASS,
             "cruise_control_tpu.executor.notifier.LoggingExecutorNotifier", None, Importance.LOW,
             "ExecutorNotifier implementation.")
    d.define("metric.sampler.partition.assignor.class", Type.CLASS,
             "cruise_control_tpu.monitor.fetcher.DefaultMetricSamplerPartitionAssignor",
             None, Importance.LOW,
             "MetricSamplerPartitionAssignor implementation for the fetcher manager.")
    d.define("network.client.provider.class", Type.CLASS,
             "cruise_control_tpu.monitor.metadata.MetadataClient", None, Importance.LOW,
             "Cluster-facing network client provider (host-side I/O).")
    d.define("max.allowed.extrapolations.per.partition", Type.INT, 5, at_least(0), Importance.LOW,
             "Partitions with more extrapolated windows than this are invalid.")
    d.define("max.allowed.extrapolations.per.broker", Type.INT, 5, at_least(0), Importance.LOW,
             "Brokers with more extrapolated windows than this are invalid.")
    d.define("linear.regression.model.cpu.util.bucket.size", Type.INT, 5, between(1, 100), Importance.LOW,
             "CPU-utilization bucket width (percent) for LR observation balancing.")
    d.define("anomaly.detection.allow.capacity.estimation", Type.BOOLEAN, True, None, Importance.LOW,
             "Allow estimated broker capacities during anomaly detection.")
    d.define("goal.violation.exclude.recently.demoted.brokers", Type.BOOLEAN, True, None, Importance.LOW,
             "Exclude recently demoted brokers from goal-violation leadership fixes.")
    d.define("goal.violation.exclude.recently.removed.brokers", Type.BOOLEAN, True, None, Importance.LOW,
             "Exclude recently removed brokers from goal-violation replica fixes.")
    d.define("broker.failure.exclude.recently.demoted.brokers", Type.BOOLEAN, True, None, Importance.LOW,
             "Exclude recently demoted brokers from broker-failure leadership fixes.")
    d.define("broker.failure.exclude.recently.removed.brokers", Type.BOOLEAN, True, None, Importance.LOW,
             "Exclude recently removed brokers from broker-failure replica fixes.")
    d.define("num.cached.recent.anomaly.states", Type.INT, 10, at_least(1), Importance.LOW,
             "Recent anomaly states kept per anomaly type for /state.")
    d.define("demotion.history.retention.time.ms", Type.LONG, 1209600000, at_least(0), Importance.LOW,
             "How long demotion history is kept (reference default 336h).")
    d.define("removal.history.retention.time.ms", Type.LONG, 1209600000, at_least(0), Importance.LOW,
             "How long removal history is kept (reference default 336h).")
    d.define("max.cached.completed.kafka.monitor.user.tasks", Type.INT, 25, at_least(0), Importance.LOW,
             "Completed monitor-type user tasks retained (per-type retention).")
    d.define("max.cached.completed.kafka.admin.user.tasks", Type.INT, 25, at_least(0), Importance.LOW,
             "Completed admin-type user tasks retained (per-type retention).")
    # per-type caches/retention for CC-endpoint tasks; negative = fall back
    # to the generic key (the reference defaults these to null with the same
    # fallback, KafkaCruiseControlConfig.java:967-1022)
    d.define("max.cached.completed.cruise.control.monitor.user.tasks", Type.INT, -1, None,
             Importance.LOW, "Completed CC-monitor-type user tasks retained; "
             "negative = max.cached.completed.user.tasks.")
    d.define("max.cached.completed.cruise.control.admin.user.tasks", Type.INT, -1, None,
             Importance.LOW, "Completed CC-admin-type user tasks retained; "
             "negative = max.cached.completed.user.tasks.")
    d.define("completed.cruise.control.monitor.user.task.retention.time.ms", Type.LONG, -1, None,
             Importance.LOW, "Retention of completed CC-monitor-type user tasks; "
             "negative = completed.user.task.retention.time.ms.")
    d.define("completed.cruise.control.admin.user.task.retention.time.ms", Type.LONG, -1, None,
             Importance.LOW, "Retention of completed CC-admin-type user tasks; "
             "negative = completed.user.task.retention.time.ms.")
    d.define("completed.kafka.monitor.user.task.retention.time.ms", Type.LONG, -1, None,
             Importance.LOW, "Retention of completed kafka-monitor-type user tasks; "
             "negative = completed.user.task.retention.time.ms.")
    d.define("completed.kafka.admin.user.task.retention.time.ms", Type.LONG, -1, None,
             Importance.LOW, "Retention of completed kafka-admin-type user tasks; "
             "negative = completed.user.task.retention.time.ms.")
    d.define("partition.metric.sample.aggregator.completeness.cache.size", Type.INT, 5,
             at_least(0), Importance.LOW,
             "Reference-parity key (KafkaCruiseControlConfig.java:940). The "
             "TPU aggregator recomputes completeness per call — one dense "
             "reduction over the ring buffers, cheaper than the reference's "
             "object walk it caches — so this key is accepted but unused.")
    d.define("broker.metric.sample.aggregator.completeness.cache.size", Type.INT, 5,
             at_least(0), Importance.LOW,
             "Reference-parity key (KafkaCruiseControlConfig.java:1049); "
             "accepted but unused, as the partition twin above.")
    d.define("linear.regression.model.min.num.cpu.util.buckets", Type.INT, 5, at_least(1),
             Importance.LOW,
             "Minimum full CPU-utilization buckets required before the linear "
             "regression model is considered trained (KafkaCruiseControlConfig.java:1121).")
    d.define("linear.regression.model.required.samples.per.bucket", Type.INT, 100, at_least(1),
             Importance.LOW,
             "Training samples required per CPU-utilization bucket "
             "(KafkaCruiseControlConfig.java:1126).")
    # static web-UI serving (KafkaCruiseControlMain.java:75-111)
    d.define("webserver.ui.diskpath", Type.STRING, "", None, Importance.LOW,
             "Directory of static web-UI files to serve; empty = disabled.")
    d.define("webserver.ui.urlprefix", Type.STRING, "/*", None, Importance.LOW,
             "URL prefix the static web-UI is served under.")
    d.define("webserver.http.cors.origin", Type.STRING, "*", None, Importance.LOW,
             "CORS Access-Control-Allow-Origin value.")
    d.define("webserver.http.cors.allowmethods", Type.STRING, "OPTIONS, GET, POST", None, Importance.LOW,
             "CORS Access-Control-Allow-Methods value.")
    d.define("webserver.http.cors.exposeheaders", Type.STRING, "User-Task-ID", None, Importance.LOW,
             "CORS Access-Control-Expose-Headers value.")
    d.define("failed.brokers.zk.path", Type.STRING, "/CruiseControlBrokerList", None, Importance.LOW,
             "Reference-compat alias of failed.brokers.file.path for ZK deployments.")
    d.define("zookeeper.connect", Type.STRING, "", None, Importance.LOW,
             "Reference-compat: ZK quorum of the managed cluster (unused by the "
             "simulator driver; a ZK-backed ClusterDriver reads it).")
    d.define("zookeeper.security.enabled", Type.BOOLEAN, False, None, Importance.LOW,
             "Reference-compat: secure ZK for the managed cluster.")
    # --- TPU execution
    d.define("tpu.mesh.axis.name", Type.STRING, "partitions", None, Importance.LOW,
             "Mesh axis name candidate/partition arrays are sharded over "
             "(parallel/sharding.make_mesh_from_config; the shard_map kernels "
             "read it back off the mesh, docs/SHARDING.md).")
    d.define("tpu.mesh.devices", Type.INT, 0, at_least(0), Importance.LOW,
             "Devices in the partition-axis mesh: 0 = auto (all visible "
             "devices, mesh only when more than one), 1 = sharding disabled, "
             "N = exactly the first N visible devices (error when fewer).")
    # cclint: disable=reg-config-key-reachable -- reserved knob: donation is unconditional in the jit factories (optimizer.py donate_argnums); making it configurable changes program identity and waits for the ROADMAP-1 on-device round fusion, whose donation set is certified per commit by trace-donation-integrity and whose while/scan carries by trace-carry-stability (lint/entrypoints.py: fused-stack-step / chunked-goal-machine)
    d.define("tpu.donate.model.buffers", Type.BOOLEAN, True, None, Importance.LOW,
             "Donate model buffers between optimizer rounds to avoid copies.")
    # --- resilience (TPU-native keys; docs/RESILIENCE.md)
    d.define("executor.task.deadline.s", Type.DOUBLE, 0.0, at_least(0.0), Importance.MEDIUM,
             "Per-task wall-clock deadline during execution: a task IN_PROGRESS longer "
             "than this is aborted (ABORTING -> ABORTED) and its broker slots released, "
             "while the rest of the batch continues. 0 disables (the poll cap still "
             "bounds the phase).")
    d.define("executor.retry.attempts", Type.INT, 4, at_least(1), Importance.MEDIUM,
             "Attempts per cluster-agent op (reconnect-on-failure between attempts). "
             "All five protocol ops are retry-safe: finished/ongoing/ping are reads, "
             "reassign/leader are executionId-idempotent.")
    d.define("executor.retry.backoff.s", Type.DOUBLE, 0.05, at_least(0.0), Importance.LOW,
             "Base backoff before the first retry; doubles per attempt.")
    d.define("executor.retry.max.backoff.s", Type.DOUBLE, 2.0, at_least(0.0), Importance.LOW,
             "Backoff ceiling for the exponential ladder.")
    d.define("selfhealing.breaker.threshold", Type.INT, 3, at_least(1), Importance.MEDIUM,
             "Consecutive failed self-healing fixes of one anomaly type before that "
             "type's circuit breaker opens and fixes degrade to delayed CHECKs.")
    d.define("selfhealing.breaker.cooldown.s", Type.DOUBLE, 300.0, at_least(0.0), Importance.MEDIUM,
             "Seconds an open self-healing breaker waits before admitting one "
             "half-open probe fix (success closes it, failure re-opens).")
    d.define("executor.proposal.revalidate", Type.BOOLEAN, True, None, Importance.MEDIUM,
             "Revalidate generation-stamped proposals against fresh metadata at "
             "admission and before every dispatch batch; stale proposals are trimmed "
             "with per-proposal reason codes (DEST_DEAD, REPLICA_MOVED, TOPIC_GONE, ...) "
             "into the execution summary instead of being dispatched or raising.")
    d.define("executor.proposal.max.generation.skew", Type.INT, 8, at_least(0), Importance.MEDIUM,
             "Abort the whole proposal batch (through the never-raise contract) and "
             "notify the anomaly detector to recompute when the monitor generation "
             "has moved more than this past the batch's model-build stamp. "
             "0 disables the abort (per-proposal trimming still applies).")
    # --- observability (TPU-native keys; docs/OBSERVABILITY.md)
    d.define("observability.trace.ring.size", Type.INT, 4096, at_least(16), Importance.LOW,
             "Completed tracer spans retained in memory (the /trace window); "
             "oldest spans drop first.")
    d.define("observability.trace.jsonl.path", Type.STRING, "", None, Importance.LOW,
             "Append every completed tracer span as one JSON line to this file "
             "(durable traces); empty = disabled.")
    d.define("observability.profile.dir", Type.STRING, "", None, Importance.LOW,
             "Arm a one-shot JAX profiler capture: the first proposal computation "
             "after startup writes an xplane trace here (parse with "
             "scripts/parse_xplane.py); empty = disabled.")
    d.define("observability.history.interval.s", Type.DOUBLE, 0.0, at_least(0.0), Importance.LOW,
             "Cadence of the background sensor time-series sampler (GET /timeseries). "
             "0 (the default) disables the sampler thread; snapshots still happen at "
             "proposal/execution boundaries and on /timeseries scrapes.")
    d.define("observability.history.ring.size", Type.INT, 512, at_least(16), Importance.LOW,
             "Sensor-registry snapshots retained in the time-series ring; oldest "
             "points drop first.")
    d.define("observability.history.jsonl.path", Type.STRING, "", None, Importance.LOW,
             "Append every history snapshot as one JSON line to this file (durable "
             "time series, next to the trace JSONL sink); empty = disabled.")
    d.define("telemetry.enabled", Type.BOOLEAN, True, None, Importance.LOW,
             "Collect device telemetry (per-program XLA cost analysis, device memory "
             "watermarks, host-device transfer meters) into the sensor registry and "
             "GET /perf; disable to shave the (already <2%) collection overhead.")
    d.define("optimizer.provenance.ledger", Type.BOOLEAN, True, None, Importance.LOW,
             "Collect the decision-provenance MoveLedger: compiled programs snapshot "
             "the assignment + attribution tags once per goal phase, and every run's "
             "per-move goal/engine/round attribution becomes queryable via "
             "GET /explain and scripts/diff_runs.py. Disabling removes the snapshot "
             "buffers from the compiled programs (recompile on toggle); proposals "
             "are byte-identical either way.")
    d.define("observability.ledger.runs", Type.INT, 8, at_least(1), Importance.LOW,
             "Recorded optimization runs retained by the provenance MoveLedger "
             "(GET /explain's query window); oldest runs evict first.")
    return d


_DEF = _config_def()


def _simple_goal_name(name: str) -> str:
    """Accept reference Java class paths by mapping to their simple name."""
    return name.rsplit(".", 1)[-1]


class CruiseControlConfig(AbstractConfig):
    def __init__(self, props: Mapping[str, Any] | None = None):
        super().__init__(_DEF, dict(props or {}))

    @classmethod
    def from_properties_file(cls, path: str) -> "CruiseControlConfig":
        return cls(load_properties(path))

    def goal_names(self, key: str = "default.goals") -> List[str]:
        return [_simple_goal_name(g) for g in self.get_list(key)]
