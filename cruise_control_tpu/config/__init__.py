from cruise_control_tpu.config.balancing import BalancingConstraint  # noqa: F401
from cruise_control_tpu.config.configdef import (  # noqa: F401
    AbstractConfig,
    ConfigDef,
    ConfigException,
    load_properties,
)
from cruise_control_tpu.config.cruise_config import (  # noqa: F401
    ANOMALY_DETECTION_GOALS,
    DEFAULT_GOALS,
    HARD_GOALS,
    CruiseControlConfig,
)
