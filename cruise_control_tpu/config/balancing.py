"""Balancing constraint: the analyzer's threshold bundle as kernel-ready arrays.

Mirrors cc/analyzer/BalancingConstraint.java:22-66 — per-resource balance
percentages, capacity thresholds, low-utilization thresholds, replica/leader/
topic-replica balance percentages, max replicas per broker, and the
self-healing distribution threshold multiplier — stored as numpy arrays indexed
by `Resource` so goal kernels can consume them without Python dict lookups.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource

_RES_KEY = {
    Resource.CPU: "cpu",
    Resource.NW_IN: "network.inbound",
    Resource.NW_OUT: "network.outbound",
    Resource.DISK: "disk",
}


@dataclasses.dataclass(frozen=True)
class BalancingConstraint:
    #: balance margin per resource (>= 1.0); balanced iff util in [avg/x, avg*x]
    resource_balance_percentage: np.ndarray  # f32[4]
    #: usable fraction of capacity per resource (<= 1.0)
    capacity_threshold: np.ndarray  # f32[4]
    #: below this fraction of capacity a broker is "low utilization"
    low_utilization_threshold: np.ndarray  # f32[4]
    replica_balance_percentage: float = 1.10
    leader_replica_balance_percentage: float = 1.10
    topic_replica_balance_percentage: float = 3.00
    goal_violation_distribution_threshold_multiplier: float = 1.00
    max_replicas_per_broker: int = 10000

    @classmethod
    def from_config(cls, config) -> "BalancingConstraint":
        balance = np.ones(NUM_RESOURCES, dtype=np.float32)
        capacity = np.ones(NUM_RESOURCES, dtype=np.float32)
        low = np.zeros(NUM_RESOURCES, dtype=np.float32)
        for res in Resource:
            key = _RES_KEY[res]
            balance[res] = config.get_double(f"{key}.balance.threshold")
            capacity[res] = config.get_double(f"{key}.capacity.threshold")
            low[res] = config.get_double(f"{key}.low.utilization.threshold")
        return cls(
            resource_balance_percentage=balance,
            capacity_threshold=capacity,
            low_utilization_threshold=low,
            replica_balance_percentage=config.get_double("replica.count.balance.threshold"),
            leader_replica_balance_percentage=config.get_double("leader.replica.count.balance.threshold"),
            topic_replica_balance_percentage=config.get_double("topic.replica.count.balance.threshold"),
            goal_violation_distribution_threshold_multiplier=config.get_double(
                "goal.violation.distribution.threshold.multiplier"
            ),
            max_replicas_per_broker=config.get_long("max.replicas.per.broker"),
        )

    @classmethod
    def default(cls) -> "BalancingConstraint":
        return cls(
            resource_balance_percentage=np.full(NUM_RESOURCES, 1.10, dtype=np.float32),
            capacity_threshold=np.full(NUM_RESOURCES, 0.80, dtype=np.float32),
            low_utilization_threshold=np.zeros(NUM_RESOURCES, dtype=np.float32),
        )

    def with_multiplier_applied(self) -> "BalancingConstraint":
        """Thresholds relaxed for self-healing runs.

        Mirrors how distribution goals widen their balance margin by
        `goal.violation.distribution.threshold.multiplier` when triggered by a
        goal violation (cc/analyzer/goals/ResourceDistributionGoal.java
        balancePercentageWithMargin usage).
        """
        m = self.goal_violation_distribution_threshold_multiplier
        return dataclasses.replace(
            self,
            resource_balance_percentage=np.float32(1.0)
            + (self.resource_balance_percentage - np.float32(1.0)) * np.float32(m),
            replica_balance_percentage=1.0 + (self.replica_balance_percentage - 1.0) * m,
            leader_replica_balance_percentage=1.0 + (self.leader_replica_balance_percentage - 1.0) * m,
            topic_replica_balance_percentage=1.0 + (self.topic_replica_balance_percentage - 1.0) * m,
        )
