"""Kafka-style typed configuration framework.

Re-creates the behavior of the reference's config core
(core/common/config/ConfigDef.java + AbstractConfig.java): a registry of typed
keys with defaults, range/choice validators, importance levels and docs; parsing
from dicts or .properties files; and reflection-based plug-in instantiation
(`AbstractConfig.getConfiguredInstance`) used to load goals, samplers, sample
stores, notifiers and movement strategies by class path.
"""

from __future__ import annotations

import dataclasses
import enum
import importlib
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional


class ConfigException(ValueError):
    """Mirrors core/common/config/ConfigException.java."""


class Type(enum.Enum):
    BOOLEAN = "boolean"
    STRING = "string"
    INT = "int"
    LONG = "long"
    DOUBLE = "double"
    LIST = "list"
    CLASS = "class"
    PASSWORD = "password"


class Importance(enum.Enum):
    HIGH = "high"
    MEDIUM = "medium"
    LOW = "low"


class Password:
    """Opaque wrapper so secrets never repr into logs (core ConfigDef.Password)."""

    def __init__(self, value: str):
        self.value = value

    def __repr__(self) -> str:  # pragma: no cover - trivial
        return "[hidden]"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Password) and other.value == self.value

    def __hash__(self) -> int:
        return hash(self.value)


#: Sentinel for keys with no default (required keys).
NO_DEFAULT = object()


def at_least(minimum) -> Callable[[str, Any], None]:
    def validate(name: str, value) -> None:
        if value is not None and value < minimum:
            raise ConfigException(f"{name} must be at least {minimum}, got {value}")

    return validate


def between(lo, hi) -> Callable[[str, Any], None]:
    def validate(name: str, value) -> None:
        if value is not None and not (lo <= value <= hi):
            raise ConfigException(f"{name} must be in [{lo}, {hi}], got {value}")

    return validate


def in_choices(choices: Iterable[str]) -> Callable[[str, Any], None]:
    allowed = set(choices)

    def validate(name: str, value) -> None:
        if value is not None and value not in allowed:
            raise ConfigException(f"{name} must be one of {sorted(allowed)}, got {value}")

    return validate


@dataclasses.dataclass
class ConfigKey:
    name: str
    type: Type
    default: Any
    validator: Optional[Callable[[str, Any], None]]
    importance: Importance
    doc: str

    @property
    def has_default(self) -> bool:
        return self.default is not NO_DEFAULT


class ConfigDef:
    """Registry of config keys; `parse` turns raw strings into typed values."""

    def __init__(self) -> None:
        self._keys: Dict[str, ConfigKey] = {}

    def define(
        self,
        name: str,
        type: Type,
        default: Any = NO_DEFAULT,
        validator: Optional[Callable[[str, Any], None]] = None,
        importance: Importance = Importance.MEDIUM,
        doc: str = "",
    ) -> "ConfigDef":
        if name in self._keys:
            raise ConfigException(f"Config key {name} is defined twice")
        if default is not NO_DEFAULT and default is not None:
            default = _parse_value(name, type, default)
        self._keys[name] = ConfigKey(name, type, default, validator, importance, doc)
        return self

    def keys(self) -> Mapping[str, ConfigKey]:
        return self._keys

    def parse(self, props: Mapping[str, Any]) -> Dict[str, Any]:
        values: Dict[str, Any] = {}
        for name, key in self._keys.items():
            if name in props:
                value = _parse_value(name, key.type, props[name])
            elif key.has_default:
                value = key.default
            else:
                raise ConfigException(f"Missing required configuration '{name}'")
            if key.validator is not None:
                key.validator(name, value)
            values[name] = value
        return values


def _parse_value(name: str, type: Type, value: Any) -> Any:
    try:
        if value is None:
            return None
        if type is Type.BOOLEAN:
            if isinstance(value, bool):
                return value
            s = str(value).strip().lower()
            if s not in ("true", "false"):
                raise ConfigException(f"{name}: expected boolean, got {value!r}")
            return s == "true"
        if type is Type.STRING:
            return str(value).strip()
        if type is Type.INT or type is Type.LONG:
            return int(str(value).strip())
        if type is Type.DOUBLE:
            return float(str(value).strip())
        if type is Type.LIST:
            if isinstance(value, (list, tuple)):
                return list(value)
            s = str(value).strip()
            return [item.strip() for item in s.split(",") if item.strip()] if s else []
        if type is Type.CLASS:
            return str(value).strip()
        if type is Type.PASSWORD:
            return value if isinstance(value, Password) else Password(str(value))
    except ConfigException:
        raise
    except (TypeError, ValueError) as e:
        raise ConfigException(f"Invalid value {value!r} for configuration {name}: {e}") from e
    raise ConfigException(f"Unknown type {type} for configuration {name}")


def load_properties(path: str) -> Dict[str, str]:
    """Parse a Java-style .properties file (the reference's config format)."""
    props: Dict[str, str] = {}
    with open(path, "r", encoding="utf-8") as f:
        pending = ""
        for raw in f:
            line = pending + raw.strip()
            pending = ""
            if not line or line.startswith("#") or line.startswith("!"):
                continue
            if line.endswith("\\"):
                pending = line[:-1]
                continue
            _store_property(props, line)
        if pending:
            _store_property(props, pending)
    return props


def _store_property(props: Dict[str, str], line: str) -> None:
    for sep in ("=", ":"):
        idx = _unescaped_index(line, sep)
        if idx >= 0:
            props[line[:idx].strip()] = line[idx + 1 :].strip()
            return
    props[line.strip()] = ""


def _unescaped_index(line: str, sep: str) -> int:
    idx = -1
    start = 0
    while True:
        idx = line.find(sep, start)
        if idx <= 0 or line[idx - 1] != "\\":
            return idx
        start = idx + 1


class AbstractConfig:
    """Typed view over parsed values + plug-in loading.

    Mirrors core/common/config/AbstractConfig.java: `get_*` typed accessors,
    `originals` passthrough for unknown keys (handed to plug-ins on configure),
    and `get_configured_instance` reflection loading.
    """

    def __init__(self, definition: ConfigDef, props: Mapping[str, Any]):
        self._definition = definition
        self._originals = dict(props)
        self._values = definition.parse(props)
        self._used: set = set()

    def originals(self) -> Dict[str, Any]:
        return dict(self._originals)

    def _get(self, name: str):
        if name not in self._values:
            raise ConfigException(f"Unknown configuration '{name}'")
        self._used.add(name)
        return self._values[name]

    def get(self, name: str):
        return self._get(name)

    def get_boolean(self, name: str) -> bool:
        return self._get(name)

    def get_int(self, name: str) -> int:
        return self._get(name)

    def get_long(self, name: str) -> int:
        return self._get(name)

    def get_double(self, name: str) -> float:
        return self._get(name)

    def get_string(self, name: str) -> str:
        return self._get(name)

    def get_list(self, name: str) -> List[str]:
        value = self._get(name)
        return list(value) if value is not None else []

    def unused(self) -> List[str]:
        """Supplied keys never read through an accessor (Kafka AbstractConfig
        semantics: originals minus used, regardless of being defined)."""
        return sorted(set(self._originals) - self._used)

    def get_configured_instance(self, name: str, expected_type: type):
        """Instantiate the class named by config key `name` and configure it."""
        class_path = self._get(name)
        return self.instantiate(class_path, expected_type)

    def get_configured_instances(self, name: str, expected_type: type) -> List[Any]:
        return [self.instantiate(cp, expected_type) for cp in self.get_list(name)]

    def instantiate(self, class_path: str, expected_type: type):
        cls = resolve_class(class_path)
        if not (isinstance(cls, type) and issubclass(cls, expected_type)):
            raise ConfigException(
                f"{class_path} is not a subclass of {expected_type.__name__}"
            )
        instance = cls()
        configure = getattr(instance, "configure", None)
        if callable(configure):
            configure(self.originals())
        return instance


def resolve_class(class_path: str):
    """Import `pkg.module.Class` (reflection-style plug-in loading)."""
    module_name, _, cls_name = class_path.rpartition(".")
    if not module_name:
        raise ConfigException(f"Invalid class path {class_path!r}")
    try:
        module = importlib.import_module(module_name)
        return getattr(module, cls_name)
    except (ImportError, AttributeError) as e:
        raise ConfigException(f"Could not load class {class_path!r}: {e}") from e
