"""JAX platform pinning that cannot hang the process.

Round-1 failure mode: the environment's sitecustomize pins ``jax_platforms``
to the tunneled TPU platform programmatically, so when that tunnel is absent
or unreachable, the very first backend touch (``jax.devices()``) blocks
forever — env vars alone don't override it, ``jax.config`` must be updated
before any backend initializes (see tests/conftest.py, which already does
this for the test suite).

Two entry points:

- ``pin_cpu(device_count=None)``: unconditionally pin the CPU platform (and
  optionally a virtual device count) before any backend init. Used by the
  multichip dry run, which by contract runs on virtual CPU devices.
- ``ensure_live_backend(timeout_s)``: probe default-platform init in a
  *subprocess* with a hard timeout; if it completes, leave the default
  platform (real TPU) in place, otherwise fall back to ``pin_cpu``. Used by
  the benchmark so a dead TPU tunnel degrades to a labeled CPU number
  instead of an rc=124 with no output.
"""

from __future__ import annotations

import os
import subprocess
import sys


def _add_host_device_flag(n: int) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n}"
    if "xla_force_host_platform_device_count" in flags:
        kept = [
            f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f
        ]
        os.environ["XLA_FLAGS"] = " ".join(kept + [flag])
    else:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()


def pin_cpu(device_count: int | None = None) -> None:
    """Pin JAX to the host CPU platform before any backend initializes."""
    if device_count is not None:
        _add_host_device_flag(device_count)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        # backend already initialized by the caller; if it initialized it was
        # live, so there is nothing to rescue — leave it alone
        pass


def probe_default_backend(timeout_s: float = 75.0) -> str | None:
    """Return the default backend's platform name, or None if init hangs/fails.

    Runs in a subprocess so a hanging backend init can be killed; the parent
    process never touches the backend until the probe verdict is in.
    """
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print(jax.default_backend())"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0:
        return None
    name = out.stdout.strip().splitlines()
    return name[-1] if name else None


def ensure_live_backend(timeout_s: float = 75.0, log=None) -> str:
    """Guarantee the in-process backend will init promptly; return its name.

    If the default platform (TPU under axon) proves live within ``timeout_s``,
    nothing is changed and its name is returned. Otherwise the process is
    pinned to CPU and ``"cpu"`` is returned.
    """
    if log is None:
        def log(msg):  # pragma: no cover - trivial default
            print(msg, file=sys.stderr, flush=True)

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        pin_cpu()
        log("platform: cpu (pre-pinned via JAX_PLATFORMS)")
        return "cpu"
    log(f"probing default JAX backend (subprocess, {timeout_s:.0f}s timeout)...")
    name = probe_default_backend(timeout_s)
    if name is None:
        pin_cpu()
        log("platform: default backend init hung or failed -> pinned cpu")
        return "cpu"
    log(f"platform: default backend live -> {name}")
    return name
