"""JAX platform pinning that cannot hang the process.

Round-1 failure mode: the environment's sitecustomize pins ``jax_platforms``
to the tunneled TPU platform programmatically, so when that tunnel is absent
or unreachable, the very first backend touch (``jax.devices()``) blocks
forever — env vars alone don't override it, ``jax.config`` must be updated
before any backend initializes (see tests/conftest.py, which already does
this for the test suite).

Two entry points:

- ``pin_cpu(device_count=None)``: unconditionally pin the CPU platform (and
  optionally a virtual device count) before any backend init. Used by the
  multichip dry run, which by contract runs on virtual CPU devices.
- ``ensure_live_backend(timeout_s)``: probe default-platform init in a
  *subprocess* with a hard timeout; if it completes, leave the default
  platform (real TPU) in place, otherwise fall back to ``pin_cpu``. Used by
  the benchmark so a dead TPU tunnel degrades to a labeled CPU number
  instead of an rc=124 with no output.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import NamedTuple


class ProbeResult(NamedTuple):
    """Outcome of ensure_live_backend, recorded by callers that must make a
    CPU fallback impossible to miss (the benchmark's compact JSON lines)."""

    platform: str  # the platform the process will actually use
    fallback: bool  # True when the default backend was dead and cpu was pinned
    attempts: int  # subprocess probes performed (0 when pre-pinned)


def _add_host_device_flag(n: int) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n}"
    if "xla_force_host_platform_device_count" in flags:
        kept = [
            f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f
        ]
        os.environ["XLA_FLAGS"] = " ".join(kept + [flag])
    else:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()


def pin_cpu(device_count: int | None = None) -> None:
    """Pin JAX to the host CPU platform before any backend initializes."""
    if device_count is not None:
        _add_host_device_flag(device_count)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        # backend already initialized by the caller; if it initialized it was
        # live, so there is nothing to rescue — leave it alone
        pass


def probe_default_backend(timeout_s: float = 75.0, env=None) -> str | None:
    """Return the default backend's platform name, or None if init hangs/fails.

    Runs in a subprocess so a hanging backend init can be killed; the parent
    process never touches the backend until the probe verdict is in.
    """
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print(jax.default_backend())"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0:
        return None
    name = out.stdout.strip().splitlines()
    return name[-1] if name else None


def probe_only(timeout_s: float = 75.0) -> str | None:
    """One subprocess probe of the DEFAULT platform, touching nothing in this
    process — safe to call even after the caller pinned CPU (the subprocess
    gets a cleaned environment so the parent's pin does not leak in). Used to
    re-check a dead tunnel between benchmark stages."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    return probe_default_backend(timeout_s, env=env)


def ensure_live_backend(timeout_s: float = 75.0, log=None,
                        retries: int = 1, backoff_s: float = 10.0) -> ProbeResult:
    """Guarantee the in-process backend will init promptly; return the verdict.

    The default platform (TPU under axon) is probed in a subprocess up to
    ``retries`` times with ``backoff_s`` sleeps between attempts. The default
    is ONE probe — interactive service startup (main.py) should degrade to
    CPU after a single timeout, not block for minutes. Benchmarks opt into
    retries explicitly (bench.py, BENCH_PROBE_RETRIES): a tunnel that hiccups
    at minute 0 must not silently convert the headline into a CPU number. If
    any probe succeeds, nothing is changed; otherwise the process is pinned
    to CPU and the result says ``fallback=True``.
    """
    if log is None:
        def log(msg):  # pragma: no cover - trivial default
            print(msg, file=sys.stderr, flush=True)

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        pin_cpu()
        log("platform: cpu (pre-pinned via JAX_PLATFORMS)")
        return _record(ProbeResult(platform="cpu", fallback=False, attempts=0))
    retries = max(1, int(retries))
    for attempt in range(1, retries + 1):
        log(
            f"probing default JAX backend (attempt {attempt}/{retries}, "
            f"subprocess, {timeout_s:.0f}s timeout)..."
        )
        name = probe_default_backend(timeout_s)
        if name is not None:
            log(f"platform: default backend live -> {name}")
            return _record(ProbeResult(platform=name, fallback=False, attempts=attempt))
        if attempt < retries:
            log(f"probe {attempt} hung or failed; retrying in {backoff_s:.0f}s")
            time.sleep(backoff_s)
    pin_cpu()
    log(f"platform: default backend dead after {retries} probes -> pinned cpu")
    return _record(ProbeResult(platform="cpu", fallback=True, attempts=retries))


def _record(result: ProbeResult) -> ProbeResult:
    """Stamp the probe verdict into the telemetry fingerprint: a CPU fallback
    must be visible in every provenance block downstream (the BENCH_r05
    artifact-drift fix), not only in the caller that probed."""
    from cruise_control_tpu.common.telemetry import TELEMETRY

    TELEMETRY.set_probe_fallback(result.fallback)
    return result
