"""Device-mesh parallelism for the analyzer kernels.

The reference is a single-JVM multi-threaded optimizer; its only "distributed"
surface is client-server I/O (SURVEY.md §2, §5). Here the optimizer itself is
the SPMD program: candidate-action grids are data-parallel over the partition
axis, so the natural mesh is one `partitions` axis over all chips — per-round
scoring shards over ICI and the top-k / argmax reductions become XLA
collectives inserted by GSPMD.
"""

from cruise_control_tpu.parallel.sharding import (
    PARTITION_AXIS,
    make_mesh,
    pad_partitions,
    place_aggregates,
    place_static,
    shard_model,
)

__all__ = [
    "PARTITION_AXIS",
    "make_mesh",
    "pad_partitions",
    "place_aggregates",
    "place_static",
    "shard_model",
]
