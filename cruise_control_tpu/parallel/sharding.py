"""Partition-axis sharding over a `jax.sharding.Mesh`.

Design (scaling-book recipe): pick ONE mesh axis, annotate the input shardings,
let GSPMD insert the collectives.

- Arrays with a leading partition axis (`part_load [P, M]`, `assignment
  [P, R]`, `rack_replica_count [P, NR]`, per-partition masks/scores) are
  sharded over `partitions`.
- Per-broker / per-rack / per-topic aggregates (`broker_load [B, 4]`,
  `replica_count [B]`, `topic_replica_count [T, B]`, thresholds) are
  replicated: every chip scores its partition shard against the full broker
  state, exactly the layout `ClusterModel.utilizationMatrix` suggests
  (cc/model/ClusterModel.java:1113).
- The per-round reduction (argmax over candidates, global `top_k` over
  partitions) crosses the mesh axis once per round — an all-gather of
  [K] winners, tiny against ICI bandwidth.

The same program runs unchanged on 1 chip (trivial mesh) or N chips; the
driver's `dryrun_multichip` validates the N-chip lowering on a virtual CPU
mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from cruise_control_tpu.analyzer.context import Aggregates, StaticCtx
from cruise_control_tpu.models.flat_model import FlatClusterModel

PARTITION_AXIS = "partitions"


def make_mesh(
    n_devices: Optional[int] = None, devices=None,
    axis_name: str = PARTITION_AXIS,
) -> Mesh:
    """1-D mesh over the partition axis. Defaults to all visible devices.

    `axis_name` renames the mesh axis (`tpu.mesh.axis.name`); everything
    downstream — placement specs here, the shard_map kernels in
    `parallel.spmd` — reads the name back off the mesh (`mesh.axis_names[0]`)
    rather than assuming the constant, so a renamed axis flows through
    shardings, collectives, and traces consistently."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def make_mesh_from_config(cfg) -> Optional[Mesh]:
    """Mesh from the `tpu.mesh.*` keys (`main --config` ->
    `GoalOptimizer(mesh=...)`).

    `tpu.mesh.devices`: 0 = auto — all visible devices, and only when more
    than one is visible (a 1-device mesh adds padding without parallelism);
    1 = sharding explicitly disabled; N>1 = exactly the first N visible
    devices (raises when fewer exist — a silently smaller mesh would change
    which programs the compile cache considers warm)."""
    n = cfg.get_int("tpu.mesh.devices")
    axis = cfg.get_string("tpu.mesh.axis.name") or PARTITION_AXIS
    if n == 1:
        return None
    if n == 0:
        if len(jax.devices()) < 2:
            return None
        return make_mesh(axis_name=axis)
    return make_mesh(n, axis_name=axis)


def _p_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard dim 0 over the partition axis, replicate the rest."""
    return NamedSharding(
        mesh, PartitionSpec(mesh.axis_names[0], *([None] * (ndim - 1)))
    )


def _replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def pad_partitions_to(model: FlatClusterModel, target: int) -> FlatClusterModel:
    """Pad the partition axis up to exactly `target` rows.

    Padding rows are fully-invalid partitions (`assignment == -1` in every
    slot, zero load): every candidate built from them fails the structural
    `valid` mask and their slots route to the segment-sum overflow bucket, so
    they contribute to no aggregate and generate no proposals.
    """
    p = model.num_partitions
    pad = target - p
    if pad <= 0:
        return model
    a = np.asarray(model.assignment)
    load = np.asarray(model.part_load)
    topic = np.asarray(model.topic_id)
    return model._replace(
        assignment=np.concatenate(
            [a, np.full((pad, a.shape[1]), -1, dtype=a.dtype)], axis=0
        ),
        part_load=np.concatenate(
            [load, np.zeros((pad, load.shape[1]), dtype=load.dtype)], axis=0
        ),
        topic_id=np.concatenate([topic, np.zeros(pad, dtype=topic.dtype)], axis=0),
    )


def pad_partitions(model: FlatClusterModel, multiple: int) -> FlatClusterModel:
    """Pad the partition axis to a multiple of the mesh size."""
    p = model.num_partitions
    return pad_partitions_to(model, p + ((-p) % multiple))


def size_bucket(n: int) -> int:
    """Round an axis size up to a coarse bucket (1/8 granularity).

    Keyed into the goal-step compile cache through `Dims`, this keeps churn
    (partition create/delete, topic add/remove) from recompiling the whole
    goal stack: any size inside the same bucket reuses the padded program.
    Padding overhead is bounded at 12.5%; tiny fixtures (<= 32) are left
    exact. The 32..64 range buckets to 64 so the seeded ~60-partition models
    that several test modules share (test_executor / test_facade_detector /
    test_rest) key to ONE compiled stack program instead of three.
    """
    return geom_bucket(n, ratio=1.125, floor=32)


#: historical name for the partition-axis use
partition_bucket = size_bucket


def geom_bucket(n: int, ratio: float = 1.25, floor: int = 64) -> int:
    """Round an axis size up a geometric bucket ladder (~`ratio` steps).

    The generalized form of `size_bucket` for every model axis (brokers,
    hosts, racks, topics, partitions): rungs are multiples of a power-of-two
    step, with granularity derived from `ratio` (1.25 -> quarter-octave
    rungs, worst-case padding 25%; 1.125 -> eighth-octave, 12.5%). Any axis
    size inside a rung reuses the rung's compiled programs, so churn — a
    broker add/remove, partition-count drift — stays inside a warm program
    instead of recompiling the stack. Sizes <= `floor` stay EXACT: tiny
    fixtures pay no padding, and the sub-`floor` regime is where padded and
    exact candidate-grid clamps could diverge (docs/OPTIMIZER.md); the
    32..64 range buckets to 64 (one shared rung for the seeded ~60-row test
    models) whenever the floor admits it.
    """
    if n <= floor:
        return n
    if n <= 64:
        return 64
    g = max(2, round(1.0 / (ratio - 1.0)))  # rungs per octave
    step = max(1, (1 << (n.bit_length() - 1)) // g)
    return ((n + step - 1) // step) * step


def pad_brokers_to(
    model: FlatClusterModel, target_b: int, num_racks: int, num_hosts: int
) -> FlatClusterModel:
    """Pad the broker axis up to exactly `target_b` rows.

    Padding brokers are INVALID, not merely dead: zero capacity, DEAD state
    at the model level (so model-level alive-masked stats skip them), and —
    through `build_static_ctx(valid_brokers=...)` — excluded from BOTH the
    `alive` and `dead` masks, so they are never move destinations, never
    evacuation sources, and never enter any goal's averages or windows.
    They live on the padded rack/host ids (when `num_racks`/`num_hosts`
    exceed the real counts) so real racks' and hosts' aggregates stay
    byte-identical to the unpadded model; with no padded rack/host rows
    they round-robin over the real ones, which zero-capacity zero-load rows
    cannot perturb.
    """
    b = model.num_brokers
    pad = target_b - b
    if pad <= 0:
        return model
    cap = np.asarray(model.broker_capacity)
    rack = np.asarray(model.broker_rack)
    host = np.asarray(model.broker_host)
    state = np.asarray(model.broker_state)
    nr = int(rack.max()) + 1 if rack.size else 0
    nh = int(host.max()) + 1 if host.size else 0
    idx = np.arange(pad)
    pad_rack = (
        nr + idx % (num_racks - nr) if num_racks > nr else idx % max(nr, 1)
    ).astype(rack.dtype)
    pad_host = (
        nh + idx % (num_hosts - nh) if num_hosts > nh else idx % max(nh, 1)
    ).astype(host.dtype)
    from cruise_control_tpu.common.resources import BrokerState

    return model._replace(
        broker_capacity=np.concatenate(
            [cap, np.zeros((pad, cap.shape[1]), dtype=cap.dtype)], axis=0
        ),
        broker_rack=np.concatenate([rack, pad_rack]),
        broker_host=np.concatenate([host, pad_host]),
        broker_state=np.concatenate(
            [state, np.full(pad, BrokerState.DEAD, dtype=state.dtype)]
        ),
    )


def shard_model(model: FlatClusterModel, mesh: Mesh) -> FlatClusterModel:
    """Place a (pre-padded) model's arrays on the mesh."""
    return FlatClusterModel(
        assignment=jax.device_put(model.assignment, _p_sharding(mesh, 2)),
        part_load=jax.device_put(model.part_load, _p_sharding(mesh, 2)),
        topic_id=jax.device_put(model.topic_id, _p_sharding(mesh, 1)),
        broker_capacity=jax.device_put(model.broker_capacity, _replicated(mesh)),
        broker_rack=jax.device_put(model.broker_rack, _replicated(mesh)),
        broker_host=jax.device_put(model.broker_host, _replicated(mesh)),
        broker_state=jax.device_put(model.broker_state, _replicated(mesh)),
    )


def place_static(static: StaticCtx, mesh: Mesh) -> StaticCtx:
    """Annotate a StaticCtx: partition-axis arrays sharded, the rest replicated."""
    sharded_fields = {"part_load", "topic_id", "movable_partition"}

    def place(name, x):
        arr = jax.numpy.asarray(x)
        if name in sharded_fields:
            return jax.device_put(arr, _p_sharding(mesh, arr.ndim))
        return jax.device_put(arr, _replicated(mesh))

    return StaticCtx(**{k: place(k, v) for k, v in static._asdict().items()})


def place_aggregates(agg: Aggregates, mesh: Mesh) -> Aggregates:
    """Annotate Aggregates: per-partition arrays sharded, summaries replicated."""
    sharded_fields = {"assignment", "rack_replica_count", "touch_tag"}

    def place(name, x):
        arr = jax.numpy.asarray(x)
        if name in sharded_fields:
            return jax.device_put(arr, _p_sharding(mesh, arr.ndim))
        return jax.device_put(arr, _replicated(mesh))

    return Aggregates(**{k: place(k, v) for k, v in agg._asdict().items()})


def place_replicated(tree, mesh: Mesh):
    """Replicate every leaf of a pytree on the mesh (acceptance tables &co:
    broker/topic-sized summaries that every shard reads in full)."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(jax.numpy.asarray(x), _replicated(mesh)), tree
    )
