# cclint: kernel-module
"""Explicit `shard_map` SPMD kernels over the `partitions` mesh axis.

`parallel.sharding` places arrays (partition-axis fields sharded, broker/
rack/topic aggregates replicated) and lets GSPMD infer the collectives.
This module makes the per-round hot path *explicit* instead: the [P, R, K]
candidate grid — the dominant compute of the exhaustive scoring round
(analyzer.optimizer one_round) — runs as a `shard_map` program where each
device scores only its own partition shard against the replicated broker
state, and the mesh is crossed exactly once per round.

Round anatomy (make_grid_shortlist):

  1. **Local scoring** — each device builds the move/leadership grids over
     its P/D partition rows (actions.make_move_batch is row-local: every
     candidate reads only `act.p` rows of the sharded fields plus the
     replicated broker aggregates) and reduces to a per-partition best
     (score, kind, slot, dst). Zero communication.
  2. **Local top-k** — `lax.top_k` over the shard's per-partition bests.
     k_local = min(k_sel, P/D), so the union of per-shard winners always
     contains the global top-k_sel.
  3. **One all-gather** — the per-shard winner tuples (score, global index,
     kind, slot, dst) cross the mesh once: 5 arrays of k_local elements per
     device, tiny against ICI bandwidth.
  4. **Deterministic merge** — every device sorts the gathered [D * k_local]
     winners by (-score, global index) and keeps the first k_sel. This
     reproduces `lax.top_k`'s value order AND its lowest-index tie-break
     bit-for-bit, which is what makes a mesh-N run provenance-digest-equal
     to mesh-1: the shortlist — the only cross-shard decision — is
     identical by construction, and everything downstream (apply waves,
     precision wave) computes from the replicated shortlist + replicated
     broker aggregates. Shard-order-dependent reductions (psum of float
     scores, gather-order argmax) are exactly what this merge avoids.

The apply path stays outside the shard_map: winner application touches
[k_sel] rows (gather + scatter into the sharded assignment/touch_tag with
replicated indices) and the replicated broker aggregates, both of which
GSPMD already lowers without extra mesh crossings.

`make_partition_stats` is the integer-`psum` companion: exact per-shard
counts reduced across the mesh (int sums are associative, so unlike float
reductions they cannot perturb digests), used by the multichip dryrun to
certify shard coverage and registered as a lint trace entry.

All kernels use `check_rep=False`: the replicated outputs are produced from
all-gathered (or psum'd) values by identical per-device computation, which
shard_map's static replication checker cannot see through the sort/gather
ops; the mesh-equivalence tests assert the stronger property (bit-identical
decisions) end to end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec

from cruise_control_tpu.analyzer.context import Aggregates, StaticCtx
from cruise_control_tpu.parallel.sharding import PARTITION_AXIS

#: StaticCtx fields carried with a leading partition axis (must mirror
#: parallel.sharding.place_static — the shard_map in_specs and the GSPMD
#: placement hints describe the SAME layout, so no resharding happens at the
#: shard_map boundary).
STATIC_SHARDED_FIELDS = frozenset({"part_load", "topic_id", "movable_partition"})

#: Aggregates fields with a leading partition axis (mirror of
#: parallel.sharding.place_aggregates).
AGG_SHARDED_FIELDS = frozenset({"assignment", "rack_replica_count", "touch_tag"})


def static_partition_specs(axis: str = PARTITION_AXIS) -> StaticCtx:
    """PartitionSpec tree for a StaticCtx (shard_map in_specs / lint entries)."""
    return StaticCtx(**{
        f: PartitionSpec(axis) if f in STATIC_SHARDED_FIELDS else PartitionSpec()
        for f in StaticCtx._fields
    })


def agg_partition_specs(axis: str = PARTITION_AXIS) -> Aggregates:
    """PartitionSpec tree for Aggregates (shard_map in_specs / lint entries)."""
    return Aggregates(**{
        f: PartitionSpec(axis) if f in AGG_SHARDED_FIELDS else PartitionSpec()
        for f in Aggregates._fields
    })


def replicated_specs(tree):
    """A PartitionSpec() for every leaf of an arbitrary pytree (goal state,
    acceptance tables: broker/topic-sized values every shard reads whole)."""
    return jax.tree_util.tree_map(lambda _: PartitionSpec(), tree)


def make_grid_shortlist(mesh: Mesh, goal, dims, settings):
    """Build the SPMD grid-scoring round kernel for one goal.

    Returns shortlist(static, agg, gs, tables, dst_cands) ->
    (top_scores f32[k_sel], sel_p i32[k_sel], sel_kind i32[k_sel],
    sel_slot i32[k_sel], sel_dst i32[k_sel]) — bit-identical to the
    unsharded `lax.top_k` shortlist of analyzer.optimizer's one_round
    (see module docstring for why), with the [P, R, K] scoring grid
    partitioned across the mesh. Traceable inside jit / while_loop; the
    caller guarantees dims.num_partitions is a multiple of mesh.size
    (GoalOptimizer._build_ctx pads to it).
    """
    from cruise_control_tpu.analyzer.acceptance import score_batch
    from cruise_control_tpu.analyzer.actions import (
        KIND_LEADERSHIP,
        KIND_MOVE,
        make_leadership_batch,
        make_move_batch,
    )

    p_count, r = dims.num_partitions, dims.max_rf
    n_dev = mesh.size
    axis = mesh.axis_names[0]  # tpu.mesh.axis.name flows through the mesh
    if p_count % n_dev != 0:
        raise ValueError(
            f"partition axis {p_count} not divisible by mesh size {n_dev}"
        )
    p_local = p_count // n_dev
    k_sel = max(1, min(settings.batch_k, p_count))
    # min(k_sel, P/D) per shard: when the shard is smaller than the
    # shortlist, it contributes ALL its rows, so the gathered union still
    # contains the global top-k_sel
    k_loc = min(k_sel, p_local)
    use_leadership = goal.uses_leadership and r >= 2

    def local_grid(static: StaticCtx, agg: Aggregates, gs, tables, dst_cands):
        # identical math to the unsharded grid, over this shard's rows: the
        # candidate builders and scoring kernels only read `act.p` rows of
        # the sharded fields (actions.make_move_batch; acceptance.py), so
        # local row indices against local shards produce bitwise-identical
        # per-candidate scores
        kk = dst_cands.shape[0]
        best_score = jnp.full((p_local,), -jnp.inf)
        best_kind = jnp.zeros((p_local,), dtype=jnp.int32)
        best_slot = jnp.zeros((p_local,), dtype=jnp.int32)
        best_dst = jnp.zeros((p_local,), dtype=jnp.int32)

        if goal.uses_moves:
            mv = make_move_batch(static.part_load, agg.assignment, dst_cands)
            s = score_batch(static, agg, mv, goal, gs, tables)
            s = jnp.broadcast_to(s, (p_local, r, kk)).reshape(p_local, r * kk)
            j = jnp.argmax(s, axis=1)
            best_score = jnp.take_along_axis(s, j[:, None], axis=1)[:, 0]
            best_kind = jnp.full((p_local,), KIND_MOVE, dtype=jnp.int32)
            best_slot = (j // kk).astype(jnp.int32)
            best_dst = dst_cands[(j % kk).astype(jnp.int32)]

        if use_leadership:
            lb = make_leadership_batch(static.part_load, agg.assignment)
            sl = score_batch(static, agg, lb, goal, gs, tables)
            sl = jnp.broadcast_to(sl, (p_local, r - 1))
            j2 = jnp.argmax(sl, axis=1)
            sbest = jnp.take_along_axis(sl, j2[:, None], axis=1)[:, 0]
            lead_slot = (j2 + 1).astype(jnp.int32)
            take_lead = sbest > best_score
            best_score = jnp.maximum(best_score, sbest)
            best_kind = jnp.where(take_lead, KIND_LEADERSHIP, best_kind)
            best_slot = jnp.where(take_lead, lead_slot, best_slot)
            rows = jnp.arange(p_local, dtype=jnp.int32)
            best_dst = jnp.where(
                take_lead, agg.assignment[rows, lead_slot], best_dst
            )

        # per-shard winners -> global indices
        loc_scores, loc_p = jax.lax.top_k(best_score, k_loc)
        offset = jax.lax.axis_index(axis).astype(jnp.int32) * p_local
        glob_p = loc_p.astype(jnp.int32) + offset

        # the ONE mesh crossing of the round: [D, k_loc] winner tuples
        g_score, g_p, g_kind, g_slot, g_dst = jax.lax.all_gather(
            (loc_scores, glob_p, best_kind[loc_p], best_slot[loc_p],
             best_dst[loc_p]),
            axis,
        )
        g_score = g_score.reshape(-1)
        g_p = g_p.reshape(-1)
        g_kind = g_kind.reshape(-1)
        g_slot = g_slot.reshape(-1)
        g_dst = g_dst.reshape(-1)

        # deterministic merge == global lax.top_k: descending score, ties to
        # the LOWEST global partition index (XLA top_k's stable tie-break)
        order = jnp.lexsort((g_p, -g_score))
        sel = order[:k_sel]
        return g_score[sel], g_p[sel], g_kind[sel], g_slot[sel], g_dst[sel]

    static_spec = static_partition_specs(axis)
    agg_spec = agg_partition_specs(axis)
    rep = PartitionSpec()

    def shortlist(static: StaticCtx, agg: Aggregates, gs, tables, dst_cands):
        fn = shard_map(
            local_grid, mesh,
            in_specs=(static_spec, agg_spec, replicated_specs(gs),
                      replicated_specs(tables), rep),
            out_specs=(rep, rep, rep, rep, rep),
            check_rep=False,
        )
        return fn(static, agg, gs, tables, dst_cands)

    return shortlist


def make_partition_stats(mesh: Mesh):
    """Exact integer shard-coverage stats, reduced with explicit `psum`.

    Returns stats(static, agg) -> (movable i32[], assigned_slots i32[],
    rows i32[]): the mesh-wide count of movable partitions, populated
    assignment slots, and partition rows, each computed per shard and
    psum'd across `partitions`. Integer sums are associative, so the mesh
    total is exactly the mesh-1 value — the dryrun's shard-coverage
    certificate (every row is owned by exactly one shard) and the lint
    trace tier's smallest sharded entry.
    """

    axis = mesh.axis_names[0]

    def local_stats(static: StaticCtx, agg: Aggregates):
        movable = jnp.sum(static.movable_partition.astype(jnp.int32))
        assigned = jnp.sum((agg.assignment >= 0).astype(jnp.int32))
        rows = jnp.full((), agg.assignment.shape[0], dtype=jnp.int32)
        return (
            jax.lax.psum(movable, axis),
            jax.lax.psum(assigned, axis),
            jax.lax.psum(rows, axis),
        )

    rep = PartitionSpec()

    def stats(static: StaticCtx, agg: Aggregates):
        fn = shard_map(
            local_stats, mesh,
            in_specs=(static_partition_specs(axis), agg_partition_specs(axis)),
            out_specs=(rep, rep, rep),
            check_rep=False,
        )
        return fn(static, agg)

    return stats
