"""Broker-side metrics agent analog.

The reference runs `CruiseControlMetricsReporter` inside every Kafka broker
(mr/CruiseControlMetricsReporter.java:41) pumping ~50 typed raw metrics to the
`__CruiseControlMetrics` topic. Here the agent is a host-side sampler thread
publishing the same taxonomy through a pluggable transport (in-memory queue,
JSONL file, or any user SPI impl) that the monitor's sampler consumes.
"""

from cruise_control_tpu.reporter.metrics import (
    BrokerMetric,
    CruiseControlMetric,
    MetricScope,
    PartitionMetric,
    RawMetricType,
    TopicMetric,
    deserialize_metric,
    serialize_metric,
)
from cruise_control_tpu.reporter.transport import (
    InMemoryTransport,
    JsonlFileTransport,
    MetricsTransport,
)
from cruise_control_tpu.reporter.reporter import MetricsReporter, MetricsReporterConfig

__all__ = [
    "BrokerMetric",
    "CruiseControlMetric",
    "InMemoryTransport",
    "JsonlFileTransport",
    "MetricScope",
    "MetricsReporter",
    "MetricsReporterConfig",
    "MetricsTransport",
    "PartitionMetric",
    "RawMetricType",
    "TopicMetric",
    "deserialize_metric",
    "serialize_metric",
]
