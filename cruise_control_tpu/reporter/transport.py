"""Metric transport SPI — the `__CruiseControlMetrics` topic analog.

The reference moves raw metrics broker -> monitor through a Kafka topic
(mr/CruiseControlMetricsReporter.java:110-128 producer side;
cc/monitor/sampling/CruiseControlMetricsReporterSampler.java:100 consumer
side). The SPI below decouples the agent from the wire: an in-memory queue for
tests/embedded use, a JSONL file transport for durable local runs, and any
user impl (a real Kafka client would subclass MetricsTransport).
"""

from __future__ import annotations

import collections
import json
import os
import threading
from typing import List, Optional

from cruise_control_tpu.reporter.metrics import (
    CruiseControlMetric,
    RawMetricType,
    deserialize_metric,
    serialize_metric,
)


class MetricsTransport:
    """Producer+consumer contract for raw metric records."""

    def publish(self, metrics: List[CruiseControlMetric]) -> None:
        raise NotImplementedError

    def poll(self, max_records: int = 10000) -> List[CruiseControlMetric]:
        """Consume up to max_records pending metrics (at-most-once)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryTransport(MetricsTransport):
    """Thread-safe bounded queue; the embedded-cluster test analog."""

    def __init__(self, max_pending: int = 1_000_000):
        self._q: collections.deque = collections.deque(maxlen=max_pending)
        self._lock = threading.Lock()

    def publish(self, metrics: List[CruiseControlMetric]) -> None:
        with self._lock:
            self._q.extend(metrics)

    def poll(self, max_records: int = 10000) -> List[CruiseControlMetric]:
        out = []
        with self._lock:
            while self._q and len(out) < max_records:
                out.append(self._q.popleft())
        return out


class JsonlFileTransport(MetricsTransport):
    """Append-only JSONL file with a persisted consumer offset.

    Survives restarts the way the reference's Kafka topic does; the offset
    file plays the consumer-group-offset role.
    """

    def __init__(self, path: str):
        self._path = path
        self._offset_path = path + ".offset"
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def publish(self, metrics: List[CruiseControlMetric]) -> None:
        with self._lock, open(self._path, "ab") as f:
            for m in metrics:
                f.write(serialize_metric(m).hex().encode() + b"\n")

    def _read_offset(self) -> int:
        try:
            with open(self._offset_path) as f:
                return int(f.read().strip() or 0)
        except FileNotFoundError:
            return 0

    def poll(self, max_records: int = 10000) -> List[CruiseControlMetric]:
        with self._lock:
            offset = self._read_offset()
            out = []
            try:
                with open(self._path, "rb") as f:
                    f.seek(offset)
                    for _ in range(max_records):
                        line = f.readline()
                        if not line:
                            break
                        out.append(deserialize_metric(bytes.fromhex(line.strip().decode())))
                    new_offset = f.tell()
            except FileNotFoundError:
                return []
            with open(self._offset_path, "w") as f:
                f.write(str(new_offset))
            return out

    def replay_all(self) -> List[CruiseControlMetric]:
        """Re-read from the beginning without moving the consumer offset
        (bootstrap/backfill use; KafkaSampleStore.loadSamples analog)."""
        with self._lock:
            out = []
            try:
                with open(self._path, "rb") as f:
                    for line in f:
                        if line.strip():
                            out.append(deserialize_metric(bytes.fromhex(line.strip().decode())))
            except FileNotFoundError:
                pass
            return out


class TcpMetricsTransport(MetricsTransport):
    """Metrics over the cluster-agent wire protocol (executor/tcp_driver.py):
    the socket analog of the `__CruiseControlMetrics` topic for deployments
    where brokers reach the monitor through an agent rather than Kafka.

    Protocol ops (hex-encoded binary records, the serde is the wire format):
      {"op": "metrics_publish", "records": [hex, ...]} -> {"ok": true}
      {"op": "metrics_poll", "max": int}
          -> {"ok": true, "records": [hex, ...]}   (at-most-once consume)
    """

    def __init__(self, host: str, port: int, timeout_s: float = 10.0,
                 ssl_context=None, server_hostname: Optional[str] = None):
        from cruise_control_tpu.executor.tcp_driver import _LineClient

        self._client = _LineClient(host, port, timeout_s, ssl_context=ssl_context,
                                   server_hostname=server_hostname)

    def publish(self, metrics: List[CruiseControlMetric]) -> None:
        # NOT retried on a mid-exchange drop: a re-send could double-count
        # the records agent-side; the reporter's next interval re-samples
        self._client.request({
            "op": "metrics_publish",
            "records": [serialize_metric(m).hex() for m in metrics],
        }, idempotent=False)

    def poll(self, max_records: int = 10000) -> List[CruiseControlMetric]:
        # NOT retried: a lost response already consumed its batch agent-side
        # (at-most-once, same stance as the in-memory transport)
        resp = self._client.request(
            {"op": "metrics_poll", "max": max_records}, idempotent=False
        )
        return [deserialize_metric(bytes.fromhex(r)) for r in resp.get("records", ())]

    def close(self) -> None:
        self._client.close()
