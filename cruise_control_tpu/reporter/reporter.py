"""The in-broker metrics agent loop.

Analog of CruiseControlMetricsReporter (mr/CruiseControlMetricsReporter.java:41):
every `reporting_interval_s` it walks a metric source (the Yammer-registry
analog — any callable returning the broker's current raw metrics) and
publishes the records through the transport. One reporter instance per
(simulated or real) broker.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, List, Optional

from cruise_control_tpu.reporter.metrics import CruiseControlMetric
from cruise_control_tpu.reporter.transport import MetricsTransport

#: A metric source returns the broker's current raw metrics, stamped by the
#: caller-supplied time (ms). The Yammer metrics walk equivalent.
MetricSource = Callable[[int], List[CruiseControlMetric]]


@dataclasses.dataclass(frozen=True)
class MetricsReporterConfig:
    """Key names mirror cruise.control.metrics.reporter.* where meaningful."""

    reporting_interval_s: float = 10.0


class MetricsReporter:
    def __init__(
        self,
        broker_id: int,
        source: MetricSource,
        transport: MetricsTransport,
        config: MetricsReporterConfig = MetricsReporterConfig(),
        clock: Callable[[], float] = time.time,
    ):
        self._broker_id = broker_id
        self._source = source
        self._transport = transport
        self._config = config
        self._clock = clock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def report_once(self) -> int:
        """One reporting round; returns the number of records published."""
        now_ms = int(self._clock() * 1000)
        metrics = self._source(now_ms)
        if metrics:
            self._transport.publish(metrics)
        return len(metrics)

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("reporter already started")
        self._stop.clear()

        def run():
            while not self._stop.wait(self._config.reporting_interval_s):
                try:
                    self.report_once()
                except Exception:  # keep the pump alive like the reference agent
                    pass

        self._thread = threading.Thread(target=run, name=f"metrics-reporter-{self._broker_id}", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
