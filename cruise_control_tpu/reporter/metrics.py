"""Raw metric taxonomy + versioned binary serde.

Mirrors the reference's metric vocabulary exactly — RawMetricType
(mr/metric/RawMetricType.java:27-80: 63 typed metrics over
BROKER/TOPIC/PARTITION scopes with a version watermark per type) and the
record classes CruiseControlMetric/BrokerMetric/TopicMetric/PartitionMetric +
MetricSerde (mr/metric/MetricSerde.java) — so dashboards/tooling written
against the reference taxonomy carry over.
"""

from __future__ import annotations

import dataclasses
import enum
import struct
from typing import Optional


class MetricScope(enum.IntEnum):
    BROKER = 0
    TOPIC = 1
    PARTITION = 2


_BROKER = MetricScope.BROKER
_TOPIC = MetricScope.TOPIC
_PARTITION = MetricScope.PARTITION


class RawMetricType(enum.IntEnum):
    """Same names and wire ids as mr/metric/RawMetricType.java:27-80."""

    ALL_TOPIC_BYTES_IN = 0
    ALL_TOPIC_BYTES_OUT = 1
    TOPIC_BYTES_IN = 2
    TOPIC_BYTES_OUT = 3
    PARTITION_SIZE = 4
    BROKER_CPU_UTIL = 5
    ALL_TOPIC_REPLICATION_BYTES_IN = 6
    ALL_TOPIC_REPLICATION_BYTES_OUT = 7
    ALL_TOPIC_PRODUCE_REQUEST_RATE = 8
    ALL_TOPIC_FETCH_REQUEST_RATE = 9
    ALL_TOPIC_MESSAGES_IN_PER_SEC = 10
    TOPIC_REPLICATION_BYTES_IN = 11
    TOPIC_REPLICATION_BYTES_OUT = 12
    TOPIC_PRODUCE_REQUEST_RATE = 13
    TOPIC_FETCH_REQUEST_RATE = 14
    TOPIC_MESSAGES_IN_PER_SEC = 15
    BROKER_PRODUCE_REQUEST_RATE = 16
    BROKER_CONSUMER_FETCH_REQUEST_RATE = 17
    BROKER_FOLLOWER_FETCH_REQUEST_RATE = 18
    BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT = 19
    BROKER_REQUEST_QUEUE_SIZE = 20
    BROKER_RESPONSE_QUEUE_SIZE = 21
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX = 22
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN = 23
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = 24
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = 25
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = 26
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = 27
    BROKER_PRODUCE_TOTAL_TIME_MS_MAX = 28
    BROKER_PRODUCE_TOTAL_TIME_MS_MEAN = 29
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MAX = 30
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MEAN = 31
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MAX = 32
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MEAN = 33
    BROKER_PRODUCE_LOCAL_TIME_MS_MAX = 34
    BROKER_PRODUCE_LOCAL_TIME_MS_MEAN = 35
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MAX = 36
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN = 37
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MAX = 38
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN = 39
    BROKER_LOG_FLUSH_RATE = 40
    BROKER_LOG_FLUSH_TIME_MS_MAX = 41
    BROKER_LOG_FLUSH_TIME_MS_MEAN = 42
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_50TH = 43
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_999TH = 44
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_50TH = 45
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_999TH = 46
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_50TH = 47
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_999TH = 48
    BROKER_PRODUCE_TOTAL_TIME_MS_50TH = 49
    BROKER_PRODUCE_TOTAL_TIME_MS_999TH = 50
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_50TH = 51
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_999TH = 52
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_50TH = 53
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_999TH = 54
    BROKER_PRODUCE_LOCAL_TIME_MS_50TH = 55
    BROKER_PRODUCE_LOCAL_TIME_MS_999TH = 56
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_50TH = 57
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_999TH = 58
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_50TH = 59
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_999TH = 60
    BROKER_LOG_FLUSH_TIME_MS_50TH = 61
    BROKER_LOG_FLUSH_TIME_MS_999TH = 62

    @property
    def scope(self) -> MetricScope:
        return METRIC_SCOPE[self]

    @property
    def supported_version_since(self) -> int:
        """First serde version carrying this type (-1 = always supported),
        matching RawMetricType's per-type version watermark."""
        return METRIC_VERSION_SINCE[self]


_TOPIC_TYPES = {
    RawMetricType.TOPIC_BYTES_IN,
    RawMetricType.TOPIC_BYTES_OUT,
    RawMetricType.TOPIC_REPLICATION_BYTES_IN,
    RawMetricType.TOPIC_REPLICATION_BYTES_OUT,
    RawMetricType.TOPIC_PRODUCE_REQUEST_RATE,
    RawMetricType.TOPIC_FETCH_REQUEST_RATE,
    RawMetricType.TOPIC_MESSAGES_IN_PER_SEC,
}

METRIC_SCOPE = {
    t: (
        MetricScope.PARTITION
        if t == RawMetricType.PARTITION_SIZE
        else MetricScope.TOPIC
        if t in _TOPIC_TYPES
        else MetricScope.BROKER
    )
    for t in RawMetricType
}

#: BROKER types gained version watermarks in the reference (v4 for rate/time
#: means, v5 for percentiles); TOPIC/PARTITION types are versionless (-1).
METRIC_VERSION_SINCE = {
    t: (-1 if t.scope != MetricScope.BROKER else (5 if t >= RawMetricType.BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_50TH else 4))
    for t in RawMetricType
}

BROKER_METRIC_TYPES = [t for t in RawMetricType if t.scope == MetricScope.BROKER]
TOPIC_METRIC_TYPES = [t for t in RawMetricType if t.scope == MetricScope.TOPIC]
PARTITION_METRIC_TYPES = [t for t in RawMetricType if t.scope == MetricScope.PARTITION]


@dataclasses.dataclass(frozen=True)
class CruiseControlMetric:
    """One raw metric observation (mr/metric/CruiseControlMetric.java)."""

    metric_type: RawMetricType
    time_ms: int
    broker_id: int
    value: float
    topic: Optional[str] = None
    partition: Optional[int] = None

    def __post_init__(self):
        scope = self.metric_type.scope
        if scope == MetricScope.TOPIC and self.topic is None:
            raise ValueError(f"{self.metric_type.name} requires a topic")
        if scope == MetricScope.PARTITION and (self.topic is None or self.partition is None):
            raise ValueError(f"{self.metric_type.name} requires topic and partition")


def BrokerMetric(metric_type, time_ms, broker_id, value) -> CruiseControlMetric:
    return CruiseControlMetric(metric_type, time_ms, broker_id, value)


def TopicMetric(metric_type, time_ms, broker_id, topic, value) -> CruiseControlMetric:
    return CruiseControlMetric(metric_type, time_ms, broker_id, value, topic=topic)


def PartitionMetric(metric_type, time_ms, broker_id, topic, partition, value) -> CruiseControlMetric:
    return CruiseControlMetric(metric_type, time_ms, broker_id, value, topic=topic, partition=partition)


# -- wire format ---------------------------------------------------------------

SERDE_VERSION = 1

# header: version u8, type u8, time i64, broker i32, value f64, topic_len u16
_HEADER = struct.Struct(">BBqid H")


def serialize_metric(m: CruiseControlMetric) -> bytes:
    """Versioned binary serde, the analog of MetricSerde.toBytes
    (mr/metric/MetricSerde.java)."""
    topic_bytes = m.topic.encode("utf-8") if m.topic is not None else b""
    out = _HEADER.pack(
        SERDE_VERSION, int(m.metric_type), m.time_ms, m.broker_id, m.value, len(topic_bytes)
    )
    out += topic_bytes
    if m.metric_type.scope == MetricScope.PARTITION:
        out += struct.pack(">i", m.partition)
    return out


def deserialize_metric(data: bytes) -> CruiseControlMetric:
    version, type_id, time_ms, broker_id, value, topic_len = _HEADER.unpack_from(data, 0)
    if version > SERDE_VERSION:
        raise ValueError(f"unsupported metric serde version {version}")
    mt = RawMetricType(type_id)
    off = _HEADER.size
    topic = data[off : off + topic_len].decode("utf-8") if topic_len else None
    off += topic_len
    partition = None
    if mt.scope == MetricScope.PARTITION:
        (partition,) = struct.unpack_from(">i", data, off)
    return CruiseControlMetric(mt, time_ms, broker_id, value, topic=topic, partition=partition)
