"""Anomaly detection + self-healing.

Analog of cc/detector/ (SURVEY.md §2g): three detectors (goal violation,
broker failure, metric anomaly) feed a queue consumed by the anomaly handler,
which consults the notifier (FIX / CHECK / IGNORE) and triggers fixes through
the facade — goal violations rebalance, broker failures decommission.
"""

from cruise_control_tpu.detector.anomalies import (
    Anomaly,
    AnomalyNotificationResult,
    AnomalyType,
    BrokerFailures,
    GoalViolations,
    MetricAnomaly,
)
from cruise_control_tpu.detector.notifier import (
    AnomalyNotifier,
    NoopNotifier,
    SelfHealingNotifier,
    WebhookNotifier,
)
from cruise_control_tpu.detector.detectors import (
    BrokerFailureDetector,
    GoalViolationDetector,
    MetricAnomalyDetector,
    PercentileMetricAnomalyFinder,
)
from cruise_control_tpu.detector.anomaly_detector import AnomalyDetector, AnomalyDetectorConfig

__all__ = [
    "Anomaly",
    "AnomalyDetector",
    "AnomalyDetectorConfig",
    "AnomalyNotificationResult",
    "AnomalyNotifier",
    "AnomalyType",
    "BrokerFailureDetector",
    "BrokerFailures",
    "GoalViolationDetector",
    "GoalViolations",
    "MetricAnomaly",
    "MetricAnomalyDetector",
    "NoopNotifier",
    "PercentileMetricAnomalyFinder",
    "SelfHealingNotifier",
    "WebhookNotifier",
]
