"""Anomaly notifiers.

Analogs of cc/detector/notifier/: the AnomalyNotifier SPI maps each anomaly
to FIX / CHECK(delay) / IGNORE; SelfHealingNotifier
(SelfHealingNotifier.java:46) adds per-type self-healing enable flags and the
broker-failure grace-period state machine (alert threshold, then fix
threshold, onBrokerFailure :170); WebhookNotifier posts JSON to a callable
sink (the Slack webhook analog, egress-free).

Degraded mode (docs/RESILIENCE.md): each anomaly type carries a
CircuitBreaker. The anomaly handler reports every fix outcome back through
`record_fix_result`; after `breaker_threshold` consecutive failed fixes the
type's breaker opens and would-be FIX decisions degrade to delayed CHECKs
(delay = remaining cooldown) until the cooldown elapses, when one half-open
probe fix is admitted — success closes the breaker, failure re-opens it.
This stops a persistently failing fix (a wedged cluster, a bad goal config)
from being re-fired forever while keeping the anomaly on the queue."""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from cruise_control_tpu.common.retry import CircuitBreaker
from cruise_control_tpu.detector.anomalies import (
    Anomaly,
    AnomalyNotificationResult,
    AnomalyType,
    BrokerFailures,
)


class AnomalyNotifier:
    def on_anomaly(self, anomaly: Anomaly, now_ms: int) -> Tuple[AnomalyNotificationResult, float]:
        """-> (result, check_delay_s when result is CHECK)."""
        raise NotImplementedError

    def self_healing_enabled(self) -> Dict[str, bool]:
        return {t.name: False for t in AnomalyType}


class NoopNotifier(AnomalyNotifier):
    def on_anomaly(self, anomaly, now_ms):
        return AnomalyNotificationResult.IGNORE, 0.0


@dataclasses.dataclass
class SelfHealingNotifier(AnomalyNotifier):
    """Per-type enables + broker-failure grace period.

    A failed broker first trips an alert after `broker_failure_alert_threshold_s`
    and is fixed only after `self_healing_threshold_s` (both measured from the
    failure time), giving transient bounces a chance to recover — the exact
    two-threshold ladder of SelfHealingNotifier.onBrokerFailure (:170)."""

    self_healing_goal_violation_enabled: bool = True
    self_healing_broker_failure_enabled: bool = True
    self_healing_metric_anomaly_enabled: bool = False
    broker_failure_alert_threshold_s: float = 900.0
    self_healing_threshold_s: float = 1800.0
    alert_sink: Optional[Callable[[Dict], None]] = None
    #: consecutive failed fixes of one anomaly type before its breaker opens
    #: (`selfhealing.breaker.threshold`)
    breaker_threshold: int = 3
    #: seconds the breaker stays open before a half-open probe fix
    #: (`selfhealing.breaker.cooldown.s`)
    breaker_cooldown_s: float = 300.0
    #: injectable monotonic clock (deterministic breaker tests)
    breaker_clock: Callable[[], float] = time.monotonic
    #: guarded_by(_lock)
    _breakers: Dict[str, CircuitBreaker] = dataclasses.field(
        default_factory=dict, init=False, repr=False, compare=False
    )
    _lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, init=False, repr=False, compare=False
    )

    def _alert(self, payload: Dict) -> None:
        if self.alert_sink is not None:
            self.alert_sink(payload)

    # -- per-type circuit breakers ---------------------------------------------

    def breaker(self, anomaly_type: AnomalyType) -> CircuitBreaker:
        # get-or-create under the lock: the anomaly handler and the /state
        # server thread race here, and a duplicate breaker would silently
        # split the consecutive-failure count across two instances
        name = anomaly_type.name
        with self._lock:
            br = self._breakers.get(name)
            if br is None:
                br = self._breakers[name] = CircuitBreaker(
                    f"SelfHealing.{name}",
                    failure_threshold=self.breaker_threshold,
                    cooldown_s=self.breaker_cooldown_s,
                    clock=self.breaker_clock,
                )
        return br

    def record_fix_result(self, anomaly_type: AnomalyType, success: bool) -> None:
        """Fix outcome feedback from the anomaly handler."""
        br = self.breaker(anomaly_type)
        if success:
            br.record_success()
        else:
            br.record_failure()
            if br.state == CircuitBreaker.OPEN:
                self._alert({
                    "anomalyType": anomaly_type.name,
                    "selfHealingBreaker": br.snapshot(),
                })

    def breakers_state(self) -> Dict[str, Dict]:
        """Snapshot of every anomaly type's breaker (for /state)."""
        return {t.name: self.breaker(t).snapshot() for t in AnomalyType}

    def _gate_fix(self, anomaly_type: AnomalyType) -> Tuple[AnomalyNotificationResult, float]:
        """FIX if the type's breaker admits it; otherwise degrade to a
        delayed CHECK for the remaining cooldown (floor 1s so a CHECK is
        never an immediate-requeue busy loop)."""
        br = self.breaker(anomaly_type)
        if br.allow():
            return AnomalyNotificationResult.FIX, 0.0
        return AnomalyNotificationResult.CHECK, max(1.0, br.remaining_cooldown_s())

    def self_healing_enabled(self) -> Dict[str, bool]:
        return {
            AnomalyType.GOAL_VIOLATION.name: self.self_healing_goal_violation_enabled,
            AnomalyType.BROKER_FAILURE.name: self.self_healing_broker_failure_enabled,
            AnomalyType.METRIC_ANOMALY.name: self.self_healing_metric_anomaly_enabled,
        }

    def on_anomaly(self, anomaly: Anomaly, now_ms: int) -> Tuple[AnomalyNotificationResult, float]:
        t = anomaly.anomaly_type
        if t == AnomalyType.GOAL_VIOLATION:
            if self.self_healing_goal_violation_enabled:
                return self._gate_fix(t)
            return AnomalyNotificationResult.IGNORE, 0.0
        if t == AnomalyType.METRIC_ANOMALY:
            self._alert(anomaly.describe())
            if self.self_healing_metric_anomaly_enabled:
                return self._gate_fix(t)
            return AnomalyNotificationResult.IGNORE, 0.0
        # broker failure ladder
        assert isinstance(anomaly, BrokerFailures)
        if not anomaly.failed_brokers:
            return AnomalyNotificationResult.IGNORE, 0.0
        earliest_ms = min(anomaly.failed_brokers.values())
        alert_at = earliest_ms + self.broker_failure_alert_threshold_s * 1000
        fix_at = earliest_ms + self.self_healing_threshold_s * 1000
        if now_ms >= alert_at:
            self._alert({**anomaly.describe(), "autoFixTriggered": now_ms >= fix_at})
        if not self.self_healing_broker_failure_enabled:
            return AnomalyNotificationResult.IGNORE, 0.0
        if now_ms >= fix_at:
            return self._gate_fix(t)
        return AnomalyNotificationResult.CHECK, max(0.0, (fix_at - now_ms) / 1000.0)


class WebhookNotifier(SelfHealingNotifier):
    """Slack-style notifier: alerts render to a text payload and go to a
    `post` callable (an HTTP client in production; captured in tests) —
    cc/detector/notifier/SlackSelfHealingNotifier.java without the egress."""

    def __init__(self, post: Callable[[str], None], **kwargs):
        super().__init__(**kwargs)
        self._post = post
        self.alert_sink = self._to_text

    def _to_text(self, payload: Dict) -> None:
        kind = payload.get("anomalyType", "ANOMALY")
        self._post(f":warning: [{kind}] {payload}")
