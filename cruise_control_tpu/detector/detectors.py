"""The three detectors.

- GoalViolationDetector (cc/detector/GoalViolationDetector.java:46): builds a
  fresh model and dry-runs each detection goal; proposals => fixable
  violation, hard-goal failure => unfixable; skips when dead brokers exist
  (that's the broker-failure detector's job, run :135-212).
- BrokerFailureDetector (cc/detector/BrokerFailureDetector.java:39): compares
  metadata liveness against brokers hosting replicas; persists failure times
  (failed.brokers.zk.path analog -> local JSON file) so failures survive
  restarts.
- MetricAnomalyDetector (cc/detector/MetricAnomalyDetector.java:26) with the
  percentile finder (core PercentileMetricAnomalyFinder): current broker
  metric outside [p_lower, p_upper] of its own history => anomaly.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from cruise_control_tpu.analyzer.optimizer import OptimizationFailureException
from cruise_control_tpu.common.resources import BrokerState
from cruise_control_tpu.detector.anomalies import BrokerFailures, GoalViolations, MetricAnomaly
from cruise_control_tpu.monitor.metricdef import KafkaMetricDef


class GoalViolationDetector:
    def __init__(self, facade, detection_goals: Optional[Sequence[str]] = None):
        self._facade = facade
        self._goals = list(detection_goals) if detection_goals else None

    def detect(self) -> Optional[GoalViolations]:
        from cruise_control_tpu.analyzer.goals import goals_by_priority

        try:
            with self._facade._monitor.acquire_for_model_generation():
                model, _ = self._facade._monitor.cluster_model()
        except ValueError:
            return None  # insufficient data; try next round
        if (np.asarray(model.broker_state) == BrokerState.DEAD).any():
            return None  # dead brokers are the broker-failure detector's job

        fixable: List[str] = []
        unfixable: List[str] = []
        optimizer = self._facade._optimizer
        for goal in goals_by_priority(self._goals):
            try:
                result = optimizer.optimizations(
                    model, goal_names=[goal.name], raise_on_hard_failure=True
                )
            except OptimizationFailureException:
                unfixable.append(goal.name)
                continue
            if result.proposals:
                fixable.append(goal.name)
        if fixable or unfixable:
            return GoalViolations(fixable_goals=fixable, unfixable_goals=unfixable)
        return None


class BrokerFailureDetector:
    """Liveness watcher with persisted failure times."""

    def __init__(self, metadata_client, persist_path: Optional[str] = None,
                 clock=None):
        import time as _time

        self._metadata = metadata_client
        self._path = persist_path
        self._clock = clock or _time.time
        self._lock = threading.Lock()
        self._failure_time_ms: Dict[int, int] = {}
        self._load()

    def _load(self) -> None:
        if self._path and os.path.exists(self._path):
            try:
                with open(self._path) as f:
                    self._failure_time_ms = {int(k): int(v) for k, v in json.load(f).items()}
            except (ValueError, OSError):
                self._failure_time_ms = {}

    def _persist(self) -> None:
        if self._path:
            with open(self._path, "w") as f:
                json.dump({str(k): v for k, v in self._failure_time_ms.items()}, f)

    def detect(self) -> Optional[BrokerFailures]:
        topo = self._metadata.refresh_metadata(force=True)
        hosts_replicas = np.zeros(topo.num_brokers, dtype=bool)
        a = np.asarray(topo.assignment)
        ids = a[a >= 0]
        hosts_replicas[ids[ids < topo.num_brokers]] = True
        dead = np.asarray(topo.broker_state) == BrokerState.DEAD
        now_ms = int(self._clock() * 1000)
        with self._lock:
            current = set(np.nonzero(dead & hosts_replicas)[0].tolist())
            for b in current:
                self._failure_time_ms.setdefault(int(b), now_ms)
            for b in list(self._failure_time_ms):
                if b not in current:
                    del self._failure_time_ms[b]  # broker recovered
            self._persist()
            if not self._failure_time_ms:
                return None
            return BrokerFailures(failed_brokers=dict(self._failure_time_ms))


@dataclasses.dataclass
class PercentileMetricAnomalyFinder:
    """core/detector/metricanomaly/PercentileMetricAnomalyFinder semantics:
    current value outside [lower_pct, upper_pct] of the broker's own history
    (requiring a minimum history) flags an anomaly."""

    upper_percentile: float = 95.0
    lower_percentile: float = 2.0
    min_history_windows: int = 3
    interested_metrics: Sequence[KafkaMetricDef] = (
        KafkaMetricDef.BROKER_PRODUCE_LOCAL_TIME_MS_MEAN,
        KafkaMetricDef.BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN,
        KafkaMetricDef.BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN,
        KafkaMetricDef.BROKER_LOG_FLUSH_TIME_MS_MEAN,
        KafkaMetricDef.BROKER_REQUEST_QUEUE_SIZE,
        KafkaMetricDef.BROKER_RESPONSE_QUEUE_SIZE,
    )

    def find(self, history: np.ndarray, current: np.ndarray) -> List[MetricAnomaly]:
        """history f32[B, W, M] (completed windows), current f32[B, M]."""
        out: List[MetricAnomaly] = []
        if history.shape[1] < self.min_history_windows:
            return out
        for m in self.interested_metrics:
            h = history[:, :, m].astype(np.float64)  # [B, W]
            # zero windows are absent data (NO_VALID_EXTRAPOLATION fills,
            # pre-join padding after a resize) — exclude them from the
            # baseline so they can't deflate the percentiles
            h_obs = np.where(h > 0, h, np.nan)
            n_obs = np.sum(~np.isnan(h_obs), axis=1)
            has_signal = n_obs >= self.min_history_windows
            with np.errstate(all="ignore"):
                upper = np.nanpercentile(h_obs, self.upper_percentile, axis=1)
                lower = np.nanpercentile(h_obs, self.lower_percentile, axis=1)
            upper = np.where(has_signal, upper, np.inf)
            lower = np.where(has_signal, lower, -np.inf)
            cur = current[:, m]
            too_high = has_signal & (cur > np.maximum(upper, 1e-9))
            too_low = has_signal & (cur < lower)
            for b in np.nonzero(too_high)[0]:
                out.append(
                    MetricAnomaly(
                        int(b), KafkaMetricDef(m).name, float(cur[b]), float(upper[b]),
                        f"value above P{self.upper_percentile:g} of history",
                    )
                )
            for b in np.nonzero(too_low)[0]:
                out.append(
                    MetricAnomaly(
                        int(b), KafkaMetricDef(m).name, float(cur[b]), float(lower[b]),
                        f"value below P{self.lower_percentile:g} of history",
                    )
                )
        return out


class MetricAnomalyDetector:
    def __init__(self, load_monitor, finder: Optional[PercentileMetricAnomalyFinder] = None):
        self._monitor = load_monitor
        self._finder = finder or PercentileMetricAnomalyFinder()

    def detect(self) -> List[MetricAnomaly]:
        agg = self._monitor._broker_agg
        try:
            result = agg.aggregate(include_current=False)
        except ValueError:
            return []
        values = result.values  # [B, W, M]
        if values.shape[1] < 2:
            return []
        history, current = values[:, :-1, :], values[:, -1, :]
        return self._finder.find(history, current)
