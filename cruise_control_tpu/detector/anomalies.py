"""Anomaly vocabulary + fix contracts.

Analogs of core/detector/Anomaly.java:22 (`fix()` contract),
cc/detector/GoalViolations.java:76 (fix -> rebalance with self-healing
goals), cc/detector/BrokerFailures.java:75 (fix -> decommission), and the
notifier result vocabulary (AnomalyNotificationResult {FIX, CHECK, IGNORE},
AnomalyType)."""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Set


class AnomalyType(enum.IntEnum):
    GOAL_VIOLATION = 0
    BROKER_FAILURE = 1
    METRIC_ANOMALY = 2


class AnomalyNotificationResult(enum.IntEnum):
    FIX = 0
    CHECK = 1
    IGNORE = 2


class Anomaly:
    anomaly_type: AnomalyType

    def fix(self, facade) -> Optional[object]:
        """Apply the self-healing action through the facade; returns the
        operation result or None when nothing was done."""
        raise NotImplementedError

    def describe(self) -> Dict:
        raise NotImplementedError


@dataclasses.dataclass
class GoalViolations(Anomaly):
    """fixable[name] = the goal produced proposals; unfixable[name] = the goal
    raised OptimizationFailure during detection (GoalViolations.java)."""

    fixable_goals: List[str]
    unfixable_goals: List[str]
    anomaly_type = AnomalyType.GOAL_VIOLATION

    def fix(self, facade):
        if not self.fixable_goals:
            return None
        from cruise_control_tpu.analyzer.context import OptimizationOptions

        return facade.rebalance(
            dryrun=False,
            options=OptimizationOptions(is_triggered_by_goal_violation=True),
            ignore_proposal_cache=True,
        )

    def describe(self) -> Dict:
        return {
            "anomalyType": self.anomaly_type.name,
            "fixableViolatedGoals": self.fixable_goals,
            "unfixableViolatedGoals": self.unfixable_goals,
        }


@dataclasses.dataclass
class BrokerFailures(Anomaly):
    """failed_brokers: broker index -> failure time ms."""

    failed_brokers: Dict[int, int]
    anomaly_type = AnomalyType.BROKER_FAILURE

    def fix(self, facade):
        if not self.failed_brokers:
            return None
        return facade.decommission_brokers(set(self.failed_brokers), dryrun=False)

    def describe(self) -> Dict:
        return {
            "anomalyType": self.anomaly_type.name,
            "failedBrokers": {str(k): v for k, v in self.failed_brokers.items()},
        }


@dataclasses.dataclass
class ProposalDriftAnomaly(Anomaly):
    """The executor aborted a proposal batch because the cluster drifted too
    far from the batch's model (generation skew past
    `executor.proposal.max.generation.skew`, docs/RESILIENCE.md). The stale
    plan is gone; the fix is a fresh one — ride the INCREMENTAL lane
    (analyzer/incremental.py): derive the drift as typed model deltas,
    scatter them into the device-resident padded context of the last solve,
    and re-solve only the sensitivity-affected goals, seeded from the
    surviving placement. The facade falls back to the full goal-violation
    rebalance (same cache-bypassing path a violated goal triggers) when the
    lane reports a typed fallback reason, so breakers, enables, and the
    busy-executor gate still apply on both lanes."""

    drift: Dict
    anomaly_type = AnomalyType.GOAL_VIOLATION

    def fix(self, facade):
        incremental = getattr(facade, "incremental_reproposal", None)
        if incremental is not None:
            return incremental(dryrun=False)
        # Facade without the incremental surface: ride the classic
        # cache-bypassing goal-violation rebalance directly.
        from cruise_control_tpu.analyzer.context import OptimizationOptions

        return facade.rebalance(
            dryrun=False,
            options=OptimizationOptions(is_triggered_by_goal_violation=True),
            ignore_proposal_cache=True,
        )

    def describe(self) -> Dict:
        return {
            "anomalyType": self.anomaly_type.name,
            "kind": "PROPOSAL_DRIFT",
            "drift": dict(self.drift),
        }


@dataclasses.dataclass
class MetricAnomaly(Anomaly):
    """One broker metric out of its historical band. Fix is a no-op, matching
    KafkaMetricAnomaly's TODO fix (cc/detector/KafkaMetricAnomaly.java)."""

    broker_index: int
    metric_name: str
    current_value: float
    threshold: float
    description: str = ""
    anomaly_type = AnomalyType.METRIC_ANOMALY

    def fix(self, facade):
        return None

    def describe(self) -> Dict:
        return {
            "anomalyType": self.anomaly_type.name,
            "broker": self.broker_index,
            "metric": self.metric_name,
            "value": self.current_value,
            "threshold": self.threshold,
            "description": self.description,
        }
