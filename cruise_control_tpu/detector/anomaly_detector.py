"""Anomaly detector orchestrator.

Analog of AnomalyDetector (cc/detector/AnomalyDetector.java:46): schedules
the three detectors at the detection interval, queues anomalies, and runs the
handler (AnomalyHandlerTask :231) that consults the notifier — FIX calls
anomaly.fix() through the facade (skipped while the executor is busy, which
becomes a delayed CHECK), CHECK re-queues after the delay, IGNORE drops.
Tracks per-type counts for /state."""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Dict, List, Optional

from cruise_control_tpu.detector.anomalies import (
    Anomaly,
    AnomalyNotificationResult,
    AnomalyType,
)
from cruise_control_tpu.detector.detectors import (
    BrokerFailureDetector,
    GoalViolationDetector,
    MetricAnomalyDetector,
)
from cruise_control_tpu.detector.notifier import AnomalyNotifier, SelfHealingNotifier


@dataclasses.dataclass(frozen=True)
class AnomalyDetectorConfig:
    detection_interval_s: float = 300.0  # anomaly.detection.interval.ms


class AnomalyDetector:
    def __init__(
        self,
        facade,
        notifier: Optional[AnomalyNotifier] = None,
        goal_violation_detector: Optional[GoalViolationDetector] = None,
        broker_failure_detector: Optional[BrokerFailureDetector] = None,
        metric_anomaly_detector: Optional[MetricAnomalyDetector] = None,
        config: AnomalyDetectorConfig = AnomalyDetectorConfig(),
        clock=time.time,
    ):
        self._facade = facade
        self._notifier = notifier or SelfHealingNotifier()
        self._gv = goal_violation_detector or GoalViolationDetector(facade)
        self._bf = broker_failure_detector or BrokerFailureDetector(
            facade._monitor._metadata, clock=clock
        )
        self._ma = metric_anomaly_detector or MetricAnomalyDetector(facade._monitor)
        self._config = config
        self._clock = clock
        self._queue: "queue.Queue[Anomaly]" = queue.Queue()
        self._counts: Dict[str, int] = {t.name: 0 for t in AnomalyType}
        self._fixes: Dict[str, int] = {t.name: 0 for t in AnomalyType}
        self._fix_failures: Dict[str, int] = {t.name: 0 for t in AnomalyType}
        self._recent: List[Dict] = []
        self._drift_notifications = 0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._register_breaker_gauge()
        # executor → detector drift channel: a batch aborted for generation
        # skew queues a recompute through the normal self-healing path
        set_listener = getattr(facade._executor, "set_drift_listener", None)
        if set_listener is not None:
            set_listener(self.on_proposal_drift)

    def on_proposal_drift(self, info: Dict) -> None:
        """Executor drift-abort callback: queue a ProposalDriftAnomaly so the
        recompute rides the anomaly handler (notifier gating, breakers, and
        the busy-executor delayed-CHECK all apply)."""
        from cruise_control_tpu.common.oplog import op_log
        from cruise_control_tpu.common.sensors import REGISTRY
        from cruise_control_tpu.detector.anomalies import ProposalDriftAnomaly

        REGISTRY.meter("AnomalyDetector.proposal-drift-notifications").mark()
        self._drift_notifications += 1
        anomaly = ProposalDriftAnomaly(drift=dict(info))
        self._counts[anomaly.anomaly_type.name] += 1
        self._recent.append(anomaly.describe())
        self._recent = self._recent[-50:]
        self._queue.put(anomaly)
        op_log("Proposal drift notification queued for recompute: %s", info)

    def _register_breaker_gauge(self) -> None:
        """Expose breaker states on /metrics (0=closed, 1=half-open, 2=open);
        full snapshots ride /state. Guarded: only notifiers with breakers
        (SelfHealingNotifier and subclasses) report."""
        from cruise_control_tpu.common.retry import CircuitBreaker
        from cruise_control_tpu.common.sensors import REGISTRY

        import weakref

        ref = weakref.ref(self)

        def breaker_codes():
            det = ref()
            if det is None:
                return {}
            breakers = getattr(det._notifier, "breakers_state", None)
            if breakers is None:
                return {}
            return {
                name: CircuitBreaker.STATE_CODES.get(snap["state"], -1)
                for name, snap in breakers().items()
            }

        REGISTRY.gauge("AnomalyDetector.breaker-state", breaker_codes)

    # -- one detection round (callable directly; the loop just schedules it) ---

    def detect_once(self) -> int:
        """Run all three detectors, queue anomalies; returns queued count."""
        from cruise_control_tpu.common.sensors import REGISTRY
        from cruise_control_tpu.common.tracing import TRACER

        with TRACER.span("anomaly-sweep", kind="detector") as span, \
                REGISTRY.histogram("AnomalyDetector.detection-timer"):
            found: List[Anomaly] = []
            bf = self._bf.detect()
            if bf:
                found.append(bf)
            gv = self._gv.detect()
            if gv:
                found.append(gv)
            found.extend(self._ma.detect())
            for a in found:
                self._counts[a.anomaly_type.name] += 1
                self._recent.append(a.describe())
                self._recent = self._recent[-50:]
                self._queue.put(a)
            span.attributes["anomalies"] = len(found)
            return len(found)

    def handle_once(self, block_s: float = 0.0) -> Optional[str]:
        """Consume one queued anomaly (AnomalyHandlerTask); returns the action
        taken ('FIX'/'CHECK'/'IGNORE') or None when the queue is empty."""
        try:
            anomaly = self._queue.get(timeout=block_s) if block_s else self._queue.get_nowait()
        except queue.Empty:
            return None
        now_ms = int(self._clock() * 1000)
        # executor busy => delayed re-check, never a concurrent fix
        if self._facade._executor.has_ongoing_execution:
            self._requeue_later(anomaly, delay_s=1.0)
            return AnomalyNotificationResult.CHECK.name
        from cruise_control_tpu.common.oplog import op_log
        from cruise_control_tpu.common.tracing import TRACER

        # the span threads one trace id through the decision, the (possibly
        # long) self-healing fix, and every op_log line they emit
        with TRACER.span(
            "anomaly-handle", kind="detector",
            anomalyType=anomaly.anomaly_type.name,
        ) as span:
            result, delay_s = self._notifier.on_anomaly(anomaly, now_ms)
            span.attributes["decision"] = result.name
            op_log("Anomaly %s: notifier decided %s", anomaly, result.name)
            if result == AnomalyNotificationResult.FIX:
                from cruise_control_tpu.common.sensors import REGISTRY

                record = getattr(self._notifier, "record_fix_result", None)
                type_name = anomaly.anomaly_type.name
                try:
                    anomaly.fix(self._facade)
                    self._fixes[type_name] += 1
                    op_log("Self-healing fix completed for %s", anomaly)
                    if record is not None:
                        record(anomaly.anomaly_type, True)
                except Exception as e:
                    # fix failures surface through executor/notifier state, but
                    # the audit trail must still record them — and they feed
                    # the type's circuit breaker (degraded mode)
                    self._fix_failures[type_name] += 1
                    REGISTRY.meter("AnomalyDetector.fix-failures").mark()
                    span.attributes["fixError"] = f"{type(e).__name__}: {e}"
                    op_log("Self-healing fix FAILED for %s: %r", anomaly, e)
                    if record is not None:
                        record(anomaly.anomaly_type, False)
            elif result == AnomalyNotificationResult.CHECK:
                self._requeue_later(anomaly, delay_s)
            return result.name

    def _requeue_later(self, anomaly: Anomaly, delay_s: float) -> None:
        t = threading.Timer(delay_s, lambda: self._queue.put(anomaly))
        t.daemon = True
        t.start()

    # -- background loop -------------------------------------------------------

    def start_detection(self) -> None:
        """AnomalyDetector.startDetection (:143)."""
        self._stop.clear()

        def detect_loop():
            while not self._stop.wait(self._config.detection_interval_s):
                try:
                    self.detect_once()
                except Exception:
                    pass

        def handle_loop():
            while not self._stop.is_set():
                try:
                    self.handle_once(block_s=1.0)
                except Exception:
                    pass

        for fn, name in ((detect_loop, "anomaly-detectors"), (handle_loop, "anomaly-handler")):
            th = threading.Thread(target=fn, name=name, daemon=True)
            th.start()
            self._threads.append(th)

    def shutdown(self) -> None:
        self._stop.set()
        for th in self._threads:
            th.join(timeout=5)
        self._threads.clear()

    def state(self) -> Dict:
        out = {
            "selfHealingEnabled": self._notifier.self_healing_enabled(),
            "anomalyCounts": dict(self._counts),
            "fixesTriggered": dict(self._fixes),
            "fixFailures": dict(self._fix_failures),
            "recentAnomalies": list(self._recent),
            "queuedAnomalies": self._queue.qsize(),
            "proposalDriftNotifications": self._drift_notifications,
        }
        breakers = getattr(self._notifier, "breakers_state", None)
        if breakers is not None:
            out["selfHealingBreakers"] = breakers()
        return out
