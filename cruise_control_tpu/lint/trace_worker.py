"""cclint trace-tier worker: abstract evaluation of registered kernel entry
points, run in a SUBPROCESS so the parent linter never imports JAX.

The token rules see source tokens and ASTs; everything they cannot see — a
host callback buried three calls under a jit boundary, a donated buffer
with no output to alias into, a `weak_type` carry that would fork a
compiled program out of its shape bucket — is visible in the jaxpr. This
worker loads every module that declares a `CCLINT_TRACE_ENTRYPOINTS`
registry (lint/entrypoints.py for the package; trace-rule fixtures declare
their own), builds each entry's callable and example arguments, traces it
with `jax.make_jaxpr`, and walks the closed jaxpr recursively. Sharded
entries are additionally lowered AND compiled under a virtual 8-device mesh
(the process pins `--xla_force_host_platform_device_count` before JAX
initializes — same mechanism as the multichip dryrun, platform_probe).

Findings are attributed to the LINE of the entry's `name="..."` declaration
in the registry module, so the normal suppression syntax works there:

    dict(name="noisy-entry", build=_b),  # cclint: disable=trace-constant-bloat -- reason

Protocol: `python -m cruise_control_tpu.lint.trace_worker --root R rel.py...`
prints one JSON document: {"version", "findings": [{rule, path, line,
message}], "stats": {...}}. rules_trace.py caches that document keyed by
the content hash of the linted sources, so the tracing cost is paid once
per source state.

Entry registry protocol (plain dicts — fixtures need no package imports):

    CCLINT_TRACE_ENTRYPOINTS = [
        dict(name="my-kernel", build=_build),   # one entry per line
    ]

where `build()` returns a dict with keys:
    fn              callable (plain or already-jitted)
    args            tuple of example arguments (small concrete arrays)
    donate_argnums  optional tuple — positions whose buffers the real call
                    site donates (checked for dead donations)
    shardings       optional per-arg PartitionSpec trees (tuples of axis
                    names / None, or pytrees of those matching the arg);
                    presence opts the entry into the sharded lower+compile
    mesh_shape      optional ((axis, size), ...), default (("partitions", 8),)
    max_all_gathers optional int, default 0 — compiled all-gather budget
    const_bytes_limit optional int, default 65536 — baked-constant budget
"""

from __future__ import annotations

import argparse
import hashlib
import importlib.util
import json
import pathlib
import re
import sys
import time
from typing import Dict, Iterable, List, Optional, Tuple

#: bump when the check semantics change: the content-hash cache key
#: includes this, so stale cached verdicts cannot survive a worker upgrade
WORKER_SCHEMA = 3

#: primitives that round-trip to the host from inside traced code
CALLBACK_PRIMITIVES = ("pure_callback", "debug_callback", "io_callback")

DEFAULT_MESH_SHAPE = (("partitions", 8),)
DEFAULT_CONST_BYTES_LIMIT = 1 << 16

#: trace errors that mean "the loop carry is not shape/dtype/pytree-stable"
#: (jax refuses to trace them — which is exactly the fusibility violation)
_CARRY_ERROR_RE = re.compile(
    r"carry|body_fun output and input|while_loop|scan body", re.IGNORECASE
)


def _finding(rule: str, path: str, line: int, message: str) -> Dict:
    return {"rule": rule, "path": path, "line": line, "message": message}


def _entry_line(source_lines: List[str], name: str) -> int:
    """The 1-based line declaring `name="<name>"` — the suppression anchor."""
    pat = re.compile(r"""name\s*=\s*['"]""" + re.escape(name) + r"""['"]""")
    for i, line in enumerate(source_lines, start=1):
        if pat.search(line):
            return i
    return 1


def _walk_jaxprs(jaxpr, seen: set):
    """Yield every (sub)jaxpr eqn plus the ClosedJaxprs hiding in params."""
    if id(jaxpr) in seen:
        return
    seen.add(id(jaxpr))
    for eqn in jaxpr.eqns:
        yield eqn
        for p in eqn.params.values():
            items = p if isinstance(p, (list, tuple)) else [p]
            for it in items:
                inner = getattr(it, "jaxpr", None)
                if inner is not None and hasattr(it, "consts"):  # ClosedJaxpr
                    yield ("closed", it)
                    yield from _walk_jaxprs(inner, seen)
                elif hasattr(it, "eqns"):  # raw Jaxpr
                    yield from _walk_jaxprs(it, seen)


def _carry_avals(eqn) -> Iterable:
    """The carry avals of a while/scan eqn (the fusibility contract ROADMAP-1
    round fusion rides on: these must stay bucket-stable)."""
    import jax  # noqa: F401 - the worker owns the jax import

    if eqn.primitive.name == "while":
        return [v.aval for v in eqn.params["body_jaxpr"].jaxpr.invars]
    if eqn.primitive.name == "scan":
        nc = eqn.params["num_consts"]
        ncar = eqn.params["num_carry"]
        return [v.aval for v in eqn.params["jaxpr"].jaxpr.invars[nc:nc + ncar]]
    return []


def check_jaxpr(entry_name: str, closed, path: str, line: int,
                const_bytes_limit: int) -> List[Dict]:
    """The pure jaxpr walks: host callbacks, carry stability, constant bloat.
    Importable in-process for unit tests — only `run()` pins the platform."""
    import numpy as np

    findings: List[Dict] = []
    seen_consts = set()

    def check_consts(consts, where: str):
        for c in consts:
            if id(c) in seen_consts:
                continue
            seen_consts.add(id(c))
            nbytes = getattr(c, "nbytes", 0)
            if nbytes > const_bytes_limit:
                shape = tuple(np.shape(c))
                findings.append(_finding(
                    "trace-constant-bloat", path, line,
                    f"entry `{entry_name}` bakes a {nbytes}-byte constant "
                    f"(shape {shape}) into the compiled program (limit "
                    f"{const_bytes_limit}); closure-captured arrays ship "
                    "with every program in the bucket ladder — pass it as "
                    "an argument instead",
                ))

    check_consts(closed.consts, "top")
    seen: set = set()
    for item in _walk_jaxprs(closed.jaxpr, seen):
        if isinstance(item, tuple) and item[0] == "closed":
            check_consts(item[1].consts, "inner")
            continue
        eqn = item
        name = eqn.primitive.name
        if name in CALLBACK_PRIMITIVES:
            findings.append(_finding(
                "trace-host-callback", path, line,
                f"entry `{entry_name}` reaches a `{name}` primitive under "
                "the jit boundary — a host round-trip inside traced code "
                "serializes the device pipeline; hoist it to the host shell "
                "or drop it",
            ))
        for aval in _carry_avals(eqn):
            dtype = getattr(aval, "dtype", None)
            if dtype is not None and str(dtype) == "float64":
                findings.append(_finding(
                    "trace-carry-stability", path, line,
                    f"entry `{entry_name}`: {name} carry holds a float64 "
                    f"aval ({aval}) — a double-precision carry forks the "
                    "compiled program out of its f32 shape bucket",
                ))
            if getattr(aval, "weak_type", False):
                findings.append(_finding(
                    "trace-carry-stability", path, line,
                    f"entry `{entry_name}`: {name} carry holds a weak-typed "
                    f"aval ({aval}) — seed the carry with explicit dtypes "
                    "(jnp.int32/jnp.float32), or the same program retraces "
                    "when a strongly-typed carry arrives",
                ))
    return findings


def check_donation(entry_name: str, closed, args: tuple,
                   donate_argnums: Tuple[int, ...], path: str,
                   line: int) -> List[Dict]:
    """Dead-donation check: every donated input leaf must find an output
    leaf of identical shape/dtype to alias into (XLA's matching rule) —
    otherwise the donation frees nothing and the caller merely lost the
    buffer. Catches the class the `tpu.donate.model.buffers` reservation
    exists for."""
    import jax

    pool: Dict[Tuple, int] = {}
    for aval in closed.out_avals:
        key = (tuple(aval.shape), str(aval.dtype))
        pool[key] = pool.get(key, 0) + 1
    findings: List[Dict] = []
    for i in donate_argnums:
        if i >= len(args):
            findings.append(_finding(
                "trace-donation-integrity", path, line,
                f"entry `{entry_name}` declares donate_argnums position {i} "
                f"but only {len(args)} example arguments",
            ))
            continue
        for leaf in jax.tree_util.tree_leaves(args[i]):
            key = (tuple(leaf.shape), str(leaf.dtype))
            if pool.get(key, 0) > 0:
                pool[key] -= 1
            else:
                findings.append(_finding(
                    "trace-donation-integrity", path, line,
                    f"entry `{entry_name}`: donated argument {i} holds a "
                    f"{key[1]}{list(key[0])} buffer with no same-shape/dtype "
                    "output to alias into — the donation is dead (the "
                    "buffer is freed, nothing is reused); drop it from "
                    "donate_argnums or return the updated buffer",
                ))
    return findings


def _build_shardings(spec_tree, args, mesh):
    """Per-arg PartitionSpec trees -> NamedSharding trees matching `args`."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    def to_sharding(spec):
        if spec is None:
            return NamedSharding(mesh, PartitionSpec())
        return NamedSharding(mesh, PartitionSpec(*spec))

    out = []
    for spec, arg in zip(spec_tree, args):
        if isinstance(spec, (tuple, list)) and all(
            s is None or isinstance(s, str) for s in spec
        ):
            out.append(to_sharding(tuple(spec)))
        else:
            # a pytree of specs matching the arg's structure (NamedTuples)
            out.append(jax.tree_util.tree_map(
                to_sharding, spec,
                is_leaf=lambda x: x is None or (
                    isinstance(x, (tuple, list))
                    and all(s is None or isinstance(s, str) for s in x)
                ),
            ))
    return tuple(out)


def check_sharding(entry_name: str, fn, args: tuple, spec_tree, mesh_shape,
                   max_all_gathers: int, path: str, line: int) -> List[Dict]:
    """Sharding-readiness: the entry must lower AND compile under a virtual
    mesh with its declared PartitionSpecs, and the compiled program may not
    gather the sharded axis back together more than its budget allows (the
    PAPER.md target is vmap-scored moves reduced with `psum`: all-reduce is
    the intended collective, an all-gather is replication)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    findings: List[Dict] = []
    sizes = [s for _, s in mesh_shape]
    need = int(np.prod(sizes))
    devices = jax.devices()
    if len(devices) < need:
        findings.append(_finding(
            "trace-sharding-lowering", path, line,
            f"entry `{entry_name}` needs a {need}-device mesh but the worker "
            f"sees {len(devices)} devices — virtual-device pinning failed",
        ))
        return findings
    mesh = Mesh(
        np.asarray(devices[:need]).reshape(sizes), tuple(a for a, _ in mesh_shape)
    )
    try:
        in_shardings = _build_shardings(spec_tree, args, mesh)
        jitted = jax.jit(fn, in_shardings=in_shardings)
        compiled = jitted.lower(*args).compile()
    except Exception as e:  # surface the lowering verdict, whatever its class
        findings.append(_finding(
            "trace-sharding-lowering", path, line,
            f"entry `{entry_name}` fails to lower/compile under the "
            f"{'x'.join(str(s) for s in sizes)} `"
            f"{','.join(a for a, _ in mesh_shape)}` mesh: "
            f"{type(e).__name__}: {str(e)[:300]}",
        ))
        return findings
    hlo = compiled.as_text()
    gathers = [
        ln.strip() for ln in hlo.splitlines()
        if "all-gather" in ln and "=" in ln and not ln.lstrip().startswith("//")
    ]
    if len(gathers) > max_all_gathers:
        sample = gathers[0][:160] if gathers else ""
        findings.append(_finding(
            "trace-sharding-lowering", path, line,
            f"entry `{entry_name}` compiles to {len(gathers)} all-gather "
            f"op(s) under the mesh (budget {max_all_gathers}) — an op in "
            "this entry forces the sharded axis to be replicated instead of "
            f"psum-reduced; first: `{sample}`",
        ))
    return findings


def analyze_entry(entry: Dict, path: str, line: int) -> Tuple[List[Dict], Dict]:
    """All checks for one built entry. Returns (findings, stats)."""
    import jax

    name = entry["name"]
    subject = entry["build"]()
    fn = subject["fn"]
    args = tuple(subject.get("args", ()))
    donate = tuple(subject.get("donate_argnums", ()))
    spec_tree = subject.get("shardings")
    stats = {"name": name, "traceS": 0.0}
    findings: List[Dict] = []
    t0 = time.monotonic()
    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:
        msg = str(e)
        rule = (
            "trace-carry-stability"
            if _CARRY_ERROR_RE.search(msg)
            else "trace-entry-error"
        )
        detail = (
            "loop carry is not shape/dtype/pytree-stable across iterations "
            "(round fusion cannot hold this program in one bucket): "
            if rule == "trace-carry-stability" else "cannot trace: "
        )
        findings.append(_finding(
            rule, path, line,
            f"entry `{name}` {detail}{type(e).__name__}: {msg[:300]}",
        ))
        stats["traceS"] = round(time.monotonic() - t0, 3)
        return findings, stats
    stats["traceS"] = round(time.monotonic() - t0, 3)
    findings.extend(check_jaxpr(
        name, closed, path, line,
        int(subject.get("const_bytes_limit", DEFAULT_CONST_BYTES_LIMIT)),
    ))
    if donate:
        findings.extend(check_donation(name, closed, args, donate, path, line))
    if spec_tree is not None:
        findings.extend(check_sharding(
            name, fn, args, spec_tree,
            tuple(subject.get("mesh_shape", DEFAULT_MESH_SHAPE)),
            int(subject.get("max_all_gathers", 0)), path, line,
        ))
    return findings, stats


def load_entry_modules(root: pathlib.Path, rels: List[str]):
    """Import each registry module by file path; yield (rel, module_or_error)."""
    for rel in rels:
        full = root / rel
        modname = "cclint_trace_" + hashlib.sha1(rel.encode()).hexdigest()[:10]
        try:
            spec = importlib.util.spec_from_file_location(modname, full)
            mod = importlib.util.module_from_spec(spec)
            sys.modules[modname] = mod  # entries may self-reference on import
            spec.loader.exec_module(mod)
            yield rel, mod, None
        except Exception as e:
            yield rel, None, f"{type(e).__name__}: {str(e)[:300]}"


def run(root: pathlib.Path, rels: List[str]) -> Dict:
    t_start = time.monotonic()
    findings: List[Dict] = []
    entry_stats: List[Dict] = []
    for rel, mod, err in load_entry_modules(root, rels):
        if err is not None:
            findings.append(_finding(
                "trace-entry-error", rel, 1,
                f"entry-point module failed to import: {err}",
            ))
            continue
        entries = getattr(mod, "CCLINT_TRACE_ENTRYPOINTS", None)
        if not isinstance(entries, (list, tuple)):
            findings.append(_finding(
                "trace-entry-error", rel, 1,
                "CCLINT_TRACE_ENTRYPOINTS must be a list of "
                "dict(name=..., build=...) entries",
            ))
            continue
        lines = (root / rel).read_text().splitlines()
        for entry in entries:
            name = entry.get("name") if isinstance(entry, dict) else None
            if not name or not callable(entry.get("build")):
                findings.append(_finding(
                    "trace-entry-error", rel, 1,
                    f"malformed registry entry {entry!r}: needs a `name` "
                    "string and a callable `build`",
                ))
                continue
            line = _entry_line(lines, name)
            try:
                fs, st = analyze_entry(entry, rel, line)
            except Exception as e:
                fs = [_finding(
                    "trace-entry-error", rel, line,
                    f"entry `{name}` build() failed: {type(e).__name__}: "
                    f"{str(e)[:300]}",
                )]
                st = {"name": name, "traceS": 0.0}
            # dedup identical findings within one entry: the unrolled stack
            # repeats each goal body per phase, so a single kernel violation
            # would otherwise print once per unroll copy
            seen_f = set()
            for f in fs:
                key = (f["rule"], f["line"], f["message"])
                if key not in seen_f:
                    seen_f.add(key)
                    findings.append(f)
            entry_stats.append(st)
    return {
        "version": WORKER_SCHEMA,
        "findings": findings,
        "stats": {
            "modules": len(rels),
            "entryPoints": len(entry_stats),
            "entries": entry_stats,
            "wallS": round(time.monotonic() - t_start, 3),
        },
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="cclint-trace-worker")
    parser.add_argument("--root", type=pathlib.Path, required=True)
    parser.add_argument("rels", nargs="+")
    args = parser.parse_args(argv)

    # pin BEFORE jax initializes: the sharding checks need the virtual
    # 8-device mesh, and a dead TPU tunnel must not hang the linter
    from cruise_control_tpu.platform_probe import pin_cpu

    need = max(
        (s for _, s in DEFAULT_MESH_SHAPE), default=8
    )
    pin_cpu(device_count=max(8, need))

    doc = run(args.root.resolve(), list(args.rels))
    json.dump(doc, sys.stdout)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
