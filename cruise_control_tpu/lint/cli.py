"""cclint command line: `python scripts/cclint.py` / `python -m cruise_control_tpu.lint`.

Both entry points are THIS function — there is exactly one CLI, pinned by
tests/test_lint_trace.py's exit-code identity cases.

Exit codes (stable):
  0  clean (no unsuppressed findings)
  1  unsuppressed findings
  2  usage or internal error

`--tier` selects the analysis tier: `token` (pure ast/text — the fast local
loop), `trace` (jaxpr-level evaluation of the registered kernel entry
points, content-hash cached), or `all` (default; what CI runs). `--json`
emits the machine schema v2 (per-rule family/tier/wall-time plus the trace
cache verdict); the default human format is one `path:line: rule  message`
per finding plus a summary line. `--changed-only` lints the full context
(registry rules need every file) but reports only findings in files that
differ from `--base` (default `main`) or are locally modified/untracked —
stale suppressions for the selected rules are judged on these runs too.
"""

from __future__ import annotations

import argparse
import pathlib
import subprocess
import sys
from typing import List, Optional, Sequence

from cruise_control_tpu.lint.core import (
    EXIT_CLEAN,
    EXIT_ERROR,
    EXIT_FINDINGS,
    RULES,
    all_rules,
    build_context,
    render_human,
    render_json,
    run_rules,
    tier_rules,
    unsuppressed,
)


def changed_paths(root: pathlib.Path, base: str = "main") -> Optional[List[str]]:
    """Repo-relative posix paths that differ from `base` or the index, plus
    untracked files; None when git is unavailable (callers fall back to a
    full report)."""
    out: List[str] = []
    succeeded = 0
    for args in (
        ["git", "diff", "--name-only", f"{base}...HEAD"],
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                args, cwd=root, capture_output=True, text=True, timeout=30
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            # the three-dot diff fails when `base` is missing; degrade to
            # the working-tree diffs rather than silently reporting nothing
            continue
        succeeded += 1
        out.extend(line.strip() for line in proc.stdout.splitlines() if line.strip())
    if not succeeded:
        return None  # not a repo / git missing: caller falls back to full report
    return sorted(set(out))


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="cclint",
        description="repo-native static analysis: TPU hygiene, concurrency "
                    "discipline, config/sensor registry consistency, and "
                    "jaxpr-level kernel certification (docs/LINTING.md)",
    )
    parser.add_argument("paths", nargs="*", type=pathlib.Path,
                        help="files or directories to lint (default: the "
                             "cruise_control_tpu package)")
    parser.add_argument("--root", type=pathlib.Path, default=None,
                        help="repo root (default: auto from this file)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output (schema v2)")
    parser.add_argument("--rule", action="append", default=None, metavar="ID",
                        help="run only this rule (repeatable; overrides --tier)")
    parser.add_argument("--tier", choices=("token", "trace", "all"),
                        default="all",
                        help="analysis tier: token = ast/text rules only, "
                             "trace = jaxpr-level entry-point rules only, "
                             "all = both (default)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--changed-only", action="store_true",
                        help="report only findings in files changed vs --base")
    parser.add_argument("--base", default="main",
                        help="comparison ref for --changed-only (default: main)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="include suppressed findings in human output")
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id:28s} [{r.family}/{r.tier}] {r.rationale}")
        return EXIT_CLEAN

    if args.rule:
        missing = [rid for rid in args.rule if rid not in RULES]
        if missing:
            print(f"cclint: unknown rule id(s): {', '.join(missing)}",
                  file=sys.stderr)
            return EXIT_ERROR
        rules = [RULES[rid] for rid in args.rule]
    else:
        rules = tier_rules(args.tier)

    root = args.root
    if root is None:
        root = pathlib.Path(__file__).resolve().parents[2]
    try:
        ctx = build_context(root, py_paths=args.paths or None)
    except OSError as e:
        print(f"cclint: cannot read sources: {e}", file=sys.stderr)
        return EXIT_ERROR

    timings: dict = {}
    findings = run_rules(ctx, rules=rules, timings=timings)

    if args.changed_only:
        changed = changed_paths(root, base=args.base)
        if changed is None:
            print("cclint: git unavailable; reporting all findings",
                  file=sys.stderr)
        else:
            changed_set = set(changed)
            findings = [f for f in findings if f.path in changed_set]

    if args.as_json:
        print(render_json(findings, len(ctx.files), rules, timings=timings,
                          trace_stats=ctx.cache.get("trace-stats")))
    else:
        print(render_human(findings, len(ctx.files), len(rules),
                           show_suppressed=args.show_suppressed))
    return EXIT_FINDINGS if unsuppressed(findings) else EXIT_CLEAN


if __name__ == "__main__":
    raise SystemExit(main())
