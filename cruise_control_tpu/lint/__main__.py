"""`python -m cruise_control_tpu.lint` == `python scripts/cclint.py`."""

from cruise_control_tpu.lint.cli import main

raise SystemExit(main())
