"""Kernel entry-point registry for the cclint trace tier.

This is the certification manifest of the kernel stack: every jitted
surface the optimizer dispatches in production is declared here with a
small concrete problem instance, and the trace worker
(lint/trace_worker.py) abstractly evaluates each one — `jax.make_jaxpr`
for the host-callback / donation / carry / constant contracts, a
lower+compile under the virtual 8-device partition mesh for the sharding
contracts. Keeping the registry in lint/ (not analyzer/) is deliberate:
findings and their suppressions anchor to the `name="..."` line of the
entry below, so this file is also where any written trace-tier waiver
must live, in plain sight.

Registered surfaces (mirroring the production call sites in
analyzer/optimizer.py `optimizations()` / `_machine_executable` and the
engine factories):

  fused-stack-step          the whole priority stack as ONE jitted program
                            (donates the Aggregates, _make_stack_step)
  chunked-goal-machine      the bounded-duration stack executor with the
                            (agg, tables, metrics, snapshots) donation set
  bulk-count-round          the count-family surplus/deficit wave planner
  pair-drain-round          the (topic, broker) pair drain engine
  swap-round                the resource-distribution swap engine
  sharded-compute-aggregates  the partition-axis model aggregation under
                            the parallel/sharding.py PartitionSpec rules
  sharded-compute-stats     model stats under the same mesh placement
  spmd-grid-shortlist       the explicit shard_map grid-scoring round
                            shortlist — one winner all-gather per round
                            (parallel/spmd.py, batch_k=1 grid engine)
  spmd-partition-stats      the integer-psum shard-coverage stats kernel
                            (zero all-gathers allowed)
  incremental-delta-apply   the in-place model-delta scatter of the
                            incremental rebalancing lane
                            (analyzer/incremental.py apply_delta_batch)

Everything heavy is imported inside the builders: this module is imported
by the trace worker subprocess only — the in-process linter merely scans
it for the `CCLINT_TRACE_ENTRYPOINTS` declaration.

The tiny `unbalanced()` generator model keeps tracing cheap (~25 s for the
two whole-stack programs, cached by content hash thereafter); trace-level
contracts are shape-generic, so the verdict at 4 partitions is the verdict
at 200k.
"""

from __future__ import annotations

#: Per-entry all-gather budgets (the worker's `max_all_gathers` is per-entry;
#: one constant per entry class keeps each budget's rationale next to its
#: number instead of flattening them into a shared ceiling):
#:
#: * aggregation entries — XLA materializes a handful of tiny index
#:   all-gathers (s32 broker/topic id vectors) when scattering the
#:   per-partition shards into broker bins: measured 6 per entry on jax
#:   0.4.37. The budget leaves two ops of layout-assignment jitter while
#:   still firing long before anything gathers the [P, M] load matrix
#:   itself (the replication class the rule exists for).
AGGREGATION_ALL_GATHER_BUDGET = 8
#: * the SPMD grid-shortlist round kernel — its design IS one explicit
#:   tuple all-gather of the per-shard winner 5-tuples (parallel/spmd.py),
#:   which XLA lowers to one instruction per tuple leaf plus operand
#:   references the worker's line count also matches: measured 12 lines on
#:   jax 0.4.37. The budget leaves headroom for layout jitter while firing
#:   if anything ever gathers a grid-sized array (thousands of lines).
SPMD_SHORTLIST_ALL_GATHER_BUDGET = 16
#: * the psum partition-stats kernel — pure integer psum (all-reduce);
#:   ANY all-gather is a regression.
SPMD_STATS_ALL_GATHER_BUDGET = 0

#: partition-axis mesh the sharded entries must survive (ROADMAP-2's v5e-8)
MESH_SHAPE = (("partitions", 8),)


def _tiny_problem():
    """One small concrete problem instance shared by the builders."""
    from cruise_control_tpu.analyzer import optimizer as opt
    from cruise_control_tpu.analyzer.context import build_static_ctx, dims_of
    from cruise_control_tpu.config.balancing import BalancingConstraint
    from cruise_control_tpu.models.generators import unbalanced
    from cruise_control_tpu.parallel.sharding import pad_partitions

    # pad the partition axis to the mesh size so the SAME instance serves
    # the sharded entries (8 | P is the mesh-divisibility precondition)
    model = pad_partitions(unbalanced(), 8)
    dims = dims_of(model)
    settings = opt.OptimizerSettings()
    static = build_static_ctx(model, BalancingConstraint.default(), dims)
    agg = opt._jit_compute_aggregates(static, model.assignment, dims)
    return model, dims, settings, static, agg


def _default_goal_names():
    from cruise_control_tpu.analyzer.goals import goals_by_priority

    return tuple(g.name for g in goals_by_priority())


def _build_fused_stack():
    from cruise_control_tpu.analyzer import optimizer as opt

    _model, dims, settings, static, agg = _tiny_problem()
    fn = opt._make_stack_step(_default_goal_names(), dims, settings)
    # donate_argnums mirrors _make_stack_step's jit(..., donate_argnums=(1,))
    return dict(fn=fn, args=(static, agg), donate_argnums=(1,))


def _build_goal_machine():
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer import optimizer as opt
    from cruise_control_tpu.analyzer.acceptance import empty_tables

    _model, dims, settings, static, agg = _tiny_problem()
    names = _default_goal_names()
    fn = opt._make_goal_machine(names, dims, settings)
    n_phases = 2 * len(names) if settings.polish_rounds > 0 else len(names)
    args = (
        static, agg, empty_tables(dims), jnp.int32(0), jnp.int32(0),
        jnp.int32(0), opt.empty_stack_metrics(len(names)), jnp.int32(8),
        jnp.ones((len(names),), bool),
        opt.empty_prov_snapshots(n_phases, dims, settings.ledger),
    )
    # mirrors _make_goal_machine's donate_argnums=(1, 2, 6, 9):
    # agg / tables / metrics / provenance snapshots thread through chunks
    return dict(fn=fn, args=args, donate_argnums=(1, 2, 6, 9))


def _build_bulk_round():
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer.acceptance import empty_tables
    from cruise_control_tpu.analyzer.bulk import make_bulk_count_round
    from cruise_control_tpu.analyzer.goals import GOAL_REGISTRY

    _model, dims, settings, static, agg = _tiny_problem()
    goal = GOAL_REGISTRY["ReplicaDistributionGoal"]
    gs = goal.prepare(static, agg, dims)
    contrib = goal.drain_contrib(static, gs, agg)
    fn = make_bulk_count_round(
        goal, dims, settings.drain_per_broker, settings.bulk_waves
    )
    return dict(
        fn=fn,
        args=(static, agg, empty_tables(dims), gs, contrib, jnp.int32(0)),
    )


def _build_pair_drain_round():
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer.acceptance import empty_tables
    from cruise_control_tpu.analyzer.drain import make_pair_drain_round
    from cruise_control_tpu.analyzer.goals import GOAL_REGISTRY

    _model, dims, settings, static, agg = _tiny_problem()
    goal = GOAL_REGISTRY["TopicReplicaDistributionGoal"]
    gs = goal.prepare(static, agg, dims)
    contrib = goal.drain_contrib(static, gs, agg)
    fn = make_pair_drain_round(
        goal, dims, settings.drain_src, settings.apply_waves
    )
    return dict(
        fn=fn,
        args=(static, agg, empty_tables(dims), gs, contrib, jnp.int32(0)),
    )


def _build_swap_round():
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer.acceptance import empty_tables
    from cruise_control_tpu.analyzer.goals import GOAL_REGISTRY
    from cruise_control_tpu.analyzer.swaps import make_swap_round

    _model, dims, settings, static, agg = _tiny_problem()
    goal = GOAL_REGISTRY["DiskUsageDistributionGoal"]
    gs = goal.prepare(static, agg, dims)
    contrib = goal.drain_contrib(static, gs, agg)
    fn = make_swap_round(
        goal, (), dims, settings.num_swap_pairs, settings.swap_candidates,
        settings.swaps_per_broker, apply_waves=settings.apply_waves,
    )
    return dict(
        fn=fn,
        args=(static, agg, empty_tables(dims), contrib, jnp.int32(0)),
    )


def _partition_specs_for(tree, sharded_fields, axis="partitions"):
    """Per-field PartitionSpec tuples mirroring parallel/sharding.py's
    place_static/place_aggregates: leading-axis shard for the named fields,
    full replication for the rest."""
    import numpy as np

    specs = {}
    for name, value in tree._asdict().items():
        arr = np.asarray(value)
        if name in sharded_fields:
            specs[name] = (axis,) + (None,) * max(0, arr.ndim - 1)
        else:
            specs[name] = None
    return type(tree)(**specs)


def _build_sharded_aggregates():
    import functools

    from cruise_control_tpu.analyzer.context import compute_aggregates

    model, dims, _settings, static, _agg = _tiny_problem()
    static_spec = _partition_specs_for(
        static, {"part_load", "topic_id", "movable_partition"}
    )
    fn = functools.partial(compute_aggregates, dims=dims)
    return dict(
        fn=fn,
        args=(static, model.assignment),
        shardings=(static_spec, ("partitions", None)),
        mesh_shape=MESH_SHAPE,
        max_all_gathers=AGGREGATION_ALL_GATHER_BUDGET,
    )


def _build_sharded_stats():
    import functools

    from cruise_control_tpu.analyzer.stats import compute_stats

    model, dims, _settings, _static, _agg = _tiny_problem()
    model_spec = _partition_specs_for(
        model, {"assignment", "part_load", "topic_id"}
    )
    fn = functools.partial(compute_stats, num_topics=dims.num_topics)
    return dict(
        fn=fn,
        args=(model,),
        shardings=(model_spec,),
        mesh_shape=MESH_SHAPE,
        max_all_gathers=AGGREGATION_ALL_GATHER_BUDGET,
    )


def _build_spmd_grid_shortlist():
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer import optimizer as opt
    from cruise_control_tpu.analyzer.acceptance import empty_tables
    from cruise_control_tpu.analyzer.goals import GOAL_REGISTRY
    from cruise_control_tpu.parallel import spmd
    from cruise_control_tpu.parallel.sharding import make_mesh

    _model, dims, _settings, static, agg = _tiny_problem()
    # batch_k=1 is the greedy/parity grid-engine mode — the regime the
    # shard_map shortlist serves (optimizer._make_goal_loop routes batch_k>1
    # and swap goals to the drain engines)
    settings = opt.OptimizerSettings(batch_k=1)
    goal = GOAL_REGISTRY["DiskUsageDistributionGoal"]
    gs = goal.prepare(static, agg, dims)
    dst_cands = jnp.arange(min(dims.num_brokers, 16), dtype=jnp.int32)
    fn = spmd.make_grid_shortlist(make_mesh(8), goal, dims, settings)
    return dict(
        fn=fn,
        args=(static, agg, gs, empty_tables(dims), dst_cands),
        shardings=(
            _partition_specs_for(static, spmd.STATIC_SHARDED_FIELDS),
            _partition_specs_for(agg, spmd.AGG_SHARDED_FIELDS),
            None, None, None,
        ),
        mesh_shape=MESH_SHAPE,
        max_all_gathers=SPMD_SHORTLIST_ALL_GATHER_BUDGET,
    )


def _build_spmd_partition_stats():
    from cruise_control_tpu.parallel import spmd
    from cruise_control_tpu.parallel.sharding import make_mesh

    _model, _dims, _settings, static, agg = _tiny_problem()
    fn = spmd.make_partition_stats(make_mesh(8))
    return dict(
        fn=fn,
        args=(static, agg),
        shardings=(
            _partition_specs_for(static, spmd.STATIC_SHARDED_FIELDS),
            _partition_specs_for(agg, spmd.AGG_SHARDED_FIELDS),
        ),
        mesh_shape=MESH_SHAPE,
        max_all_gathers=SPMD_STATS_ALL_GATHER_BUDGET,
    )


def _build_incremental_delta_apply():
    import jax.numpy as jnp
    import numpy as np

    from cruise_control_tpu.analyzer import incremental as inc
    from cruise_control_tpu.common.resources import BrokerState

    model, dims, _settings, static, _agg = _tiny_problem()
    num_metrics = int(np.asarray(static.part_load).shape[1])
    deltas = [
        inc.ModelDelta(
            kind=inc.DELTA_BROKER_DEATH, broker=0, state=int(BrokerState.DEAD)
        ),
        inc.ModelDelta(
            kind=inc.DELTA_LOAD_SPIKE, row=1,
            load=np.ones(num_metrics, np.float32),
        ),
    ]
    batch = inc.build_delta_batch(deltas, max_deltas=8, num_metrics=num_metrics)
    base = jnp.asarray(np.asarray(static.broker_valid, dtype=bool))
    # NO donation: the kernel's inputs are shared with the optimizer's prep
    # cache (apply_delta_batch docstring) — the trace tier checks that too
    return dict(fn=inc.apply_delta_batch, args=(static, batch, base, base))


CCLINT_TRACE_ENTRYPOINTS = [
    dict(name="fused-stack-step", build=_build_fused_stack),
    dict(name="chunked-goal-machine", build=_build_goal_machine),
    dict(name="bulk-count-round", build=_build_bulk_round),
    dict(name="pair-drain-round", build=_build_pair_drain_round),
    dict(name="swap-round", build=_build_swap_round),
    dict(name="sharded-compute-aggregates", build=_build_sharded_aggregates),
    dict(name="sharded-compute-stats", build=_build_sharded_stats),
    dict(name="spmd-grid-shortlist", build=_build_spmd_grid_shortlist),
    dict(name="spmd-partition-stats", build=_build_spmd_partition_stats),
    dict(name="incremental-delta-apply", build=_build_incremental_delta_apply),
]
