"""Trace-tier rules (family `trace`): jaxpr-level contracts the token rules
cannot see.

The token tier reads source text; these rules read the PROGRAM. Every
module declaring a `CCLINT_TRACE_ENTRYPOINTS` registry (lint/entrypoints.py
registers the real fused stack, chunked goal machine, bulk/drain/swap round
kernels, and the parallel/sharding dispatch surfaces) is handed to a
JAX-tracing subprocess (lint/trace_worker.py) that abstractly evaluates
each entry with `jax.make_jaxpr` / a sharded lower+compile and reports
violations of five contracts:

  trace-host-callback      no pure/debug/io_callback primitive under jit
  trace-donation-integrity every donate_argnums buffer aliases an output
  trace-carry-stability    while/scan carries bucket-stable (no weak_type,
                           no float64, no shape/pytree drift)
  trace-constant-bloat     no oversized closure-captured program constants
  trace-sharding-lowering  sharded entries lower+compile under a virtual
                           8-device mesh without replication-forcing ops
  trace-entry-error        the registry itself is well-formed and traceable

Cost model: the subprocess pays a real JAX import plus ~10 s of tracing for
the full goal stack, so results are cached on disk keyed by the CONTENT
HASH of the linted sources (plus jax/jaxlib versions and the worker schema)
— a repeat run with unchanged sources never spawns the worker and the
combined token+trace package run stays inside the PR-6 <10 s budget
(tests/test_lint_trace.py pins hit/miss/invalidation and the budget).

This module itself imports no JAX: version strings come from package
metadata, and all tracing happens in the worker subprocess.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import pathlib
import subprocess
import sys
from typing import Dict, Iterator, List

from cruise_control_tpu.lint.core import (
    Finding,
    LintContext,
    Rule,
    SourceFile,
    register,
)
from cruise_control_tpu.lint.trace_worker import WORKER_SCHEMA

#: cache directory: env override (tests point it at tmp), else a dot-dir at
#: the repo root. Entries for the committed tree are committed alongside the
#: sources so a fresh checkout's first CI run is already warm.
CACHE_ENV = "CCLINT_TRACE_CACHE"
#: worker wall-clock ceiling (seconds); the full-stack trace is ~25 s cold
TIMEOUT_ENV = "CCLINT_TRACE_TIMEOUT"
DEFAULT_TIMEOUT_S = 540.0

#: process-lifetime cache counters, reset-able by tests
CACHE_STATS = {"hits": 0, "misses": 0}

_REGISTRY_NAME = "CCLINT_TRACE_ENTRYPOINTS"
_REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


def cache_dir() -> pathlib.Path:
    env = os.environ.get(CACHE_ENV)
    return pathlib.Path(env) if env else _REPO_ROOT / ".cclint_cache"


def entry_modules(ctx: LintContext) -> List[SourceFile]:
    """Files whose module level assigns CCLINT_TRACE_ENTRYPOINTS (AST, not
    text — a docstring mentioning the name must not opt a module in)."""
    out = []
    for src in ctx.parsed_files:
        for node in src.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            if any(
                isinstance(t, ast.Name) and t.id == _REGISTRY_NAME
                for t in targets
            ):
                out.append(src)
                break
    return out


def _versions() -> str:
    """jax/jaxlib versions WITHOUT importing them (metadata only): part of
    the cache key, since a toolchain bump can change every verdict."""
    from importlib import metadata

    parts = []
    for pkg in ("jax", "jaxlib"):
        try:
            parts.append(f"{pkg}={metadata.version(pkg)}")
        except metadata.PackageNotFoundError:
            parts.append(f"{pkg}=absent")
    return ";".join(parts)


def content_key(ctx: LintContext) -> str:
    """sha256 over every linted source (rel path + bytes), the toolchain
    versions, and the worker schema. Conservative by design: ANY source
    edit in the linted set invalidates — tracing is cheap enough to redo
    and a dependency-graph hash would miss transitive kernel imports."""
    h = hashlib.sha256()
    h.update(f"schema={WORKER_SCHEMA};{_versions()}".encode())
    for src in sorted(ctx.files, key=lambda s: s.rel):
        h.update(b"\x00")
        h.update(src.rel.encode())
        h.update(b"\x00")
        h.update(src.text.encode())
    return h.hexdigest()


def _cache_load(key: str):
    path = cache_dir() / f"trace-{key[:32]}.json"
    try:
        doc = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if doc.get("key") != key or doc.get("version") != WORKER_SCHEMA:
        return None
    return doc


def _cache_store(key: str, payload: Dict) -> None:
    d = cache_dir()
    try:
        d.mkdir(parents=True, exist_ok=True)
        tmp = d / f".trace-{key[:32]}.tmp.{os.getpid()}"
        tmp.write_text(json.dumps(
            {"key": key, "version": WORKER_SCHEMA, **payload}, indent=2,
            sort_keys=True,
        ))
        tmp.replace(d / f"trace-{key[:32]}.json")
    except OSError:
        pass  # a read-only checkout still lints, it just re-traces


def _spawn_worker(ctx: LintContext, mods: List[SourceFile]) -> Dict:
    cmd = [
        sys.executable, "-m", "cruise_control_tpu.lint.trace_worker",
        "--root", str(ctx.root),
    ] + [m.rel for m in mods]
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        str(_REPO_ROOT) + os.pathsep + env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    timeout = float(os.environ.get(TIMEOUT_ENV, DEFAULT_TIMEOUT_S))
    try:
        proc = subprocess.run(
            cmd, cwd=_REPO_ROOT, env=env, capture_output=True, text=True,
            timeout=timeout,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        return {"findings": [
            {
                "rule": "trace-entry-error", "path": m.rel, "line": 1,
                "message": f"trace worker did not run: {type(e).__name__}: "
                           f"{str(e)[:200]}",
            }
            for m in mods
        ], "stats": {"workerError": str(e)[:200]}}
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-6:]
        return {"findings": [
            {
                "rule": "trace-entry-error", "path": m.rel, "line": 1,
                "message": f"trace worker exited {proc.returncode}: "
                           + " | ".join(tail)[:300],
            }
            for m in mods
        ], "stats": {"workerError": f"rc={proc.returncode}"}}
    try:
        doc = json.loads(proc.stdout)
    except ValueError:
        return {"findings": [
            {
                "rule": "trace-entry-error", "path": m.rel, "line": 1,
                "message": "trace worker produced unparseable output: "
                           + proc.stdout[:200],
            }
            for m in mods
        ], "stats": {"workerError": "bad-json"}}
    return {"findings": doc.get("findings", []), "stats": doc.get("stats", {})}


def trace_payload(ctx: LintContext) -> Dict:
    """The shared per-context trace verdict: computed once, memoized in
    ctx.cache for the run and on disk (content-hash keyed) across runs."""
    cached = ctx.cache.get("trace-payload")
    if cached is not None:
        return cached
    mods = entry_modules(ctx)
    if not mods:
        payload = {"findings": [], "stats": {"entryPoints": 0, "modules": 0},
                   "cacheHit": False, "skipped": True}
        ctx.cache["trace-payload"] = payload
        ctx.cache["trace-stats"] = _public_stats(payload)
        return payload
    key = content_key(ctx)
    doc = _cache_load(key)
    if doc is not None:
        CACHE_STATS["hits"] += 1
        payload = {"findings": doc["findings"], "stats": doc.get("stats", {}),
                   "cacheHit": True, "skipped": False}
    else:
        CACHE_STATS["misses"] += 1
        fresh = _spawn_worker(ctx, mods)
        if "workerError" not in fresh.get("stats", {}):
            _cache_store(key, fresh)
        payload = {**fresh, "cacheHit": False, "skipped": False}
    ctx.cache["trace-payload"] = payload
    ctx.cache["trace-stats"] = _public_stats(payload)
    return payload


def _public_stats(payload: Dict) -> Dict:
    """The `trace` block of the --json schema."""
    stats = payload.get("stats", {})
    return {
        "cacheHit": payload.get("cacheHit", False),
        "skipped": payload.get("skipped", False),
        "entryPoints": stats.get("entryPoints", 0),
        "modules": stats.get("modules", 0),
        "workerWallS": stats.get("wallS", 0.0),
    }


class TraceRule(Rule):
    """Shared driver: each rule yields its slice of the worker's findings.
    The first trace rule to run pays (or cache-loads) the shared payload."""

    family = "trace"
    tier = "trace"

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for f in trace_payload(ctx)["findings"]:
            if f["rule"] == self.id:
                yield Finding(
                    rule=self.id, path=f["path"], line=int(f["line"]),
                    message=f["message"],
                )


@register
class HostCallbackRule(TraceRule):
    id = "trace-host-callback"
    rationale = (
        "a pure/debug/io_callback primitive under a jit boundary is a host "
        "round-trip inside traced code — invisible to token rules when "
        "buried in a helper, fatal to the fused-round dispatch budget"
    )


@register
class DonationIntegrityRule(TraceRule):
    id = "trace-donation-integrity"
    rationale = (
        "a donate_argnums buffer with no same-shape/dtype output to alias "
        "into is a dead donation: the caller lost the buffer and XLA reused "
        "nothing — the class the tpu.donate.model.buffers reservation guards"
    )


@register
class CarryStabilityRule(TraceRule):
    id = "trace-carry-stability"
    rationale = (
        "while/scan carries must be shape/dtype/pytree-stable with no "
        "weak_type or float64 avals, or the ROADMAP-1 fused round loop "
        "forks compiled programs out of the PR-3 shape-bucket ladder"
    )


@register
class ConstantBloatRule(TraceRule):
    id = "trace-constant-bloat"
    rationale = (
        "a closure-captured array baked into program constants ships with "
        "every compiled program in the bucket ladder and silently pins "
        "device memory; big operands must arrive as arguments"
    )


@register
class ShardingLoweringRule(TraceRule):
    id = "trace-sharding-lowering"
    rationale = (
        "sharded entry points must lower and compile under the virtual "
        "8-device partition mesh without ops that force the sharded axis "
        "to replicate (psum is the intended collective, PAPER.md) — the "
        "per-commit gate on the shard_map round kernels (docs/SHARDING.md)"
    )


@register
class EntryErrorRule(TraceRule):
    id = "trace-entry-error"
    rationale = (
        "an entry-point registry that fails to import, build, or trace is "
        "a kernel surface no trace rule certifies — equivalent to "
        "lint-parse-error one tier up"
    )
