"""cclint core: rule registry, suppression handling, runner, output.

The invariants this package enforces grew one PR at a time — padding
invariance and shape-bucketed program reuse (docs/OPTIMIZER.md), the
never-raise executor contract and its lock discipline (docs/RESILIENCE.md),
and the config/sensor/span inventories (docs/OBSERVABILITY.md). Until now
they lived in prose and two narrow AST tests; cclint turns them into a
compiler-shaped gate: every rule is an AST (or cross-file inventory) check
with a stable id, per-rule fixtures under tests/lint_fixtures/, and a
suppression syntax that *requires* a written justification:

    something_hairy()  # cclint: disable=rule-id -- why this one is safe

A suppression with no `-- reason` is itself a finding
(`lint-malformed-suppression`); a suppression that stops matching anything
is too (`lint-unused-suppression`, judged per selected rule, so partial
runs — `--rule`, `--tier`, `--changed-only` — still retire stale debt for
the rules they ran), so the escape hatch cannot silently rot.

Rules come in two TIERS. The `token` tier is pure `ast` + text — no JAX
import, no compilation — and stays tier-1 cheap on every run. The `trace`
tier (rules_trace.py) abstractly evaluates the REAL jitted entry points
declared in lint/entrypoints.py and walks their jaxprs; it pays one
JAX-tracing subprocess per linted file set, memoized on disk keyed by
source content hash, so repeat runs stay inside the same <10 s budget
(see tests/test_static_guards.py).

Entry points: `scripts/cclint.py` (CLI, JSON or human output, stable exit
codes) and `run_rules()` (the tier-1 test drives it directly). Rule catalog
and policy: docs/LINTING.md.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import io
import json
import pathlib
import re
import time
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

#: exit codes of the CLI (stable; CI scripts may match on them)
EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_ERROR = 2

_SUPPRESS_RE = re.compile(
    r"#\s*cclint:\s*disable=([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(\S.*))?$"
)

#: modules holding jitted kernels: the TPU-hygiene family applies here.
#: Matched on repo-relative posix paths; a module can also opt in with a
#: `# cclint: kernel-module` marker in its first lines (fixtures do).
KERNEL_PATH_PATTERNS: Tuple[str, ...] = (
    "*/analyzer/goals/*.py",
    "*/analyzer/bulk.py",
    "*/models/flat_model.py",
)
KERNEL_MARKER_RE = re.compile(r"^#\s*cclint:\s*kernel-module\s*$")


@dataclasses.dataclass
class Suppression:
    """One `# cclint: disable=...` comment, keyed to the line it covers."""

    comment_line: int
    target_line: int
    rules: Tuple[str, ...]
    reason: str
    malformed: bool
    used: set = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str
    suppressed: bool = False
    suppress_reason: Optional[str] = None

    def to_dict(self) -> Dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "suppressed": self.suppressed,
            "suppressReason": self.suppress_reason,
        }


class SourceFile:
    """One parsed python file: AST, raw lines, suppressions, kernel flag."""

    def __init__(self, path: pathlib.Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.parse_error: Optional[str] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(text, filename=rel)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = f"{e.msg} (line {e.lineno})"
        #: real comment tokens only (tokenize): a docstring showing the
        #: suppression syntax as an example must not register one
        self.comments: Dict[int, str] = self._comment_map()
        self.suppressions: Dict[int, Suppression] = {}
        self._parse_suppressions()
        self.is_kernel = any(
            KERNEL_MARKER_RE.match(line.strip()) for line in self.lines[:5]
        ) or any(fnmatch.fnmatch("/" + rel, pat) for pat in KERNEL_PATH_PATTERNS)

    def _comment_map(self) -> Dict[int, str]:
        out: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # unparseable files already carry a lint-parse-error finding
        return out

    def _parse_suppressions(self) -> None:
        for i, comment in sorted(self.comments.items()):
            m = _SUPPRESS_RE.search(comment)
            if m is None:
                continue
            line = self.lines[i - 1]
            rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
            reason = (m.group(2) or "").strip()
            # a standalone comment covers the NEXT line; a trailing comment
            # covers its own line
            standalone = line.strip().startswith("#")
            target = i + 1 if standalone else i
            self.suppressions[target] = Suppression(
                comment_line=i,
                target_line=target,
                rules=rules,
                reason=reason,
                malformed=not rules or not reason,
            )


class LintContext:
    """Everything the rules see: parsed sources, doc texts, a shared cache.

    Registry-family rules reconcile cross-file inventories (config keys,
    sensor names, span kinds) and memoize their extractions in `cache`.
    """

    def __init__(self, root: pathlib.Path, files: List[SourceFile],
                 docs: Dict[str, str]):
        self.root = root
        self.files = files
        self.docs = docs
        self.cache: Dict[str, object] = {}

    @property
    def kernel_files(self) -> List[SourceFile]:
        return [f for f in self.files if f.is_kernel and f.tree is not None]

    @property
    def parsed_files(self) -> List[SourceFile]:
        return [f for f in self.files if f.tree is not None]

    def files_named(self, basename: str) -> List[SourceFile]:
        return [f for f in self.files if pathlib.PurePosixPath(f.rel).name == basename]

    def doc_corpus(self) -> str:
        return "\n".join(self.docs.values())


_EXCLUDED_DIR_PARTS = {"__pycache__", "lint_fixtures", ".git"}


def _collect(root: pathlib.Path, paths: Iterable[pathlib.Path], suffix: str) -> List[pathlib.Path]:
    out = []
    for p in paths:
        if p.is_dir():
            # exclusion is relative to the scanned base, so linting a
            # fixture directory itself (tests do) still sees its files
            out.extend(
                q for q in sorted(p.rglob(f"*{suffix}"))
                if not (_EXCLUDED_DIR_PARTS & set(q.relative_to(p).parts))
            )
        elif p.suffix == suffix:
            out.append(p)
    return out


def build_context(
    root: pathlib.Path,
    py_paths: Optional[Sequence[pathlib.Path]] = None,
    doc_paths: Optional[Sequence[pathlib.Path]] = None,
) -> LintContext:
    """Build a context for `root` (the repo checkout or a fixture dir).

    Defaults: lint the `cruise_control_tpu` package (or, absent one — the
    fixture case — every .py under root) against README.md + docs/*.md (or
    every .md under root).
    """
    root = pathlib.Path(root).resolve()
    if py_paths is None:
        pkg = root / "cruise_control_tpu"
        py_paths = [pkg] if pkg.is_dir() else [root]
    if doc_paths is None:
        doc_paths = [p for p in (root / "README.md", root / "docs") if p.exists()]
        if not doc_paths:
            doc_paths = [root]
    files = []
    for p in _collect(root, py_paths, ".py"):
        rel = p.resolve().relative_to(root).as_posix() if p.resolve().is_relative_to(root) else p.name
        files.append(SourceFile(p, rel, p.read_text()))
    docs = {}
    for p in _collect(root, doc_paths, ".md"):
        rel = p.resolve().relative_to(root).as_posix() if p.resolve().is_relative_to(root) else p.name
        docs[rel] = p.read_text()
    return LintContext(root, files, docs)


# -- rule registry -------------------------------------------------------------


#: the two analysis tiers (CLI `--tier`): `token` rules read source text
#: and ASTs; `trace` rules abstractly evaluate the registered jitted entry
#: points and walk the resulting jaxprs (rules_trace.py)
TIERS = ("token", "trace")


class Rule:
    """Base class: subclass, set the class attributes, implement check()."""

    id: str = ""
    family: str = ""  # "tpu" | "concurrency" | "registry" | "trace" | "lint"
    tier: str = "token"  # "token" (ast/text) | "trace" (jaxpr-level)
    rationale: str = ""

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, src: SourceFile, line: int, message: str) -> Finding:
        return Finding(rule=self.id, path=src.rel, line=line, message=message)


RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the global registry."""
    inst = cls()
    if not inst.id or not inst.family or not inst.rationale:
        raise ValueError(f"rule {cls.__name__} must declare id/family/rationale")
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    RULES[inst.id] = inst
    return cls


def all_rules() -> List[Rule]:
    # importing the rule modules populates RULES exactly once
    from cruise_control_tpu.lint import (  # noqa: F401
        rules_concurrency,
        rules_registry,
        rules_tpu,
        rules_trace,
    )

    return sorted(RULES.values(), key=lambda r: (r.family, r.id))


def tier_rules(tier: str) -> List[Rule]:
    """The rule subset for a CLI `--tier` selection (`token`/`trace`/`all`)."""
    rules = all_rules()
    if tier == "all":
        return rules
    if tier not in TIERS:
        raise ValueError(f"unknown tier {tier!r}; expected one of {TIERS + ('all',)}")
    return [r for r in rules if r.tier == tier]


# -- meta rules (emitted by the runner, registered so they are cataloged) ------


@register
class ParseErrorRule(Rule):
    id = "lint-parse-error"
    family = "lint"
    rationale = "a file the linter cannot parse is a file no rule protects"

    def check(self, ctx):  # runner-emitted
        return iter(())


@register
class MalformedSuppressionRule(Rule):
    id = "lint-malformed-suppression"
    family = "lint"
    rationale = "every suppression must name its rules AND carry a `-- reason`"

    def check(self, ctx):  # runner-emitted
        return iter(())


@register
class UnusedSuppressionRule(Rule):
    id = "lint-unused-suppression"
    family = "lint"
    rationale = "a suppression that no longer matches a finding is stale debt"

    def check(self, ctx):  # runner-emitted
        return iter(())


_META_RULES = {"lint-parse-error", "lint-malformed-suppression", "lint-unused-suppression"}


# -- runner --------------------------------------------------------------------


def run_rules(
    ctx: LintContext,
    rules: Optional[Sequence[Rule]] = None,
    check_unused: Optional[bool] = None,
    timings: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """Run `rules` (default: all registered) over the context.

    Suppression semantics: a finding on line N is suppressed by a
    well-formed `# cclint: disable=<rule>[,<rule>...] -- reason` comment on
    line N, or standalone on line N-1. Staleness is judged PER SELECTED
    RULE: a suppression naming a rule that ran and matched nothing is flagged
    even on partial (`--rule`/`--tier`/`--changed-only`) runs — only rules
    that did not run are off the table (a partial run cannot judge them).
    A suppression naming a rule id that does not exist at all is always
    stale. `check_unused=False` disables the staleness pass entirely.

    `timings`, when given, is filled with per-rule wall seconds (the
    `--json` schema's wallMs; a trace rule's first check carries the shared
    jaxpr-evaluation payload for its tier, cache permitting).
    """
    selected = list(rules) if rules is not None else all_rules()
    if check_unused is None:
        check_unused = True
    findings: List[Finding] = []
    for src in ctx.files:
        if src.parse_error is not None:
            findings.append(Finding(
                rule="lint-parse-error", path=src.rel, line=1,
                message=f"cannot parse: {src.parse_error}",
            ))
        for sup in src.suppressions.values():
            if sup.malformed:
                findings.append(Finding(
                    rule="lint-malformed-suppression", path=src.rel,
                    line=sup.comment_line,
                    message="suppression must be `# cclint: disable=<rule-id>"
                            " -- <justification>` (reason is mandatory)",
                ))
    for rule in selected:
        t0 = time.monotonic()
        findings.extend(rule.check(ctx))
        if timings is not None:
            timings[rule.id] = timings.get(rule.id, 0.0) + (time.monotonic() - t0)
    by_rel = {src.rel: src for src in ctx.files}
    for f in findings:
        src = by_rel.get(f.path)
        if src is None or f.rule in _META_RULES:
            continue
        sup = src.suppressions.get(f.line)
        if sup is not None and not sup.malformed and f.rule in sup.rules:
            f.suppressed = True
            f.suppress_reason = sup.reason
            sup.used.add(f.rule)
    if check_unused:
        # per-rule-scoped staleness: only rules that actually ran (or ids
        # that exist in no registry — typos) are judged, so a `--tier token`
        # or `--rule X` run cannot false-flag a live trace-rule suppression
        selected_ids = {r.id for r in selected}
        known_ids = {r.id for r in all_rules()}
        for src in ctx.files:
            for sup in src.suppressions.values():
                if sup.malformed:
                    continue
                stale = [
                    r for r in sup.rules
                    if (r in selected_ids or r not in known_ids)
                    and r not in sup.used
                ]
                for r in stale:
                    findings.append(Finding(
                        rule="lint-unused-suppression", path=src.rel,
                        line=sup.comment_line,
                        message=f"suppression for `{r}` matches no finding —"
                                " delete it or fix the rule id",
                    ))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def unsuppressed(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if not f.suppressed]


# -- output --------------------------------------------------------------------


def render_human(findings: Sequence[Finding], num_files: int,
                 num_rules: int, show_suppressed: bool = False) -> str:
    lines = []
    for f in findings:
        if f.suppressed and not show_suppressed:
            continue
        mark = " (suppressed: %s)" % f.suppress_reason if f.suppressed else ""
        lines.append(f"{f.path}:{f.line}: {f.rule}  {f.message}{mark}")
    open_count = len(unsuppressed(findings))
    sup_count = len(findings) - open_count
    lines.append(
        f"{open_count} finding(s), {sup_count} suppressed — "
        f"{num_rules} rule(s) over {num_files} file(s)"
    )
    return "\n".join(lines)


def render_json(findings: Sequence[Finding], num_files: int,
                rules: Sequence[Rule],
                timings: Optional[Dict[str, float]] = None,
                trace_stats: Optional[Dict] = None) -> str:
    """Schema v2: every rule row carries its family, tier, and wall-time
    (CI archives this artifact next to the tier-1 log — scripts/ci.sh)."""
    by_rule: Dict[str, int] = {}
    for f in findings:
        if not f.suppressed:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    timings = timings or {}
    doc = {
        "version": 2,
        "rules": [
            {
                "id": r.id,
                "family": r.family,
                "tier": r.tier,
                "wallMs": round(timings.get(r.id, 0.0) * 1000.0, 3),
            }
            for r in rules
        ],
        "numFiles": num_files,
        "findings": [f.to_dict() for f in findings],
        "summary": {
            "total": len(findings),
            "unsuppressed": len(unsuppressed(findings)),
            "suppressed": len(findings) - len(unsuppressed(findings)),
            "byRule": dict(sorted(by_rule.items())),
        },
    }
    if trace_stats is not None:
        doc["trace"] = trace_stats
    return json.dumps(doc, indent=2)


# -- shared AST helpers --------------------------------------------------------


def node_names(node: ast.AST) -> set:
    """Every identifier mentioned in an expression (Name ids + Attribute attrs)."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def literal_or_fstring_pattern(node: ast.AST) -> Optional[str]:
    """A string literal as itself; an f-string as an fnmatch pattern with
    `*` standing in for each interpolation; anything else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    return None


def patterns_intersect(a: str, b: str) -> bool:
    """Loose intersection test for two fnmatch-style patterns: does either,
    read as a plain string, satisfy the other read as a pattern? Exact for
    literal-vs-pattern; conservative (may over-match) for pattern-vs-pattern,
    which is the right failure mode for an inventory check."""
    return fnmatch.fnmatchcase(a, b) or fnmatch.fnmatchcase(b, a)
