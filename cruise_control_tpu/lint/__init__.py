"""cclint: repo-native static analysis for the TPU, concurrency, registry,
and jaxpr-level invariants the codebase rests on (docs/LINTING.md).

Two tiers. The `token` tier is pure-AST/text analysis (no JAX import):
`tpu` guards the shape-bucketed kernel contract, `concurrency` generalizes
the never-raise/lock-discipline contracts package-wide, and `registry`
reconciles config keys, sensor names, and span kinds against their
declarations and documentation. The `trace` tier abstractly evaluates the
REAL jitted entry points registered in lint/entrypoints.py and walks their
jaxprs for the contracts token rules cannot see — host callbacks under
jit, dead donations, bucket-unstable loop carries, baked constants, and
sharding readiness under the 8-device mesh — with results content-hash
cached so repeat runs stay tier-1 cheap. CLI: `scripts/cclint.py`.
"""

from cruise_control_tpu.lint.core import (  # noqa: F401
    EXIT_CLEAN,
    EXIT_ERROR,
    EXIT_FINDINGS,
    Finding,
    LintContext,
    Rule,
    RULES,
    TIERS,
    all_rules,
    build_context,
    render_human,
    render_json,
    run_rules,
    tier_rules,
    unsuppressed,
)
