"""cclint: repo-native static analysis for the TPU, concurrency, and
registry invariants the codebase rests on (docs/LINTING.md).

Three rule families over pure-AST/text analysis (no JAX import, tier-1
cheap): `tpu` guards the shape-bucketed kernel contract, `concurrency`
generalizes the never-raise/lock-discipline contracts package-wide, and
`registry` reconciles config keys, sensor names, and span kinds against
their declarations and documentation. CLI: `scripts/cclint.py`.
"""

from cruise_control_tpu.lint.core import (  # noqa: F401
    EXIT_CLEAN,
    EXIT_ERROR,
    EXIT_FINDINGS,
    Finding,
    LintContext,
    Rule,
    RULES,
    all_rules,
    build_context,
    render_human,
    render_json,
    run_rules,
    unsuppressed,
)
