"""JAX/TPU hygiene rules (family `tpu`).

These guard the shape-bucketed program-reuse contract (docs/OPTIMIZER.md):
one compiled XLA program serves every cluster in a bucket, which only holds
while kernels (a) never sync device buffers back to the host mid-pipeline,
(b) never branch or loop on concrete axis sizes (each distinct size would
retrace and recompile), (c) never read a buffer after donating it, and
(d) never denominate a mean by a padded axis length where a valid-count
mask exists — the exact bug class PR 3 fixed by hand five times.

Scope: "kernel modules" — analyzer/goals/, analyzer/bulk.py,
models/flat_model.py by path, plus any module carrying a
`# cclint: kernel-module` marker (core.KERNEL_PATH_PATTERNS). The
donated-reuse rule runs package-wide: `donate_argnums` call sites live in
the optimizer, not the kernel modules themselves.
"""

from __future__ import annotations

import ast
from typing import Iterator

from cruise_control_tpu.lint.core import (
    Finding,
    LintContext,
    Rule,
    node_names,
    register,
)

#: identifiers that name a partition/broker/topic axis extent; looping or
#: dividing by one of these inside a kernel is a padding/recompile hazard
AXIS_NAMES = {
    "num_partitions", "num_brokers", "num_topics", "num_racks", "num_hosts",
    "p_count", "b_count", "t_count", "max_rf",
}


@register
class HostSyncRule(Rule):
    id = "tpu-host-sync"
    family = "tpu"
    rationale = (
        "`.item()`, `float()/int()` on arrays, `np.asarray`, and "
        "`jax.device_get` block on the device and break async dispatch; "
        "inside kernel modules they turn a fused pipeline into ping-pong"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for src in ctx.kernel_files:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if isinstance(fn, ast.Attribute):
                    if fn.attr == "item" and not node.args and not node.keywords:
                        yield self.finding(
                            src, node.lineno,
                            "`.item()` forces a device->host sync; keep the "
                            "value on-device or move this off the kernel path",
                        )
                    elif (
                        fn.attr == "asarray"
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id in ("np", "numpy")
                    ):
                        yield self.finding(
                            src, node.lineno,
                            "`np.asarray` on a device array copies to host; "
                            "use `jnp.asarray` or hoist to the host-side shell",
                        )
                    elif (
                        fn.attr in ("device_get", "block_until_ready")
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id == "jax"
                    ):
                        yield self.finding(
                            src, node.lineno,
                            f"`jax.{fn.attr}` synchronizes with the device; "
                            "kernel modules must stay async",
                        )
                elif (
                    isinstance(fn, ast.Name)
                    and fn.id in ("float", "int")
                    and node.args
                    and not isinstance(node.args[0], (ast.Name, ast.Constant))
                ):
                    yield self.finding(
                        src, node.lineno,
                        f"`{fn.id}(...)` of a computed value syncs if it is a "
                        "device array; use jnp casts on-device or hoist",
                    )


@register
class PythonLoopRule(Rule):
    id = "tpu-python-loop"
    family = "tpu"
    rationale = (
        "a Python `for` over a partition/broker axis unrolls into the traced "
        "program (compile blow-up) or runs one dispatch per element; use "
        "vmap/scan/segment_sum"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for src in ctx.kernel_files:
            for node in ast.walk(src.tree):
                iters = []
                if isinstance(node, ast.For):
                    iters.append(node.iter)
                elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                       ast.GeneratorExp)):
                    iters.extend(g.iter for g in node.generators)
                for it in iters:
                    names = node_names(it)
                    if AXIS_NAMES & names or "shape" in names:
                        yield self.finding(
                            src, node.lineno,
                            "Python loop over a model axis "
                            f"({', '.join(sorted((AXIS_NAMES & names) | ({'shape'} if 'shape' in names else set())))}); "
                            "vectorize with vmap/scan or move off the kernel path",
                        )
                        break


@register
class ShapeBranchRule(Rule):
    id = "tpu-shape-branch"
    family = "tpu"
    rationale = (
        "branching on a concrete `.shape` retraces per shape and defeats "
        "shape-bucketed program reuse; branch on static dims passed via "
        "static argnums, or use jnp.where"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for src in ctx.kernel_files:
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.If, ast.IfExp, ast.While)):
                    if "shape" in node_names(node.test):
                        yield self.finding(
                            src, node.lineno,
                            "branch tests a concrete array shape — a "
                            "recompile per distinct shape; thread the dim "
                            "through Dims/static argnums instead",
                        )


def _donated_positions(call: ast.Call):
    """The donate_argnums of a `jax.jit`/`jit` call, or None."""
    fn = call.func
    is_jit = (isinstance(fn, ast.Name) and fn.id == "jit") or (
        isinstance(fn, ast.Attribute) and fn.attr == "jit"
    )
    if not is_jit:
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        out.append(e.value)
                return tuple(out)
            return ()  # dynamic spec: can't track positions
    return None


@register
class DonatedReuseRule(Rule):
    id = "tpu-donated-reuse"
    family = "tpu"
    rationale = (
        "an argument donated via donate_argnums is dead after the call — "
        "XLA may alias its buffer for the output; reading it afterwards is "
        "use-after-free that only fails on real hardware"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for src in ctx.parsed_files:
            for scope in ast.walk(src.tree):
                if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
                    yield from self._check_scope(src, scope)

    def _check_scope(self, src, scope) -> Iterator[Finding]:
        # pass 1: names bound to donating jitted callables in this scope
        donors = {}
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                pos = _donated_positions(node.value)
                if pos:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            donors[t.id] = pos
        if not donors and not any(
            _donated_positions(n) for n in ast.walk(scope) if isinstance(n, ast.Call)
        ):
            return
        # pass 2: calls of donors -> donated Name args; later loads flag.
        # Lexical (lineno) ordering — a deliberate heuristic: kernels are
        # straight-line dispatch code, and a false negative in a loop is
        # still caught by the fixture-tested common case.
        donated_at = {}  # name -> call lineno
        # same-line ordering mirrors runtime: arg loads happen before the
        # call donates, and the assignment stores after it — so
        # `model = step(model, n)` cleanly rebinds, not use-after-donate
        prio = {"load": 0, "donate": 1, "store": 2}
        events = []  # (lineno, prio, kind, name)
        for node in ast.walk(scope):
            if isinstance(node, ast.Call):
                pos = None
                if isinstance(node.func, ast.Name) and node.func.id in donors:
                    pos = donors[node.func.id]
                elif isinstance(node.func, ast.Call):
                    pos = _donated_positions(node.func)  # jit(f, donate...)(x)
                if pos:
                    for i in pos:
                        if i < len(node.args) and isinstance(node.args[i], ast.Name):
                            events.append(
                                (node.lineno, prio["donate"], "donate", node.args[i].id)
                            )
            elif isinstance(node, ast.Name):
                kind = "store" if isinstance(node.ctx, (ast.Store, ast.Del)) else "load"
                events.append((node.lineno, prio[kind], kind, node.id))
        events.sort(key=lambda e: (e[0], e[1]))
        for lineno, _, kind, name in events:
            if kind == "donate":
                donated_at[name] = lineno
            elif kind == "store":
                donated_at.pop(name, None)
            elif name in donated_at and lineno > donated_at[name]:
                yield self.finding(
                    src, lineno,
                    f"`{name}` was donated to a jitted call on line "
                    f"{donated_at[name]} and read afterwards — its buffer "
                    "may already be aliased; rebind the result instead",
                )
                donated_at.pop(name, None)  # one report per donation


@register
class PaddingDenominatorRule(Rule):
    id = "tpu-padding-denominator"
    family = "tpu"
    rationale = (
        "dividing by a raw axis extent (num_partitions/num_brokers) makes "
        "means drift with the shape bucket's padding; denominate by the "
        "valid-count masks (StaticCtx.num_valid_partitions, broker_valid "
        "sums) so bucketed runs stay result-identical"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for src in ctx.kernel_files:
            for scope in ast.walk(src.tree):
                if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._check_fn(src, scope)

    def _check_fn(self, src, fn) -> Iterator[Finding]:
        aliases = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Attribute):
                if node.value.attr in AXIS_NAMES:
                    aliases.update(
                        t.id for t in node.targets if isinstance(t, ast.Name)
                    )
            # tuple unpack: p_count, r = dims.num_partitions, dims.max_rf
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Tuple):
                for t in node.targets:
                    if isinstance(t, ast.Tuple) and len(t.elts) == len(node.value.elts):
                        for tgt, val in zip(t.elts, node.value.elts):
                            if (
                                isinstance(tgt, ast.Name)
                                and isinstance(val, ast.Attribute)
                                and val.attr in AXIS_NAMES
                            ):
                                aliases.add(tgt.id)
        for node in ast.walk(fn):
            if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Div, ast.FloorDiv)):
                d = node.right
                hit = None
                if isinstance(d, ast.Attribute) and d.attr in AXIS_NAMES:
                    hit = d.attr
                elif isinstance(d, ast.Name) and (d.id in AXIS_NAMES or d.id in aliases):
                    hit = d.id
                if hit is not None:
                    yield self.finding(
                        src, node.lineno,
                        f"division by raw axis extent `{hit}` — under shape "
                        "bucketing this denominator includes padding; use the "
                        "num_valid_* masks (see soft.py LeaderBytesIn.bulk_counts)",
                    )
