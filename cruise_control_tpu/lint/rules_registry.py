"""Registry-consistency rules (family `reg`).

Five PRs of config keys (`optimizer.*`, `executor.*`, `observability.*`,
`selfhealing.*`), sensor names, and span kinds are wired by hand across
code, `config/cruise_config.py`, `main --config`, `/metrics`, and the docs.
These rules reconcile the inventories so drift between them fails tier-1
instead of surfacing as a dead knob or an undocumented metric:

  * every config key READ (`config.get_int("...")` etc.) must be DECLARED
    in cruise_config.py and DOCUMENTED in README/docs;
  * every TPU-native key DECLARED must be READ somewhere (reachable via
    `main --config` plumbing) — reference-parity keys are exempt, they are
    accepted-but-unused by design;
  * every sensor name emitted through the process REGISTRY must appear in
    the docs/OBSERVABILITY.md inventory, and one name may not be reused
    across sensor types (REGISTRY.snapshot() merges by name — a meter and
    a gauge sharing a name silently shadow each other);
  * every span kind passed to the TRACER must be a documented kind;
  * every REST endpoint the servlet registers must have a row in
    docs/ENDPOINTS.md (an undocumented endpoint is API surface operators
    cannot discover).

F-string names (`f"Retry.{name}.retries"`) become fnmatch patterns
(`Retry.*.retries`) and match the docs' placeholder spellings
(`Retry.<name>.retries`, `...bucket.P…-B…-T…-RF…`).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set, Tuple

from cruise_control_tpu.lint.core import (
    Finding,
    LintContext,
    Rule,
    literal_or_fstring_pattern,
    patterns_intersect,
    register,
)

#: config accessor methods whose literal first argument is a key read
_READ_METHODS = {
    "get_boolean", "get_int", "get_long", "get_double", "get_string",
    "get_list", "get_password", "get_configured_instance",
    "get_configured_instances",
}

#: TPU-native key namespaces: declared keys here must be reachable (read);
#: reference-parity Kafka keys are allowed to be accepted-but-unused
_NATIVE_NAMESPACES = ("optimizer.", "executor.", "observability.",
                      "selfhealing.", "tpu.")

#: the file declaring the config universe and the doc carrying the
#: sensor/span inventory (matched by basename so fixtures can ship stubs)
_CONFIG_BASENAME = "cruise_config.py"
_SENSOR_DOC_BASENAME = "OBSERVABILITY.md"


def _config_reads(ctx: LintContext):
    """[(src, lineno, pattern)] for every literal/f-string config key read."""
    if "config_reads" in ctx.cache:
        return ctx.cache["config_reads"]
    out = []
    for src in ctx.parsed_files:
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr in _READ_METHODS):
                continue
            pattern = literal_or_fstring_pattern(node.args[0])
            # config keys are dotted; a dotless literal is some other API
            if pattern is None or "." not in pattern:
                continue
            out.append((src, node.lineno, pattern))
    ctx.cache["config_reads"] = out
    return out


def _declared_keys(ctx: LintContext):
    """[(src, lineno, pattern)] for every `*.define("key", ...)` declaration."""
    if "declared_keys" in ctx.cache:
        return ctx.cache["declared_keys"]
    out = []
    for src in ctx.files_named(_CONFIG_BASENAME):
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr == "define"):
                continue
            pattern = literal_or_fstring_pattern(node.args[0])
            if pattern is not None:
                out.append((src, node.lineno, pattern))
    ctx.cache["declared_keys"] = out
    return out


@register
class ConfigKeyDeclaredRule(Rule):
    id = "reg-config-key-declared"
    family = "registry"
    rationale = (
        "a key read anywhere must be declared in config/cruise_config.py — "
        "an undeclared read raises at runtime only on the config path that "
        "exercises it"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        declared = [p for _, _, p in _declared_keys(ctx)]
        if not declared:
            return  # no config universe in this context: nothing to judge
        for src, lineno, pattern in _config_reads(ctx):
            if not any(patterns_intersect(pattern, d) for d in declared):
                yield self.finding(
                    src, lineno,
                    f"config key `{pattern}` is read but never declared in "
                    f"{_CONFIG_BASENAME} (ConfigDef.define)",
                )


@register
class ConfigKeyDocumentedRule(Rule):
    id = "reg-config-key-documented"
    family = "registry"
    rationale = (
        "a key an operator can set must be documented — every key read by "
        "the code has to appear in README.md or docs/*.md"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        if not ctx.docs:
            return
        corpus = ctx.doc_corpus()
        for src, lineno, pattern in _config_reads(ctx):
            # for f-string reads, require the longest literal fragment
            fragments = [f for f in pattern.split("*") if len(f) >= 4]
            if not fragments:
                continue
            probe = max(fragments, key=len)
            if probe not in corpus:
                yield self.finding(
                    src, lineno,
                    f"config key `{pattern}` is read but appears nowhere in "
                    "README.md/docs — add a row to the relevant key table",
                )


@register
class ConfigKeyReachableRule(Rule):
    id = "reg-config-key-reachable"
    family = "registry"
    rationale = (
        "a TPU-native key declared but never read is a dead knob: operators "
        "set it via `main --config` and nothing changes; wire it through a "
        "from_config path or drop it"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        reads = [p for _, _, p in _config_reads(ctx)]
        for src, lineno, pattern in _declared_keys(ctx):
            if not pattern.startswith(_NATIVE_NAMESPACES):
                continue
            if not any(patterns_intersect(pattern, r) for r in reads):
                yield self.finding(
                    src, lineno,
                    f"TPU-native key `{pattern}` is declared but never read "
                    "via a config accessor — unreachable from `main --config`",
                )


# -- sensors and spans ---------------------------------------------------------

_BACKTICK_RE = re.compile(r"`([^`]+)`")
#: docs placeholder spellings that mean "anything here"
_PLACEHOLDER_RE = re.compile(r"<[^<>`]*>|…|\{[^{}`]*\}")
_SENSOR_METHODS = {"meter", "timer", "histogram", "gauge"}


def _doc_name_patterns(ctx: LintContext) -> List[str]:
    """All backtick code spans in the sensor doc (fixtures: every doc), as
    fnmatch patterns. Compound rows like `` `X.cache-hits` / `-misses` `` or
    `` `CircuitBreaker.<name>.open` / `.half_open` `` contribute the joined
    spellings too (previous span's prefix + the continuation)."""
    if "doc_name_patterns" in ctx.cache:
        return ctx.cache["doc_name_patterns"]
    texts = [
        t for rel, t in ctx.docs.items()
        if rel.endswith(_SENSOR_DOC_BASENAME)
    ] or list(ctx.docs.values())
    spans: List[str] = []
    for text in texts:
        spans.extend(_BACKTICK_RE.findall(text))
    names: List[str] = []
    prev = None
    for span in spans:
        span = span.strip()
        if span.startswith(("-", ".")) and prev:
            sep = span[0]
            cut = prev.rfind(sep)
            if cut > 0:
                names.append(prev[:cut] + span)
            names.append(prev + span)
        else:
            names.append(span)
            prev = span
    patterns = []
    for n in names:
        p = _PLACEHOLDER_RE.sub("*", n)
        # a placeholder-only span (`…`) would become `*` and match the
        # world; require some literal substance
        if re.search(r"[A-Za-z0-9_]{2,}", p):
            patterns.append(p)
    ctx.cache["doc_name_patterns"] = patterns
    return patterns


def _sensor_emits(ctx: LintContext):
    """[(src, lineno, method, pattern)] for REGISTRY.<method>("name", ...)."""
    if "sensor_emits" in ctx.cache:
        return ctx.cache["sensor_emits"]
    out = []
    for src in ctx.parsed_files:
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in _SENSOR_METHODS
                and isinstance(fn.value, ast.Name)
                and "REGISTRY" in fn.value.id
            ):
                continue
            pattern = literal_or_fstring_pattern(node.args[0])
            if pattern is not None:
                out.append((src, node.lineno, fn.attr, pattern))
    ctx.cache["sensor_emits"] = out
    return out


@register
class SensorDocumentedRule(Rule):
    id = "reg-sensor-documented"
    family = "registry"
    rationale = (
        "every sensor on /metrics must have a row in the "
        "docs/OBSERVABILITY.md inventory — an undocumented sensor is "
        "invisible drift between code and the operator's dashboard"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        if not ctx.docs:
            return
        doc_patterns = _doc_name_patterns(ctx)
        for src, lineno, method, pattern in _sensor_emits(ctx):
            if not any(patterns_intersect(pattern, d) for d in doc_patterns):
                yield self.finding(
                    src, lineno,
                    f"sensor `{pattern}` ({method}) is emitted but absent "
                    f"from the {_SENSOR_DOC_BASENAME} sensor table",
                )


@register
class SensorCollisionRule(Rule):
    id = "reg-sensor-collision"
    family = "registry"
    rationale = (
        "REGISTRY.snapshot() merges all sensor types into one dict by name; "
        "the same name emitted as two different types silently shadows one "
        "of them on /state and /metrics"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        by_name: Dict[str, Set[str]] = {}
        sites: Dict[str, List[Tuple]] = {}
        for src, lineno, method, pattern in _sensor_emits(ctx):
            if "*" in pattern:
                continue  # patterns can collide spuriously
            by_name.setdefault(pattern, set()).add(method)
            sites.setdefault(pattern, []).append((src, lineno, method))
        for name, methods in sorted(by_name.items()):
            if len(methods) < 2:
                continue
            for src, lineno, method in sites[name]:
                yield self.finding(
                    src, lineno,
                    f"sensor name `{name}` is registered as {method} here "
                    f"but also as {', '.join(sorted(methods - {method}))} "
                    "elsewhere — one will shadow the other in snapshots",
                )


#: the servlet wiring file and the doc carrying the endpoint inventory
_SERVER_BASENAME = "server.py"
_ENDPOINT_DOC_BASENAME = "ENDPOINTS.md"


def _endpoint_registrations(ctx: LintContext):
    """[(src, lineno, endpoint)] for every endpoint the servlet wires up:
    `("name", self.handler)` tuples in the build_app endpoint lists, plus
    literal route paths on `router.add_get/add_post` (the root scrape
    aliases). Dynamic path segments (`{tail:...}`) and "/" are skipped."""
    if "endpoint_registrations" in ctx.cache:
        return ctx.cache["endpoint_registrations"]
    out = []
    for src in ctx.files_named(_SERVER_BASENAME):
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Tuple) and len(node.elts) == 2:
                first, second = node.elts
                if (
                    isinstance(first, ast.Constant)
                    and isinstance(first.value, str)
                    and first.value.isidentifier()
                    and isinstance(second, ast.Attribute)
                    and isinstance(second.value, ast.Name)
                    and second.value.id == "self"
                ):
                    out.append((src, node.lineno, first.value))
            elif isinstance(node, ast.Call):
                fn = node.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in ("add_get", "add_post")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    seg = node.args[0].value.rstrip("/").rsplit("/", 1)[-1]
                    if seg and "{" not in seg:
                        out.append((src, node.lineno, seg))
    ctx.cache["endpoint_registrations"] = out
    return out


@register
class EndpointDocumentedRule(Rule):
    id = "reg-endpoint-documented"
    family = "registry"
    rationale = (
        "every REST endpoint the servlet serves must have a row in "
        "docs/ENDPOINTS.md — an undocumented endpoint is API surface "
        "operators cannot discover and clients cannot validate against"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        if not ctx.docs:
            return
        texts = [
            t for rel, t in ctx.docs.items()
            if rel.endswith(_ENDPOINT_DOC_BASENAME)
        ] or list(ctx.docs.values())
        corpus = "\n".join(texts)
        seen: Set[Tuple[str, str]] = set()
        for src, lineno, name in _endpoint_registrations(ctx):
            if (src.rel, name) in seen:  # root aliases duplicate the row
                continue
            seen.add((src.rel, name))
            if f"`{name}`" not in corpus:
                yield self.finding(
                    src, lineno,
                    f"endpoint `{name}` is registered but has no row in "
                    f"{_ENDPOINT_DOC_BASENAME} — document its parameters "
                    "and response shape",
                )


@register
class SpanKindRule(Rule):
    id = "reg-span-kind"
    family = "registry"
    rationale = (
        "span kinds are the /trace grouping axis and the per-kind latency "
        "table's key; an undocumented kind means dashboards and "
        "docs/OBSERVABILITY.md disagree about the pipeline's stages"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        if not ctx.docs:
            return
        doc_patterns = _doc_name_patterns(ctx)
        for src in ctx.parsed_files:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if not (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in ("span", "record_span")
                    and isinstance(fn.value, ast.Name)
                    and "TRACER" in fn.value.id
                ):
                    continue
                for kw in node.keywords:
                    if kw.arg != "kind":
                        continue
                    kind = literal_or_fstring_pattern(kw.value)
                    if kind is None:
                        continue
                    if not any(patterns_intersect(kind, d) for d in doc_patterns):
                        yield self.finding(
                            src, node.lineno,
                            f"span kind `{kind}` is not in the documented "
                            f"kind inventory ({_SENSOR_DOC_BASENAME})",
                        )
