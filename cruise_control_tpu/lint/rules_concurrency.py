"""Concurrency and resilience rules (family `conc`).

The never-raise executor contract (PRs 4-5, docs/RESILIENCE.md) rests on
mechanical properties every unattended loop in this package must hold:
errors keep their class (no bare `except:`), every loop bounds itself
(deadline or poll cap), state shared across threads is touched only under
its lock, nothing sleeps while holding a lock, and background threads never
pin the interpreter at shutdown. The first two generalize the original
tests/test_static_guards.py checks from four directories to the whole
package; the lock-discipline rule turns the `#: guarded_by(_lock)`
annotation (tracer ring, sensor registry, executor tracker, breaker state)
into an enforced contract.

Lock-discipline conventions:
  * annotate the owning assignment:  `self._ring = ...  #: guarded_by(_lock)`
    (or put the comment on its own line directly above);
  * methods named `__init__` or ending in `_locked` are exempt (construction
    is single-threaded; `*_locked` helpers document that the caller holds
    the lock);
  * a nested def/lambda does NOT inherit an enclosing `with self._lock` —
    it runs later, when the lock may be free.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set, Tuple

from cruise_control_tpu.lint.core import (
    Finding,
    LintContext,
    Rule,
    register,
)

_GUARD_RE = re.compile(r"#:\s*guarded_by\((\w+)\)")
#: the annotated owner: `self.X = ...` in a method, or a class-level
#: (dataclass-style) field declaration `X: T = ...`
_SELF_ATTR_RE = re.compile(r"self\.(\w+)\s*(?::[^=]+)?=[^=]")
_CLASS_FIELD_RE = re.compile(r"^\s*(\w+)\s*:[^=]+(?:=|$)")


@register
class BareExceptRule(Rule):
    id = "conc-bare-except"
    family = "concurrency"
    rationale = (
        "bare `except:` swallows KeyboardInterrupt/SystemExit and erases the "
        "error class the retry layer's retryable classification needs"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for src in ctx.parsed_files:
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ExceptHandler) and node.type is None:
                    yield self.finding(
                        src, node.lineno,
                        "bare `except:` — catch `Exception` (or narrower) so "
                        "interrupts propagate and the error class survives",
                    )


def _has_escape(loop: ast.While) -> bool:
    """A break/return lexically inside the loop body that can exit THIS loop
    (not one bound to a nested loop or belonging to a nested function)."""

    def walk(nodes, inside_nested_loop):
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # its returns/breaks don't exit our loop
            if isinstance(node, ast.Return):
                return True
            if isinstance(node, ast.Break) and not inside_nested_loop:
                return True
            nested = inside_nested_loop or isinstance(node, (ast.While, ast.For))
            if walk(ast.iter_child_nodes(node), nested):
                return True
        return False

    return walk(loop.body, False)


@register
class UnboundedLoopRule(Rule):
    id = "conc-unbounded-loop"
    family = "concurrency"
    rationale = (
        "`while True` with no reachable break/return is an unbounded loop "
        "with no deadline or poll cap — the exact shape of a wedged "
        "controller (docs/RESILIENCE.md requires every poll loop to bound "
        "itself)"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for src in ctx.parsed_files:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.While):
                    continue
                test = node.test
                if (
                    isinstance(test, ast.Constant)
                    and test.value is True
                    and not _has_escape(node)
                ):
                    yield self.finding(
                        src, node.lineno,
                        "`while True` without break/return — add a deadline "
                        "or poll cap (resilience contract)",
                    )


def _with_lock_names(node: ast.With) -> Set[str]:
    """Lock attribute names entered by `with self.<name>[, ...]`."""
    out = set()
    for item in node.items:
        e = item.context_expr
        if (
            isinstance(e, ast.Attribute)
            and isinstance(e.value, ast.Name)
            and e.value.id == "self"
        ):
            out.add(e.attr)
    return out


def _guarded_attrs(src, cls: ast.ClassDef) -> Dict[str, str]:
    """attr -> lock name, from `#: guarded_by(<lock>)` annotations in the
    class's source range (same line as the `self.X = ...`, or the line
    directly above it)."""
    end = getattr(cls, "end_lineno", None) or len(src.lines)
    out: Dict[str, str] = {}
    for i in range(cls.lineno, min(end, len(src.lines)) + 1):
        comment = src.comments.get(i)
        if comment is None:
            continue
        m = _GUARD_RE.search(comment)
        if m is None:
            continue
        line = src.lines[i - 1]
        lock = m.group(1)
        target = _SELF_ATTR_RE.search(line) or _CLASS_FIELD_RE.match(
            line.split("#")[0]
        )
        if target is None and i < len(src.lines):  # standalone: next line
            nxt = src.lines[i]
            target = _SELF_ATTR_RE.search(nxt) or _CLASS_FIELD_RE.match(
                nxt.split("#")[0]
            )
        if target is not None:
            out[target.group(1)] = lock
    return out


@register
class GuardedByRule(Rule):
    id = "conc-guarded-by"
    family = "concurrency"
    rationale = (
        "attributes annotated `#: guarded_by(<lock>)` may only be touched "
        "inside `with self.<lock>` (or from __init__ / *_locked helpers) — "
        "the tracer ring, sensor registry, executor tracker, and breaker "
        "state are all read by server threads while loops mutate them"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for src in ctx.parsed_files:
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef):
                    guarded = _guarded_attrs(src, node)
                    if guarded:
                        yield from self._check_class(src, node, guarded)

    def _check_class(self, src, cls, guarded) -> Iterator[Finding]:
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if stmt.name == "__init__" or stmt.name.endswith("_locked"):
                    continue
                yield from self._visit(src, stmt.body, guarded, held=set())

    def _visit(self, src, nodes, guarded, held) -> Iterator[Finding]:
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                # a nested callable runs later: the enclosing lock is NOT held
                body = node.body if isinstance(node.body, list) else [node.body]
                yield from self._visit(src, body, guarded, held=set())
                continue
            if isinstance(node, ast.With):
                now_held = held | _with_lock_names(node)
                for item in node.items:
                    yield from self._visit(
                        src, [item.context_expr], guarded, held
                    )
                yield from self._visit(src, node.body, guarded, now_held)
                continue
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in guarded
                and guarded[node.attr] not in held
            ):
                yield self.finding(
                    src, node.lineno,
                    f"`self.{node.attr}` is `#: guarded_by({guarded[node.attr]})` "
                    f"but accessed outside `with self.{guarded[node.attr]}` — "
                    "take the lock, or rename the helper `*_locked`",
                )
            yield from self._visit(src, ast.iter_child_nodes(node), guarded, held)


@register
class SleepUnderLockRule(Rule):
    id = "conc-sleep-under-lock"
    family = "concurrency"
    rationale = (
        "sleeping while holding a lock serializes every other thread behind "
        "the sleeper — poll pauses belong outside critical sections"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for src in ctx.parsed_files:
            yield from self._visit(src, [src.tree], held=False)

    def _visit(self, src, nodes, held) -> Iterator[Finding]:
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                body = node.body if isinstance(node.body, list) else [node.body]
                yield from self._visit(src, body, held=False)
                continue
            if isinstance(node, ast.With):
                lockish = any(
                    "lock" in name.lower() for name in _with_lock_names(node)
                )
                yield from self._visit(src, node.body, held or lockish)
                continue
            if isinstance(node, ast.Call):
                fn = node.func
                is_sleep = (
                    isinstance(fn, ast.Attribute) and fn.attr == "sleep"
                ) or (isinstance(fn, ast.Name) and fn.id == "sleep")
                if is_sleep and held:
                    yield self.finding(
                        src, node.lineno,
                        "sleep while holding a lock — release the lock "
                        "around the pause",
                    )
            yield from self._visit(src, ast.iter_child_nodes(node), held)


@register
class DaemonThreadRule(Rule):
    id = "conc-daemon-thread"
    family = "concurrency"
    rationale = (
        "a non-daemon background thread pins the interpreter at shutdown; "
        "every loop thread must be `daemon=True` (or set `.daemon = True` "
        "before start) so operators can stop the service"
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for src in ctx.parsed_files:
            for scope in ast.walk(src.tree):
                if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
                    yield from self._check_scope(src, scope)

    def _check_scope(self, src, scope) -> Iterator[Finding]:
        def own(nodes):  # this scope's nodes, nested defs excluded
            for node in nodes:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # handled by its own _check_scope call
                yield node
                yield from own(ast.iter_child_nodes(node))

        # `x.daemon = True` anywhere in the scope clears the whole scope:
        # the common pattern constructs then flips the flag on the next line
        for n in own(scope.body):
            if (
                isinstance(n, ast.Assign)
                and any(
                    isinstance(t, ast.Attribute) and t.attr == "daemon"
                    for t in n.targets
                )
                and isinstance(n.value, ast.Constant)
                and n.value.value is True
            ):
                return
        for n in own(scope.body):
            if not isinstance(n, ast.Call):
                continue
            fn = n.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("Thread", "Timer")
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "threading"
            ):
                continue
            daemon_kw = any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in n.keywords
            )
            if not daemon_kw:
                yield self.finding(
                    src, n.lineno,
                    f"threading.{fn.attr} without daemon=True — a "
                    "non-daemon background thread blocks shutdown",
                )
