"""Persistent XLA compilation cache.

The fused goal-stack program (analyzer.optimizer) costs one XLA compile per
problem shape; this module makes that compile survive process restarts —
the driver's warmup pass, the test suite, and production restarts all reuse
the same on-disk executables. The reference has no analog (JVM JIT warmup is
implicit); for an XLA-based service this is part of the startup contract.

Call `enable_persistent_cache()` before the first jit execution. Safe to call
multiple times; a no-op if the cache was already enabled with another path.
"""

from __future__ import annotations

import os
from typing import Optional

_DEFAULT_DIR = os.path.join(os.path.dirname(__file__), os.pardir, ".jax_cache")

_enabled: Optional[str] = None


def enable_persistent_cache(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's compilation cache at a durable directory and drop the
    min-compile-time / min-entry-size gates so every program is cached.

    TPU-only: XLA:CPU AOT executable serialization is unreliable in this
    build — the serializer can segfault on write (observed in
    compilation_cache.put_executable_and_time) and the loader hard-aborts on
    entries recorded under different target-machine features — so on a CPU
    backend this is a no-op unless CRUISE_CONTROL_JAX_CACHE_FORCE=1. TPU
    compiles are also the ones worth persisting (minutes at north-star
    scale vs seconds on CPU).

    Returns the cache dir, or None when disabled or no writable directory is
    available — the cache is an accelerator, never a startup requirement."""
    global _enabled
    if _enabled is not None:
        return _enabled
    import jax

    force = os.environ.get("CRUISE_CONTROL_JAX_CACHE_FORCE") == "1"
    if not force and jax.default_backend() != "tpu":
        return None
    cache_dir = os.path.abspath(
        path or os.environ.get("CRUISE_CONTROL_JAX_CACHE", _DEFAULT_DIR)
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        return None
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _enabled = cache_dir
    return cache_dir
