"""Protocol-level fake cluster agent: the controller side of the TCP driver.

Implements the cluster-agent wire protocol (executor.tcp_driver module
docstring) against a SimulatedCluster — the analog of the reference's
embedded-ZK/Kafka integration harness (cct/executor/ExecutorTest.java boots a
real broker; here the protocol surface is real and the cluster behind it is
the simulator). Movements complete after `latency_polls` "finished" probes,
exercising the executor's poll loop exactly like a controller that takes time
to move data.

Runs in-process (`FakeClusterAgent(...).start()`), which keeps the
integration test deterministic while every byte still crosses a real socket.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

from cruise_control_tpu.common.lineserver import JsonLinesServer


class FakeClusterAgent:
    """JSON-lines TCP server applying reassignments to a SimulatedCluster.

    Transport (threaded socket loop, TLS termination — the SslTest analog)
    is the SHARED JsonLinesServer, the same scaffolding the production
    Kafka agent serves on; only the dispatch differs."""

    def __init__(self, sim, latency_polls: int = 0, host: str = "127.0.0.1",
                 ssl_context=None, fault_plan=None):
        """`fault_plan` (testing.faults.FaultPlan): injected faults consulted
        before dispatch (fail/drop/delay) and when recording movements
        (never_finish)."""
        self._sim = sim
        self._latency = latency_polls
        self._faults = fault_plan
        self._lock = threading.Lock()
        #: executionId -> (kind, payload, remaining_probes); remaining < 0
        #: means the movement NEVER completes (injected hung controller)
        self._pending: Dict[int, Tuple[str, Dict, int]] = {}
        self._finished: set = set()
        self._metrics: list = []  # hex-encoded records, consumed by poll
        self._server = JsonLinesServer(
            self._dispatch, host=host, ssl_context=ssl_context,
            name="fake-cluster-agent",
        )

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.address

    def start(self) -> "FakeClusterAgent":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop()

    # -- protocol ops ----------------------------------------------------------

    def _dispatch(self, req: Dict) -> Dict:
        if self._faults is not None:
            injected = self._faults.server_intercept(req)
            if injected is not None:
                return injected
        op = req.get("op")
        if op == "ping":
            return {"ok": True}
        if op in ("reassign", "leader"):
            latency = self._latency
            if self._faults is not None and self._faults.never_finishes(req):
                latency = -1
            with self._lock:
                self._pending[int(req["executionId"])] = (op, req, latency)
            return {"ok": True}
        if op == "finished":
            done = []
            with self._lock:
                for eid in req.get("executionIds", ()):
                    eid = int(eid)
                    if eid in self._finished:
                        done.append(eid)
                        continue
                    entry = self._pending.get(eid)
                    if entry is None:
                        continue  # unknown id (restarted driver): unfinished
                    kind, payload, remaining = entry
                    if remaining < 0:
                        continue  # injected never-finishing movement
                    if remaining > 0:
                        self._pending[eid] = (kind, payload, remaining - 1)
                        continue
                    self._apply(kind, payload)
                    del self._pending[eid]
                    self._finished.add(eid)
                    done.append(eid)
            return {"ok": True, "finished": done}
        if op == "ongoing":
            with self._lock:
                return {"ok": True, "ongoing": bool(self._pending)}
        if op == "metrics_publish":
            with self._lock:
                self._metrics.extend(req.get("records", ()))
            return {"ok": True}
        if op == "metrics_poll":
            n = int(req.get("max", 10000))
            with self._lock:
                out, self._metrics = self._metrics[:n], self._metrics[n:]
            return {"ok": True, "records": out}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _apply(self, kind: str, req: Dict) -> None:
        partition = int(req["partition"])
        if kind == "leader":
            self._sim.apply_leadership(partition, int(req["leader"]))
            return
        new = list(req["replicas"])
        current = [
            b for b in range(self._sim.model().num_brokers)
            if self._sim.has_partition(partition, b)
        ]
        removed = [b for b in current if b not in new]
        added = [b for b in new if b not in current]
        for i, dst in enumerate(added):
            if i < len(removed):
                self._sim.apply_movement(partition, removed[i], dst)
            else:
                self._sim.add_replica(partition, dst)
        for src in removed[len(added):]:
            self._sim.remove_replica(partition, src)
        if new and self._sim.leader_of(partition) != new[0]:
            self._sim.apply_leadership(partition, new[0])
