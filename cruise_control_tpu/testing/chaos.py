"""Seeded chaos replay harness: perturbations streamed mid-execution.

ROADMAP item 4 asks for a load-replay harness that streams perturbations
while the executor is mid-batch; this module is that harness for the drift
layer (executor/validation.py). It composes three pieces:

  * `ChaosPlan` — a deterministic schedule of `Perturbation`s (broker
    death/revival, topic delete, partition-count change, hot-load spike,
    synthetic generation bumps) keyed by driver poll count, applied to the
    SimulatedCluster from inside the driver's poll loop — i.e. exactly
    between the executor's batch boundaries, never concurrently with a
    dispatch;
  * `InvariantChecker` — consulted at every dispatch: no task may go to a
    dead or out-of-range broker, no task may reference a vanished
    partition/replica, and end-to-end the replication factor of every
    surviving partition must be preserved. Violations are RECORDED (not
    raised) so a test can assert the full picture;
  * `ChaosReplayDriver` — a SimulatorClusterDriver that advances the plan on
    every poll, checks invariants on every dispatch, and resolves in-flight
    movements by topic-partition NAME when topology rows shift underneath
    them (a deleted topic renumbers the dense axis; a real controller keys
    on names, so the harness must too).

Protocol-level faults (testing/faults.py) compose with this: a FaultPlan
drives the wire, a ChaosPlan drives the cluster.

Typical use (tests/test_chaos_replay.py):

    sim = SimulatedCluster(random_cluster(...))
    plan = ChaosPlan([Perturbation(at_poll=2, action="kill_broker", broker=3)])
    harness = ChaosHarness(sim, plan)
    summary = harness.execute(harness.stamped_proposals(seed=7, count=40))
    assert harness.checker.violations == []
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.common.resources import BrokerState
from cruise_control_tpu.executor.driver import SimulatorClusterDriver
from cruise_control_tpu.executor.executor import Executor, ExecutorConfig
from cruise_control_tpu.executor.task import ExecutionTask, TaskType
from cruise_control_tpu.executor.validation import TopologyFingerprint, TopologyView
from cruise_control_tpu.monitor.metadata import MetadataClient

ACTIONS = (
    "kill_broker", "restore_broker", "revive_broker", "delete_topic",
    "add_partitions", "spike_load", "bump_generation",
)


@dataclasses.dataclass
class Perturbation:
    """One scheduled cluster mutation. `at_poll` is the driver poll count at
    (or after) which it fires; rows with the same at_poll fire in order."""

    at_poll: int
    action: str
    broker: int = -1
    topic: int = -1
    count: int = 1
    factor: float = 4.0

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(f"unknown perturbation action {self.action!r}")

    def apply(self, sim, plan: "ChaosPlan") -> None:
        if self.action == "kill_broker":
            sim.kill_broker(self.broker)
        elif self.action == "restore_broker":
            sim.restore_broker(self.broker)
        elif self.action == "revive_broker":
            sim.revive_broker(self.broker)
        elif self.action == "delete_topic":
            sim.delete_topic(self.topic)
        elif self.action == "add_partitions":
            sim.add_partitions(self.topic, self.count)
        elif self.action == "spike_load":
            sim.spike_load(self.topic, self.factor)
        else:  # bump_generation: pure monitor-side drift, no cluster change
            plan.generation_bumps += self.count


class ChaosPlan:
    """Ordered, deterministic perturbation schedule."""

    def __init__(self, perturbations=()):
        self._pending: List[Perturbation] = sorted(
            perturbations, key=lambda p: p.at_poll
        )
        #: every perturbation actually applied, in order (for assertions)
        self.applied: List[Dict] = []
        #: synthetic monitor-generation drift (bump_generation actions)
        self.generation_bumps = 0

    def add(self, p: Perturbation) -> "ChaosPlan":
        self._pending.append(p)
        self._pending.sort(key=lambda x: x.at_poll)
        return self

    def advance(self, sim, poll: int) -> int:
        """Apply every perturbation due at `poll`; returns how many fired."""
        fired = 0
        while self._pending and self._pending[0].at_poll <= poll:
            p = self._pending.pop(0)
            p.apply(sim, self)
            self.applied.append({**dataclasses.asdict(p), "firedAtPoll": poll})
            fired += 1
        return fired

    @property
    def exhausted(self) -> bool:
        return not self._pending


class InvariantChecker:
    """Dispatch-time + end-to-end safety assertions, recorded not raised."""

    def __init__(self, sim):
        self._sim = sim
        self.violations: List[Dict] = []
        self.dispatches = 0
        #: pre-execution RF keyed by topic-partition name
        view = TopologyView(sim.fetch_topology())
        self._initial_rf: Dict[str, int] = {
            name: len(view.replicas(row)) for name, row in view.items()
        }

    def _violate(self, kind: str, task: ExecutionTask, detail: str) -> None:
        self.violations.append({
            "kind": kind,
            "executionId": task.execution_id,
            "partition": task.proposal.partition,
            "topicPartition": task.proposal.topic_partition,
            "detail": detail,
        })

    def check_dispatch(self, task: ExecutionTask) -> None:
        """No dispatch to a dead/invalid broker; no dispatch referencing a
        vanished partition or replica — checked against the cluster's
        CURRENT ground truth, not the executor's view."""
        self.dispatches += 1
        view = TopologyView(self._sim.fetch_topology())
        p = task.proposal
        for b in p.replicas_to_add:
            if b < 0 or b >= view.num_brokers:
                self._violate("DISPATCH_TO_INVALID_BROKER", task, f"dest {b}")
            elif view.broker_dead(b):
                self._violate("DISPATCH_TO_DEAD_BROKER", task, f"dest {b}")
        row, err = view.resolve(p)
        if err is not None:
            self._violate("DISPATCH_TO_VANISHED_PARTITION", task, err)
            return
        current = view.replicas(row)
        for b in p.replicas_to_remove:
            if b not in current:
                self._violate("DISPATCH_REFERENCES_VANISHED_REPLICA", task,
                              f"source {b} not in {current}")
        if task.task_type == TaskType.LEADER_ACTION:
            if p.new_leader not in current:
                self._violate("DISPATCH_REFERENCES_VANISHED_REPLICA", task,
                              f"leader {p.new_leader} not in {current}")
            elif view.broker_dead(p.new_leader):
                self._violate("DISPATCH_TO_DEAD_BROKER", task,
                              f"leader {p.new_leader}")

    def check_dense_masks(self) -> List[Dict]:
        """The simulator's dense arrays must stay mutually consistent after
        every perturbation — the same alignment contract build_static_ctx
        and the incremental delta kernel (analyzer/incremental.py) assume
        when they derive alive/valid masks from these arrays. Checked after
        each poll; violations are recorded under DENSE_MASK_INCONSISTENT."""
        topo = self._sim.fetch_topology()
        a = np.asarray(topo.assignment)
        tid = np.asarray(topo.topic_id)
        pidx = np.asarray(topo.partition_index)
        state = np.asarray(topo.broker_state)
        rack = np.asarray(topo.broker_rack)
        host = np.asarray(topo.broker_host)
        num_brokers = int(state.shape[0])

        def bad(detail: str) -> None:
            self.violations.append({
                "kind": "DENSE_MASK_INCONSISTENT", "detail": detail,
            })

        if not (a.shape[0] == tid.shape[0] == pidx.shape[0]):
            bad(f"partition axes diverge: assignment {a.shape[0]}, "
                f"topic_id {tid.shape[0]}, partition_index {pidx.shape[0]}")
        if not (rack.shape[0] == host.shape[0] == num_brokers):
            bad(f"broker axes diverge: state {num_brokers}, "
                f"rack {rack.shape[0]}, host {host.shape[0]}")
        if tid.size and (tid.min() < 0 or tid.max() >= len(topo.topic_names)):
            bad(f"topic_id out of range [0, {len(topo.topic_names)}): "
                f"[{tid.min()}, {tid.max()}]")
        if a.size and (a.min() < -1 or a.max() >= num_brokers):
            bad(f"assignment broker index out of range [-1, {num_brokers}): "
                f"[{a.min()}, {a.max()}]")
        if a.size and (a[:, 0] < 0).any():
            rows = np.nonzero(a[:, 0] < 0)[0][:8]
            bad(f"leaderless partitions (slot 0 empty): rows {rows.tolist()}")
        valid_states = {int(s) for s in (
            BrokerState.ALIVE, BrokerState.NEW, BrokerState.DEMOTED,
            BrokerState.DEAD,
        )}
        unknown = sorted(set(int(s) for s in state) - valid_states)
        if unknown:
            bad(f"unknown broker states {unknown}")
        return self.violations

    def check_final(self) -> List[Dict]:
        """Replication factor preserved end-to-end for every partition that
        survived the run (deleted topics are exempt; added partitions have
        no baseline). Appends to (and returns) the violation list."""
        view = TopologyView(self._sim.fetch_topology())
        for name, row in view.items():
            initial = self._initial_rf.get(name)
            if initial is None:
                continue
            rf = len(view.replicas(row))
            if rf != initial:
                self.violations.append({
                    "kind": "RF_NOT_PRESERVED",
                    "topicPartition": name,
                    "detail": f"rf {initial} -> {rf}",
                })
        return self.violations


class ChaosReplayDriver(SimulatorClusterDriver):
    """SimulatorClusterDriver that advances a ChaosPlan on every poll, runs
    the InvariantChecker on every dispatch, and keys in-flight movements by
    topic-partition name so a mid-flight dense-index shift (topic delete)
    lands on the right partition — or evaporates with its topic — exactly
    like a name-keyed controller."""

    def __init__(self, sim, plan: ChaosPlan, checker: InvariantChecker,
                 latency_polls: int = 1):
        super().__init__(sim, latency_polls=latency_polls)
        self._plan = plan
        self._checker = checker
        self.polls = 0
        #: in-flight movements whose partition vanished mid-flight
        self.evaporated: List[int] = []

    # -- chaos injection -------------------------------------------------------

    def poll(self) -> None:
        self.polls += 1
        if self._plan.advance(self._sim, self.polls):
            # only perturbations can break dense-array alignment, so the
            # mask audit rides the polls where something actually fired
            self._checker.check_dense_masks()
        super().poll()

    # -- name-keyed addressing -------------------------------------------------

    def _current(self, task: ExecutionTask) -> Optional[ExecutionTask]:
        """The task re-addressed against CURRENT topology (dense rows may
        have shifted); None when its partition no longer exists."""
        view = TopologyView(self._sim.fetch_topology())
        name = task.proposal.topic_partition
        if name is None:
            return task if task.proposal.partition < view.num_partitions else None
        row = view.row_of(name)
        if row is None:
            return None
        if row == task.proposal.partition:
            return task
        return ExecutionTask(
            task.execution_id,
            dataclasses.replace(task.proposal, partition=row),
            task.task_type,
        )

    def _apply(self, task: ExecutionTask) -> None:
        current = self._current(task)
        if current is None:
            self.evaporated.append(task.execution_id)
            return
        super()._apply(current)

    def is_finished(self, task: ExecutionTask) -> bool:
        with self._lock:
            if task.execution_id in self._pending:
                return False
        current = self._current(task)
        if current is None:
            return True  # partition vanished: nothing left to wait for
        return super().is_finished(current)

    # -- invariant checks ------------------------------------------------------

    def start_replica_movement(self, task: ExecutionTask) -> None:
        self._checker.check_dispatch(task)
        super().start_replica_movement(task)

    def start_leadership_movement(self, task: ExecutionTask) -> None:
        self._checker.check_dispatch(task)
        super().start_leadership_movement(task)


class ChaosHarness:
    """One-stop wiring: simulator + chaos driver + drift-validating executor.

    The executor revalidates against a zero-TTL MetadataClient over the
    simulator (always fresh) and reads its generation through the plan (so
    `bump_generation` perturbations model pure monitor-side drift)."""

    def __init__(self, sim, plan: ChaosPlan, latency_polls: int = 2,
                 config: Optional[ExecutorConfig] = None):
        self.sim = sim
        self.plan = plan
        self.metadata = MetadataClient(sim.fetch_topology, ttl_s=0.0)
        self.checker = InvariantChecker(sim)
        self.driver = ChaosReplayDriver(sim, plan, self.checker,
                                        latency_polls=latency_polls)
        # per-broker concurrency 1 + multi-poll movement latency force MANY
        # batch boundaries, so perturbations land mid-batch by construction;
        # the 5ms progress interval keeps revalidation overhead honest
        # (<2% of batch wall) without making the suite slow
        self.executor = Executor(
            self.driver,
            config=config or ExecutorConfig(
                num_concurrent_partition_movements_per_broker=1,
                execution_progress_check_interval_s=0.005,
            ),
            topology_source=lambda: self.metadata.refresh_metadata(force=True),
            generation_source=self._generation,
        )

    def _generation(self) -> int:
        self.metadata.refresh_metadata(force=True)
        return self.metadata.generation + self.plan.generation_bumps

    def stamped_proposals(self, seed: int, count: int) -> Tuple[
        List[ExecutionProposal], int, TopologyFingerprint
    ]:
        """Deterministic movement proposals against the CURRENT topology
        (compile-free: hand-diffed, not optimizer output), plus the
        generation/fingerprint stamps the facade would attach."""
        rng = np.random.default_rng(seed)
        topo = self.metadata.refresh_metadata(force=True)
        view = TopologyView(topo)
        a = np.asarray(topo.assignment)
        proposals: List[ExecutionProposal] = []
        rows = rng.permutation(view.num_partitions)
        for row in rows:
            if len(proposals) >= count:
                break
            old = view.replicas(int(row))
            if not old:
                continue
            candidates = [b for b in range(view.num_brokers)
                          if b not in old and not view.broker_dead(b)]
            if not candidates:
                continue
            name = view.name_of(int(row))
            if rng.random() < 0.25 and len(old) > 1:
                # leadership-only movement to an existing follower
                new = (old[1],) + (old[0],) + tuple(old[2:])
            else:
                src_slot = int(rng.integers(len(old)))
                dst = candidates[int(rng.integers(len(candidates)))]
                new = tuple(dst if i == src_slot else b
                            for i, b in enumerate(old))
            proposals.append(ExecutionProposal(
                partition=int(row), old_replicas=old, new_replicas=new,
                topic_partition=name,
            ))
        generation = self._generation()
        fingerprint = TopologyFingerprint.from_topology(topo)
        return proposals, generation, fingerprint

    def execute(self, stamped) -> Dict:
        """Run the batch through the executor, then the end-to-end RF check;
        returns the execution summary."""
        proposals, generation, fingerprint = stamped
        summary = self.executor.execute_proposals(
            proposals, generation=generation, fingerprint=fingerprint
        )
        self.checker.check_final()
        return summary
