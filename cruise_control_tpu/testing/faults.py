"""Deterministic fault injection for the cluster-agent protocol.

A FaultPlan is an ordered list of FaultRules consulted on every request, on
either side of the wire:

  * server-side — FakeClusterAgent passes each decoded request through
    `server_intercept` before dispatching it, so a rule can fail the op,
    delay it, sever the connection unanswered, or mark a movement as
    never-finishing;
  * client-side — `_LineClient(fault_hook=plan.client_intercept)` consults
    the plan before each send, so a rule can simulate the client's OWN
    socket dying mid-exchange (drop) or a slow network (delay).

Rules are consumed deterministically: a rule matches its op pattern at most
`times` times (-1 = forever), in plan order, first match wins. Every
integration test in tests/test_resilience.py is driven through this plan —
the retry, deadline, and breaker behaviors are exercised against the real
socket protocol, not mocks.

Protocol faults compose with CLUSTER faults: testing/chaos.py streams
seeded topology perturbations (broker death, topic delete, partition-count
change, load spikes) into the simulator while the executor is mid-batch —
a FaultPlan drives the wire, a ChaosPlan drives the cluster
(tests/test_chaos_replay.py runs both at once).

Actions:
  fail          answer {"ok": false, "error": ...} without dispatching
  drop          sever the connection without answering (DropConnection
                server-side, ConnectionError client-side)
  delay         sleep `delay_s` then pass through (drive client timeouts)
  never_finish  the matched reassign/leader execution never completes
                (its "finished" probe never reports it) — the hung-
                controller case the task deadline exists for
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence

from cruise_control_tpu.common.lineserver import DropConnection

_ACTIONS = ("fail", "drop", "delay", "never_finish")


@dataclasses.dataclass
class FaultRule:
    """One injectable fault. `op` matches the request's op field ("*" = any);
    `partition`, when set, additionally matches the request's partition."""

    op: str
    action: str
    times: int = 1  # matches consumed before the rule retires; -1 = forever
    delay_s: float = 0.0
    partition: Optional[int] = None
    error: str = "injected fault"

    def __post_init__(self):
        if self.action not in _ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}")

    def matches(self, req: Dict) -> bool:
        if self.op != "*" and req.get("op") != self.op:
            return False
        if self.partition is not None and req.get("partition") != self.partition:
            return False
        return True


class FaultPlan:
    """Thread-safe, order-preserving fault schedule over FaultRules."""

    def __init__(self, rules: Sequence[FaultRule] = (),
                 sleep=time.sleep):
        self._rules: List[FaultRule] = list(rules)
        self._lock = threading.Lock()
        self._sleep = sleep
        #: (rule index, op) log of every fault actually fired, for assertions
        self.fired: List[Dict] = []

    def add(self, rule: FaultRule) -> "FaultPlan":
        with self._lock:
            self._rules.append(rule)
        return self

    def _take(self, req: Dict, actions: Sequence[str]) -> Optional[FaultRule]:
        """First live rule matching `req` with one of `actions`, consuming
        one of its `times`."""
        with self._lock:
            for i, rule in enumerate(self._rules):
                if rule.action not in actions or rule.times == 0:
                    continue
                if not rule.matches(req):
                    continue
                if rule.times > 0:
                    rule.times -= 1
                self.fired.append({"rule": i, "action": rule.action,
                                   "op": req.get("op")})
                return rule
        return None

    # -- server side (FakeClusterAgent) ----------------------------------------

    def server_intercept(self, req: Dict) -> Optional[Dict]:
        """Consult the plan for one decoded request. Returns an error
        response to send instead of dispatching, raises DropConnection to
        sever, sleeps for delay rules, or returns None to pass through
        (never_finish rules pass through here — the agent consults
        `never_finishes` when it records the movement)."""
        rule = self._take(req, ("fail", "drop", "delay"))
        if rule is None:
            return None
        if rule.action == "fail":
            return {"ok": False, "error": rule.error}
        if rule.action == "drop":
            raise DropConnection(rule.error)
        self._sleep(rule.delay_s)
        return None

    def never_finishes(self, req: Dict) -> bool:
        """Whether a never_finish rule covers this reassign/leader request
        (checked by the agent when it records the pending movement; `times`
        counts movements, not completion probes)."""
        return self._take(req, ("never_finish",)) is not None

    # -- client side (_LineClient fault_hook) ----------------------------------

    def client_intercept(self, payload: Dict) -> None:
        """fault_hook contract: called with the payload before each send.
        drop → ConnectionError (the client treats it like a dead socket and
        reconnects on the next attempt); delay → sleep; fail/never_finish
        are server-side-only and pass through here."""
        rule = self._take(payload, ("drop", "delay"))
        if rule is None:
            return
        if rule.action == "drop":
            raise ConnectionError(rule.error)
        self._sleep(rule.delay_s)
