"""Test/simulation harness.

The analog of the reference's embedded-cluster integration tier
(AbstractKafkaIntegrationTestHarness, SURVEY.md §4 tier 5): an in-process
simulated cluster that produces real raw metrics through the reporter
transport and accepts executor operations, so the full
reporter -> monitor -> analyzer -> executor loop runs without Kafka.
"""

from cruise_control_tpu.testing.faults import FaultPlan, FaultRule
from cruise_control_tpu.testing.simulator import SimulatedCluster

__all__ = ["FaultPlan", "FaultRule", "SimulatedCluster"]
