"""In-process cluster simulator.

Holds a ground-truth FlatClusterModel and plays every external role the
reference gets from a live Kafka cluster:

- metadata backend for MetadataClient (`fetch_topology`)
- per-broker metric sources for MetricsReporter (`metric_source`), emitting
  the same raw types the in-broker agent produces (byte rates in bytes/s,
  partition sizes in bytes, broker CPU in cumulative util) so the processor's
  unit conversions and CPU attribution are exercised end to end
- cluster mutation surface for the executor (`apply_movement`,
  `apply_leadership`, `kill_broker`, `restore_broker`, `add_broker`) with
  configurable completion latency, standing in for the ZK-reassignment path
  (scala/executor/ExecutorUtils.scala:32)
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

import numpy as np

from cruise_control_tpu.common.resources import BrokerState, PartMetric
from cruise_control_tpu.models.flat_model import ClusterMetadata, FlatClusterModel
from cruise_control_tpu.models.generators import metadata_for
from cruise_control_tpu.monitor.metadata import ClusterTopology
from cruise_control_tpu.monitor.processor import BYTES_IN_KB, BYTES_IN_MB
from cruise_control_tpu.reporter.metrics import (
    BrokerMetric,
    CruiseControlMetric,
    PartitionMetric,
    RawMetricType,
    TopicMetric,
)


class SimulatedCluster:
    def __init__(self, model: FlatClusterModel, metadata: Optional[ClusterMetadata] = None):
        self._lock = threading.RLock()
        self._assignment = np.array(model.assignment, dtype=np.int32)
        self._part_load = np.array(model.part_load, dtype=np.float32)
        self._topic_id = np.array(model.topic_id, dtype=np.int32)
        self._capacity = np.array(model.broker_capacity, dtype=np.float32)
        self._rack = np.array(model.broker_rack, dtype=np.int32)
        self._host = np.array(model.broker_host, dtype=np.int32)
        self._state = np.array(model.broker_state, dtype=np.int32)
        self._meta = metadata or metadata_for(model)

    # -- snapshots -------------------------------------------------------------

    def model(self) -> FlatClusterModel:
        with self._lock:
            return FlatClusterModel(
                assignment=self._assignment.copy(),
                part_load=self._part_load.copy(),
                topic_id=self._topic_id.copy(),
                broker_capacity=self._capacity.copy(),
                broker_rack=self._rack.copy(),
                broker_host=self._host.copy(),
                broker_state=self._state.copy(),
            )

    def fetch_topology(self) -> ClusterTopology:
        """Backend for MetadataClient."""
        with self._lock:
            return ClusterTopology(
                topic_names=self._meta.topic_names,
                topic_id=self._topic_id.copy(),
                partition_index=np.asarray(self._meta.partition_index, dtype=np.int32),
                assignment=self._assignment.copy(),
                broker_ids=np.asarray(self._meta.broker_ids, dtype=np.int32),
                broker_rack=self._rack.copy(),
                broker_host=self._host.copy(),
                broker_state=self._state.copy(),
            )

    # -- reporter metric sources -----------------------------------------------

    def metric_source(self, broker_index: int) -> Callable[[int], List[CruiseControlMetric]]:
        """Raw-metric source for one broker's MetricsReporter."""

        def source(now_ms: int) -> List[CruiseControlMetric]:
            with self._lock:
                if self._state[broker_index] == BrokerState.DEAD:
                    return []
                bid = int(self._meta.broker_ids[broker_index])
                a = self._assignment
                pl = self._part_load
                leads = a[:, 0] == broker_index
                follows = (a[:, 1:] == broker_index).any(axis=1)
                out: List[CruiseControlMetric] = []

                cpu = float(
                    pl[leads, PartMetric.CPU_LEADER].sum()
                    + pl[follows, PartMetric.CPU_FOLLOWER].sum()
                )
                bytes_in = float(pl[leads, PartMetric.NW_IN_LEADER].sum()) * BYTES_IN_KB
                bytes_out = float(pl[leads, PartMetric.NW_OUT_LEADER].sum()) * BYTES_IN_KB
                rep_in = float(pl[follows, PartMetric.NW_IN_FOLLOWER].sum()) * BYTES_IN_KB
                # a leader ships NW_IN_FOLLOWER to EACH of its followers
                n_followers = (a[:, 1:] >= 0).sum(axis=1).astype(np.float32)
                rep_out = float(
                    (pl[leads, PartMetric.NW_IN_FOLLOWER] * n_followers[leads]).sum()
                ) * BYTES_IN_KB
                out.append(BrokerMetric(RawMetricType.BROKER_CPU_UTIL, now_ms, bid, cpu))
                out.append(BrokerMetric(RawMetricType.ALL_TOPIC_BYTES_IN, now_ms, bid, bytes_in))
                out.append(BrokerMetric(RawMetricType.ALL_TOPIC_BYTES_OUT, now_ms, bid, bytes_out))
                out.append(
                    BrokerMetric(RawMetricType.ALL_TOPIC_REPLICATION_BYTES_IN, now_ms, bid, rep_in)
                )
                out.append(
                    BrokerMetric(RawMetricType.ALL_TOPIC_REPLICATION_BYTES_OUT, now_ms, bid, rep_out)
                )

                # per-topic IO led by this broker
                led = np.nonzero(leads)[0]
                for t in np.unique(self._topic_id[led]):
                    sel = led[self._topic_id[led] == t]
                    name = self._meta.topic_names[int(t)]
                    t_in = float(pl[sel, PartMetric.NW_IN_LEADER].sum()) * BYTES_IN_KB
                    t_out = float(pl[sel, PartMetric.NW_OUT_LEADER].sum()) * BYTES_IN_KB
                    t_rep_in = float(pl[sel, PartMetric.NW_IN_FOLLOWER].sum()) * BYTES_IN_KB
                    t_rep_out = float(
                        (pl[sel, PartMetric.NW_IN_FOLLOWER] * n_followers[sel]).sum()
                    ) * BYTES_IN_KB
                    out.append(TopicMetric(RawMetricType.TOPIC_BYTES_IN, now_ms, bid, name, t_in))
                    out.append(TopicMetric(RawMetricType.TOPIC_BYTES_OUT, now_ms, bid, name, t_out))
                    out.append(
                        TopicMetric(RawMetricType.TOPIC_REPLICATION_BYTES_IN, now_ms, bid, name, t_rep_in)
                    )
                    out.append(
                        TopicMetric(RawMetricType.TOPIC_REPLICATION_BYTES_OUT, now_ms, bid, name, t_rep_out)
                    )
                    # partition sizes for this topic's leader partitions here
                    for pid in sel:
                        out.append(
                            PartitionMetric(
                                RawMetricType.PARTITION_SIZE,
                                now_ms,
                                bid,
                                name,
                                int(self._meta.partition_index[pid]),
                                float(pl[pid, PartMetric.DISK]) * BYTES_IN_MB,
                            )
                        )
                return out

        return source

    def all_metrics(self, now_ms: int) -> List[CruiseControlMetric]:
        """Every alive broker's metrics for one interval."""
        out: List[CruiseControlMetric] = []
        for i in range(self._state.shape[0]):
            out.extend(self.metric_source(i)(now_ms))
        return out

    # -- executor surface ------------------------------------------------------

    def apply_movement(self, partition: int, source_broker: int, dest_broker: int) -> bool:
        """Replace source_broker with dest_broker in the partition's replica
        set (the reassignment the ZK write would trigger)."""
        with self._lock:
            row = self._assignment[partition]
            slots = np.nonzero(row == source_broker)[0]
            if slots.size == 0 or (row == dest_broker).any():
                return False
            self._assignment[partition, slots[0]] = dest_broker
            return True

    def add_replica(self, partition: int, broker_index: int) -> bool:
        """Grow the partition's replica set (RF increase), widening the
        assignment matrix when every slot is taken."""
        with self._lock:
            row = self._assignment[partition]
            if (row == broker_index).any():
                return False
            free = np.nonzero(row < 0)[0]
            if free.size == 0:
                pad = np.full((self._assignment.shape[0], 1), -1, dtype=np.int32)
                self._assignment = np.concatenate([self._assignment, pad], axis=1)
                self._assignment[partition, -1] = broker_index
            else:
                self._assignment[partition, free[0]] = broker_index
            return True

    def remove_replica(self, partition: int, broker_index: int) -> bool:
        """Drop a non-leader replica (RF decrease), left-packing the row."""
        with self._lock:
            row = self._assignment[partition]
            slots = np.nonzero(row == broker_index)[0]
            if slots.size == 0 or slots[0] == 0:
                return False
            s = slots[0]
            row[s:-1] = row[s + 1 :]
            row[-1] = -1
            return True

    def apply_leadership(self, partition: int, new_leader_broker: int) -> bool:
        """Preferred-leader election to an in-set replica."""
        with self._lock:
            row = self._assignment[partition]
            slots = np.nonzero(row == new_leader_broker)[0]
            if slots.size == 0:
                return False
            s = slots[0]
            row[0], row[s] = row[s], row[0]
            return True

    def kill_broker(self, broker_index: int) -> None:
        with self._lock:
            self._state[broker_index] = BrokerState.DEAD

    def restore_broker(self, broker_index: int) -> None:
        with self._lock:
            self._state[broker_index] = BrokerState.ALIVE

    def revive_broker(self, broker_index: int) -> None:
        """A dead broker re-joins as NEW (not ALIVE): its replicas survived
        on disk but the rebalancer should treat it as a fresh destination —
        the incremental lane's `broker_revival` delta keys off this
        transition (analyzer/incremental.py)."""
        with self._lock:
            if self._state[broker_index] == BrokerState.DEAD:
                self._state[broker_index] = BrokerState.NEW

    # -- topology perturbations (chaos replay, testing/chaos.py) ---------------

    def delete_topic(self, topic: int) -> int:
        """Drop every partition of the topic (the mid-batch topic-delete
        drift case): all partition-axis arrays shrink and the dense indices
        of later partitions SHIFT — exactly the hazard the executor's
        revalidation must catch. Returns the number of partitions removed."""
        from cruise_control_tpu.models.flat_model import ClusterMetadata

        with self._lock:
            keep = self._topic_id != int(topic)
            removed = int((~keep).sum())
            if removed == 0:
                return 0
            self._assignment = self._assignment[keep]
            self._part_load = self._part_load[keep]
            self._topic_id = self._topic_id[keep]
            self._meta = ClusterMetadata(
                topic_names=self._meta.topic_names,
                partition_index=np.asarray(self._meta.partition_index)[keep],
                broker_ids=np.asarray(self._meta.broker_ids),
                rack_names=self._meta.rack_names,
                host_names=self._meta.host_names,
                topic_of_partition=self._topic_id.copy(),
            )
            return removed

    def add_partitions(self, topic: int, count: int) -> int:
        """Grow a topic by `count` partitions (the partition-count-change
        drift case): new rows append with replicas round-robined over alive
        brokers and zero load. Returns the new partition count of the topic."""
        from cruise_control_tpu.models.flat_model import ClusterMetadata

        with self._lock:
            mask = self._topic_id == int(topic)
            if mask.any():  # new partitions inherit the topic's RF
                rf = int((self._assignment[mask] >= 0).sum(axis=1).max())
            else:
                rf = min(2, int(self._state.shape[0]))
            rf = max(1, rf)
            alive = [int(b) for b in range(self._state.shape[0])
                     if self._state[b] != BrokerState.DEAD]
            if not alive:
                return 0
            pidx = np.asarray(self._meta.partition_index)
            existing = pidx[self._topic_id == int(topic)]
            next_index = int(existing.max()) + 1 if existing.size else 0
            rows = []
            for i in range(count):
                replicas = [alive[(next_index + i + j) % len(alive)]
                            for j in range(min(rf, len(alive)))]
                row = np.full(self._assignment.shape[1], -1, dtype=np.int32)
                row[: len(replicas)] = replicas
                rows.append(row)
            self._assignment = np.concatenate([self._assignment, np.stack(rows)])
            self._part_load = np.concatenate([
                self._part_load,
                np.zeros((count, self._part_load.shape[1]), dtype=np.float32),
            ])
            self._topic_id = np.concatenate([
                self._topic_id, np.full(count, int(topic), dtype=np.int32)
            ])
            self._meta = ClusterMetadata(
                topic_names=self._meta.topic_names,
                partition_index=np.concatenate([
                    pidx, np.arange(next_index, next_index + count, dtype=np.int32)
                ]),
                broker_ids=np.asarray(self._meta.broker_ids),
                rack_names=self._meta.rack_names,
                host_names=self._meta.host_names,
                topic_of_partition=self._topic_id.copy(),
            )
            return int((self._topic_id == int(topic)).sum())

    def spike_load(self, topic: int, factor: float) -> None:
        """Multiply the topic's partition load (hot-load spike): no topology
        change, so the metadata generation must NOT bump — load drift is the
        optimizer's business, not admission's."""
        with self._lock:
            self._part_load[self._topic_id == int(topic)] *= np.float32(factor)

    def replication_factor_of(self, partition: int) -> int:
        with self._lock:
            return int((self._assignment[partition] >= 0).sum())

    def has_partition(self, partition: int, broker_index: int) -> bool:
        with self._lock:
            return bool((self._assignment[partition] == broker_index).any())

    def leader_of(self, partition: int) -> int:
        with self._lock:
            return int(self._assignment[partition, 0])
