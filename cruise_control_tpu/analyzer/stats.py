"""Cluster model statistics kernels.

The analog of ClusterModelStats (cc/model/ClusterModelStats.java:22): per-
resource utilization mean / standard deviation / min / max over alive brokers,
replica / leader / topic-replica count statistics, and potential NW_OUT —
computed as one fused jitted kernel over the FlatClusterModel instead of the
reference's per-broker object walks. Used by the optimizer's per-goal
comparator (AbstractGoal's stats regression check) and by the /load and
proposal-summary responses.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.models.flat_model import (
    FlatClusterModel,
    alive_broker_mask,
    broker_loads,
    leader_counts,
    potential_nw_out,
    replica_counts,
    topic_replica_counts,
)


class ClusterModelStats(NamedTuple):
    """Per-cluster summary statistics, all over *alive* brokers only
    (matching ClusterModelStats.populate which skips dead brokers)."""

    # f32[4] each, indexed by Resource
    resource_mean: jax.Array
    resource_std: jax.Array
    resource_min: jax.Array
    resource_max: jax.Array
    # replica count stats, f32[] each
    replica_mean: jax.Array
    replica_std: jax.Array
    replica_min: jax.Array
    replica_max: jax.Array
    # leader replica count stats
    leader_mean: jax.Array
    leader_std: jax.Array
    # topic-replica spread: mean over topics of per-topic stddev across brokers
    topic_replica_std: jax.Array
    # potential nw out stats
    potential_nw_out_mean: jax.Array
    potential_nw_out_max: jax.Array
    num_alive_brokers: jax.Array
    num_replicas: jax.Array
    num_leaders: jax.Array


def _masked_stats(values: jax.Array, mask: jax.Array):
    """(mean, std, min, max) of `values` where mask, as f32 scalars."""
    v = values.astype(jnp.float32)
    n = jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
    mean = jnp.sum(jnp.where(mask, v, 0.0)) / n
    var = jnp.sum(jnp.where(mask, (v - mean) ** 2, 0.0)) / n
    vmin = jnp.min(jnp.where(mask, v, jnp.inf))
    vmax = jnp.max(jnp.where(mask, v, -jnp.inf))
    return mean, jnp.sqrt(var), vmin, vmax


def compute_stats(model: FlatClusterModel, num_topics: int) -> ClusterModelStats:
    """Fused statistics kernel. `num_topics` must be static (trace-time)."""
    alive = alive_broker_mask(model)
    loads = broker_loads(model)  # f32[B, 4]
    util = loads / jnp.maximum(model.broker_capacity, 1e-9)

    means, stds, mins, maxs = [], [], [], []
    for res in Resource:
        m, s, lo, hi = _masked_stats(util[:, res], alive)
        means.append(m)
        stds.append(s)
        mins.append(lo)
        maxs.append(hi)

    replicas = replica_counts(model)
    leaders = leader_counts(model)
    r_mean, r_std, r_min, r_max = _masked_stats(replicas, alive)
    l_mean, l_std, _, _ = _masked_stats(leaders, alive)

    # per-topic replica spread across alive brokers. The mean runs over
    # topics that actually hold replicas: `num_topics` may be a shape bucket
    # (analyzer.optimizer shape bucketing), and empty padded topic rows in
    # the denominator would make the statistic drift with the bucket size
    # instead of matching the exact-shape model. Real topics always hold at
    # least one replica (every partition has a leader), so the mask is
    # exactly the padding mask.
    t_counts = topic_replica_counts(model, num_topics).astype(jnp.float32)  # [T, B]
    alive_f = alive.astype(jnp.float32)[None, :]
    n_alive = jnp.maximum(jnp.sum(alive_f, axis=1), 1.0)
    t_mean = jnp.sum(t_counts * alive_f, axis=1, keepdims=True) / n_alive[:, None]
    t_var = jnp.sum(jnp.where(alive_f > 0, (t_counts - t_mean) ** 2, 0.0), axis=1) / n_alive
    t_nonempty = jnp.sum(t_counts, axis=1) > 0.0
    topic_std = jnp.sum(jnp.where(t_nonempty, jnp.sqrt(t_var), 0.0)) / jnp.maximum(
        jnp.sum(t_nonempty.astype(jnp.float32)), 1.0
    )

    pnw = potential_nw_out(model)
    p_mean, _, _, p_max = _masked_stats(pnw, alive)

    return ClusterModelStats(
        resource_mean=jnp.stack(means),
        resource_std=jnp.stack(stds),
        resource_min=jnp.stack(mins),
        resource_max=jnp.stack(maxs),
        replica_mean=r_mean,
        replica_std=r_std,
        replica_min=r_min,
        replica_max=r_max,
        leader_mean=l_mean,
        leader_std=l_std,
        topic_replica_std=topic_std,
        potential_nw_out_mean=p_mean,
        potential_nw_out_max=p_max,
        num_alive_brokers=jnp.sum(alive.astype(jnp.int32)),
        num_replicas=jnp.sum(replicas),
        num_leaders=jnp.sum(leaders),
    )


def stats_to_dict(stats: ClusterModelStats) -> dict:
    """Host-side JSON-friendly rendering (servlet response stats analog)."""
    import numpy as np

    res_names = [r.name for r in Resource]
    out = {
        "resources": {
            name: {
                "mean": float(np.asarray(stats.resource_mean)[i]),
                "std": float(np.asarray(stats.resource_std)[i]),
                "min": float(np.asarray(stats.resource_min)[i]),
                "max": float(np.asarray(stats.resource_max)[i]),
            }
            for i, name in enumerate(res_names)
        },
        "replicas": {
            "mean": float(stats.replica_mean),
            "std": float(stats.replica_std),
            "min": float(stats.replica_min),
            "max": float(stats.replica_max),
        },
        "leaderReplicas": {"mean": float(stats.leader_mean), "std": float(stats.leader_std)},
        "topicReplicasStd": float(stats.topic_replica_std),
        "potentialNwOut": {
            "mean": float(stats.potential_nw_out_mean),
            "max": float(stats.potential_nw_out_max),
        },
        "numAliveBrokers": int(stats.num_alive_brokers),
        "numReplicas": int(stats.num_replicas),
        "numLeaders": int(stats.num_leaders),
    }
    return out
