"""Optimizer context: static inputs and incrementally-updated aggregates.

`StaticCtx` carries everything that is constant across an optimization run
(the flattened cluster inputs, constraint thresholds, and the
`OptimizationOptions` masks — cc/analyzer/OptimizationOptions.java:14 turned
into boolean arrays). `Aggregates` carries the per-broker/per-rack/per-topic
summaries the goals consult; they are recomputed from the assignment with
segment-sums and updated incrementally inside the apply scan — the dense
equivalent of the bookkeeping ClusterModel does inside relocateReplica /
relocateLeadership (cc/model/ClusterModel.java:280,:307).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.common.resources import BrokerState, PartMetric, Resource
from cruise_control_tpu.config.balancing import BalancingConstraint
from cruise_control_tpu.analyzer.actions import KIND_MOVE, ActionBatch
from cruise_control_tpu.models.flat_model import FlatClusterModel


@dataclasses.dataclass(frozen=True)
class OptimizationOptions:
    """Mask-encoded request options (cc/analyzer/OptimizationOptions.java:14).

    The `*_pattern`/`*_ids` fields are SYMBOLIC: a REST caller doesn't know
    the model's partition/broker axes, so it names topics by regex and
    brokers by id and `resolve_options` turns them into masks once the model
    exists (the reference resolves excludedTopics the same way,
    KafkaCruiseControlUtils/GoalUtils)."""

    #: replicas of these partitions may not be moved (excluded topics)
    excluded_partitions: Optional[np.ndarray] = None  # bool[P]
    #: these brokers may not *receive leadership*
    excluded_brokers_for_leadership: Optional[np.ndarray] = None  # bool[B]
    #: these brokers may not *receive replicas*
    excluded_brokers_for_replica_move: Optional[np.ndarray] = None  # bool[B]
    #: if set, only these brokers are valid destinations (add_broker mode)
    requested_destination_brokers: Optional[np.ndarray] = None  # bool[B]
    #: self-healing mode: only move replicas that sit on dead brokers
    only_move_immigrants: bool = False
    #: triggered by the goal-violation detector (relaxes distribution margins)
    is_triggered_by_goal_violation: bool = False
    #: regex over topic names; matching topics' partitions may not move
    #: (resolved against the model by resolve_options)
    excluded_topic_pattern: Optional[str] = None
    #: broker ids that are the only valid destinations (resolved to the
    #: requested_destination_brokers mask by resolve_options)
    destination_broker_ids: Optional[tuple] = None


def resolve_options(
    options: OptimizationOptions, model, topic_names=None
) -> OptimizationOptions:
    """Materialize symbolic fields into masks for this model's axes."""
    out = options
    if options.excluded_topic_pattern is not None:
        if topic_names is None:
            raise ValueError(
                "excluded_topic_pattern requires topic names (monitor-built model)"
            )
        import re

        rx = re.compile(options.excluded_topic_pattern)
        topic_ids = np.asarray(model.topic_id)
        excluded_topics = np.array(
            [bool(rx.fullmatch(name)) for name in topic_names], dtype=bool
        )
        mask = excluded_topics[topic_ids]
        if options.excluded_partitions is not None:
            mask = mask | np.asarray(options.excluded_partitions, dtype=bool)
        out = dataclasses.replace(out, excluded_partitions=mask, excluded_topic_pattern=None)
    if options.destination_broker_ids is not None:
        bad = [
            b for b in options.destination_broker_ids
            if b < 0 or b >= model.num_brokers
        ]
        if bad:
            raise ValueError(
                f"destination_broker_ids out of range [0, {model.num_brokers}): {bad}"
            )
        dst = np.zeros(model.num_brokers, dtype=bool)
        dst[list(options.destination_broker_ids)] = True
        if out.requested_destination_brokers is not None:
            dst = dst & np.asarray(out.requested_destination_brokers, dtype=bool)
        out = dataclasses.replace(
            out, requested_destination_brokers=dst, destination_broker_ids=None
        )
    return out


class StaticCtx(NamedTuple):
    """Trace-time-constant arrays + python ints for an optimization run."""

    part_load: jax.Array  # f32[P, M]
    topic_id: jax.Array  # i32[P]
    broker_capacity: jax.Array  # f32[B, 4]
    capacity_limit: jax.Array  # f32[B, 4] capacity * capacity.threshold
    broker_rack: jax.Array  # i32[B]
    broker_host: jax.Array  # i32[B]
    broker_state: jax.Array  # i32[B]
    alive: jax.Array  # bool[B]
    dead: jax.Array  # bool[B]
    new: jax.Array  # bool[B]
    demoted: jax.Array  # bool[B]
    #: brokers eligible to receive a replica: alive & not excluded & dst filter
    replica_dst_ok: jax.Array  # bool[B]
    #: brokers eligible to receive leadership
    leadership_dst_ok: jax.Array  # bool[B]
    #: partitions whose replicas may move
    movable_partition: jax.Array  # bool[P]
    host_cpu_capacity_limit: jax.Array  # f32[H]
    #: REAL brokers (False = shape-bucket padding). Padding brokers are
    #: neither `alive` nor `dead` — invisible to every goal window, never a
    #: destination, never an evacuation source (docs/OPTIMIZER.md mask
    #: invariants).
    broker_valid: jax.Array  # bool[B]
    #: count of REAL partitions (shape-bucket padding excluded) — the
    #: denominator for any per-partition mean (a padded axis length would
    #: drift with the bucket and change results vs the exact shape)
    num_valid_partitions: jax.Array  # f32[]
    # constraint thresholds (from BalancingConstraint)
    resource_balance_pct: jax.Array  # f32[4]
    low_utilization_threshold: jax.Array  # f32[4]
    replica_balance_pct: jax.Array  # f32[]
    leader_replica_balance_pct: jax.Array  # f32[]
    topic_replica_balance_pct: jax.Array  # f32[]
    max_replicas_per_broker: jax.Array  # i32[]
    only_move_immigrants: jax.Array  # bool[]


class Aggregates(NamedTuple):
    """Mutable (functionally-updated) summaries; pytree carried through scans."""

    assignment: jax.Array  # i32[P, R]
    broker_load: jax.Array  # f32[B, 4]
    replica_count: jax.Array  # i32[B]
    leader_count: jax.Array  # i32[B]
    potential_nw_out: jax.Array  # f32[B]
    leader_nw_in: jax.Array  # f32[B]
    rack_replica_count: jax.Array  # i32[P, NR] replicas of p on each rack
    topic_replica_count: jax.Array  # i32[T, B]
    host_cpu_load: jax.Array  # f32[H]
    #: provenance attribution: packed (round, wave) tag of the last accepted
    #: action that wrote each assignment cell (`make_touch_tag`; -1 = never
    #: touched this run). Rides every apply alongside the assignment writes —
    #: never read inside a kernel, fetched once per run by the MoveLedger
    #: (analyzer/provenance.py) at the existing span boundaries.
    touch_tag: jax.Array  # i32[P, R]


#: touch-tag packing width: `tag = round * TAG_WAVE_BASE + wave`. apply-wave
#: budgets are <= 16 everywhere, and rounds <= rounds_ceiling (8192), so the
#: packed value stays far inside i32.
TAG_WAVE_BASE = 1024


def make_touch_tag(rnd, wave):
    """i32 scalar: packed (round, wave) provenance tag for an apply site."""
    return jnp.int32(rnd) * jnp.int32(TAG_WAVE_BASE) + jnp.int32(wave)


@dataclasses.dataclass(frozen=True)
class Dims:
    """Static (python int) problem dimensions, fixed at trace time."""

    num_partitions: int
    max_rf: int
    num_brokers: int
    num_racks: int
    num_hosts: int
    num_topics: int


def dims_of(model: FlatClusterModel) -> Dims:
    rack = np.asarray(model.broker_rack)
    host = np.asarray(model.broker_host)
    topic = np.asarray(model.topic_id)
    return Dims(
        num_partitions=model.num_partitions,
        max_rf=model.max_replication_factor,
        num_brokers=model.num_brokers,
        num_racks=int(rack.max()) + 1 if rack.size else 0,
        num_hosts=int(host.max()) + 1 if host.size else 0,
        num_topics=int(topic.max()) + 1 if topic.size else 0,
    )


def build_static_ctx(
    model: FlatClusterModel,
    constraint: BalancingConstraint,
    dims: Dims,
    options: OptimizationOptions = OptimizationOptions(),
    valid_brokers: Optional[int] = None,
    valid_partitions: Optional[int] = None,
) -> StaticCtx:
    """`valid_brokers`/`valid_partitions`: count of REAL rows when the model
    was padded to a shape bucket (padding is appended, so a prefix count
    suffices); None = every row is real (unpadded models)."""
    b = dims.num_brokers
    state = jnp.asarray(model.broker_state)
    valid = jnp.arange(b) < (b if valid_brokers is None else valid_brokers)
    # padding brokers are neither alive nor dead: every goal window averages
    # over `alive`, and evacuation/self-healing triggers on `dead` — a
    # padded broker must never enter either set
    alive = (state != BrokerState.DEAD) & valid
    demoted = (state == BrokerState.DEMOTED) & valid

    def mask_or(arr, default):
        if arr is None:
            return jnp.full((b,), default)
        return jnp.asarray(arr, dtype=bool)

    replica_dst_ok = alive & ~mask_or(options.excluded_brokers_for_replica_move, False)
    if options.requested_destination_brokers is not None:
        replica_dst_ok = replica_dst_ok & jnp.asarray(
            options.requested_destination_brokers, dtype=bool
        )
    leadership_dst_ok = alive & ~demoted & ~mask_or(
        options.excluded_brokers_for_leadership, False
    )

    if options.excluded_partitions is None:
        movable = jnp.ones((dims.num_partitions,), dtype=bool)
    else:
        movable = ~jnp.asarray(options.excluded_partitions, dtype=bool)

    effective = constraint
    if options.is_triggered_by_goal_violation:
        effective = constraint.with_multiplier_applied()

    capacity = jnp.asarray(model.broker_capacity)
    cap_threshold = jnp.asarray(effective.capacity_threshold)
    capacity_limit = capacity * cap_threshold[None, :]
    # CPU capacity is host-level (cc/common/Resource.java:18): a host's limit is
    # the sum of its brokers' CPU capacities times the CPU threshold.
    host_cpu_cap = jax.ops.segment_sum(
        capacity[:, Resource.CPU], jnp.asarray(model.broker_host), num_segments=dims.num_hosts
    )
    return StaticCtx(
        part_load=jnp.asarray(model.part_load),
        topic_id=jnp.asarray(model.topic_id),
        broker_capacity=capacity,
        capacity_limit=capacity_limit,
        broker_rack=jnp.asarray(model.broker_rack),
        broker_host=jnp.asarray(model.broker_host),
        broker_state=state,
        alive=alive,
        dead=(state == BrokerState.DEAD) & valid,
        new=(state == BrokerState.NEW) & valid,
        demoted=demoted,
        replica_dst_ok=replica_dst_ok,
        leadership_dst_ok=leadership_dst_ok,
        movable_partition=movable,
        host_cpu_capacity_limit=host_cpu_cap * cap_threshold[Resource.CPU],
        broker_valid=valid,
        num_valid_partitions=jnp.float32(
            dims.num_partitions if valid_partitions is None else valid_partitions
        ),
        resource_balance_pct=jnp.asarray(effective.resource_balance_percentage),
        low_utilization_threshold=jnp.asarray(effective.low_utilization_threshold),
        replica_balance_pct=jnp.float32(effective.replica_balance_percentage),
        leader_replica_balance_pct=jnp.float32(effective.leader_replica_balance_percentage),
        topic_replica_balance_pct=jnp.float32(effective.topic_replica_balance_percentage),
        max_replicas_per_broker=jnp.int32(effective.max_replicas_per_broker),
        only_move_immigrants=jnp.asarray(options.only_move_immigrants),
    )


def compute_aggregates(static: StaticCtx, assignment: jax.Array, dims: Dims) -> Aggregates:
    """Full recompute of all aggregates via segment-sums (round boundaries)."""
    p, r = assignment.shape
    b = dims.num_brokers
    valid = assignment >= 0
    seg = jnp.where(valid, assignment, b).reshape(p * r)

    pl = static.part_load
    lead_vec = jnp.stack(
        [
            pl[:, PartMetric.CPU_LEADER],
            pl[:, PartMetric.NW_IN_LEADER],
            pl[:, PartMetric.NW_OUT_LEADER],
            pl[:, PartMetric.DISK],
        ],
        axis=-1,
    )
    foll_vec = jnp.stack(
        [
            pl[:, PartMetric.CPU_FOLLOWER],
            pl[:, PartMetric.NW_IN_FOLLOWER],
            jnp.zeros_like(pl[:, 0]),
            pl[:, PartMetric.DISK],
        ],
        axis=-1,
    )
    is_leader = (jnp.arange(r) == 0)[None, :, None]
    contrib = jnp.where(is_leader, lead_vec[:, None, :], foll_vec[:, None, :])
    broker_load = jax.ops.segment_sum(contrib.reshape(p * r, 4), seg, num_segments=b + 1)[:b]

    ones = jnp.ones((p * r,), dtype=jnp.int32)
    replica_count = jax.ops.segment_sum(ones, seg, num_segments=b + 1)[:b]

    leader_seg = jnp.where(assignment[:, 0] >= 0, assignment[:, 0], b)
    leader_count = jax.ops.segment_sum(
        jnp.ones((p,), dtype=jnp.int32), leader_seg, num_segments=b + 1
    )[:b]
    leader_nw_in = jax.ops.segment_sum(
        pl[:, PartMetric.NW_IN_LEADER], leader_seg, num_segments=b + 1
    )[:b]

    pnw_contrib = jnp.broadcast_to(pl[:, PartMetric.NW_OUT_LEADER, None], (p, r)).reshape(p * r)
    potential = jax.ops.segment_sum(pnw_contrib, seg, num_segments=b + 1)[:b]

    # replicas of partition p per rack: scatter-add into [P, NR+1]
    nr = dims.num_racks
    rack_of = jnp.where(valid, static.broker_rack[jnp.where(valid, assignment, 0)], nr)
    p_idx = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32)[:, None], (p, r))
    rack_flat = (p_idx * (nr + 1) + rack_of).reshape(p * r)
    rack_replica_count = jax.ops.segment_sum(
        ones, rack_flat, num_segments=p * (nr + 1)
    ).reshape(p, nr + 1)[:, :nr]

    t = dims.num_topics
    topic = jnp.broadcast_to(static.topic_id[:, None], (p, r))
    topic_flat = (topic * (b + 1) + jnp.where(valid, assignment, b)).reshape(p * r)
    topic_replica_count = jax.ops.segment_sum(
        ones, topic_flat, num_segments=t * (b + 1)
    ).reshape(t, b + 1)[:, :b]

    host_cpu = jax.ops.segment_sum(
        broker_load[:, Resource.CPU], static.broker_host, num_segments=dims.num_hosts
    )
    return Aggregates(
        assignment=assignment,
        broker_load=broker_load,
        replica_count=replica_count,
        leader_count=leader_count,
        potential_nw_out=potential,
        leader_nw_in=leader_nw_in,
        rack_replica_count=rack_replica_count,
        topic_replica_count=topic_replica_count,
        host_cpu_load=host_cpu,
        touch_tag=jnp.full((p, r), -1, dtype=jnp.int32),
    )


def apply_action(static: StaticCtx, agg: Aggregates, act: ActionBatch, apply_flag) -> Aggregates:
    """Apply ONE action (scalar fields in `act`) to the aggregates.

    Used inside the optimizer's sequential re-validated scan. `apply_flag` is a
    traced bool; when False the update is the identity (masked no-op, keeping
    the scan shape-static). Covers both action kinds with `where` masks — the
    incremental counterpart of compute_aggregates.
    """
    is_move = act.kind == KIND_MOVE
    p, slot, src, dst = act.p, act.slot, act.src, act.dst
    w = apply_flag

    # assignment: move sets (p, slot) = dst; leadership swaps slots 0 and slot.
    a = agg.assignment
    move_a = a.at[p, slot].set(jnp.where(w, dst, a[p, slot]))
    old_leader = a[p, 0]
    lead_a = a.at[p, 0].set(jnp.where(w, a[p, slot], a[p, 0]))
    lead_a = lead_a.at[p, slot].set(jnp.where(w, old_leader, lead_a[p, slot]))
    new_assignment = jnp.where(is_move, move_a, lead_a)

    dload = act.dload * jnp.where(w, 1.0, 0.0)
    broker_load = agg.broker_load.at[src].add(-dload).at[dst].add(dload)

    dint = jnp.where(w, 1, 0)
    drep = act.drep * dint
    replica_count = agg.replica_count.at[src].add(-drep).at[dst].add(drep)
    dlead = act.dleader * dint
    leader_count = agg.leader_count.at[src].add(-dlead).at[dst].add(dlead)

    dpnw = act.dpnw * jnp.where(w, 1.0, 0.0)
    potential = agg.potential_nw_out.at[src].add(-dpnw).at[dst].add(dpnw)
    dlnw = act.dleader_nw_in * jnp.where(w, 1.0, 0.0)
    leader_nw_in = agg.leader_nw_in.at[src].add(-dlnw).at[dst].add(dlnw)

    # rack / topic counts only change for replica moves
    dmove = jnp.where(w & is_move, 1, 0)
    rack_src = static.broker_rack[src]
    rack_dst = static.broker_rack[dst]
    rack_counts = (
        agg.rack_replica_count.at[p, rack_src].add(-dmove).at[p, rack_dst].add(dmove)
    )
    topic = static.topic_id[p]
    topic_counts = (
        agg.topic_replica_count.at[topic, src].add(-dmove).at[topic, dst].add(dmove)
    )

    dcpu = dload[..., Resource.CPU]
    host_cpu = (
        agg.host_cpu_load.at[static.broker_host[src]]
        .add(-dcpu)
        .at[static.broker_host[dst]]
        .add(dcpu)
    )
    p_total = agg.assignment.shape[0]
    pw = jnp.where(w, p, p_total)
    pl = jnp.where(w & ~is_move, p, p_total)
    touch = agg.touch_tag.at[pw, slot].set(jnp.int32(-1), mode="drop")
    touch = touch.at[pl, jnp.zeros_like(slot)].set(jnp.int32(-1), mode="drop")
    return Aggregates(
        assignment=new_assignment,
        broker_load=broker_load,
        replica_count=replica_count,
        leader_count=leader_count,
        potential_nw_out=potential,
        leader_nw_in=leader_nw_in,
        rack_replica_count=rack_counts,
        topic_replica_count=topic_counts,
        host_cpu_load=host_cpu,
        touch_tag=touch,
    )


def wave_select(score, src, dst, dst_host, valid, num_brokers: int, num_hosts: int,
                dst_host2=None, parts=(), num_partitions: int = 0,
                brokers3=None):
    """bool[N]: a conflict-free, score-prioritized subset of candidate actions.

    Contract: among selected entries, every broker appears in at most ONE
    action (either endpoint), every destination HOST receives at most one
    action, and — when `parts` carries the entries' partition ids — every
    PARTITION appears in at most one action. Under that disjointness a wave
    of individually-validated actions composes exactly like sequential
    application (no shared aggregate is touched twice, no per-partition rack
    count is double-spent), including the host-level CPU capacity check —
    this is what lets the optimizer apply a whole shortlist in O(waves)
    sequential steps instead of O(batch_k).

    `parts` is a tuple of i32[N] arrays (a swap touches two partitions, so it
    passes both); callers whose candidate sets are per-partition by
    construction (the optimizer's top-k-over-partitions shortlist) may omit
    it. Selection: an entry survives iff it holds the max score on BOTH its
    brokers (ties broken by lowest index), then at most one survivor per
    destination host and per partition. Chains (A beats B on a shared broker,
    B beats C) can under-select; later waves retry the losers against updated
    state.
    """
    n = score.shape[0]
    s = jnp.where(valid, score, -jnp.inf)
    src_c = jnp.where(valid, src, num_brokers)
    dst_c = jnp.where(valid, dst, num_brokers)
    gmax = jnp.full((num_brokers + 1,), -jnp.inf).at[src_c].max(s).at[dst_c].max(s)
    cand = valid & (s >= gmax[src_c]) & (s >= gmax[dst_c])
    idx = jnp.arange(n, dtype=jnp.int32)
    big = jnp.int32(n + 1)
    idx_c = jnp.where(cand, idx, big)
    imin = jnp.full((num_brokers + 1,), big).at[src_c].min(idx_c).at[dst_c].min(idx_c)
    sel = cand & (idx == imin[src_c]) & (idx == imin[dst_c])
    def unique_per_group(sel, claim_arrays, n_groups):
        """Keep, per group id, only the best-scoring selected entry (ties by
        lowest index) — over the UNION of the claim arrays (an entry must win
        every group it claims, so A's first claim conflicts with B's
        second). Score-priority keeps the selector's invariant that the
        globally best valid action always survives every filtering stage."""
        claims = [jnp.where(sel, c, n_groups) for c in claim_arrays]
        s_sel = jnp.where(sel, s, -jnp.inf)
        smax = jnp.full((n_groups + 1,), -jnp.inf)
        for c in claims:
            smax = smax.at[c].max(s_sel)
        c_and = sel
        for c in claims:
            c_and = c_and & (s_sel >= smax[c])
        idx_s = jnp.where(c_and, idx, big)
        cmin = jnp.full((n_groups + 1,), big)
        for c in claims:
            cmin = cmin.at[c].min(idx_s)
        for c in claims:
            sel = c_and & (idx == cmin[c])
            c_and = sel
        return sel

    # a THIRD broker endpoint (leadership relays touch b, d and e): enforce
    # the same per-broker uniqueness over all three claim arrays
    if brokers3 is not None:
        b3_c = jnp.where(valid, brokers3, num_brokers)
        sel = unique_per_group(sel, [src_c, dst_c, b3_c], num_brokers)
    # at most one action lands per destination host per wave (swaps load both
    # ends, so they pass both endpoint hosts)
    hosts = [h for h in (dst_host, dst_host2) if h is not None]
    if hosts:
        sel = unique_per_group(sel, hosts, num_hosts)
    # at most one action per partition per wave: two replicas of the same
    # partition moving in one wave would each pass a rack check that is
    # jointly wrong (both landing on the same rack) and would race their
    # assignment-row writes
    if parts:
        sel = unique_per_group(sel, list(parts), num_partitions)
    return sel


def rank_paired_destinations(valid_src, dst_key, offset) -> jax.Array:
    """i32[B]: pair the i-th valid source broker (by broker id) with the
    (i + offset)-th-best destination by `dst_key`, wrapping over the feasible
    prefix.

    The sorted-by-sorted matching the optimizer's shortlist waves use,
    generalized to broker-wide source sets (the bulk count planner,
    analyzer.bulk): a per-source argmax would send every source to the same
    best destination, and the waves' broker-disjointness would then admit ONE
    action per wave. Rank pairing keeps the whole surplus set moving in
    parallel; rotating `offset` across waves retries failed pairs against
    different destinations, and exact re-validation drops any mispair.
    `dst_key`: higher = better, -inf = ineligible (an all-ineligible key
    degrades to broker rank[0] and every nomination fails validation).
    """
    rank = jnp.argsort(-dst_key).astype(jnp.int32)
    n_feasible = jnp.maximum(
        jnp.sum(jnp.isfinite(dst_key)).astype(jnp.int32), 1
    )
    rr = jnp.cumsum(valid_src.astype(jnp.int32)) - 1
    return rank[(rr + offset) % n_feasible]


def apply_actions_batch(
    static: StaticCtx, agg: Aggregates, act: ActionBatch, flags: jax.Array,
    tag=None,
) -> Aggregates:
    """Apply a WAVE of actions (1-D fields in `act`, `flags: bool[N]`) at once.

    Correct when the flagged actions are pairwise conflict-free — distinct
    partitions and distinct src/dst brokers (wave_select's contract, above):
    applying them together then equals applying them
    sequentially in any order, with each individually valid at its turn —
    i.e. a batch of reference-legal greedy steps, not an approximation.
    Scatter-adds are duplicate-safe regardless; only the per-action
    *validation* relies on disjointness.

    `tag`: optional i32 scalar provenance tag (`make_touch_tag(rnd, wave)`)
    scattered into `touch_tag` for exactly the cells this wave writes; it
    never feeds back into any decision, so results are tag-invariant.
    """
    p_total = agg.assignment.shape[0]
    is_move = act.kind == KIND_MOVE
    p, slot, src, dst = act.p, act.slot, act.src, act.dst
    w = flags
    a = agg.assignment

    # (p, slot) receives: dst for moves, the old leader for leadership swaps;
    # (p, 0) additionally receives the old slot-holder for leadership swaps.
    # Masked-out writes are routed out of bounds and dropped, so a move into
    # slot 0 never races a leadership write to the same element.
    old_leader = a[p, 0]
    old_holder = a[p, slot]
    val_slot = jnp.where(is_move, dst, old_leader)
    p_any = jnp.where(w, p, p_total)
    p_lead = jnp.where(w & ~is_move, p, p_total)
    new_assignment = a.at[p_any, slot].set(val_slot, mode="drop")
    new_assignment = new_assignment.at[p_lead, jnp.zeros_like(slot)].set(
        old_holder, mode="drop"
    )

    wf = jnp.where(w, 1.0, 0.0)
    dload = act.dload * wf[..., None]
    broker_load = agg.broker_load.at[src].add(-dload).at[dst].add(dload)

    dint = jnp.where(w, 1, 0)
    drep = act.drep * dint
    replica_count = agg.replica_count.at[src].add(-drep).at[dst].add(drep)
    dlead = act.dleader * dint
    leader_count = agg.leader_count.at[src].add(-dlead).at[dst].add(dlead)

    dpnw = act.dpnw * wf
    potential = agg.potential_nw_out.at[src].add(-dpnw).at[dst].add(dpnw)
    dlnw = act.dleader_nw_in * wf
    leader_nw_in = agg.leader_nw_in.at[src].add(-dlnw).at[dst].add(dlnw)

    dmove = jnp.where(w & is_move, 1, 0)
    rack_src = static.broker_rack[src]
    rack_dst = static.broker_rack[dst]
    rack_counts = (
        agg.rack_replica_count.at[p, rack_src].add(-dmove).at[p, rack_dst].add(dmove)
    )
    topic = static.topic_id[p]
    topic_counts = (
        agg.topic_replica_count.at[topic, src].add(-dmove).at[topic, dst].add(dmove)
    )

    dcpu = dload[..., Resource.CPU]
    host_cpu = (
        agg.host_cpu_load.at[static.broker_host[src]]
        .add(-dcpu)
        .at[static.broker_host[dst]]
        .add(dcpu)
    )
    # provenance: stamp the tag into exactly the cells written above (the
    # same routed indices, so masked-out entries drop identically)
    t = jnp.int32(-1) if tag is None else jnp.int32(tag)
    touch = agg.touch_tag.at[p_any, slot].set(t, mode="drop")
    touch = touch.at[p_lead, jnp.zeros_like(slot)].set(t, mode="drop")
    return Aggregates(
        assignment=new_assignment,
        broker_load=broker_load,
        replica_count=replica_count,
        leader_count=leader_count,
        potential_nw_out=potential,
        leader_nw_in=leader_nw_in,
        rack_replica_count=rack_counts,
        topic_replica_count=topic_counts,
        host_cpu_load=host_cpu,
        touch_tag=touch,
    )


def utilization(agg: Aggregates, static: StaticCtx) -> jax.Array:
    """f32[B, 4] load / capacity."""
    return agg.broker_load / jnp.maximum(static.broker_capacity, 1e-9)


def replicas_on_dead(static: StaticCtx, assignment: jax.Array) -> jax.Array:
    """bool[P, R]: slots whose replica currently sits on a dead broker.

    Unassigned slots (-1) are clamped to broker 0 for the gather and masked
    back out — the one shared home for this subtle idiom (evacuation checks
    in the drain engine and the goal loop's convergence test)."""
    valid = assignment >= 0
    return static.dead[jnp.where(valid, assignment, 0)] & valid


def dst_hosts_partition(agg: Aggregates, p, dst) -> jax.Array:
    """bool[...]: does dst already host a replica of p (any slot)?

    The dense form of GoalUtils.legitMove's "destination must not contain the
    partition" check (cc/analyzer/goals/GoalUtils.java).
    """
    row = agg.assignment[p]  # [..., R]
    return jnp.any(row == dst[..., None], axis=-1)
