"""Generalized drain/fill round: the batched-mode engine for every goal.

This is the TPU-native form of the reference's actual greedy structure —
AbstractGoal.optimize walks brokersToBalance and calls rebalanceForBroker,
which drains/fills ONE broker via its SortedReplicas views
(cc/analyzer/goals/AbstractGoal.java:80-85, cc/model/SortedReplicas.java:50).
Vectorized: per round, the top-V source brokers each nominate their top-K
drain candidates toward C goal-chosen destinations, the [V, K, C] grid is
scored exactly (structural + merged prior-goal tables + this goal), and
conflict-free waves apply a broker-disjoint subset per wave.

Why this shape: per-round cost scales with the VIOLATED SET (V, K, C are
hundreds), not with the partition count. The previous engine re-scored a
[P, R, K] grid every round — ~10M candidate actions × ~30 gathered aggregates
at north-star scale (2,600 brokers / 200k partitions), ~0.9 s/round on a TPU
where the useful decisions are all broker-level. Profiled hot spots replaced
here:

  per-broker candidate lists   ONE shared [P*R] variadic sort per round
                               (broker asc, drain priority desc) + run
                               offsets, instead of V vmapped top_k calls over
                               [P*R] each (cc/model/SortedReplicas.java kept
                               these incrementally; a single device sort is
                               the batch equivalent)
  candidate actions            [V, K, C] + leadership [V, K, R-1] grids
                               (~300k actions) instead of [P, R, K] (~10M)
  destinations                 goal-aware: each candidate replica gets
                               destinations chosen FOR IT (e.g. the
                               under-count brokers of ITS topic), so wave
                               nominations mostly validate instead of mostly
                               failing against topic-blind global rankings

Greedy parity mode (batch_k=1) does NOT use this engine for non-swap goals —
it keeps the exhaustive [P, R, K] + full-destination-scan path
(optimizer._make_goal_loop.one_round), which is the stronger-than-reference
baseline the bench gates against.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.actions import (
    KIND_LEADERSHIP,
    KIND_MOVE,
    build_selected,
)
from cruise_control_tpu.analyzer.acceptance import band_move_acceptance, score_batch
from cruise_control_tpu.analyzer.context import (
    Aggregates,
    StaticCtx,
    apply_actions_batch,
    make_touch_tag,
    wave_select,
)


def round_jitter(n: int, rnd) -> jax.Array:
    """f32[n] in [0.5, 1): round-seeded multiplicative jitter for candidate
    rankings. Walking the ranking across rounds keeps a uniformly-infeasible
    top-K from starving a goal — candidate ORDER is free because every
    nomination is exactly re-validated before applying. The constants form
    one coupled recipe shared by every rotated selection site (the goal-loop
    drain rotation and the leadership-swap candidate picks must stay in the
    same family so their slices interleave, not collide)."""
    ids = jnp.arange(n, dtype=jnp.uint32)
    h = (ids + jnp.asarray(rnd).astype(jnp.uint32) * jnp.uint32(40503)) * jnp.uint32(
        2654435761
    )
    return 0.5 + 0.5 * (h >> 8).astype(jnp.float32) / float(1 << 24)


def broker_top_replicas(static: StaticCtx, agg: Aggregates, contrib: jax.Array,
                        k: int, num_brokers: int, heaviest: bool = True):
    """(p, slot, valid), each [B, k]: every broker's top-k drain candidates by
    `contrib` (descending when `heaviest`, ascending otherwise).

    Sort-free: k iterative (segment_max -> segment_min-of-index) passes over
    the flat replica axis. A full (broker, contrib) sort of the 600k replica
    slots at north-star scale costs ~1s/round on CPU and tens of ms on TPU
    (XLA sorts are comparator-serial); the k segment passes are plain
    scatter/gather reductions — bandwidth-bound, a few ms — and every goal
    only ever consumes the top few candidates per broker anyway
    (SortedReplicas consumers in the reference walk the head of the view,
    cc/model/SortedReplicas.java:50).

    Excluded replicas (invalid slot, immovable partition, -inf/NaN contrib)
    never surface; `valid` is False where a broker has fewer than k eligible
    replicas.
    """
    p_count, r = agg.assignment.shape
    n = p_count * r
    movable = static.movable_partition[:, None] & (agg.assignment >= 0)
    included = movable & jnp.isfinite(contrib)
    seg = jnp.where(included, agg.assignment, num_brokers).reshape(n)
    val = jnp.where(included, contrib if heaviest else -contrib, -jnp.inf)
    val = val.reshape(n)
    pos = jnp.arange(n, dtype=jnp.int32)
    taken = jnp.zeros((n,), dtype=bool)
    ps, ss, ok = [], [], []
    for _ in range(k):
        v = jnp.where(taken, -jnp.inf, val)
        best = jax.ops.segment_max(v, seg, num_segments=num_brokers + 1)
        is_best = (v == best[seg]) & jnp.isfinite(v)
        idx_best = jax.ops.segment_min(
            jnp.where(is_best, pos, n), seg, num_segments=num_brokers + 1
        )[:num_brokers]
        found = idx_best < n
        sel = jnp.minimum(idx_best, n - 1)
        ps.append((sel // r).astype(jnp.int32))
        ss.append((sel % r).astype(jnp.int32))
        ok.append(found)
        full_idx = jnp.concatenate([idx_best, jnp.full((1,), n, jnp.int32)])
        taken = taken | (pos == full_idx[seg])
    return jnp.stack(ps, axis=1), jnp.stack(ss, axis=1), jnp.stack(ok, axis=1)


def heavy_picks(static, agg, contrib, brokers: jax.Array, k: int, num_brokers: int):
    """(p, slot, valid) [V, k]: top-k drain candidates of the given brokers."""
    p, s, ok = broker_top_replicas(static, agg, contrib, k, num_brokers, True)
    return p[brokers], s[brokers], ok[brokers]


def light_picks(static, agg, contrib, brokers: jax.Array, k: int, num_brokers: int):
    """(p, slot, valid) [V, k]: the k lightest candidates of the given brokers."""
    p, s, ok = broker_top_replicas(static, agg, contrib, k, num_brokers, False)
    return p[brokers], s[brokers], ok[brokers]


def table_demoted_pref(static: StaticCtx, gs, agg: Aggregates, goal, tables):
    """f32[B]: the goal's destination preference, -inf for ineligible brokers,
    with table-infeasible brokers demoted below every feasible one.

    Demoted, not excluded — if a whole rack is saturated its least-bad broker
    still represents it: a goal's own preference (e.g. NW_IN-lightest) is
    blind to earlier goals' bounds, and in tight regimes the preferred broker
    is often table-infeasible while a feasible one sits next to it."""
    pref = goal.dst_preference(static, gs, agg)
    pref = jnp.where(static.replica_dst_ok, pref, -jnp.inf)
    if tables is not None:
        headroom = (
            jnp.all(agg.broker_load < tables.hi_load, axis=1)
            & (agg.replica_count < tables.hi_rep)
            & (agg.potential_nw_out < tables.hi_pnw)
            & (agg.leader_nw_in < tables.hi_lnw)
        )
        span = 1.0 + jnp.max(jnp.abs(jnp.where(jnp.isfinite(pref), pref, 0.0)))
        pref = jnp.where(headroom, pref, pref - 2.0 * span)
    return pref


def rack_diverse_cold(static: StaticCtx, gs, agg: Aggregates, goal, tables,
                      dims, c: int) -> jax.Array:
    """i32[C]: global destination list — the best eligible broker of each
    NON-EMPTY rack first (so RackAwareGoal always finds an eligible rack),
    then the globally best-preferred brokers (duplicates are harmless; the
    waves' disjointness keeps at most one action per broker anyway).

    One combined top-k over [rack-best entries (boosted), all brokers]
    instead of separate per-rack and global passes: the list CONTENT is then
    independent of how many EMPTY racks the rack axis carries — a padded
    rack (shape bucketing) contributes a -inf entry that sorts after every
    real broker, so bucketed and exact runs nominate identical destinations
    (the padding-equivalence contract, docs/OPTIMIZER.md)."""
    pref = table_demoted_pref(static, gs, agg, goal, tables)
    nr = dims.num_racks
    rack_mask = static.broker_rack[None, :] == jnp.arange(nr)[:, None]  # [NR, B]
    per_rack = jnp.where(rack_mask, pref[None, :], -jnp.inf)
    best_broker = jnp.argmax(per_rack, axis=1).astype(jnp.int32)  # [NR]
    best_val = jnp.max(per_rack, axis=1)
    # rack representatives outrank every plain broker entry; empty racks
    # stay at -inf and lose to every real broker
    span = 2.0 + jnp.max(jnp.abs(jnp.where(jnp.isfinite(pref), pref, 0.0)))
    combined = jnp.concatenate(
        [jnp.where(jnp.isfinite(best_val), best_val + 2.0 * span, -jnp.inf), pref]
    )
    _, idx = jax.lax.top_k(combined, min(c, nr + pref.shape[0]))
    idx = idx.astype(jnp.int32)
    return jnp.where(idx < nr, best_broker[jnp.minimum(idx, nr - 1)], idx - nr)


def select_surplus_pairs(static: StaticCtx, agg: Aggregates, tables, gs,
                         rnd, v: int, t_count: int, b_count: int):
    """(pair_t, pair_b, pair_ok), each [V]: one (topic, broker) surplus pair
    per source broker — the broker's worst over-topic — for the top-V
    brokers, shared by the topic pair-drain and topic-swap rounds.

    Dead brokers: every (topic, broker) group with replicas is a
    maximal-surplus pair — evacuation precedes balance
    (GoalUtils.ensureNoReplicaOnDeadBrokers), and score_batch's evacuation
    bonus makes those moves win regardless of topic math. Tie-breaks are
    round-rotated: surplus is almost always exactly 1, so a fixed order
    would retry the same (possibly band-blocked) pairs every round while
    thousands behind them go untried. A mobility proxy ranks brokers that
    can shed an average-sized replica without breaking a contributed lower
    bound above band-frozen ones (which still surface once the mobile set
    drains)."""
    excess = agg.topic_replica_count.astype(jnp.float32) - gs.upper[:, None]
    excess = jnp.where(
        static.alive[None, :],
        excess,
        jnp.where(agg.topic_replica_count > 0, jnp.float32(1e9), -jnp.inf),
    )
    t_ids = jnp.arange(t_count, dtype=jnp.int32)
    rot_t = (((t_ids + rnd * 7919) * 131) % 104729).astype(jnp.float32) / 104729.0
    key_tb = jnp.where(
        jnp.isfinite(excess), excess + 1e-3 * rot_t[:, None], -jnp.inf
    )
    best_t = jnp.argmax(key_tb, axis=0).astype(jnp.int32)  # [B]
    b_ids = jnp.arange(b_count, dtype=jnp.int32)
    best_val = excess[best_t, b_ids]
    rot_b = (((b_ids + rnd * 104729) * 257) % 7919).astype(jnp.float32) / 7919.0
    typ = jnp.sum(agg.broker_load, axis=0) / jnp.maximum(
        1.0, jnp.sum(agg.replica_count).astype(jnp.float32)
    )  # f32[4] mean per-replica load
    lo_margin = agg.broker_load - tables.band_lo
    mobile = jnp.all(
        ~tables.band_on[None, :] | (lo_margin >= 0.5 * typ[None, :]), axis=1
    )
    mobile = mobile & (
        agg.replica_count.astype(jnp.float32) - 1.0 >= tables.lo_rep
    )
    brk_key = jnp.where(
        jnp.isfinite(best_val) & (best_val > 0.0),
        best_val + jnp.where(mobile, 1e3, 0.0) + 1e-3 * rot_b, -jnp.inf,
    )
    _, hot_b = jax.lax.top_k(brk_key, v)
    pair_b = hot_b.astype(jnp.int32)
    pair_t = best_t[pair_b]
    vals = excess[pair_t, pair_b]
    return pair_t, pair_b, jnp.isfinite(vals) & (vals > 0.0)


def pair_replica_picks(static: StaticCtx, agg: Aggregates, pair_t, pair_b,
                       k: int, t_count: int, b_count: int):
    """(cand_p, cand_s, found) [V, k]: the first k movable replicas of each
    (topic, broker) pair, via iterated segment-min of flat position over
    group ids (sort-free)."""
    p_count, r = agg.assignment.shape
    n = p_count * r
    n_groups = t_count * b_count
    pair_idx = pair_t * b_count + pair_b
    movable = static.movable_partition[:, None] & (agg.assignment >= 0)
    group = static.topic_id[:, None] * b_count + jnp.where(
        movable, agg.assignment, 0
    )
    seg = jnp.where(movable, group, n_groups).reshape(n)
    pos = jnp.arange(n, dtype=jnp.int32)
    excluded = jnp.zeros((n,), dtype=bool)
    cols = []
    for _ in range(k):
        mth = jax.ops.segment_min(
            jnp.where(excluded, n, pos), seg, num_segments=n_groups + 1
        )
        cols.append(mth[pair_idx])
        excluded = excluded | (pos == mth[seg])
    picks = jnp.stack(cols, axis=1)  # [V, k]
    found = picks < n
    sel = jnp.minimum(picks, n - 1)
    return (sel // r).astype(jnp.int32), (sel % r).astype(jnp.int32), found


def topic_dst_list(static: StaticCtx, agg: Aggregates, tables, gs,
                   pair_t, pair_b, rnd, c_dst: int, b_count: int):
    """i32[V, C]: per-pair destination candidates — brokers that are BOTH
    under-count for the pair's topic AND have load-band headroom (the prior
    usage goals' bands are what actually veto most destinations; a
    topic-only ranking finds under-count brokers whose bands then reject
    everything). Band headroom is a scalar proxy (mean replica load) and
    exact validation decides; the jitter is ROUND-rotated so near-tied
    candidates beyond the first C surface on later rounds instead of being
    permanently shadowed by the same top C."""
    cnt_rows = agg.topic_replica_count[pair_t].astype(jnp.float32)  # [V, B]
    topic_ok = static.replica_dst_ok[None, :] & (
        cnt_rows + 1.0 <= gs.upper[pair_t][:, None]
    )
    typ = jnp.sum(agg.broker_load, axis=0) / jnp.maximum(
        1.0, jnp.sum(agg.replica_count).astype(jnp.float32)
    )
    band_room = jnp.all(
        ~tables.band_on[None, :]
        | (agg.broker_load + 0.5 * typ[None, :] <= tables.band_hi),
        axis=1,
    )  # bool[B]
    d_pref = jnp.where(
        topic_ok,
        -cnt_rows + jnp.where(band_room, 1e3, 0.0)[None, :],
        -jnp.inf,
    )
    # per-row modular ROTATION of the tie-break ramp: near-tied rows then
    # prefer staggered destinations (a hash here lets rows collide on the
    # same broker and the waves' disjointness serializes them — measured 3-4x
    # more topic rounds at the 520-broker scale). The wrap runs over the
    # VALID broker count, not the axis length: the ramp value of a given
    # real broker must not depend on how much shape-bucket padding the axis
    # carries (padding-equivalence contract; padded brokers' d_pref is -inf,
    # so their ramp values are inert).
    n_valid = jnp.maximum(jnp.sum(static.broker_valid.astype(jnp.int32)), 1)
    b_all = jnp.arange(b_count, dtype=jnp.int32)
    jit_d = (
        (b_all[None, :] + pair_b[:, None] * 151 + rnd * 977) % n_valid
    ).astype(jnp.float32) / n_valid.astype(jnp.float32)
    _, dst_list = jax.lax.top_k(d_pref + 1e-4 * jit_d, c_dst)  # [V, C]
    return dst_list.astype(jnp.int32)


def make_pair_drain_round(goal, dims, n_pairs: int, apply_waves: int):
    """Drain round for TopicReplicaDistributionGoal, whose natural candidate
    unit is the (topic, broker) SURPLUS PAIR — the same granularity the
    reference's per-broker-per-topic loop works at
    (cc/analyzer/goals/TopicReplicaDistributionGoal.java:53).

    Per-broker replica picks starve this goal: a broker's top candidates by
    over-count are mostly replicas of the SAME over topic, only one of which
    can usefully move. Instead, per round:
      1. top-V (topic, broker) pairs by surplus (count - upper bound);
      2. a few concrete replicas per pair (iterated segment-min over
         (topic, broker) group ids — sort-free). These are ALTERNATIVES, not
         just extra surplus: the pair's replicas are different partitions
         with different loads, and typically only some fit the
         previously-optimized goals' load bands at any destination — the
         waves' exact re-scoring keeps extra candidates safe (once the pair
         is no longer over, the remaining candidates stop scoring);
      3. exact scores against a round-rotated top-C destination list per pair
         (topic_dst_list): a feasible destination must be under-count for the
         pair's topic AND inside every previously-optimized goal's bands — a
         rare intersection once the usage goals have converged. The proxy
         ranking puts band-feasible under-count brokers first, the rotation
         surfaces candidates beyond C across rounds, and the topic-SWAP
         fallback (make_topic_swap_round) escapes pairs whose every single
         move is band-frozen;
      4. waves argmax the remaining cells (blocked-cell bookkeeping), apply a
         broker/partition-disjoint subset, repeat.
    """
    p_count, r = dims.num_partitions, dims.max_rf
    t_count, b_count = dims.num_topics, dims.num_brokers
    v = max(1, min(n_pairs, b_count))  # one pair per source broker
    k = min(4, p_count)
    n = p_count * r
    n_groups = t_count * b_count

    def pair_round(static: StaticCtx, agg: Aggregates, tables, gs, contrib,
                   rnd=jnp.int32(0)):
        del contrib  # pair surplus is computed from the count table directly
        # one surplus pair per source broker (waves admit one action per
        # source per wave, so distinct sources maximize round throughput);
        # dead-broker evacuation, tie-rotation, and the mobility proxy all
        # live in the shared selector
        pair_t, pair_b, pair_ok = select_surplus_pairs(
            static, agg, tables, gs, rnd, v, t_count, b_count
        )
        cand_p, cand_s, found = pair_replica_picks(
            static, agg, pair_t, pair_b, k, t_count, b_count
        )
        cand_ok = found & pair_ok[:, None]

        c_dst = min(64, b_count)
        dst_list = topic_dst_list(
            static, agg, tables, gs, pair_t, pair_b, rnd, c_dst, b_count
        )

        # lazy broadcast shapes (see make_drain_round): gathers index
        # [V, K, 1] partitions and [V, 1, C] destinations, never the dense
        # [V, K, C] cube; comparisons broadcast
        full = (v, k, c_dst)
        acts = build_selected(
            static.part_load, agg.assignment,
            cand_p[:, :, None],
            jnp.int32(KIND_MOVE),
            cand_s[:, :, None],
            dst_list[:, None, :],
        )
        s = score_batch(static, agg, acts, goal, gs, tables)
        s = jnp.broadcast_to(jnp.where(cand_ok[:, :, None], s, -jnp.inf), full)
        # de-correlate near-tied destinations across rows: goal scores for a
        # surplus move are mostly the same value (one unit of excess fixed),
        # so a plain argmax sends every pair to the same lowest-index feasible
        # broker and the waves' broker-disjointness then admits a handful of
        # moves per wave. A deterministic per-(row, dst) jitter far below any
        # real score difference spreads the nominations; validation re-scores
        # exactly, so the jitter never changes legality.
        # the per-pair destination lists are already jittered (jit_d above),
        # so near-tied rows nominate different brokers
        rows0 = jnp.arange(v, dtype=jnp.int32)
        cells = s.reshape(v, k * c_dst)
        waves = max(1, apply_waves)

        def wave(carry, w):
            agg_c, applied_any, blocked = carry
            masked = jnp.where(blocked, -jnp.inf, cells)
            ci = jnp.argmax(masked, axis=1).astype(jnp.int32)
            bs = jnp.take_along_axis(masked, ci[:, None], axis=1)[:, 0]
            k_i = ci // c_dst
            p_i = cand_p[rows0, k_i]
            s_i = cand_s[rows0, k_i]
            dst = dst_list[rows0, ci % c_dst]
            act = build_selected(
                static.part_load, agg_c.assignment, p_i,
                jnp.full((v,), KIND_MOVE, dtype=jnp.int32), s_i, dst,
            )
            s_now = score_batch(static, agg_c, act, goal, gs, tables)
            ok = jnp.isfinite(bs) & jnp.isfinite(s_now)
            w_sel = wave_select(
                s_now, act.src, act.dst, static.broker_host[act.dst], ok,
                b_count, dims.num_hosts,
                parts=(act.p,), num_partitions=p_count,
            )
            agg_c = apply_actions_batch(
                static, agg_c, act, w_sel, tag=make_touch_tag(rnd, w)
            )
            dead = w_sel | (jnp.isfinite(bs) & ~jnp.isfinite(s_now))
            blk = blocked.at[rows0, ci].set(blocked[rows0, ci] | dead)
            # a moved replica is gone: its whole destination row dies
            cols = jnp.arange(c_dst, dtype=jnp.int32)[None, :]
            row_ids = (k_i * c_dst)[:, None] + cols
            blk = blk.at[rows0[:, None], row_ids].set(
                blk[rows0[:, None], row_ids] | w_sel[:, None]
            )
            return (agg_c, applied_any | jnp.any(w_sel), blk), None

        init = (agg, jnp.asarray(False), jnp.zeros((v, k * c_dst), dtype=bool))
        (agg2, applied_any, _), _ = jax.lax.scan(
            wave, init, jnp.arange(waves, dtype=jnp.int32)
        )
        return agg2, applied_any

    return pair_round


def make_topic_swap_round(goal, dims, n_pairs: int, d_dst: int, k_ret: int,
                          apply_waves: int):
    """Swap phase for TopicReplicaDistributionGoal, run when pair-drain moves
    stall: exchange a surplus-topic replica with a similar-load replica from
    an under-count broker.

    Why swaps: once the usage-distribution goals have converged, their
    acceptance bands freeze most single moves (a replica can neither leave
    its source without breaking a band lower bound nor land without breaking
    an upper bound), but a swap's NET load transfer is the difference of two
    replica loads — tiny when the return replica is chosen close in load —
    so the bands' net check (acceptance.swap_tables_acceptance) passes where
    every single move fails. The reference has no topic swap (its topic goal
    simply leaves these states); the parity gate only requires not being
    WORSE than the greedy, and a swap strictly reduces the topic imbalance
    without degrading any previously-optimized goal.

    Per round: the stalled surplus pairs (one per source broker, as in
    make_pair_drain_round) x top-D under-count destination brokers for the
    pair's topic x each destination's K lightest/heaviest return replicas,
    validated exactly (structural legality both legs, rack safety both ways,
    prior-goal net tables, topic-cost improvement), applied in
    endpoint-disjoint waves.
    """
    p_count, r = dims.num_partitions, dims.max_rf
    t_count, b_count = dims.num_topics, dims.num_brokers
    v = max(1, min(n_pairs, b_count))
    d_dst = max(1, min(d_dst, b_count))
    k_ret = max(1, min(k_ret, p_count))
    n = p_count * r
    n_groups = t_count * b_count

    def topic_cost_delta(gs, agg_c, t1, b, d, t2):
        """f32[...]: topic-imbalance change of swapping one t1 replica
        b -> d against one t2 replica d -> b (negative = improvement)."""
        def imb(t, cnt):
            c = cnt.astype(jnp.float32)
            return jnp.maximum(0.0, c - gs.upper[t]) + jnp.maximum(
                0.0, gs.lower[t] - c
            )

        c1b = agg_c.topic_replica_count[t1, b]
        c1d = agg_c.topic_replica_count[t1, d]
        c2d = agg_c.topic_replica_count[t2, d]
        c2b = agg_c.topic_replica_count[t2, b]
        delta = (
            imb(t1, c1b - 1) - imb(t1, c1b)
            + imb(t1, c1d + 1) - imb(t1, c1d)
            + imb(t2, c2d - 1) - imb(t2, c2d)
            + imb(t2, c2b + 1) - imb(t2, c2b)
        )
        # same-topic swaps are topic-neutral at best; exclude
        return jnp.where(t1 == t2, jnp.float32(0.0), delta)

    def validate(static, agg_c, tables, gs, p1, s1, b, p2, s2, d):
        """(ok, improvement) for swap cells of any common shape: replica
        (p1, s1) of broker b exchanged with (p2, s2) of broker d."""
        from cruise_control_tpu.analyzer.acceptance import swap_tables_acceptance

        a = agg_c.assignment
        still = (a[p1, s1] == b) & (a[p2, s2] == d) & (b != d) & (p1 != p2)
        still &= static.movable_partition[p1] & static.movable_partition[p2]
        still &= static.replica_dst_ok[d] & static.replica_dst_ok[b]
        still &= ~static.only_move_immigrants
        # neither endpoint may already host the other partition
        still &= ~jnp.any(a[p1] == d[..., None], axis=-1)
        still &= ~jnp.any(a[p2] == b[..., None], axis=-1)
        # rack safety both ways (minus the departing sibling when same rack),
        # enforced only when RackAwareGoal actually ran before this goal —
        # unconditional checking would silently disable the swap fallback in
        # rack-colocated layouts where the rack goal is not in the stack
        rack_b = static.broker_rack[b]
        rack_d = static.broker_rack[d]
        same_rack = (rack_b == rack_d).astype(agg_c.rack_replica_count.dtype)
        rack_safe = ((agg_c.rack_replica_count[p1, rack_d] - same_rack) == 0) & (
            (agg_c.rack_replica_count[p2, rack_b] - same_rack) == 0
        )
        still &= rack_safe | ~tables.rack_enabled
        # leadership eligibility when a leader slot changes brokers
        still &= (s1 != 0) | static.leadership_dst_ok[d]
        still &= (s2 != 0) | static.leadership_dst_ok[b]
        mv1 = build_selected(
            static.part_load, a, p1, jnp.int32(KIND_MOVE), s1, d
        )
        mv2 = build_selected(
            static.part_load, a, p2, jnp.int32(KIND_MOVE), s2, b
        )
        still &= swap_tables_acceptance(static, tables, agg_c, mv1, mv2)
        t1 = static.topic_id[p1]
        t2 = static.topic_id[p2]
        improvement = -topic_cost_delta(gs, agg_c, t1, b, d, t2)
        ok = still & (improvement > 1e-6)
        return ok, improvement, mv1, mv2

    def swap_round(static: StaticCtx, agg: Aggregates, tables, gs, rnd):
        # same shared pair selection as the move round (dead brokers never
        # surface as SWAP sources usefully — the return leg cannot land on a
        # dead broker — but their 1e9 surplus rank is harmless: every cell
        # fails validation and the pair costs one row)
        pair_t, pair_b, pair_ok = select_surplus_pairs(
            static, agg, tables, gs, rnd, v, t_count, b_count
        )
        # two candidate replicas per pair, alternated across rounds: the
        # first may be permanently rack-blocked at every destination while
        # the second swaps legally
        c1p, c1s, c_found = pair_replica_picks(
            static, agg, pair_t, pair_b, 2, t_count, b_count
        )
        page = (rnd % 2).astype(jnp.int32)
        use_second = (page == 1) & c_found[:, 1]
        p1 = jnp.where(use_second, c1p[:, 1], c1p[:, 0])
        s1 = jnp.where(use_second, c1s[:, 1], c1s[:, 0])
        cand_ok = c_found[:, 0] & pair_ok

        dsts = topic_dst_list(
            static, agg, tables, gs, pair_t, pair_b, rnd, d_dst, b_count
        )

        # return candidates: each destination broker's lightest and heaviest
        # movable replicas by total load — the lightest bound the net transfer
        # from below, the heaviest from above; exact validation picks what the
        # bands accept
        from cruise_control_tpu.analyzer.actions import _follower_vec, _leader_vec

        p_all = jnp.arange(p_count, dtype=jnp.int32)
        lead_l1 = jnp.sum(_leader_vec(static.part_load, p_all), axis=-1)
        foll_l1 = jnp.sum(_follower_vec(static.part_load, p_all), axis=-1)
        is_leader = (jnp.arange(r) == 0)[None, :]
        load_l1 = jnp.where(is_leader, lead_l1[:, None], foll_l1[:, None])
        k_half = max(1, k_ret // 2)
        lp, ls, lok = broker_top_replicas(
            static, agg, load_l1, k_half, b_count, heaviest=False
        )
        hp, hs, hok = broker_top_replicas(
            static, agg, load_l1, k_ret - k_half, b_count, heaviest=True
        )
        ret_p = jnp.concatenate([lp, hp], axis=1)  # [B, K]
        ret_s = jnp.concatenate([ls, hs], axis=1)
        ret_ok = jnp.concatenate([lok, hok], axis=1)

        # grid [V, D, K]
        g_p2 = ret_p[dsts]  # [V, D, K]
        g_s2 = ret_s[dsts]
        g_ok = ret_ok[dsts] & cand_ok[:, None, None]
        full = g_p2.shape
        # lazy broadcast (see make_drain_round): the out-leg indices stay
        # [V, 1, 1] and destinations [V, D, 1]; only the return-leg arrays are
        # genuinely [V, D, K]
        ok, improve, _, _ = validate(
            static, agg, tables, gs,
            p1[:, None, None],
            s1[:, None, None],
            pair_b[:, None, None],
            g_p2, g_s2,
            dsts[:, :, None],
        )
        score0 = jnp.broadcast_to(jnp.where(ok & g_ok, improve, -jnp.inf), full)
        cells = score0.reshape(v, d_dst * k_ret)
        rows0 = jnp.arange(v, dtype=jnp.int32)
        waves = max(1, apply_waves)

        def wave(carry, w):
            agg_c, applied_any, blocked = carry
            masked = jnp.where(blocked, -jnp.inf, cells)
            ci = jnp.argmax(masked, axis=1).astype(jnp.int32)
            bs = jnp.take_along_axis(masked, ci[:, None], axis=1)[:, 0]
            d_i = dsts[rows0, ci // k_ret]
            p2 = g_p2[rows0, ci // k_ret, ci % k_ret]
            s2 = g_s2[rows0, ci // k_ret, ci % k_ret]
            ok_w, improve_w, mv1, mv2 = validate(
                static, agg_c, tables, gs, p1, s1, pair_b, p2, s2, d_i
            )
            ok_w = ok_w & jnp.isfinite(bs)
            w_sel = wave_select(
                jnp.where(ok_w, improve_w, -jnp.inf), pair_b, d_i,
                static.broker_host[d_i], ok_w, b_count, dims.num_hosts,
                dst_host2=static.broker_host[pair_b],
                parts=(p1, p2), num_partitions=p_count,
            )
            agg_c = apply_actions_batch(
                static, agg_c, mv1, w_sel, tag=make_touch_tag(rnd, w)
            )
            agg_c = apply_actions_batch(
                static, agg_c, mv2, w_sel, tag=make_touch_tag(rnd, w)
            )
            dead = w_sel | (jnp.isfinite(bs) & ~ok_w)
            blk = blocked.at[rows0, ci].set(blocked[rows0, ci] | dead)
            # an applied row's replica moved: its whole row dies
            blk = blk | (w_sel[:, None] & jnp.ones((1, d_dst * k_ret), bool))
            return (agg_c, applied_any | jnp.any(w_sel), blk), None

        init = (agg, jnp.asarray(False), jnp.zeros((v, d_dst * k_ret), bool))
        (agg2, applied_any, _), _ = jax.lax.scan(
            wave, init, jnp.arange(waves, dtype=jnp.int32)
        )
        return agg2, applied_any

    return swap_round


def make_leadership_relay_round(goal, dims, n_src: int, k_out: int, k_ret: int,
                                apply_waves: int):
    """Leadership-RELAY fallback for leader-load goals (LeaderBytesIn): when
    plain promotions stall, pair a heavy promotion off an over-bound broker
    with a light promotion off its destination — promote heavy leader p1 of
    over-broker b to its follower at d, and promote one of d's LIGHT leaders
    p2 to p2's follower at any broker e.

    Why relays: near convergence the leader-count goal's bounds (hi_lead /
    lo_lead, cc/analyzer/goals/LeaderReplicaDistributionGoal.java) and the
    usage bands veto every single promotion; the pairing keeps d COUNT-
    NEUTRAL and its net load gain is the difference of the two partitions'
    leader loads. The e == b case is a pure leadership SWAP (count-neutral
    at both endpoints — the round-4 fallback); the general e ≠ b case is
    what makes the fallback work at north-star scale: a partition with
    leader at d AND follower back at b is vanishingly rare at 2,600 brokers
    (~P*rf/B^2 ≈ 0.06 per ordered pair), while d always has light leaders
    whose followers live SOMEWHERE (~P/B ≈ 77 leaders per broker). The
    reference has no compound leadership action
    (LeaderBytesInDistributionGoal.java:39 relocates leadership one
    partition at a time and simply leaves these states); the parity gate
    only requires not being worse.

    Per round: top-V over-bound sources by src_rank x their K1 heaviest
    leaders x each leader's R-1 follower brokers d x d's K2 lightest leaders
    (per-broker table, round-jittered) x those leaders' R-1 follower slots
    (e), validated exactly (structural, per-endpoint prior-goal bounds with
    e == b aliasing folded into b's net, combined host-CPU, goal-cost
    improvement), applied in waves disjoint over all three brokers, both
    hosts gaining load, and both partitions.
    """
    p_count, r = dims.num_partitions, dims.max_rf
    b_count = dims.num_brokers
    v = max(1, min(n_src, b_count))
    k1 = max(1, min(k_out, p_count))
    k2 = max(1, min(k_ret, p_count))
    r_f = r - 1  # follower slots per candidate leader

    from cruise_control_tpu.analyzer.goals.base import imbalance
    from cruise_control_tpu.common.resources import PartMetric

    def endpoint_ok(static, tables, agg_c, x, dload, dlnw, dcnt):
        """Conservative per-endpoint bound checks for broker x: hard load
        box, distribution-band box (no pairwise shrink escape — a relay has
        three endpoints, so the two-case band check does not apply; box-only
        rejects some legal relays but never accepts an illegal one), leader
        bytes-in cap, leader-count box. Replica/topic counts, potential
        NW_OUT and rack safety are unchanged by construction (both legs
        transfer leadership only). Host CPU is checked COMBINED by the
        caller (endpoints may share hosts)."""
        inc = dload > 0.0
        after = agg_c.broker_load[x] + dload
        ok = jnp.all(~inc | (after <= tables.hi_load[x]), axis=-1)
        band = jnp.where(inc, after <= tables.band_hi[x], after >= tables.band_lo[x])
        ok &= jnp.all((dload == 0.0) | ~tables.band_on | band, axis=-1)
        ok &= (dlnw <= 0.0) | (agg_c.leader_nw_in[x] + dlnw <= tables.hi_lnw[x])
        cnt_after = agg_c.leader_count[x] + dcnt
        ok &= (dcnt <= 0.0) | (cnt_after <= tables.hi_lead[x])
        ok &= (dcnt >= 0.0) | (cnt_after >= tables.lo_lead[x])
        return ok

    def validate(static, agg_c, tables, gs, p1, s1, b, p2, s2, d):
        """(ok, improvement, act1, act2, e) for relay cells of any common
        shape: leadership of p1 moves b -> d (promote p1's follower slot s1)
        and leadership of p2 moves d -> e = assignment[p2, s2]."""
        a = agg_c.assignment
        e_raw = a[p2, s2]
        e = jnp.maximum(e_raw, 0)
        still = (a[p1, 0] == b) & (a[p1, s1] == d)
        still &= (a[p2, 0] == d) & (e_raw >= 0)
        still &= (b != d) & (d != e) & (p1 != p2) & (s1 >= 1) & (s2 >= 1)
        still &= static.movable_partition[p1] & static.movable_partition[p2]
        still &= static.leadership_dst_ok[d] & static.leadership_dst_ok[e]
        still &= ~static.only_move_immigrants
        act1 = build_selected(
            static.part_load, a, p1, jnp.int32(KIND_LEADERSHIP), s1, d
        )
        act2 = build_selected(
            static.part_load, a, p2, jnp.int32(KIND_LEADERSHIP), s2, e
        )
        # per-broker net deltas with the e == b alias folded into b
        eb = e == b
        ebl = eb[..., None]
        dl1, dl2 = act1.dload, act2.dload
        w1, w2 = act1.dleader_nw_in, act2.dleader_nw_in
        delta_b = -dl1 + jnp.where(ebl, dl2, 0.0)
        delta_d = dl1 - dl2
        delta_e = jnp.where(ebl, 0.0, dl2)
        lnw_b = -w1 + jnp.where(eb, w2, 0.0)
        lnw_d = w1 - w2
        lnw_e = jnp.where(eb, 0.0, w2)
        cnt_b = jnp.where(eb, 0, -1)
        cnt_e = jnp.where(eb, 0, 1)
        still &= endpoint_ok(static, tables, agg_c, b, delta_b, lnw_b, cnt_b)
        still &= endpoint_ok(static, tables, agg_c, d, delta_d, lnw_d, 0)
        still &= endpoint_ok(static, tables, agg_c, e, delta_e, lnw_e, cnt_e)
        # host CPU combined per touched host (endpoints may share hosts)
        cb, cd, ce = delta_b[..., 0], delta_d[..., 0], delta_e[..., 0]
        hb = static.broker_host[b]
        hd = static.broker_host[d]
        he = static.broker_host[e]

        def host_ok(h):
            tot = (
                jnp.where(hb == h, cb, 0.0)
                + jnp.where(hd == h, cd, 0.0)
                + jnp.where(he == h, ce, 0.0)
            )
            return (tot <= 0.0) | (
                agg_c.host_cpu_load[h] + tot <= tables.hi_host_cpu[h]
            )

        still &= host_ok(hb) & host_ok(hd) & host_ok(he)
        # goal improvement over the distinct endpoints (cost is a sum of
        # per-broker out-of-window distances, so the delta is local)
        lnwv = agg_c.leader_nw_in
        before = (
            imbalance(lnwv[b], gs.lower, gs.upper)
            + imbalance(lnwv[d], gs.lower, gs.upper)
            + jnp.where(eb, 0.0, imbalance(lnwv[e], gs.lower, gs.upper))
        )
        after = (
            imbalance(lnwv[b] + lnw_b, gs.lower, gs.upper)
            + imbalance(lnwv[d] + lnw_d, gs.lower, gs.upper)
            + jnp.where(eb, 0.0, imbalance(lnwv[e] + lnw_e, gs.lower, gs.upper))
        )
        improvement = before - after
        ok = still & (improvement > 1e-6)
        return ok, improvement, act1, act2, e

    def relay_round(static: StaticCtx, agg: Aggregates, tables, gs, rnd):
        rank = goal.src_rank(static, gs, agg)
        # dead brokers never need relays (evacuation moves handle them);
        # exclude outright
        rank = jnp.where(static.dead, -jnp.inf, rank)
        _, hot = jax.lax.top_k(rank, v)
        hot = hot.astype(jnp.int32)
        hot_ok = jnp.isfinite(rank[hot])

        # K1 leaders per source whose weight is CLOSEST to the broker's
        # excess over the upper window: the ideal first leg transfers just
        # enough to bring b under the bound without overshooting d — near
        # convergence the heaviest leader usually overshoots every
        # destination while a mid-weight one fits (the plain-promotion
        # shortlist learns this from exact scores; a compound action's
        # candidates must encode it in the rank). Round-jittered so a
        # uniformly-frozen head cannot starve the fallback.
        rot = round_jitter(p_count, rnd)
        w_all = static.part_load[:, PartMetric.NW_IN_LEADER]
        is_leader = (jnp.arange(r) == 0)[None, :]
        excess = jnp.maximum(agg.leader_nw_in - gs.upper, 0.0)
        lead_broker = agg.assignment[:, 0]
        closeness = -jnp.abs(w_all - excess[jnp.maximum(lead_broker, 0)])
        contrib = jnp.where(is_leader, (closeness * rot)[:, None], -jnp.inf)
        c1p, _, c1ok = heavy_picks(static, agg, contrib, hot, k1, b_count)
        c1ok = c1ok & hot_ok[:, None]

        # per-broker K2 leader candidates for the relay's second leg: half
        # LIGHTEST and half HEAVIEST leaders led by each broker — the light
        # end sheds just enough for d to absorb a small overshoot, the heavy
        # end lets d pass on most of the incoming load; exact validation
        # picks what the bounds accept. Same jitter family as leg 1 so the
        # slices interleave across rounds.
        lead_w = jnp.where(is_leader, w_all[:, None], -jnp.inf)
        lead_w = lead_w * rot[:, None]
        k2l = max(1, k2 // 2)
        lp, _, lok = broker_top_replicas(
            static, agg, lead_w, k2l, b_count, heaviest=False
        )
        if k2 - k2l > 0:
            hp, _, hok = broker_top_replicas(
                static, agg, lead_w, k2 - k2l, b_count, heaviest=True
            )
            ret_p = jnp.concatenate([lp, hp], axis=1)  # [B, K2]
            ret_ok = jnp.concatenate([lok, hok], axis=1)
        else:  # k2 == 1: the light pick is the whole candidate set
            ret_p, ret_ok = lp, lok

        # grid [V, K1, R-1 (s1), K2, R-1 (s2)], lazy broadcast shapes (see
        # make_drain_round): only g_p2 / g_s2-derived arrays are joint
        full = (v, k1, r_f, k2, r_f)
        s1_all = jnp.arange(1, r, dtype=jnp.int32)
        g_p1 = c1p[:, :, None, None, None]
        g_s1 = s1_all[None, None, :, None, None]
        g_b = hot[:, None, None, None, None]
        g_d = agg.assignment[g_p1, g_s1]  # [V,K1,R-1,1,1]
        g_d0 = jnp.maximum(g_d, 0)
        k2i = jnp.arange(k2, dtype=jnp.int32)[None, None, None, :, None]
        g_p2 = ret_p[g_d0, k2i]  # [V,K1,R-1,K2,1]
        g_p2ok = ret_ok[g_d0, k2i]
        g_s2 = s1_all[None, None, None, None, :]
        g_ok = c1ok[:, :, None, None, None] & (g_d >= 0) & g_p2ok
        ok, improve, _, _, _ = validate(
            static, agg, tables, gs, g_p1, g_s1, g_b, g_p2, g_s2, g_d0
        )
        score0 = jnp.broadcast_to(jnp.where(ok & g_ok, improve, -jnp.inf), full)
        n_cells = k1 * r_f * k2 * r_f
        cells = score0.reshape(v, n_cells)
        rows0 = jnp.arange(v, dtype=jnp.int32)
        waves = max(1, apply_waves)

        def cell_pick(ci):
            i1 = ci // (r_f * k2 * r_f)
            i_s1 = (ci // (k2 * r_f)) % r_f
            i2 = (ci // r_f) % k2
            i_s2 = ci % r_f
            p1 = c1p[rows0, i1]
            return p1, s1_all[i_s1], i2, s1_all[i_s2]

        def wave(carry, w):
            agg_c, applied_any, blocked = carry
            masked = jnp.where(blocked, -jnp.inf, cells)
            ci = jnp.argmax(masked, axis=1).astype(jnp.int32)
            bs = jnp.take_along_axis(masked, ci[:, None], axis=1)[:, 0]
            p1, s1, i2, s2 = cell_pick(ci)
            d_i = jnp.maximum(agg_c.assignment[p1, s1], 0)
            p2 = ret_p[d_i, i2]
            ok_w, improve_w, act1, act2, e_i = validate(
                static, agg_c, tables, gs, p1, s1, hot, p2, s2, d_i
            )
            ok_w = ok_w & jnp.isfinite(bs)
            # disjoint over all three brokers; hosts claimed for the two
            # GAINING endpoints (b only loses when e != b, and when e == b
            # its host is claimed through e)
            w_sel = wave_select(
                jnp.where(ok_w, improve_w, -jnp.inf), hot, d_i,
                static.broker_host[d_i], ok_w, b_count, dims.num_hosts,
                dst_host2=static.broker_host[e_i],
                parts=(p1, p2), num_partitions=p_count,
                brokers3=e_i,
            )
            agg_c = apply_actions_batch(
                static, agg_c, act1, w_sel, tag=make_touch_tag(rnd, w)
            )
            agg_c = apply_actions_batch(
                static, agg_c, act2, w_sel, tag=make_touch_tag(rnd, w)
            )
            dead = w_sel | (jnp.isfinite(bs) & ~ok_w)
            blk = blocked.at[rows0, ci].set(blocked[rows0, ci] | dead)
            # an applied row's leadership moved: its whole row dies
            blk = blk | (w_sel[:, None] & jnp.ones((1, n_cells), bool))
            return (agg_c, applied_any | jnp.any(w_sel), blk), None

        init = (agg, jnp.asarray(False), jnp.zeros((v, n_cells), bool))
        (agg2, applied_any, _), _ = jax.lax.scan(
            wave, init, jnp.arange(waves, dtype=jnp.int32)
        )
        return agg2, applied_any

    return relay_round


def make_drain_round(goal, dims, n_src: int, k_rep: int, c_dst: int,
                     apply_waves: int):
    """Build drain_round(static, agg, tables, gs, contrib) -> (agg, applied).

    `contrib` is the goal's drain_contrib for the current aggregates (also
    shared with the swap search). Structure per round:
      1. top-V sources by the goal's src_rank (dead brokers first — evacuation
         precedes balance, GoalUtils.ensureNoReplicaOnDeadBrokers);
      2. top-K drain candidates per source (sort-free segment passes);
      3. destinations per candidate from the goal (one global rack-diverse
         list by default; goals with rarer feasible destinations override
         dst_candidates, and TopicReplicaDistributionGoal uses its own pair
         round, make_pair_drain_round);
      4. exact [V, K, C] scoring (structural + merged prior-goal tables +
         this goal), plus — for goals that shift load by moving leadership —
         a GLOBAL top-J leadership shortlist from the full [P, R-1] promotion
         grid (the grid is ~R times smaller than one topic-goal destination
         scan, and per-source candidate lists systematically miss the
         mid-weight leaders whose transfer is the only legal action near
         convergence);
      5. `apply_waves` conflict-free waves: per wave each source nominates its
         best remaining cell (destination axis rotated per wave so the source
         set fans out over destinations; the last wave argmaxes over all
         cells) and every not-yet-applied leadership entry re-bids; all
         nominations are re-scored against CURRENT aggregates, and a
         broker-disjoint, partition-disjoint subset applies at once
         (context.wave_select contract).
    """
    p_count, r = dims.num_partitions, dims.max_rf
    v = max(1, min(n_src, dims.num_brokers))
    k = max(1, min(k_rep, p_count))
    c = max(1, min(c_dst, dims.num_brokers))
    use_leadership = goal.uses_leadership and r >= 2
    # clamped by the configured width and the promotion-grid size, NOT by the
    # broker count: a broker-count clamp would let a shape-bucketed run
    # shortlist more real promotions than the exact-shape run (extra top-k
    # slots on the PARTITION axis only ever pick up -inf padding entries,
    # which stay inert — extra slots on the broker axis pick up real ones)
    j_lead = max(1, min(n_src, p_count * (r - 1))) if use_leadership else 0

    def drain_round(static: StaticCtx, agg: Aggregates, tables, gs, contrib,
                    rnd=None):
        # source ranks are load-valued, not tie-heavy; no candidate rotation —
        # `rnd` only stamps the provenance touch tag on applied waves
        rnd = jnp.int32(-1) if rnd is None else rnd
        rank = goal.src_rank(static, gs, agg)
        rank = jnp.where(static.dead, jnp.inf, rank)
        _, hot = jax.lax.top_k(rank, v)  # i32[V]
        hot = hot.astype(jnp.int32)
        hot_ok = jnp.isfinite(rank[hot]) | static.dead[hot]

        # EVERY replica on a dead broker is a drain candidate regardless of
        # the goal's own priorities (GoalUtils.ensureNoReplicaOnDeadBrokers:
        # evacuation precedes balance for every goal): a goal whose
        # drain_contrib excludes ordinary replicas (-inf for non-violating /
        # follower slots) would otherwise rank the dead broker first as a
        # source yet nominate zero candidates from it
        from cruise_control_tpu.analyzer.context import replicas_on_dead

        contrib = jnp.where(
            replicas_on_dead(static, agg.assignment), jnp.float32(1e9), contrib
        )

        cand_p, cand_s, cand_ok = heavy_picks(
            static, agg, contrib, hot, k, dims.num_brokers
        )  # [V, K]
        cand_ok = cand_ok & hot_ok[:, None]

        cold = rack_diverse_cold(static, gs, agg, goal, tables, dims, c)
        dsts_g = goal.dst_candidates(static, gs, agg, tables, cand_p, cand_s, cold)
        # dsts_g: [C] (global list) or [V, K, C] (per-candidate). Score the
        # grid with LAZY broadcast shapes — p/slot stay [V, K, 1] and a
        # global destination list stays [1, 1, C] — so every gather indexes
        # the smallest axis set it depends on ([V,K,1,M] part loads, [C]-row
        # broker tables) instead of a materialized [V, K, C] index cube; the
        # comparisons broadcast on the VPU for free. Only the wave picker
        # needs the dense index cube (its picks are [V]-shaped).
        full = (v, k, c)
        dst_lazy = (dsts_g[None, None, :] if dsts_g.ndim == 1 else dsts_g).astype(jnp.int32)
        dsts = jnp.broadcast_to(dst_lazy, full).astype(jnp.int32)
        mv = build_selected(
            static.part_load, agg.assignment,
            cand_p[:, :, None],
            jnp.int32(KIND_MOVE),
            cand_s[:, :, None],
            dst_lazy,
        )
        s_mv = score_batch(static, agg, mv, goal, gs, tables)
        s_mv = jnp.broadcast_to(jnp.where(cand_ok[:, :, None], s_mv, -jnp.inf), full)

        if use_leadership:
            # GLOBAL leadership shortlist: promoting a follower shifts the
            # leader-borne load without moving data, and the full [P, R-1]
            # promotion grid is cheap relative to the move grid — per-source
            # candidate lists systematically miss the mid-weight leaders
            # whose transfer is the only legal action near convergence
            from cruise_control_tpu.analyzer.actions import make_leadership_batch

            lb = make_leadership_batch(static.part_load, agg.assignment)
            sl = score_batch(static, agg, lb, goal, gs, tables)
            sl = jnp.broadcast_to(sl, (p_count, r - 1)).reshape(p_count * (r - 1))
            lead_s0, lead_i = jax.lax.top_k(sl, j_lead)
            lead_p = (lead_i // (r - 1)).astype(jnp.int32)
            lead_slot = (lead_i % (r - 1)).astype(jnp.int32) + 1
            lead_kind = jnp.full((j_lead,), KIND_LEADERSHIP, dtype=jnp.int32)

        # move cells: [V, K*C]
        cells = s_mv.reshape(v, k * c)
        n_cells = k * c
        rows0 = jnp.arange(v, dtype=jnp.int32)
        waves = max(1, apply_waves)

        def move_action(agg_c, ci):
            """Materialize the nominated move cell per row: ci i32[V]."""
            k_i = ci // c
            return build_selected(
                static.part_load, agg_c.assignment,
                cand_p[rows0, k_i],
                jnp.full((v,), KIND_MOVE, dtype=jnp.int32),
                cand_s[rows0, k_i],
                dsts[rows0, k_i, ci % c],
            )

        def wave(carry, w):
            agg_c, applied_any, blocked, lead_done = carry
            masked = jnp.where(blocked, -jnp.inf, cells)

            def rotated(masked):
                """Per row: argmax over the K candidates of ONE rotated
                destination column — the sorted-by-sorted matching that keeps
                the whole source set moving in parallel (a full argmax would
                send every source to the same best destination and
                disjointness would then admit one action per wave)."""
                c_i = ((rows0 + w) % c).astype(jnp.int32)
                col = masked.reshape(v, k, c)
                col = jnp.take_along_axis(col, c_i[:, None, None], axis=2)[:, :, 0]
                j = jnp.argmax(col, axis=1)
                ci = j * c + c_i
                return ci.astype(jnp.int32), jnp.take_along_axis(col, j[:, None], axis=1)[:, 0]

            def argmax_all(masked):
                ci = jnp.argmax(masked, axis=1).astype(jnp.int32)
                return ci, jnp.take_along_axis(masked, ci[:, None], axis=1)[:, 0]

            ci, bs = jax.lax.cond(w == waves - 1, argmax_all, rotated, masked)
            act = move_action(agg_c, ci)
            s_now = score_batch(static, agg_c, act, goal, gs, tables)
            all_act = act
            all_score = s_now
            all_ok = jnp.isfinite(bs) & jnp.isfinite(s_now)
            if use_leadership:
                # every not-yet-applied leadership entry re-bids each wave
                # (its "destination" is wherever the follower lives NOW)
                l_dst = agg_c.assignment[lead_p, lead_slot]
                lact = build_selected(
                    static.part_load, agg_c.assignment, lead_p, lead_kind,
                    lead_slot, l_dst,
                )
                ls_now = score_batch(static, agg_c, lact, goal, gs, tables)
                lok = jnp.isfinite(lead_s0) & jnp.isfinite(ls_now) & ~lead_done
                all_act = jax.tree.map(
                    lambda a, b: jnp.concatenate(
                        [jnp.broadcast_to(a, (v,) + a.shape[1:]),
                         jnp.broadcast_to(b, (j_lead,) + b.shape[1:])]
                    ),
                    act, lact,
                )
                all_score = jnp.concatenate([s_now, ls_now])
                all_ok = jnp.concatenate([all_ok[:v], lok])
            sel = wave_select(
                all_score, all_act.src, all_act.dst,
                static.broker_host[all_act.dst], all_ok,
                dims.num_brokers, dims.num_hosts,
                parts=(all_act.p,), num_partitions=p_count,
            )
            agg_c = apply_actions_batch(
                static, agg_c, all_act, sel, tag=make_touch_tag(rnd, w)
            )
            sel_mv = sel[:v]
            # A nomination that failed re-scoring is a dead cell; conflict
            # losers stay available for later waves. An applied move's
            # candidate replica left its source, so ALL its destination
            # cells die.
            dead = sel_mv | (jnp.isfinite(bs) & ~jnp.isfinite(s_now))
            k_i = ci // c
            blk = blocked.at[rows0, ci].set(blocked[rows0, ci] | dead)
            cols = jnp.arange(c, dtype=jnp.int32)[None, :]
            cell_ids = (k_i * c)[:, None] + cols  # [V, C]
            blk = blk.at[rows0[:, None], cell_ids].set(
                blk[rows0[:, None], cell_ids] | sel_mv[:, None]
            )
            if use_leadership:
                # only APPLIED entries die; a transiently infeasible promotion
                # (its follower's broker filled up this wave) may become
                # feasible again when a later wave drains that broker — all
                # entries re-bid each wave anyway, so retrying costs nothing
                lead_done = lead_done | sel[v:]
            return (agg_c, applied_any | jnp.any(sel), blk, lead_done), None

        init = (
            agg, jnp.asarray(False), jnp.zeros((v, n_cells), dtype=bool),
            jnp.zeros((max(j_lead, 1),), dtype=bool)[:j_lead]
            if use_leadership else jnp.zeros((0,), dtype=bool),
        )
        (agg2, applied_any, _, _), _ = jax.lax.scan(
            wave, init, jnp.arange(waves, dtype=jnp.int32)
        )
        return agg2, applied_any

    return drain_round
