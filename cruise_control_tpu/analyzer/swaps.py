"""Replica swap search for resource-distribution goals.

The array-native counterpart of ResourceDistributionGoal's swap phase
(cc/analyzer/goals/ResourceDistributionGoal.java: rebalanceBySwappingLoadOut
:482 / ...In :610, the INTER_BROKER_REPLICA_SWAP action): when single moves
can no longer help — the classic deadlock is a hot broker whose every
candidate move is too big for any destination — exchange a heavy replica on
an over-limit broker for a light replica on an under-loaded one.

Where the reference walks SortedReplicas views under a 1 s/broker timeout,
this kernel scores a pruned dense grid in one shot:

  top-N hottest brokers x top-K heaviest movable replicas each
  paired with the N coldest brokers x their K lightest replicas
  -> [N, K, K] swap candidates, scored by imbalance reduction and masked by
  the prior-goal invariants (rack safety for BOTH partitions, capacity and
  potential-NW_OUT not-worse on both ends, leadership eligibility when a
  leader slot moves), then applied via a sequentially re-validated scan.

Replica counts are unchanged by a swap, so replica-capacity/distribution
goals are unaffected by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.actions import (
    KIND_MOVE,
    _follower_vec,
    _leader_vec,
    build_selected,
)
from cruise_control_tpu.analyzer.acceptance import tables_acceptance
from cruise_control_tpu.analyzer.context import Aggregates, StaticCtx, apply_action
from cruise_control_tpu.analyzer.goals.base import SCORE_EPS
from cruise_control_tpu.common.resources import PartMetric, Resource


def _slot_contrib(static: StaticCtx, assignment: jax.Array, res: int) -> jax.Array:
    """f32[P, R]: per-slot load contribution for one resource."""
    pl = static.part_load
    lead = {
        Resource.CPU: pl[:, PartMetric.CPU_LEADER],
        Resource.NW_IN: pl[:, PartMetric.NW_IN_LEADER],
        Resource.NW_OUT: pl[:, PartMetric.NW_OUT_LEADER],
        Resource.DISK: pl[:, PartMetric.DISK],
    }[Resource(res)]
    foll = {
        Resource.CPU: pl[:, PartMetric.CPU_FOLLOWER],
        Resource.NW_IN: pl[:, PartMetric.NW_IN_FOLLOWER],
        Resource.NW_OUT: jnp.zeros_like(lead),
        Resource.DISK: pl[:, PartMetric.DISK],
    }[Resource(res)]
    r = assignment.shape[1]
    is_leader = (jnp.arange(r) == 0)[None, :]
    return jnp.where(is_leader, lead[:, None], foll[:, None])


def make_swap_round(goal, priors, dims, n_pairs: int = 8, k: int = 8):
    """Build swap_round(static, agg, tables) -> (agg, applied_any) for a
    resource-distribution goal (jit-compatible; call inside the goal loop).

    `tables` are the merged acceptance bounds of the already-optimized goals
    (analyzer.acceptance): both directions of every candidate swap must pass
    them, the same invariant the move path enforces per candidate."""
    res = goal.resource
    p_count, r = dims.num_partitions, dims.max_rf
    n_pairs = max(1, min(n_pairs, dims.num_brokers // 2 or 1))
    k = max(1, min(k, p_count))
    del priors  # prior-goal invariants arrive via the merged tables

    def swap_round(static: StaticCtx, agg: Aggregates, tables):
        gs = goal.prepare(static, agg, dims)
        cap = jnp.maximum(static.broker_capacity[:, res], 1e-9)
        util = agg.broker_load[:, res] / cap

        # both ends RECEIVE a replica (mv2 lands on the hot broker), so both
        # must be eligible destinations; swaps are disabled entirely in
        # immigrant-only self-healing mode (a swap moves non-immigrants).
        hot_rank = jnp.where(static.alive & static.replica_dst_ok, util, -jnp.inf)
        hot_vals, hot = jax.lax.top_k(hot_rank, n_pairs)  # i32[N]
        cold_rank = jnp.where(static.alive & static.replica_dst_ok, -util, -jnp.inf)
        cold_vals, cold = jax.lax.top_k(cold_rank, n_pairs)  # i32[N]
        pair_ok = (
            jnp.isfinite(hot_vals)[:, None, None]
            & jnp.isfinite(cold_vals)[:, None, None]
            & ~static.only_move_immigrants
        )

        contrib = _slot_contrib(static, agg.assignment, res)  # f32[P, R]
        movable = static.movable_partition[:, None] & (agg.assignment >= 0)

        def pick(broker, heaviest: bool):
            mask = (agg.assignment == broker) & movable
            score = jnp.where(mask, contrib, -jnp.inf if heaviest else jnp.inf)
            flat = (score if heaviest else -score).reshape(p_count * r)
            vals, idx = jax.lax.top_k(flat, k)
            return (
                (idx // r).astype(jnp.int32),  # partitions
                (idx % r).astype(jnp.int32),  # slots
                jnp.where(jnp.isfinite(vals), jnp.abs(vals), jnp.nan),  # loads
            )

        hp, hs, hl = jax.vmap(lambda b: pick(b, True))(hot)  # [N, K] each
        cp, cs, cl = jax.vmap(lambda b: pick(b, False))(cold)

        # [N, K, K] swap grid: replica a of hot_i <-> replica b of cold_i
        delta = hl[:, :, None] - cl[:, None, :]  # load moved hot -> cold
        ok = jnp.isfinite(delta) & (delta > SCORE_EPS) & pair_ok
        ok &= hp[:, :, None] != cp[:, None, :]

        # every previously-optimized goal must accept BOTH directions
        mv1b = build_selected(
            static.part_load, agg.assignment,
            hp[:, :, None], jnp.int32(KIND_MOVE), hs[:, :, None], cold[:, None, None],
        )
        mv2b = build_selected(
            static.part_load, agg.assignment,
            cp[:, None, :], jnp.int32(KIND_MOVE), cs[:, None, :], hot[:, None, None],
        )
        ok &= tables_acceptance(static, tables, agg, mv1b)
        ok &= tables_acceptance(static, tables, agg, mv2b)

        # neither broker may already host the other's partition
        cold_b = cold[:, None, None]
        hot_b = hot[:, None, None]
        ok &= ~jnp.any(agg.assignment[hp[:, :, None]] == cold_b[..., None], axis=-1)
        ok &= ~jnp.any(agg.assignment[cp[:, None, :]] == hot_b[..., None], axis=-1)

        # rack safety for both directions (RackAwareGoal acceptance)
        rack_hot = static.broker_rack[hot][:, None, None]
        rack_cold = static.broker_rack[cold][:, None, None]
        same_rack = rack_hot == rack_cold
        cnt1 = agg.rack_replica_count[hp[:, :, None], jnp.broadcast_to(rack_cold, hp[:, :, None].shape)]
        ok &= (cnt1 - same_rack.astype(cnt1.dtype)) == 0
        cnt2 = agg.rack_replica_count[cp[:, None, :], jnp.broadcast_to(rack_hot, cp[:, None, :].shape)]
        ok &= (cnt2 - same_rack.astype(cnt2.dtype)) == 0

        # leadership eligibility when a leader slot changes brokers
        ok &= (hs[:, :, None] != 0) | static.leadership_dst_ok[cold][:, None, None]
        ok &= (cs[:, None, :] != 0) | static.leadership_dst_ok[hot][:, None, None]

        # capacity + potential NW_OUT must not get worse on either end
        # (CapacityGoal / PotentialNwOutGoal acceptance, conservative form)
        h_load1 = _all_res_contrib(static, agg.assignment, hp, hs)  # [N, K, 4]
        c_load2 = _all_res_contrib(static, agg.assignment, cp, cs)  # [N, K, 4]
        hot_before = agg.broker_load[hot][:, None, None, :]
        cold_before = agg.broker_load[cold][:, None, None, :]
        hot_after = hot_before - h_load1[:, :, None, :] + c_load2[:, None, :, :]
        cold_after = cold_before + h_load1[:, :, None, :] - c_load2[:, None, :, :]
        hot_limit = jnp.maximum(static.capacity_limit[hot][:, None, None, :], hot_before)
        cold_limit = jnp.maximum(static.capacity_limit[cold][:, None, None, :], cold_before)
        ok &= jnp.all(hot_after <= hot_limit + 1e-6, axis=-1)
        ok &= jnp.all(cold_after <= cold_limit + 1e-6, axis=-1)
        pnw1 = static.part_load[hp, PartMetric.NW_OUT_LEADER][:, :, None]
        pnw2 = static.part_load[cp, PartMetric.NW_OUT_LEADER][:, None, :]
        pnw_limit = static.capacity_limit[:, Resource.NW_OUT]
        cold_pnw_after = agg.potential_nw_out[cold][:, None, None] + pnw1 - pnw2
        ok &= (cold_pnw_after <= jnp.maximum(pnw_limit[cold][:, None, None],
                                             agg.potential_nw_out[cold][:, None, None]) + 1e-6)
        hot_pnw_after = agg.potential_nw_out[hot][:, None, None] - pnw1 + pnw2
        ok &= (hot_pnw_after <= jnp.maximum(pnw_limit[hot][:, None, None],
                                            agg.potential_nw_out[hot][:, None, None]) + 1e-6)

        # goal improvement: imbalance reduction of the (hot, cold) pair
        u_h = util[hot][:, None, None]
        u_c = util[cold][:, None, None]
        d_h = delta / cap[hot][:, None, None]
        d_c = delta / cap[cold][:, None, None]
        before = _dist(u_h, gs) + _dist(u_c, gs)
        after = _dist(u_h - d_h, gs) + _dist(u_c + d_c, gs)
        score = jnp.where(ok & gs.active, before - after, -jnp.inf)

        # best swap per hot/cold pair, applied sequentially with re-validation
        flat = score.reshape(n_pairs, k * k)
        best = jnp.argmax(flat, axis=1)
        best_score = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        a_idx = (best // k).astype(jnp.int32)
        b_idx = (best % k).astype(jnp.int32)
        rows = jnp.arange(n_pairs)
        sel = dict(
            p1=hp[rows, a_idx], s1=hs[rows, a_idx],
            p2=cp[rows, b_idx], s2=cs[rows, b_idx],
            hot=hot, cold=cold, score=best_score,
        )

        def body(carry, i):
            agg_c, any_applied = carry
            p1, s1, p2, s2 = sel["p1"][i], sel["s1"][i], sel["p2"][i], sel["s2"][i]
            h, c = sel["hot"][i], sel["cold"][i]
            # re-validate against the updated aggregates: both replicas still
            # on their brokers, swap still improves the pair
            still = (agg_c.assignment[p1, s1] == h) & (agg_c.assignment[p2, s2] == c)
            still &= ~jnp.any(agg_c.assignment[p1] == c) & ~jnp.any(agg_c.assignment[p2] == h)
            # rack safety against the CURRENT rack counts: an earlier swap in
            # this scan may have placed a sibling replica on the target rack
            rack_h = static.broker_rack[h]
            rack_c = static.broker_rack[c]
            same_rack = (rack_h == rack_c).astype(agg_c.rack_replica_count.dtype)
            still &= (agg_c.rack_replica_count[p1, rack_c] - same_rack) == 0
            still &= (agg_c.rack_replica_count[p2, rack_h] - same_rack) == 0
            u_h2 = agg_c.broker_load[h, res] / cap[h]
            u_c2 = agg_c.broker_load[c, res] / cap[c]
            d = contrib[p1, s1] - contrib[p2, s2]
            improve = (
                _dist(u_h2, gs) + _dist(u_c2, gs)
                - _dist(u_h2 - d / cap[h], gs) - _dist(u_c2 + d / cap[c], gs)
            )
            apply_flag = jnp.isfinite(sel["score"][i]) & still & (improve > SCORE_EPS)
            mv1 = build_selected(
                static.part_load, agg_c.assignment, p1,
                jnp.int32(KIND_MOVE), s1, c,
            )
            agg_c = apply_action(static, agg_c, mv1, apply_flag)
            mv2 = build_selected(
                static.part_load, agg_c.assignment, p2,
                jnp.int32(KIND_MOVE), s2, h,
            )
            agg_c = apply_action(static, agg_c, mv2, apply_flag)
            return (agg_c, any_applied | apply_flag), apply_flag

        (agg2, applied_any), _ = jax.lax.scan(
            body, (agg, jnp.asarray(False)), jnp.arange(n_pairs)
        )
        return agg2, applied_any

    return swap_round


def _dist(u, gs):
    return jnp.maximum(0.0, u - gs.upper) + jnp.maximum(0.0, gs.lower - u)


def _all_res_contrib(static: StaticCtx, assignment: jax.Array, p, slot) -> jax.Array:
    """f32[..., 4]: full per-Resource contribution of replica (p, slot)."""
    lead = _leader_vec(static.part_load, p)
    foll = _follower_vec(static.part_load, p)
    return jnp.where((slot == 0)[..., None], lead, foll)
