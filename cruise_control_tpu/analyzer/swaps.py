"""Replica swap search for resource-distribution goals.

The array-native counterpart of ResourceDistributionGoal's swap phase
(cc/analyzer/goals/ResourceDistributionGoal.java: rebalanceBySwappingLoadOut
:482 / ...In :610, the INTER_BROKER_REPLICA_SWAP action): when single moves
can no longer help — the classic deadlock is a hot broker whose every
candidate move is too big for any destination — exchange a heavy replica on
an over-limit broker for a light replica on an under-loaded one.

Where the reference walks SortedReplicas views under a 1 s/broker timeout,
this kernel scores a pruned dense grid in one shot:

  top-N hottest brokers x top-K heaviest movable replicas each
  paired with the N coldest brokers x their K lightest replicas
  -> [N, K, K] swap candidates, scored by imbalance reduction and masked by
  the prior-goal invariants (rack safety for BOTH partitions, capacity and
  potential-NW_OUT not-worse on both ends, leadership eligibility when a
  leader slot moves), then applied via a sequentially re-validated scan.

Replica counts are unchanged by a swap, so replica-capacity/distribution
goals are unaffected by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.actions import (
    KIND_LEADERSHIP,
    KIND_MOVE,
    _follower_vec,
    _leader_vec,
    build_selected,
)
from cruise_control_tpu.analyzer.acceptance import swap_tables_acceptance
from cruise_control_tpu.analyzer.context import (
    Aggregates,
    StaticCtx,
    apply_actions_batch,
    wave_select,
)
from cruise_control_tpu.analyzer.goals.base import SCORE_EPS
from cruise_control_tpu.common.resources import PartMetric, Resource


def _slot_contrib(static: StaticCtx, assignment: jax.Array, res: int) -> jax.Array:
    """f32[P, R]: per-slot load contribution for one resource."""
    pl = static.part_load
    lead = {
        Resource.CPU: pl[:, PartMetric.CPU_LEADER],
        Resource.NW_IN: pl[:, PartMetric.NW_IN_LEADER],
        Resource.NW_OUT: pl[:, PartMetric.NW_OUT_LEADER],
        Resource.DISK: pl[:, PartMetric.DISK],
    }[Resource(res)]
    foll = {
        Resource.CPU: pl[:, PartMetric.CPU_FOLLOWER],
        Resource.NW_IN: pl[:, PartMetric.NW_IN_FOLLOWER],
        Resource.NW_OUT: jnp.zeros_like(lead),
        Resource.DISK: pl[:, PartMetric.DISK],
    }[Resource(res)]
    r = assignment.shape[1]
    is_leader = (jnp.arange(r) == 0)[None, :]
    return jnp.where(is_leader, lead[:, None], foll[:, None])


def make_swap_round(goal, priors, dims, n_pairs: int = 8, k: int = 8,
                    swaps_per_broker: int = 4, apply_waves: int = 0):
    """Build swap_round(static, agg, tables) -> (agg, applied_any) for a
    resource-distribution goal (jit-compatible; call inside the goal loop).

    `tables` are the merged acceptance bounds of the already-optimized goals
    (analyzer.acceptance): every candidate swap's NET effect must pass them,
    the same invariant the move path enforces per candidate. Each round
    applies up to `swaps_per_broker` swaps per hot broker (sequentially
    re-validated) — in tight regimes where swaps are the only legal action,
    per-round throughput decides how many rounds convergence takes."""
    res = goal.resource
    p_count, r = dims.num_partitions, dims.max_rf
    n_pairs = max(1, min(n_pairs, dims.num_brokers // 2 or 1))
    k = max(1, min(k, p_count))
    del priors  # prior-goal invariants arrive via the merged tables

    def swap_round(static: StaticCtx, agg: Aggregates, tables):
        gs = goal.prepare(static, agg, dims)
        cap = jnp.maximum(static.broker_capacity[:, res], 1e-9)
        util = agg.broker_load[:, res] / cap

        # both ends RECEIVE a replica (mv2 lands on the hot broker), so both
        # must be eligible destinations; swaps are disabled entirely in
        # immigrant-only self-healing mode (a swap moves non-immigrants).
        hot_rank = jnp.where(static.alive & static.replica_dst_ok, util, -jnp.inf)
        hot_vals, hot = jax.lax.top_k(hot_rank, n_pairs)  # i32[N]
        cold_rank = jnp.where(static.alive & static.replica_dst_ok, -util, -jnp.inf)
        cold_vals, cold = jax.lax.top_k(cold_rank, n_pairs)  # i32[N]
        # full hot x cold cross product [NH, NC, K, K]: rank-matched pairing
        # (hot_i only with cold_i) stalls as soon as a few extreme brokers
        # have no compatible exchange — under tight prior-goal bounds (e.g. a
        # balanced-disk table) finding a *compatible* partner is the whole
        # search problem, so every hot broker considers every cold broker.
        pair_ok = (
            jnp.isfinite(hot_vals)[:, None, None, None]
            & jnp.isfinite(cold_vals)[None, :, None, None]
            & (hot[:, None, None, None] != cold[None, :, None, None])
            & ~static.only_move_immigrants
        )

        contrib = _slot_contrib(static, agg.assignment, res)  # f32[P, R]
        movable = static.movable_partition[:, None] & (agg.assignment >= 0)

        def pick(broker, heaviest: bool):
            mask = (agg.assignment == broker) & movable
            score = jnp.where(mask, contrib, -jnp.inf if heaviest else jnp.inf)
            flat = (score if heaviest else -score).reshape(p_count * r)
            vals, idx = jax.lax.top_k(flat, k)
            return (
                (idx // r).astype(jnp.int32),  # partitions
                (idx % r).astype(jnp.int32),  # slots
                jnp.where(jnp.isfinite(vals), jnp.abs(vals), jnp.nan),  # loads
            )

        hp, hs, hl = jax.vmap(lambda b: pick(b, True))(hot)  # [N, K] each
        cp, cs, cl = jax.vmap(lambda b: pick(b, False))(cold)

        # [NH, NC, K, K] swap grid: replica a of hot_i <-> replica b of cold_j
        delta = hl[:, None, :, None] - cl[None, :, None, :]  # load moved hot -> cold
        ok = jnp.isfinite(delta) & (delta > SCORE_EPS) & pair_ok
        ok &= hp[:, None, :, None] != cp[None, :, None, :]

        # every previously-optimized goal must accept the swap's NET effect
        # (atomic swap acceptance, AbstractGoal.maybeApplySwapAction :240)
        hot_b = hot[:, None, None, None]
        cold_b = cold[None, :, None, None]
        mv1b = build_selected(
            static.part_load, agg.assignment,
            hp[:, None, :, None], jnp.int32(KIND_MOVE), hs[:, None, :, None], cold_b,
        )
        mv2b = build_selected(
            static.part_load, agg.assignment,
            cp[None, :, None, :], jnp.int32(KIND_MOVE), cs[None, :, None, :], hot_b,
        )
        ok &= swap_tables_acceptance(static, tables, agg, mv1b, mv2b)

        # neither broker may already host the other's partition
        ok &= ~jnp.any(agg.assignment[hp[:, None, :, None]] == cold_b[..., None], axis=-1)
        ok &= ~jnp.any(agg.assignment[cp[None, :, None, :]] == hot_b[..., None], axis=-1)

        # rack safety for both directions (RackAwareGoal acceptance)
        rack_hot = static.broker_rack[hot][:, None, None, None]
        rack_cold = static.broker_rack[cold][None, :, None, None]
        same_rack = rack_hot == rack_cold
        full = (n_pairs, n_pairs, k, k)
        cnt1 = agg.rack_replica_count[
            jnp.broadcast_to(hp[:, None, :, None], full), jnp.broadcast_to(rack_cold, full)
        ]
        ok &= (cnt1 - same_rack.astype(cnt1.dtype)) == 0
        cnt2 = agg.rack_replica_count[
            jnp.broadcast_to(cp[None, :, None, :], full), jnp.broadcast_to(rack_hot, full)
        ]
        ok &= (cnt2 - same_rack.astype(cnt2.dtype)) == 0

        # leadership eligibility when a leader slot changes brokers
        ok &= (hs[:, None, :, None] != 0) | static.leadership_dst_ok[cold][None, :, None, None]
        ok &= (cs[None, :, None, :] != 0) | static.leadership_dst_ok[hot][:, None, None, None]

        # capacity + potential NW_OUT must not get worse on either end
        # (CapacityGoal / PotentialNwOutGoal acceptance, conservative form)
        h_load1 = _all_res_contrib(static, agg.assignment, hp, hs)  # [NH, K, 4]
        c_load2 = _all_res_contrib(static, agg.assignment, cp, cs)  # [NC, K, 4]
        net = h_load1[:, None, :, None, :] - c_load2[None, :, None, :, :]  # [NH,NC,K,K,4]
        hot_before = agg.broker_load[hot][:, None, None, None, :]
        cold_before = agg.broker_load[cold][None, :, None, None, :]
        hot_after = hot_before - net
        cold_after = cold_before + net
        hot_limit = jnp.maximum(static.capacity_limit[hot][:, None, None, None, :], hot_before)
        cold_limit = jnp.maximum(static.capacity_limit[cold][None, :, None, None, :], cold_before)
        ok &= jnp.all(hot_after <= hot_limit + 1e-6, axis=-1)
        ok &= jnp.all(cold_after <= cold_limit + 1e-6, axis=-1)
        pnw1 = static.part_load[hp, PartMetric.NW_OUT_LEADER][:, None, :, None]
        pnw2 = static.part_load[cp, PartMetric.NW_OUT_LEADER][None, :, None, :]
        pnw_limit = static.capacity_limit[:, Resource.NW_OUT]
        pnw_cold0 = agg.potential_nw_out[cold][None, :, None, None]
        pnw_hot0 = agg.potential_nw_out[hot][:, None, None, None]
        ok &= pnw_cold0 + pnw1 - pnw2 <= jnp.maximum(
            pnw_limit[cold][None, :, None, None], pnw_cold0
        ) + 1e-6
        ok &= pnw_hot0 - pnw1 + pnw2 <= jnp.maximum(
            pnw_limit[hot][:, None, None, None], pnw_hot0
        ) + 1e-6

        # goal improvement: imbalance reduction of the (hot, cold) pair; like
        # the move path, NEITHER endpoint may get individually worse (the
        # reference's swap search keeps both brokers within their limits —
        # rebalanceBySwappingLoadOut only swaps toward in-range states)
        u_h = util[hot][:, None, None, None]
        u_c = util[cold][None, :, None, None]
        d_h = delta / cap[hot][:, None, None, None]
        d_c = delta / cap[cold][None, :, None, None]
        h0, h1 = _dist(u_h, gs), _dist(u_h - d_h, gs)
        c0, c1 = _dist(u_c, gs), _dist(u_c + d_c, gs)
        endpoint_ok = (h1 <= h0 + SCORE_EPS) & (c1 <= c0 + SCORE_EPS)
        score = jnp.where(ok & endpoint_ok & gs.active, h0 + c0 - h1 - c1, -jnp.inf)

        # conflict-free apply waves: per wave every hot broker nominates its
        # best remaining swap with ONE cold partner — hot rank i paired with
        # cold rank (i + wave) % N (a per-hot argmax over all colds would
        # converge on the same best partner and serialize to one swap per
        # wave; the rotation walks each hot broker through every partner
        # across waves). Nominations are re-validated against the CURRENT
        # aggregates — including the merged prior-goal tables — and a
        # broker-disjoint subset (both endpoints unique, both endpoint hosts
        # unique: a swap loads BOTH ends) applies at once. Depth: `waves`
        # sequential steps instead of the former
        # n_pairs*swaps_per_broker-long scan.
        waves = max(apply_waves, swaps_per_broker, 4)
        rows0 = jnp.arange(n_pairs, dtype=jnp.int32)
        kind_move = jnp.full((n_pairs,), KIND_MOVE, dtype=jnp.int32)
        n_brokers = static.broker_capacity.shape[0]
        n_hosts = static.host_cpu_capacity_limit.shape[0]

        def wave(carry, w):
            agg_c, any_applied, cell_blk = carry
            masked = jnp.where(cell_blk, -jnp.inf, score)

            # rank-paired partner per wave; the LAST wave argmaxes over ALL
            # partners — the tail's one compatible exchange may not be the
            # rotation's pick (see dist_round)
            def paired(masked):
                j_i = ((rows0 + w) % n_pairs).astype(jnp.int32)
                block = jnp.take_along_axis(
                    masked, j_i[:, None, None, None], axis=1
                )[:, 0].reshape(n_pairs, k * k)
                bi = jnp.argmax(block, axis=1)
                return (
                    j_i,
                    (bi // k).astype(jnp.int32),
                    (bi % k).astype(jnp.int32),
                    jnp.take_along_axis(block, bi[:, None], axis=1)[:, 0],
                )

            def argmax_all(masked):
                flat = masked.reshape(n_pairs, n_pairs * k * k)
                bi = jnp.argmax(flat, axis=1)
                return (
                    (bi // (k * k)).astype(jnp.int32),
                    ((bi // k) % k).astype(jnp.int32),
                    (bi % k).astype(jnp.int32),
                    jnp.take_along_axis(flat, bi[:, None], axis=1)[:, 0],
                )

            j_idx, a_idx, b_idx, bs = jax.lax.cond(
                w == waves - 1, argmax_all, paired, masked
            )
            p1 = hp[rows0, a_idx]
            s1 = hs[rows0, a_idx]
            p2 = cp[j_idx, b_idx]
            s2 = cs[j_idx, b_idx]
            h = hot
            c = cold[j_idx]
            # re-validate against the updated aggregates: both replicas still
            # on their brokers, neither endpoint hosts the other's partition,
            # rack safety vs CURRENT counts, swap still improves the pair
            still = (agg_c.assignment[p1, s1] == h) & (agg_c.assignment[p2, s2] == c)
            still &= ~jnp.any(agg_c.assignment[p1] == c[:, None], axis=-1)
            still &= ~jnp.any(agg_c.assignment[p2] == h[:, None], axis=-1)
            rack_h = static.broker_rack[h]
            rack_c = static.broker_rack[c]
            same_rack = (rack_h == rack_c).astype(agg_c.rack_replica_count.dtype)
            still &= (agg_c.rack_replica_count[p1, rack_c] - same_rack) == 0
            still &= (agg_c.rack_replica_count[p2, rack_h] - same_rack) == 0
            u_h2 = agg_c.broker_load[h, res] / cap[h]
            u_c2 = agg_c.broker_load[c, res] / cap[c]
            d = contrib[p1, s1] - contrib[p2, s2]
            h0r, h1r = _dist(u_h2, gs), _dist(u_h2 - d / cap[h], gs)
            c0r, c1r = _dist(u_c2, gs), _dist(u_c2 + d / cap[c], gs)
            improve = h0r + c0r - h1r - c1r
            endpoint_ok2 = (h1r <= h0r + SCORE_EPS) & (c1r <= c0r + SCORE_EPS)
            # re-check the merged prior-goal tables against the CURRENT
            # aggregates: an earlier wave may have loaded an endpoint right up
            # to a hard capacity box that the round-start grid check predates
            mv1v = build_selected(
                static.part_load, agg_c.assignment, p1, kind_move, s1, c
            )
            mv2v = build_selected(
                static.part_load, agg_c.assignment, p2, kind_move, s2, h
            )
            tables_ok = swap_tables_acceptance(static, tables, agg_c, mv1v, mv2v)
            valid = still & endpoint_ok2 & (improve > SCORE_EPS) & tables_ok
            ok = jnp.isfinite(bs) & valid
            sel = wave_select(
                jnp.where(ok, improve, -jnp.inf), h, c,
                static.broker_host[c], ok, n_brokers, n_hosts,
                dst_host2=static.broker_host[h],
                parts=(p1, p2), num_partitions=p_count,
            )
            # mv1v/mv2v from the validation step are exact here too: applying
            # mv1 can't change p2's row (the grid mask excludes p1 == p2), so
            # mv2's deltas are unchanged
            agg_c = apply_actions_batch(static, agg_c, mv1v, sel)
            agg_c = apply_actions_batch(static, agg_c, mv2v, sel)
            # applied or stale-invalid nominations are dead cells; conflict
            # losers stay available for the next wave
            dead = sel | (jnp.isfinite(bs) & ~valid)
            cell_blk = cell_blk.at[rows0, j_idx, a_idx, b_idx].set(
                cell_blk[rows0, j_idx, a_idx, b_idx] | dead
            )
            return (agg_c, any_applied | jnp.any(sel), cell_blk), None

        init = (
            agg,
            jnp.asarray(False),
            jnp.zeros((n_pairs, n_pairs, k, k), dtype=bool),
        )
        (agg2, applied_any, _), _ = jax.lax.scan(
            wave, init, jnp.arange(waves, dtype=jnp.int32)
        )
        return agg2, applied_any

    return swap_round


def make_distribution_round(goal, dims, n_hot: int = 16, k_rep: int = 16,
                            j_apply: int = 4, k_dst: int = 16,
                            apply_waves: int = 0):
    """Move phase for resource-distribution goals: the array form of
    rebalanceByMovingLoadOut/-In (cc/analyzer/goals/ResourceDistributionGoal.java
    :364,:699) — per hot broker, drain its heaviest replicas toward the
    coldest brokers; fill under-loaded brokers from the richest.

    The reference's AbstractGoal pass applies MANY actions per broker while
    walking brokersToBalance (rebalanceForBroker), so applying the top-J
    moves per hot broker under sequential re-validation is structurally the
    reference loop, vectorized. Unlike the optimizer's global [P, R, K] grid
    + top-k shortlist — which picks the k best *partitions* against stale
    state and degrades the reachable optimum as k grows — this kernel's cost
    is independent of P (top_k pulls per-broker replica lists), so rounds are
    cheap enough to keep near-greedy action quality at full scale.
    """
    res = goal.resource
    p_count, r = dims.num_partitions, dims.max_rf
    n_hot = max(1, min(n_hot, dims.num_brokers))
    n_cold = n_hot
    k_rep = max(1, min(k_rep, p_count))
    use_leadership = goal.uses_leadership and r >= 2
    j_lead = max(4, j_apply)

    def dist_round(static: StaticCtx, agg: Aggregates, tables, gs):
        cap = jnp.maximum(static.broker_capacity[:, res], 1e-9)
        util = agg.broker_load[:, res] / cap

        # dead brokers outrank every live one as sources: evacuation comes
        # first (GoalUtils.ensureNoReplicaOnDeadBrokers), and score_batch's
        # DEAD_EVACUATION_BONUS makes their moves win the selection
        hot_rank = jnp.where(static.dead, jnp.inf, util)
        _, hot = jax.lax.top_k(hot_rank, n_hot)  # i32[V] sources (richest)
        cold_rank = jnp.where(static.alive & static.replica_dst_ok, -util, -jnp.inf)
        cold_ok, cold = jax.lax.top_k(cold_rank, n_cold)  # i32[V] receivers

        contrib = _slot_contrib(static, agg.assignment, res)
        movable = static.movable_partition[:, None] & (agg.assignment >= 0)

        def pick_heavy(broker):
            mask = (agg.assignment == broker) & movable
            score = jnp.where(mask, contrib, -jnp.inf)
            vals, idx = jax.lax.top_k(score.reshape(p_count * r), k_rep)
            return (idx // r).astype(jnp.int32), (idx % r).astype(jnp.int32)

        hp, hs = jax.vmap(pick_heavy)(hot)  # [V, K]

        # move grid [V, K, C]: replica k of hot_i -> cold_j
        full = (n_hot, k_rep, n_cold)
        mv = build_selected(
            static.part_load, agg.assignment,
            jnp.broadcast_to(hp[:, :, None], full),
            jnp.int32(KIND_MOVE),
            jnp.broadcast_to(hs[:, :, None], full),
            jnp.broadcast_to(cold[None, None, :], full),
        )
        from cruise_control_tpu.analyzer.acceptance import score_batch

        s = score_batch(static, agg, mv, goal, gs, tables)
        s = jnp.where(jnp.isfinite(cold_ok)[None, None, :], s, -jnp.inf)

        # leadership family (CPU / NW_OUT shift util without moving data):
        # global [P, R-1] grid, top-J overall
        if use_leadership:
            from cruise_control_tpu.analyzer.actions import make_leadership_batch

            lb = make_leadership_batch(static.part_load, agg.assignment)
            sl = score_batch(static, agg, lb, goal, gs, tables)
            sl = jnp.broadcast_to(sl, (p_count, r - 1)).reshape(p_count * (r - 1))
            lead_s, lead_i = jax.lax.top_k(sl, j_lead)
            lead_p = (lead_i // (r - 1)).astype(jnp.int32)
            lead_slot = (lead_i % (r - 1)).astype(jnp.int32) + 1
            lead_kind = jnp.full((j_lead,), KIND_LEADERSHIP, dtype=jnp.int32)

        # conflict-free apply waves (context.wave_select contract): per wave,
        # every hot broker nominates its best remaining replica toward ONE
        # cold broker — hot rank i paired with cold rank (i + wave) % C, the
        # sorted-by-sorted matching. A per-hot argmax over ALL colds would
        # send every hot broker to the same most-underloaded cold and the
        # per-destination uniqueness would then admit one move per wave;
        # rank-pairing keeps the full hot set moving in parallel, and the
        # wave rotation retries failed pairs against other colds. Nominations
        # are re-scored against the CURRENT aggregates and a broker-disjoint
        # subset applies at once. Sequential depth per round: `waves`, vs the
        # former n_hot*j_apply-long re-validated scan.
        rows0 = jnp.arange(n_hot, dtype=jnp.int32)
        kind_move = jnp.full((n_hot,), KIND_MOVE, dtype=jnp.int32)
        waves = max(apply_waves, j_apply, 4)

        def wave(carry, w):
            agg_c, applied_any, cell_blk, rep_gone, lead_done = carry
            blocked = cell_blk | rep_gone[:, :, None]
            masked = jnp.where(blocked, -jnp.inf, s)
            # rank-paired waves for throughput; the LAST wave argmaxes over
            # the full (replica, cold) grid instead — precision for the tail,
            # where the one legal pairing may not be the rotation's pick
            # (grid argmax can collapse onto one cold broker, but as a final
            # wave that still applies the single best remaining move)
            def paired(masked):
                c_i = ((rows0 + w) % n_cold).astype(jnp.int32)
                col = jnp.take_along_axis(masked, c_i[:, None, None], axis=2)[:, :, 0]
                a_i = jnp.argmax(col, axis=1).astype(jnp.int32)
                return a_i, c_i, jnp.take_along_axis(col, a_i[:, None], axis=1)[:, 0]

            def argmax_all(masked):
                flat = masked.reshape(n_hot, k_rep * n_cold)
                bi = jnp.argmax(flat, axis=1)
                return (
                    (bi // n_cold).astype(jnp.int32),
                    (bi % n_cold).astype(jnp.int32),
                    jnp.take_along_axis(flat, bi[:, None], axis=1)[:, 0],
                )

            a_idx, c_idx, bs = jax.lax.cond(
                w == waves - 1, argmax_all, paired, masked
            )
            p_e = hp[rows0, a_idx]
            slot_e = hs[rows0, a_idx]
            dst_e = cold[c_idx]
            act = build_selected(
                static.part_load, agg_c.assignment, p_e, kind_move, slot_e, dst_e
            )
            s_now = score_batch(static, agg_c, act, goal, gs, tables)
            ok = jnp.isfinite(bs) & jnp.isfinite(s_now)
            all_p, all_kind, all_slot = p_e, kind_move, slot_e
            all_dst, all_score, all_ok = dst_e, s_now, ok
            if use_leadership:
                l_dst = agg_c.assignment[lead_p, lead_slot]
                lact = build_selected(
                    static.part_load, agg_c.assignment, lead_p, lead_kind,
                    lead_slot, l_dst,
                )
                ls_now = score_batch(static, agg_c, lact, goal, gs, tables)
                lok = jnp.isfinite(lead_s) & jnp.isfinite(ls_now) & ~lead_done
                all_p = jnp.concatenate([all_p, lead_p])
                all_kind = jnp.concatenate([all_kind, lead_kind])
                all_slot = jnp.concatenate([all_slot, lead_slot])
                all_dst = jnp.concatenate([all_dst, l_dst])
                all_score = jnp.concatenate([all_score, ls_now])
                all_ok = jnp.concatenate([all_ok, lok])
            all_act = build_selected(
                static.part_load, agg_c.assignment, all_p, all_kind, all_slot, all_dst
            )
            sel = wave_select(
                all_score, all_act.src, all_act.dst,
                static.broker_host[all_act.dst], all_ok,
                static.broker_capacity.shape[0], static.host_cpu_capacity_limit.shape[0],
                parts=(all_p,), num_partitions=p_count,
            )
            agg_c = apply_actions_batch(static, agg_c, all_act, sel)
            sel_mv = sel[:n_hot]
            # a moved replica is gone from its hot broker; a nomination that
            # failed re-scoring is a dead cell (retrying it would stall the
            # argmax) — conflict losers stay available for the next wave
            rep_gone = rep_gone.at[rows0, a_idx].set(rep_gone[rows0, a_idx] | sel_mv)
            fail = jnp.isfinite(bs) & ~jnp.isfinite(s_now)
            cell_blk = cell_blk.at[rows0, a_idx, c_idx].set(
                cell_blk[rows0, a_idx, c_idx] | fail
            )
            if use_leadership:
                lead_done = lead_done | sel[n_hot:]
            return (agg_c, applied_any | jnp.any(sel), cell_blk, rep_gone, lead_done), None

        init = (
            agg,
            jnp.asarray(False),
            jnp.zeros((n_hot, k_rep, n_cold), dtype=bool),
            jnp.zeros((n_hot, k_rep), dtype=bool),
            jnp.zeros((j_lead,), dtype=bool),
        )
        (agg2, applied_any, _, _, _), _ = jax.lax.scan(
            wave, init, jnp.arange(waves, dtype=jnp.int32)
        )
        return agg2, applied_any

    return dist_round


def _dist(u, gs):
    return jnp.maximum(0.0, u - gs.upper) + jnp.maximum(0.0, gs.lower - u)


def _all_res_contrib(static: StaticCtx, assignment: jax.Array, p, slot) -> jax.Array:
    """f32[..., 4]: full per-Resource contribution of replica (p, slot)."""
    lead = _leader_vec(static.part_load, p)
    foll = _follower_vec(static.part_load, p)
    return jnp.where((slot == 0)[..., None], lead, foll)
