"""Replica swap search for resource-distribution goals.

The array-native counterpart of ResourceDistributionGoal's swap phase
(cc/analyzer/goals/ResourceDistributionGoal.java: rebalanceBySwappingLoadOut
:482 / ...In :610, the INTER_BROKER_REPLICA_SWAP action): when single moves
can no longer help — the classic deadlock is a hot broker whose every
candidate move is too big for any destination — exchange a heavy replica on
an over-limit broker for a light replica on an under-loaded one.

Where the reference walks SortedReplicas views under a 1 s/broker timeout,
this kernel scores a pruned dense grid in one shot:

  top-N hottest brokers x top-K heaviest movable replicas each
  paired with the N coldest brokers x their K lightest replicas
  -> [N, K, K] swap candidates, scored by imbalance reduction and masked by
  the prior-goal invariants (rack safety for BOTH partitions, capacity and
  potential-NW_OUT not-worse on both ends, leadership eligibility when a
  leader slot moves), then applied via a sequentially re-validated scan.

Replica counts are unchanged by a swap, so replica-capacity/distribution
goals are unaffected by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.actions import (
    KIND_LEADERSHIP,
    KIND_MOVE,
    _follower_vec,
    _leader_vec,
    build_selected,
)
from cruise_control_tpu.analyzer.acceptance import swap_tables_acceptance
from cruise_control_tpu.analyzer.context import (
    Aggregates,
    StaticCtx,
    apply_actions_batch,
    make_touch_tag,
    wave_select,
)
from cruise_control_tpu.analyzer.goals.base import SCORE_EPS
from cruise_control_tpu.common.resources import PartMetric, Resource


from cruise_control_tpu.analyzer.actions import slot_contrib


def _slot_contrib(static: StaticCtx, assignment: jax.Array, res: int) -> jax.Array:
    """f32[P, R]: per-slot load contribution for one resource."""
    return slot_contrib(static.part_load, assignment, res)


def make_swap_round(goal, priors, dims, n_pairs: int = 8, k: int = 8,
                    swaps_per_broker: int = 4, apply_waves: int = 0):
    """Build swap_round(static, agg, tables, contrib_in) -> (agg, applied_any)
    for a resource-distribution goal (jit-compatible; call inside the goal
    loop).

    `tables` are the merged acceptance bounds of the already-optimized goals
    (analyzer.acceptance): every candidate swap's NET effect must pass them,
    the same invariant the move path enforces per candidate. `contrib_in` is
    the goal's per-replica drain priority for the CURRENT aggregates
    (goal.drain_contrib, shared with the drain round): heavy_picks reads a
    hot broker's top-k heaviest candidates from it and light_picks a cold
    broker's k lightest — sort-free segment passes instead of per-broker
    top_k searches over the whole replica axis."""
    res = goal.resource
    p_count, r = dims.num_partitions, dims.max_rf
    n_pairs = max(1, min(n_pairs, dims.num_brokers // 2 or 1))
    k = max(1, min(k, p_count))
    del priors  # prior-goal invariants arrive via the merged tables

    def swap_round(static: StaticCtx, agg: Aggregates, tables, contrib_in,
                   rnd=jnp.int32(-1)):
        from cruise_control_tpu.analyzer.drain import heavy_picks, light_picks

        gs = goal.prepare(static, agg, dims)
        cap = jnp.maximum(static.broker_capacity[:, res], 1e-9)
        util = agg.broker_load[:, res] / cap

        # both ends RECEIVE a replica (mv2 lands on the hot broker), so both
        # must be eligible destinations; swaps are disabled entirely in
        # immigrant-only self-healing mode (a swap moves non-immigrants).
        hot_rank = jnp.where(static.alive & static.replica_dst_ok, util, -jnp.inf)
        hot_vals, hot = jax.lax.top_k(hot_rank, n_pairs)  # i32[N]
        hot = hot.astype(jnp.int32)
        cold_rank = jnp.where(static.alive & static.replica_dst_ok, -util, -jnp.inf)
        cold_vals, cold = jax.lax.top_k(cold_rank, n_pairs)  # i32[N]
        cold = cold.astype(jnp.int32)
        # full hot x cold cross product [NH, NC, K, K]: rank-matched pairing
        # (hot_i only with cold_i) stalls as soon as a few extreme brokers
        # have no compatible exchange — under tight prior-goal bounds (e.g. a
        # balanced-disk table) finding a *compatible* partner is the whole
        # search problem, so every hot broker considers every cold broker.
        pair_ok = (
            jnp.isfinite(hot_vals)[:, None, None, None]
            & jnp.isfinite(cold_vals)[None, :, None, None]
            & (hot[:, None, None, None] != cold[None, :, None, None])
            & ~static.only_move_immigrants
        )

        contrib = _slot_contrib(static, agg.assignment, res)  # f32[P, R]

        nb = static.broker_capacity.shape[0]
        hp, hs, h_ok = heavy_picks(static, agg, contrib_in, hot, k, nb)  # [N, K]
        hl = jnp.where(h_ok, contrib[hp, hs], jnp.nan)
        cp, cs, c_ok = light_picks(static, agg, contrib_in, cold, k, nb)
        cl = jnp.where(c_ok, contrib[cp, cs], jnp.nan)

        # [NH, NC, K, K] swap grid: replica a of hot_i <-> replica b of cold_j
        delta = hl[:, None, :, None] - cl[None, :, None, :]  # load moved hot -> cold
        ok = jnp.isfinite(delta) & (delta > SCORE_EPS) & pair_ok
        ok &= hp[:, None, :, None] != cp[None, :, None, :]

        # every previously-optimized goal must accept the swap's NET effect
        # (atomic swap acceptance, AbstractGoal.maybeApplySwapAction :240)
        hot_b = hot[:, None, None, None]
        cold_b = cold[None, :, None, None]
        mv1b = build_selected(
            static.part_load, agg.assignment,
            hp[:, None, :, None], jnp.int32(KIND_MOVE), hs[:, None, :, None], cold_b,
        )
        mv2b = build_selected(
            static.part_load, agg.assignment,
            cp[None, :, None, :], jnp.int32(KIND_MOVE), cs[None, :, None, :], hot_b,
        )
        ok &= swap_tables_acceptance(static, tables, agg, mv1b, mv2b)

        # neither broker may already host the other's partition
        ok &= ~jnp.any(agg.assignment[hp[:, None, :, None]] == cold_b[..., None], axis=-1)
        ok &= ~jnp.any(agg.assignment[cp[None, :, None, :]] == hot_b[..., None], axis=-1)

        # rack safety for both directions, only when RackAwareGoal actually
        # ran before this goal (tables_acceptance gates the move path the
        # same way) — unconditional checking would freeze swaps in
        # rack-colocated layouts with no rack goal in the stack
        rack_hot = static.broker_rack[hot][:, None, None, None]
        rack_cold = static.broker_rack[cold][None, :, None, None]
        same_rack = rack_hot == rack_cold
        full = (n_pairs, n_pairs, k, k)
        cnt1 = agg.rack_replica_count[
            jnp.broadcast_to(hp[:, None, :, None], full), jnp.broadcast_to(rack_cold, full)
        ]
        rack_safe = (cnt1 - same_rack.astype(cnt1.dtype)) == 0
        cnt2 = agg.rack_replica_count[
            jnp.broadcast_to(cp[None, :, None, :], full), jnp.broadcast_to(rack_hot, full)
        ]
        rack_safe &= (cnt2 - same_rack.astype(cnt2.dtype)) == 0
        ok &= rack_safe | ~tables.rack_enabled

        # leadership eligibility when a leader slot changes brokers
        ok &= (hs[:, None, :, None] != 0) | static.leadership_dst_ok[cold][None, :, None, None]
        ok &= (cs[None, :, None, :] != 0) | static.leadership_dst_ok[hot][:, None, None, None]

        # capacity + potential NW_OUT must not get worse on either end
        # (CapacityGoal / PotentialNwOutGoal acceptance, conservative form)
        h_load1 = _all_res_contrib(static, agg.assignment, hp, hs)  # [NH, K, 4]
        c_load2 = _all_res_contrib(static, agg.assignment, cp, cs)  # [NC, K, 4]
        net = h_load1[:, None, :, None, :] - c_load2[None, :, None, :, :]  # [NH,NC,K,K,4]
        hot_before = agg.broker_load[hot][:, None, None, None, :]
        cold_before = agg.broker_load[cold][None, :, None, None, :]
        hot_after = hot_before - net
        cold_after = cold_before + net
        hot_limit = jnp.maximum(static.capacity_limit[hot][:, None, None, None, :], hot_before)
        cold_limit = jnp.maximum(static.capacity_limit[cold][None, :, None, None, :], cold_before)
        ok &= jnp.all(hot_after <= hot_limit + 1e-6, axis=-1)
        ok &= jnp.all(cold_after <= cold_limit + 1e-6, axis=-1)
        pnw1 = static.part_load[hp, PartMetric.NW_OUT_LEADER][:, None, :, None]
        pnw2 = static.part_load[cp, PartMetric.NW_OUT_LEADER][None, :, None, :]
        pnw_limit = static.capacity_limit[:, Resource.NW_OUT]
        pnw_cold0 = agg.potential_nw_out[cold][None, :, None, None]
        pnw_hot0 = agg.potential_nw_out[hot][:, None, None, None]
        ok &= pnw_cold0 + pnw1 - pnw2 <= jnp.maximum(
            pnw_limit[cold][None, :, None, None], pnw_cold0
        ) + 1e-6
        ok &= pnw_hot0 - pnw1 + pnw2 <= jnp.maximum(
            pnw_limit[hot][:, None, None, None], pnw_hot0
        ) + 1e-6

        # goal improvement: imbalance reduction of the (hot, cold) pair; like
        # the move path, NEITHER endpoint may get individually worse (the
        # reference's swap search keeps both brokers within their limits —
        # rebalanceBySwappingLoadOut only swaps toward in-range states)
        u_h = util[hot][:, None, None, None]
        u_c = util[cold][None, :, None, None]
        d_h = delta / cap[hot][:, None, None, None]
        d_c = delta / cap[cold][None, :, None, None]
        h0, h1 = _dist(u_h, gs), _dist(u_h - d_h, gs)
        c0, c1 = _dist(u_c, gs), _dist(u_c + d_c, gs)
        endpoint_ok = (h1 <= h0 + SCORE_EPS) & (c1 <= c0 + SCORE_EPS)
        score = jnp.where(ok & endpoint_ok & gs.active, h0 + c0 - h1 - c1, -jnp.inf)

        # conflict-free apply waves: per wave every hot broker nominates its
        # best remaining swap with ONE cold partner — hot rank i paired with
        # cold rank (i + wave) % N (a per-hot argmax over all colds would
        # converge on the same best partner and serialize to one swap per
        # wave; the rotation walks each hot broker through every partner
        # across waves). Nominations are re-validated against the CURRENT
        # aggregates — including the merged prior-goal tables — and a
        # broker-disjoint subset (both endpoints unique, both endpoint hosts
        # unique: a swap loads BOTH ends) applies at once. Depth: `waves`
        # sequential steps instead of the former
        # n_pairs*swaps_per_broker-long scan.
        waves = max(apply_waves, swaps_per_broker, 4)
        rows0 = jnp.arange(n_pairs, dtype=jnp.int32)
        kind_move = jnp.full((n_pairs,), KIND_MOVE, dtype=jnp.int32)
        n_brokers = static.broker_capacity.shape[0]
        n_hosts = static.host_cpu_capacity_limit.shape[0]

        def wave(carry, w):
            agg_c, any_applied, cell_blk = carry
            masked = jnp.where(cell_blk, -jnp.inf, score)

            # rank-paired partner per wave; the LAST wave argmaxes over ALL
            # partners — the tail's one compatible exchange may not be the
            # rotation's pick (see dist_round)
            def paired(masked):
                j_i = ((rows0 + w) % n_pairs).astype(jnp.int32)
                block = jnp.take_along_axis(
                    masked, j_i[:, None, None, None], axis=1
                )[:, 0].reshape(n_pairs, k * k)
                bi = jnp.argmax(block, axis=1)
                return (
                    j_i,
                    (bi // k).astype(jnp.int32),
                    (bi % k).astype(jnp.int32),
                    jnp.take_along_axis(block, bi[:, None], axis=1)[:, 0],
                )

            def argmax_all(masked):
                flat = masked.reshape(n_pairs, n_pairs * k * k)
                bi = jnp.argmax(flat, axis=1)
                return (
                    (bi // (k * k)).astype(jnp.int32),
                    ((bi // k) % k).astype(jnp.int32),
                    (bi % k).astype(jnp.int32),
                    jnp.take_along_axis(flat, bi[:, None], axis=1)[:, 0],
                )

            j_idx, a_idx, b_idx, bs = jax.lax.cond(
                w == waves - 1, argmax_all, paired, masked
            )
            p1 = hp[rows0, a_idx]
            s1 = hs[rows0, a_idx]
            p2 = cp[j_idx, b_idx]
            s2 = cs[j_idx, b_idx]
            h = hot
            c = cold[j_idx]
            # re-validate against the updated aggregates: both replicas still
            # on their brokers, neither endpoint hosts the other's partition,
            # rack safety vs CURRENT counts, swap still improves the pair
            still = (agg_c.assignment[p1, s1] == h) & (agg_c.assignment[p2, s2] == c)
            still &= ~jnp.any(agg_c.assignment[p1] == c[:, None], axis=-1)
            still &= ~jnp.any(agg_c.assignment[p2] == h[:, None], axis=-1)
            rack_h = static.broker_rack[h]
            rack_c = static.broker_rack[c]
            same_rack = (rack_h == rack_c).astype(agg_c.rack_replica_count.dtype)
            rack_safe = ((agg_c.rack_replica_count[p1, rack_c] - same_rack) == 0) & (
                (agg_c.rack_replica_count[p2, rack_h] - same_rack) == 0
            )
            still &= rack_safe | ~tables.rack_enabled
            u_h2 = agg_c.broker_load[h, res] / cap[h]
            u_c2 = agg_c.broker_load[c, res] / cap[c]
            d = contrib[p1, s1] - contrib[p2, s2]
            h0r, h1r = _dist(u_h2, gs), _dist(u_h2 - d / cap[h], gs)
            c0r, c1r = _dist(u_c2, gs), _dist(u_c2 + d / cap[c], gs)
            improve = h0r + c0r - h1r - c1r
            endpoint_ok2 = (h1r <= h0r + SCORE_EPS) & (c1r <= c0r + SCORE_EPS)
            # re-check the merged prior-goal tables against the CURRENT
            # aggregates: an earlier wave may have loaded an endpoint right up
            # to a hard capacity box that the round-start grid check predates
            mv1v = build_selected(
                static.part_load, agg_c.assignment, p1, kind_move, s1, c
            )
            mv2v = build_selected(
                static.part_load, agg_c.assignment, p2, kind_move, s2, h
            )
            tables_ok = swap_tables_acceptance(static, tables, agg_c, mv1v, mv2v)
            valid = still & endpoint_ok2 & (improve > SCORE_EPS) & tables_ok
            ok = jnp.isfinite(bs) & valid
            sel = wave_select(
                jnp.where(ok, improve, -jnp.inf), h, c,
                static.broker_host[c], ok, n_brokers, n_hosts,
                dst_host2=static.broker_host[h],
                parts=(p1, p2), num_partitions=p_count,
            )
            # mv1v/mv2v from the validation step are exact here too: applying
            # mv1 can't change p2's row (the grid mask excludes p1 == p2), so
            # mv2's deltas are unchanged
            agg_c = apply_actions_batch(
                static, agg_c, mv1v, sel, tag=make_touch_tag(rnd, w)
            )
            agg_c = apply_actions_batch(
                static, agg_c, mv2v, sel, tag=make_touch_tag(rnd, w)
            )
            # applied or stale-invalid nominations are dead cells; conflict
            # losers stay available for the next wave
            dead = sel | (jnp.isfinite(bs) & ~valid)
            cell_blk = cell_blk.at[rows0, j_idx, a_idx, b_idx].set(
                cell_blk[rows0, j_idx, a_idx, b_idx] | dead
            )
            return (agg_c, any_applied | jnp.any(sel), cell_blk), None

        init = (
            agg,
            jnp.asarray(False),
            jnp.zeros((n_pairs, n_pairs, k, k), dtype=bool),
        )
        (agg2, applied_any, _), _ = jax.lax.scan(
            wave, init, jnp.arange(waves, dtype=jnp.int32)
        )
        return agg2, applied_any

    return swap_round


def _dist(u, gs):
    return jnp.maximum(0.0, u - gs.upper) + jnp.maximum(0.0, gs.lower - u)


def _all_res_contrib(static: StaticCtx, assignment: jax.Array, p, slot) -> jax.Array:
    """f32[..., 4]: full per-Resource contribution of replica (p, slot)."""
    lead = _leader_vec(static.part_load, p)
    foll = _follower_vec(static.part_load, p)
    return jnp.where((slot == 0)[..., None], lead, foll)
