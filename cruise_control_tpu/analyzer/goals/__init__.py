# cclint: kernel-module
"""Goal registry: name -> singleton goal instance, in reference priority order.

Mirrors the default goal stack of cc/config/KafkaCruiseControlConfig.java:1287-1322
and the goal-name resolution in KafkaCruiseControl.goalsByPriority (:1218).
Java class paths from a reference cruisecontrol.properties resolve by simple
name, so operator configs carry over unchanged.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from cruise_control_tpu.analyzer.goals.base import Goal
from cruise_control_tpu.analyzer.goals.hard import (
    CapacityGoal,
    RackAwareGoal,
    ReplicaCapacityGoal,
)
from cruise_control_tpu.analyzer.goals.preferred import elect_preferred_leaders
from cruise_control_tpu.analyzer.goals.soft import (
    LeaderBytesInDistributionGoal,
    LeaderReplicaDistributionGoal,
    PotentialNwOutGoal,
    ReplicaDistributionGoal,
    ResourceDistributionGoal,
    TopicReplicaDistributionGoal,
)
from cruise_control_tpu.common.resources import Resource

#: Priority-ordered default stack (same order as the reference's default.goals).
DEFAULT_GOAL_ORDER: List[Goal] = [
    RackAwareGoal(),
    ReplicaCapacityGoal(),
    CapacityGoal(Resource.DISK),
    CapacityGoal(Resource.NW_IN),
    CapacityGoal(Resource.NW_OUT),
    CapacityGoal(Resource.CPU),
    ReplicaDistributionGoal(),
    PotentialNwOutGoal(),
    ResourceDistributionGoal(Resource.DISK),
    ResourceDistributionGoal(Resource.NW_IN),
    ResourceDistributionGoal(Resource.NW_OUT),
    ResourceDistributionGoal(Resource.CPU),
    TopicReplicaDistributionGoal(),
    LeaderReplicaDistributionGoal(),
    LeaderBytesInDistributionGoal(),
]

from cruise_control_tpu.analyzer.goals.kafka_assigner import (  # noqa: E402
    KafkaAssignerDiskUsageDistributionGoal,
    KafkaAssignerEvenRackAwareGoal,
)

#: kafka-assigner mode goals: resolvable by name, excluded from the default
#: stack; a KafkaAssigner-prefixed request switches modes
#: (cc/KafkaCruiseControlUtils.java:193)
KAFKA_ASSIGNER_GOALS: List[Goal] = [
    KafkaAssignerEvenRackAwareGoal(),
    KafkaAssignerDiskUsageDistributionGoal(),
]

GOAL_REGISTRY: Dict[str, Goal] = {
    g.name: g for g in DEFAULT_GOAL_ORDER + KAFKA_ASSIGNER_GOALS
}

HARD_GOAL_NAMES = [g.name for g in DEFAULT_GOAL_ORDER if g.is_hard]
SOFT_GOAL_NAMES = [g.name for g in DEFAULT_GOAL_ORDER if not g.is_hard]


def is_kafka_assigner_mode(names: Sequence[str] | None) -> bool:
    return bool(names) and any(n.rsplit(".", 1)[-1].startswith("KafkaAssigner") for n in names)


def get_goal(name: str) -> Goal:
    """Resolve a goal by simple or fully-qualified (Java or Python) name."""
    simple = name.rsplit(".", 1)[-1]
    if simple not in GOAL_REGISTRY:
        raise KeyError(f"unknown goal: {name!r} (known: {sorted(GOAL_REGISTRY)})")
    return GOAL_REGISTRY[simple]


def goals_by_priority(names: Sequence[str] | None = None) -> List[Goal]:
    """Requested goals in default-priority order; None = the full stack.

    KafkaAssigner-prefixed requests switch to kafka-assigner mode: those
    goals run in the requested order, rack-awareness first."""
    if names is None:
        return list(DEFAULT_GOAL_ORDER)
    wanted = {get_goal(n).name for n in names}
    if is_kafka_assigner_mode(names):
        non_assigner = [n for n in wanted if not n.startswith("KafkaAssigner")]
        if non_assigner:
            raise ValueError(
                f"cannot mix kafka-assigner and regular goals: {sorted(non_assigner)}"
            )
        return [g for g in KAFKA_ASSIGNER_GOALS if g.name in wanted]
    return [g for g in DEFAULT_GOAL_ORDER if g.name in wanted]


__all__ = [
    "Goal",
    "DEFAULT_GOAL_ORDER",
    "GOAL_REGISTRY",
    "HARD_GOAL_NAMES",
    "get_goal",
    "goals_by_priority",
    "elect_preferred_leaders",
]
