# cclint: kernel-module
"""PreferredLeaderElectionGoal: leadership back to the preferred replica.

The reference utility goal (cc/analyzer/goals/PreferredLeaderElectionGoal.java:33)
makes replica position 0 the leader everywhere, skipping replicas on dead or
demoted brokers; it is used by the demote flow
(cc/KafkaCruiseControl.demoteBrokers:434-474). In the flat model slot order is
the preference order and slot 0 is the leader, so the kernel promotes, for each
partition whose leader sits on an excluded (demoted/dead) broker, the
lowest-slot replica on an eligible broker — one vectorized swap pass instead
of a greedy loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.context import StaticCtx


def elect_preferred_leaders(static: StaticCtx, assignment: jax.Array) -> jax.Array:
    """i32[P, R] -> i32[P, R]: move leadership off demoted/dead brokers.

    For each partition whose slot-0 broker is demoted or dead, swap slot 0 with
    the first slot whose broker is alive and not demoted. Partitions with no
    eligible replica are left unchanged (the caller surfaces them as
    optimization failures, mirroring the reference's warning path).
    """
    p, r = assignment.shape
    valid = assignment >= 0
    holder = jnp.where(valid, assignment, 0)
    ineligible = static.demoted | static.dead
    slot_ok = valid & ~ineligible[holder]  # bool[P, R]

    leader_bad = ineligible[holder[:, 0]] & valid[:, 0]
    # first eligible slot per partition (R is tiny, argmax over bool is exact)
    best_slot = jnp.argmax(slot_ok, axis=1).astype(jnp.int32)
    has_eligible = jnp.any(slot_ok, axis=1)
    do_swap = leader_bad & has_eligible & (best_slot != 0)

    rows = jnp.arange(p, dtype=jnp.int32)
    old_leader = assignment[:, 0]
    new_leader = assignment[rows, best_slot]
    out = assignment.at[:, 0].set(jnp.where(do_swap, new_leader, old_leader))
    out = out.at[rows, best_slot].set(
        jnp.where(do_swap, old_leader, assignment[rows, best_slot])
    )
    return out
