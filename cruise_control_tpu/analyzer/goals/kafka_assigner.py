# cclint: kernel-module
"""Kafka-assigner mode goals.

Drop-in replacements for the legacy kafka-assigner tool, selected when a
request's goal list carries KafkaAssigner-prefixed names
(cc/KafkaCruiseControlUtils.java:193 mode detection):

- KafkaAssignerEvenRackAwareGoal (cc/analyzer/kafkaassigner/
  KafkaAssignerEvenRackAwareGoal.java:41): rack awareness plus strictly even
  replica counts per broker — here the rack-aware kernel with the replica
  window pinned to [floor(avg), ceil(avg)].
- KafkaAssignerDiskUsageDistributionGoal (.../
  KafkaAssignerDiskUsageDistributionGoal.java:45): disk-usage balance with
  swap search, a tighter-threshold DiskUsageDistributionGoal.
"""

from __future__ import annotations

import jax.numpy as jnp

from cruise_control_tpu.analyzer.actions import KIND_MOVE, ActionBatch
from cruise_control_tpu.analyzer.goals.base import Goal, distribution_score, imbalance
from cruise_control_tpu.analyzer.goals.hard import RackAwareGoal
from cruise_control_tpu.analyzer.goals.soft import ResourceDistributionGoal, WindowState
from cruise_control_tpu.common.resources import Resource


class KafkaAssignerEvenRackAwareGoal(Goal):
    """Rack-aware + strictly even replica distribution, as one hard goal."""

    name = "KafkaAssignerEvenRackAwareGoal"
    is_hard = True
    uses_moves = True

    def __init__(self):
        self._rack = RackAwareGoal()

    def prepare(self, static, agg, dims):
        n_alive = jnp.maximum(jnp.sum(static.alive.astype(jnp.float32)), 1.0)
        avg = jnp.sum(agg.replica_count).astype(jnp.float32) / n_alive
        # strict evenness: every broker within one replica of the average
        return WindowState(
            lower=jnp.floor(avg), upper=jnp.ceil(avg), active=jnp.asarray(True)
        )

    def broker_violation(self, static, gs, agg):
        rack_bad = self._rack.broker_violation(static, None, agg)
        c = agg.replica_count.astype(jnp.float32)
        uneven = ((c > gs.upper) | (c < gs.lower)) & static.alive
        return rack_bad | uneven

    def cost(self, static, gs, agg):
        c = agg.replica_count.astype(jnp.float32)
        even_cost = jnp.sum(jnp.where(static.alive, imbalance(c, gs.lower, gs.upper), 0.0))
        return self._rack.cost(static, None, agg) + even_cost

    def acceptance(self, static, gs, agg, act: ActionBatch):
        rack_ok = self._rack.acceptance(static, None, agg, act)
        is_move = act.kind == KIND_MOVE
        dst_after = (agg.replica_count[act.dst] + 1).astype(jnp.float32)
        # strict: later goals may never push a broker past the even window
        even_ok = ~is_move | (dst_after <= gs.upper)
        return rack_ok & even_ok

    def action_score(self, static, gs, agg, act: ActionBatch):
        rack_score = self._rack.action_score(static, None, agg, act)
        is_move = act.kind == KIND_MOVE
        c_src = agg.replica_count[act.src].astype(jnp.float32)
        c_dst = agg.replica_count[act.dst].astype(jnp.float32)
        even_score = distribution_score(
            c_src, c_dst, c_src - 1.0, c_dst + 1.0, gs.lower, gs.upper,
            tiebreak=(c_src - c_dst) * 1e-2,
        )
        return rack_score + jnp.where(is_move, even_score, 0.0)

    def dst_preference(self, static, gs, agg):
        return -agg.replica_count.astype(jnp.float32)

    def src_rank(self, static, gs, agg):
        # sources: brokers with rack violations or above the even window
        rack_rank = self._rack.src_rank(static, None, agg)
        c = agg.replica_count.astype(jnp.float32)
        over = jnp.where(static.alive & (c > gs.upper), c - gs.upper, -jnp.inf)
        return jnp.maximum(jnp.where(jnp.isfinite(rack_rank), rack_rank + 1e3, -jnp.inf), over)

    def drain_contrib(self, static, gs, agg):
        # rack-violating replicas first, then any replica (cheapest first)
        from cruise_control_tpu.common.resources import PartMetric

        disk = static.part_load[:, PartMetric.DISK]
        viol = self._rack._slot_violation(static, agg)
        base = jnp.broadcast_to(-disk[:, None], agg.assignment.shape)
        return jnp.where(viol, 1.0 - 1e-9 * disk[:, None], base)

    def contribute_acceptance(self, static, gs, tables):
        tables = self._rack.contribute_acceptance(static, None, tables)
        # strict evenness caps dst only (no src lower bound in acceptance)
        return tables._replace(hi_rep=jnp.minimum(tables.hi_rep, gs.upper))


class KafkaAssignerDiskUsageDistributionGoal(ResourceDistributionGoal):
    """Disk balance in kafka-assigner mode; same kernel as
    DiskUsageDistributionGoal under its kafka-assigner name."""

    def __init__(self):
        super().__init__(Resource.DISK)
        self.name = "KafkaAssignerDiskUsageDistributionGoal"
