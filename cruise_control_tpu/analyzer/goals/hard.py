# cclint: kernel-module
"""Hard goals: rack awareness, replica capacity, resource capacity.

Kernels mirroring the semantics of:
  RackAwareGoal          cc/analyzer/goals/RackAwareGoal.java:40
  ReplicaCapacityGoal    cc/analyzer/goals/ReplicaCapacityGoal.java:37
  CapacityGoal + thin subclasses (Disk/NetworkIn/NetworkOut/Cpu)
                         cc/analyzer/goals/CapacityGoal.java:39
Each is a feasibility predicate plus a fixing score; CPU capacity is enforced
at host level as well as broker level (cc/common/Resource.java:18,
CapacityGoal host checks).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.actions import KIND_MOVE, ActionBatch
from cruise_control_tpu.analyzer.context import Aggregates, StaticCtx, utilization
from cruise_control_tpu.analyzer.goals.base import SCORE_EPS, BulkCounts, Goal
from cruise_control_tpu.common.resources import Resource


class RackAwareGoal(Goal):
    """No two replicas of a partition on the same rack."""

    name = "RackAwareGoal"
    is_hard = True
    uses_moves = True
    uses_leadership = False

    def _slot_violation(self, static, agg):
        """bool[P, R]: slot sits on a rack that hosts >1 replica of its partition."""
        a = agg.assignment
        valid = a >= 0
        rack = static.broker_rack[jnp.where(valid, a, 0)]
        count = jnp.take_along_axis(agg.rack_replica_count, rack, axis=1)
        return valid & (count > 1)

    def broker_violation(self, static, gs, agg):
        slot_viol = self._slot_violation(static, agg)
        b = static.alive.shape[0]
        seg = jnp.where(agg.assignment >= 0, agg.assignment, b).reshape(-1)
        viol = jax.ops.segment_max(
            slot_viol.reshape(-1).astype(jnp.int32), seg, num_segments=b + 1
        )[:b]
        return (viol > 0) & static.alive

    def cost(self, static, gs, agg):
        return jnp.sum(self._slot_violation(static, agg).astype(jnp.float32))

    def acceptance(self, static, gs, agg, act: ActionBatch):
        is_move = act.kind == KIND_MOVE
        rack_src = static.broker_rack[act.src]
        rack_dst = static.broker_rack[act.dst]
        # replicas of p already on dst's rack, not counting the one moving away
        count_dst = agg.rack_replica_count[act.p, rack_dst] - (rack_src == rack_dst)
        return jnp.where(is_move, count_dst == 0, True)

    def action_score(self, static, gs, agg, act: ActionBatch):
        # fixing score: the moving replica shares a rack with a sibling replica
        rack_src = static.broker_rack[act.src]
        dup = agg.rack_replica_count[act.p, rack_src] > 1
        is_move = act.kind == KIND_MOVE
        util = utilization(agg, static)
        tiebreak = 1e-3 * (1.0 - jnp.tanh(jnp.max(util, axis=1)))[act.dst]
        return jnp.where(is_move & dup, 1.0 + tiebreak, 0.0)

    def src_rank(self, static, gs, agg):
        slot_viol = self._slot_violation(static, agg)
        b = static.alive.shape[0]
        seg = jnp.where(agg.assignment >= 0, agg.assignment, b).reshape(-1)
        nviol = jax.ops.segment_sum(
            slot_viol.reshape(-1).astype(jnp.float32), seg, num_segments=b + 1
        )[:b]
        return jnp.where(static.alive & (nviol > 0), nviol, -jnp.inf)

    def drain_contrib(self, static, gs, agg):
        # only rack-violating replicas are candidates; cheapest moves first
        from cruise_control_tpu.common.resources import PartMetric

        disk = static.part_load[:, PartMetric.DISK]
        viol = self._slot_violation(static, agg)
        return jnp.where(viol, 1.0 - 1e-9 * disk[:, None], -jnp.inf)

    def contribute_acceptance(self, static, gs, tables):
        return tables._replace(rack_enabled=jnp.asarray(True))


class ReplicaCapacityGoal(Goal):
    """Replica count per broker <= max.replicas.per.broker
    (cc/analyzer/goals/ReplicaCapacityGoal.java:37)."""

    name = "ReplicaCapacityGoal"
    is_hard = True
    uses_moves = True
    #: count-family: surplus over the hard cap drains through the bulk
    #: planner's waves (one unit off every over broker per wave) instead of
    #: round-by-round — the same kernel the distribution count goals use
    count_family = True

    def broker_violation(self, static, gs, agg):
        return (agg.replica_count > static.max_replicas_per_broker) & static.alive

    def cost(self, static, gs, agg):
        over = jnp.maximum(0, agg.replica_count - static.max_replicas_per_broker)
        return jnp.sum(jnp.where(static.alive, over, 0).astype(jnp.float32))

    def acceptance(self, static, gs, agg, act: ActionBatch):
        is_move = act.kind == KIND_MOVE
        fits = agg.replica_count[act.dst] + 1 <= static.max_replicas_per_broker
        return jnp.where(is_move, fits, True)

    def action_score(self, static, gs, agg, act: ActionBatch):
        is_move = act.kind == KIND_MOVE
        over = agg.replica_count[act.src] > static.max_replicas_per_broker
        headroom = (
            static.max_replicas_per_broker - agg.replica_count[act.dst]
        ).astype(jnp.float32)
        return jnp.where(is_move & over, 1.0 + 1e-3 * jnp.tanh(headroom * 1e-3), 0.0)

    def dst_preference(self, static, gs, agg):
        return -agg.replica_count.astype(jnp.float32)

    def src_rank(self, static, gs, agg):
        over = (agg.replica_count - static.max_replicas_per_broker).astype(
            jnp.float32
        )
        return jnp.where(static.alive & (over > 0), over, -jnp.inf)

    def drain_contrib(self, static, gs, agg):
        from cruise_control_tpu.common.resources import PartMetric

        disk = static.part_load[:, PartMetric.DISK]
        return jnp.broadcast_to(-disk[:, None], agg.assignment.shape)

    def bulk_counts(self, static, gs, agg):
        c = agg.replica_count.astype(jnp.float32)
        cap = static.max_replicas_per_broker.astype(jnp.float32)
        surplus = jnp.where(static.dead, c, jnp.maximum(0.0, c - cap))
        headroom = cap - c
        dst_key = jnp.where(
            static.replica_dst_ok & (headroom > 0.0), headroom, -jnp.inf
        )
        return BulkCounts(surplus=surplus, dst_key=dst_key)

    def contribute_acceptance(self, static, gs, tables):
        cap = static.max_replicas_per_broker.astype(jnp.float32)
        return tables._replace(hi_rep=jnp.minimum(tables.hi_rep, cap))


class CapacityGoalState(NamedTuple):
    limit: jax.Array  # f32[B] usable capacity for this resource


class CapacityGoal(Goal):
    """Broker utilization of one resource <= capacity * capacity.threshold
    (cc/analyzer/goals/CapacityGoal.java:39). For CPU the same bound is also
    enforced against the host-level sum."""

    is_hard = True

    def __init__(self, resource: Resource):
        self.resource = int(resource)
        self.name = {
            Resource.DISK: "DiskCapacityGoal",
            Resource.NW_IN: "NetworkInboundCapacityGoal",
            Resource.NW_OUT: "NetworkOutboundCapacityGoal",
            Resource.CPU: "CpuCapacityGoal",
        }[Resource(resource)]
        # leadership shifts CPU and NW_OUT load, so those variants also propose
        # leadership moves (CapacityGoal leadership path for NW_OUT/CPU)
        self.uses_leadership = resource in (Resource.CPU, Resource.NW_OUT)

    def prepare(self, static, agg, dims):
        return CapacityGoalState(limit=static.capacity_limit[:, self.resource])

    def _host_ok_after(self, static, agg, act, dres):
        """CPU only: destination host stays under its limit."""
        host_src = static.broker_host[act.src]
        host_dst = static.broker_host[act.dst]
        same_host = host_src == host_dst
        after = agg.host_cpu_load[host_dst] + jnp.where(same_host, 0.0, dres)
        return after <= static.host_cpu_capacity_limit[host_dst]

    def broker_violation(self, static, gs, agg):
        over = agg.broker_load[:, self.resource] > gs.limit
        if self.resource == Resource.CPU:
            host_over = agg.host_cpu_load > static.host_cpu_capacity_limit
            over = over | host_over[static.broker_host]
        return over & static.alive

    def cost(self, static, gs, agg):
        excess = jnp.maximum(0.0, agg.broker_load[:, self.resource] - gs.limit)
        total = jnp.sum(jnp.where(static.alive, excess, 0.0))
        if self.resource == Resource.CPU:
            # host-level CPU overage counts too — broker_violation/src_rank
            # flag it, so a cost that ignored it would let convergence checks
            # declare the goal done with host violations unrepaired
            host_excess = jnp.maximum(
                0.0, agg.host_cpu_load - static.host_cpu_capacity_limit
            )
            total = total + jnp.sum(host_excess)
        return total

    def acceptance(self, static, gs, agg, act: ActionBatch):
        dres = act.dload[..., self.resource]
        after = agg.broker_load[act.dst, self.resource] + dres
        ok = (after <= gs.limit[act.dst]) | (dres <= 0)
        if self.resource == Resource.CPU:
            ok = ok & (self._host_ok_after(static, agg, act, dres) | (dres <= 0))
        return ok

    def action_score(self, static, gs, agg, act: ActionBatch):
        dres = act.dload[..., self.resource]
        src_over = agg.broker_load[act.src, self.resource] > gs.limit[act.src]
        if self.resource == Resource.CPU:
            host_over = agg.host_cpu_load > static.host_cpu_capacity_limit
            src_over = src_over | host_over[static.broker_host[act.src]]
        return jnp.where(src_over & (dres > SCORE_EPS), dres, 0.0)

    def dst_preference(self, static, gs, agg):
        return gs.limit - agg.broker_load[:, self.resource]

    def src_rank(self, static, gs, agg):
        excess = agg.broker_load[:, self.resource] - gs.limit
        over = excess > 0.0
        if self.resource == Resource.CPU:
            host_over = agg.host_cpu_load > static.host_cpu_capacity_limit
            over = over | host_over[static.broker_host]
            excess = jnp.maximum(
                excess, (agg.host_cpu_load - static.host_cpu_capacity_limit)[
                    static.broker_host]
            )
        return jnp.where(static.alive & over, excess, -jnp.inf)

    def drain_contrib(self, static, gs, agg):
        from cruise_control_tpu.analyzer.actions import slot_contrib

        return slot_contrib(static.part_load, agg.assignment, self.resource)

    def contribute_acceptance(self, static, gs, tables):
        hi = tables.hi_load.at[:, self.resource].min(gs.limit)
        tables = tables._replace(hi_load=hi)
        if self.resource == Resource.CPU:
            tables = tables._replace(
                hi_host_cpu=jnp.minimum(
                    tables.hi_host_cpu, static.host_cpu_capacity_limit
                )
            )
        return tables
