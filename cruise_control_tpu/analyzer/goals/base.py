# cclint: kernel-module
"""Goal SPI: each goal is a set of pure vectorized functions.

The counterpart of the reference Goal interface (cc/analyzer/goals/Goal.java:38)
and the greedy engine hooks of AbstractGoal (cc/analyzer/goals/AbstractGoal.java:42),
re-expressed so every method evaluates a whole *batch* of candidate actions or
all brokers at once:

  prepare           ~ initGoalState: derive thresholds from current aggregates
  broker_violation  ~ brokersToBalance / selfSatisfied, as a bool[B] mask
  acceptance        ~ actionAcceptance, vectorized over an ActionBatch
  action_score      ~ the improvement criterion the greedy loop implicitly
                      pursues; > 0 only when the action makes this goal better
  dst_preference    ~ the candidate-broker sort in GoalUtils.eligibleBrokers
  cost              ~ clusterModelStatsComparator, as a scalar

All methods must be jittable and shape-polymorphic over the action batch.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.actions import ActionBatch
from cruise_control_tpu.analyzer.context import Aggregates, StaticCtx, utilization

#: Margin factor applied inside balance thresholds, matching the reference's
#: BALANCE_MARGIN = 0.9 (cc/analyzer/goals/ResourceDistributionGoal.java and
#: ReplicaDistributionAbstractGoal: the configured percentage is tightened by
#: 10% so proposals keep headroom under the user-facing threshold).
BALANCE_MARGIN = 0.9

#: Minimum action score considered a real improvement (float32 noise floor).
SCORE_EPS = 1e-6


class BulkCounts(NamedTuple):
    """Per-broker surplus/destination snapshot for the bulk count planner
    (analyzer.bulk).

    `surplus` is denominated in approximate MOVE UNITS (replica or
    leadership transfers) so the planner's adaptive wave budget —
    ceil(max surplus) waves — is meaningful for byte-valued goals too;
    `dst_key` only orders destinations (deficit brokers first, then
    headroom), exact validation decides legality."""

    surplus: jax.Array  #: f32[B] units each broker must shed; dead: everything
    dst_key: jax.Array  #: f32[B] destination rank (higher = better; -inf = ineligible)


class Goal:
    name: str = ""
    is_hard: bool = False
    #: include the replica-move candidate family when optimizing this goal
    uses_moves: bool = True
    #: include the leadership candidate family when optimizing this goal
    uses_leadership: bool = False
    #: run the replica-swap search when plain moves stall (requires a
    #: `resource` attribute; ResourceDistributionGoal's rebalanceBySwapping*)
    uses_swaps: bool = False
    #: rotate drain-candidate ranking across rounds: when a goal's top-K
    #: candidates can be uniformly infeasible (e.g. a hot broker's heaviest
    #: leaders all exceed every destination's bound while mid-sized ones
    #: fit), a deterministic top-K starves the goal; a round-seeded
    #: multiplicative jitter walks the candidate order instead (validation
    #: is exact, so ordering is free). Goals setting this also get the
    #: multi-round stall patience (one empty round only proves one rotation
    #: slice is blocked).
    rotate_drain_candidates: bool = False
    #: count-family goal: the goal's targets are floor/ceil balance windows
    #: over integer-ish per-broker quantities, moved ~one unit per action.
    #: The bulk count planner (analyzer.bulk) drains the whole
    #: surplus/deficit grid per round via `bulk_counts` — except pair_drain
    #: goals (TopicReplicaDistributionGoal), whose (topic, broker) pair
    #: rounds (analyzer.drain.make_pair_drain_round) already ARE the
    #: per-topic×broker form of the same surplus/deficit kernel and run in
    #: every mode when the planner is enabled.
    count_family: bool = False

    def prepare(self, static: StaticCtx, agg: Aggregates, dims) -> Any:
        """Per-goal threshold state derived from current aggregates."""
        return None

    def broker_violation(self, static: StaticCtx, gs, agg: Aggregates) -> jax.Array:
        """bool[B]: alive brokers currently violating this goal."""
        raise NotImplementedError

    def cost(self, static: StaticCtx, gs, agg: Aggregates) -> jax.Array:
        """Scalar >= 0; 0 iff the goal is fully satisfied."""
        raise NotImplementedError

    def acceptance(self, static: StaticCtx, gs, agg: Aggregates, act: ActionBatch) -> jax.Array:
        """bool[...]: would this goal still hold (not get worse) after act?"""
        raise NotImplementedError

    def contribute_acceptance(self, static: StaticCtx, gs, tables):
        """Merge this goal's acceptance bounds into shared AcceptanceTables.

        Once a goal is optimized, later goals enforce it through the merged
        tables (analyzer.acceptance) instead of re-inlining this goal's
        `acceptance` kernel per candidate — the O(goals^2)-breaker. Must
        encode exactly the same box constraints `acceptance` checks."""
        raise NotImplementedError

    def action_score(self, static: StaticCtx, gs, agg: Aggregates, act: ActionBatch) -> jax.Array:
        """f32[...]: improvement of this goal from act; <= 0 when no help."""
        raise NotImplementedError

    def dst_preference(self, static: StaticCtx, gs, agg: Aggregates) -> jax.Array:
        """f32[B]: higher = better destination candidate for this goal."""
        util = utilization(agg, static)
        return -jnp.max(util, axis=1)

    # -- drain/fill round hooks (analyzer.drain) --------------------------------
    # The batched engine runs every goal as a drain/fill round (the reference's
    # rebalanceForBroker structure, vectorized); these three hooks tell it
    # which brokers to drain, which replicas to drain first, and where to
    # send them. Validation stays exact (acceptance/action_score), so the
    # hooks only shape the candidate set, never the semantics.

    def bulk_counts(self, static: StaticCtx, gs, agg: Aggregates) -> BulkCounts:
        """Count-family goals only (count_family=True, pair_drain=False):
        per-broker units to shed against the floor/ceil balance targets and
        a deficit-first destination key for the bulk count planner
        (analyzer.bulk). Dead brokers must report their entire holding as
        surplus — evacuation precedes balance."""
        raise NotImplementedError

    def src_rank(self, static: StaticCtx, gs, agg: Aggregates) -> jax.Array:
        """f32[B]: source priority for the drain round (-inf = not a source).

        Default: overall utilization — the most loaded brokers drain first,
        which both fixes over-bounds brokers and feeds under-loaded ones."""
        util = utilization(agg, static)
        return jnp.where(static.alive, jnp.max(util, axis=1), -jnp.inf)

    def drain_contrib(self, static: StaticCtx, gs, agg: Aggregates) -> jax.Array:
        """f32[P, R]: per-replica drain priority on its current broker
        (higher drains first; -inf excludes the replica from this goal's
        candidate lists). Default: total load carried by the slot."""
        from cruise_control_tpu.analyzer.actions import _follower_vec, _leader_vec

        lead = jnp.sum(_leader_vec(static.part_load, jnp.arange(
            static.part_load.shape[0], dtype=jnp.int32)), axis=-1)
        foll = jnp.sum(_follower_vec(static.part_load, jnp.arange(
            static.part_load.shape[0], dtype=jnp.int32)), axis=-1)
        r = agg.assignment.shape[1]
        is_leader = (jnp.arange(r) == 0)[None, :]
        return jnp.where(is_leader, lead[:, None], foll[:, None])

    def dst_candidates(self, static: StaticCtx, gs, agg: Aggregates, tables,
                       cand_p: jax.Array, cand_s: jax.Array,
                       cold: jax.Array) -> jax.Array:
        """Destinations for each drained candidate: i32[C] (one global list,
        the default) or i32[V, K, C] (per-candidate — e.g. the under-count
        brokers of the candidate's own topic)."""
        return cold

    def __repr__(self) -> str:  # goals are stateless singletons
        return self.name


def imbalance(value, lower, upper):
    """Distance outside [lower, upper]; 0 inside."""
    return jnp.maximum(0.0, value - upper) + jnp.maximum(0.0, lower - value)


def balance_limits(avg, balance_pct):
    """(lower, upper) around avg with the reference's margin tightening."""
    margin = (balance_pct - 1.0) * BALANCE_MARGIN
    upper = avg * (1.0 + margin)
    lower = avg * jnp.maximum(0.0, 1.0 - margin)
    return lower, upper


def distribution_score(before_src, before_dst, after_src, after_dst, lower, upper,
                       tiebreak=0.0):
    """Imbalance reduction on the two touched brokers, with a bounded tiebreak.

    Positive only when the action strictly reduces total out-of-range distance
    AND neither endpoint gets individually worse — the reference's greedy only
    ever moves load between a broker outside its limit and one that stays
    within it (ResourceDistributionGoal.rebalanceByMovingLoadOut/-In,
    ReplicaDistributionAbstractGoal), so collateral "push dst out of band for
    a bigger src gain" trades are rejected; allowing them lets an aggressive
    batched round spread small violations across many brokers and lock the
    model for every later goal's acceptance bounds.

    The tiebreak (scaled to stay below SCORE_EPS-relevant magnitudes) orders
    equally-improving actions.
    """
    i_src0 = imbalance(before_src, lower, upper)
    i_dst0 = imbalance(before_dst, lower, upper)
    i_src1 = imbalance(after_src, lower, upper)
    i_dst1 = imbalance(after_dst, lower, upper)
    red = i_src0 + i_dst0 - i_src1 - i_dst1
    endpoint_ok = (i_src1 <= i_src0 + SCORE_EPS) & (i_dst1 <= i_dst0 + SCORE_EPS)
    return jnp.where((red > SCORE_EPS) & endpoint_ok, red + 1e-3 * jnp.tanh(tiebreak), 0.0)
