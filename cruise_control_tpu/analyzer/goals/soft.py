# cclint: kernel-module
"""Soft goals: distribution balancing and potential-load guards.

Kernels with the semantics of:
  ReplicaDistributionGoal          cc/analyzer/goals/ReplicaDistributionGoal.java
  ResourceDistributionGoal x4      cc/analyzer/goals/ResourceDistributionGoal.java:53
  TopicReplicaDistributionGoal     cc/analyzer/goals/TopicReplicaDistributionGoal.java:53
  LeaderReplicaDistributionGoal    cc/analyzer/goals/LeaderReplicaDistributionGoal.java
  LeaderBytesInDistributionGoal    cc/analyzer/goals/LeaderBytesInDistributionGoal.java:39
  PotentialNwOutGoal               cc/analyzer/goals/PotentialNwOutGoal.java:40

Each computes its balance window from current aggregates (the analog of
initGoalState), flags out-of-window brokers, and scores candidate actions by
how much out-of-window distance they remove. The reference's
rebalanceBySwapping* search runs as the dedicated swap kernel
(cruise_control_tpu.analyzer.swaps) whenever plain moves stall.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.actions import KIND_MOVE, ActionBatch
from cruise_control_tpu.analyzer.context import Aggregates, StaticCtx, utilization
from cruise_control_tpu.analyzer.goals.base import (
    SCORE_EPS,
    BulkCounts,
    Goal,
    balance_limits,
    distribution_score,
    imbalance,
)
from cruise_control_tpu.common.resources import Resource


class WindowState(NamedTuple):
    lower: jax.Array  # f32[] balance window lower bound
    upper: jax.Array  # f32[]
    active: jax.Array  # bool[] goal participates (not a low-utilization cluster)


class ResourceDistributionGoal(Goal):
    """Per-broker utilization of one resource within [avg*lo, avg*hi]."""

    is_hard = False
    uses_swaps = True  # rebalanceBySwapping* when moves stall

    def __init__(self, resource: Resource):
        self.resource = int(resource)
        self.name = {
            Resource.DISK: "DiskUsageDistributionGoal",
            Resource.NW_IN: "NetworkInboundUsageDistributionGoal",
            Resource.NW_OUT: "NetworkOutboundUsageDistributionGoal",
            Resource.CPU: "CpuUsageDistributionGoal",
        }[Resource(resource)]
        self.uses_leadership = resource in (Resource.CPU, Resource.NW_OUT)

    def prepare(self, static, agg, dims):
        res = self.resource
        total_cap = jnp.sum(jnp.where(static.alive, static.broker_capacity[:, res], 0.0))
        avg = jnp.sum(agg.broker_load[:, res]) / jnp.maximum(total_cap, 1e-9)
        lower, upper = balance_limits(avg, static.resource_balance_pct[res])
        # low-utilization clusters are left alone
        # (ResourceDistributionGoal low.utilization.threshold semantics)
        active = avg >= static.low_utilization_threshold[res]
        return WindowState(lower=lower, upper=upper, active=active)

    def _util(self, static, agg):
        return agg.broker_load[:, self.resource] / jnp.maximum(
            static.broker_capacity[:, self.resource], 1e-9
        )

    def broker_violation(self, static, gs, agg):
        u = self._util(static, agg)
        out = (u > gs.upper) | (u < gs.lower)
        return out & static.alive & gs.active

    def cost(self, static, gs, agg):
        u = self._util(static, agg)
        dist = imbalance(u, gs.lower, gs.upper)
        return jnp.where(gs.active, jnp.sum(jnp.where(static.alive, dist, 0.0)), 0.0)

    def acceptance(self, static, gs, agg, act: ActionBatch):
        """Two-case acceptance (ResourceDistributionGoal.actionAcceptance
        :122-133): the balance-limit box applies only when source sits above
        its lower bound and destination under its upper bound; otherwise the
        action must strictly shrink the pairwise utilization difference
        (isGettingMoreBalanced :866) — in tight states (brokers outside the
        band) downhill moves stay possible."""
        res = self.resource
        dres = act.dload[..., res]
        cap_src = jnp.maximum(static.broker_capacity[act.src, res], 1e-9)
        cap_dst = jnp.maximum(static.broker_capacity[act.dst, res], 1e-9)
        u_src = agg.broker_load[act.src, res] / cap_src
        u_dst = agg.broker_load[act.dst, res] / cap_dst
        u_src_after = u_src - dres / cap_src
        u_dst_after = u_dst + dres / cap_dst
        dead = static.dead[act.src]
        case1 = (u_src >= gs.lower) & (u_dst <= gs.upper)
        acc1 = (u_dst_after <= gs.upper) & ((u_src_after >= gs.lower) | dead)
        prev = u_src - u_dst
        acc2 = jnp.abs(u_src_after - u_dst_after) < jnp.abs(prev)
        ok = jnp.where(case1, acc1, acc2 | dead)
        relevant = jnp.abs(dres) > 0.0
        return ~gs.active | ~relevant | ok

    def action_score(self, static, gs, agg, act: ActionBatch):
        res = self.resource
        dres = act.dload[..., res]
        cap_src = jnp.maximum(static.broker_capacity[act.src, res], 1e-9)
        cap_dst = jnp.maximum(static.broker_capacity[act.dst, res], 1e-9)
        u_src = agg.broker_load[act.src, res] / cap_src
        u_dst = agg.broker_load[act.dst, res] / cap_dst
        u_src_after = u_src - dres / cap_src
        u_dst_after = u_dst + dres / cap_dst
        score = distribution_score(
            u_src, u_dst, u_src_after, u_dst_after, gs.lower, gs.upper,
            tiebreak=(u_src - u_dst),
        )
        return jnp.where(gs.active, score, 0.0)

    def dst_preference(self, static, gs, agg):
        return -self._util(static, agg)

    def src_rank(self, static, gs, agg):
        return jnp.where(static.alive & gs.active, self._util(static, agg), -jnp.inf)

    def drain_contrib(self, static, gs, agg):
        from cruise_control_tpu.analyzer.actions import slot_contrib

        return slot_contrib(static.part_load, agg.assignment, self.resource)

    def contribute_acceptance(self, static, gs, tables):
        # balance-band bounds, enforced with the two-case semantics
        # (acceptance.band_move_acceptance) rather than as a hard box; in
        # raw-load units the utilization band is per-broker
        cap = static.broker_capacity[:, self.resource]
        hi = jnp.where(gs.active, gs.upper * cap, jnp.inf)
        lo = jnp.where(gs.active, gs.lower * cap, -jnp.inf)
        return tables._replace(
            band_hi=tables.band_hi.at[:, self.resource].min(hi),
            band_lo=tables.band_lo.at[:, self.resource].max(lo),
            band_on=tables.band_on.at[self.resource].set(
                tables.band_on[self.resource] | gs.active
            ),
        )


class ReplicaDistributionGoal(Goal):
    """Replica count per broker within the balance window around the mean
    (cc/analyzer/goals/ReplicaDistributionGoal.java, base
    ReplicaDistributionAbstractGoal.java:27)."""

    name = "ReplicaDistributionGoal"
    count_family = True

    def prepare(self, static, agg, dims):
        n_alive = jnp.maximum(jnp.sum(static.alive.astype(jnp.float32)), 1.0)
        avg = jnp.sum(agg.replica_count).astype(jnp.float32) / n_alive
        lower, upper = balance_limits(avg, static.replica_balance_pct)
        return WindowState(lower=jnp.floor(lower), upper=jnp.ceil(upper),
                           active=jnp.asarray(True))

    def broker_violation(self, static, gs, agg):
        c = agg.replica_count.astype(jnp.float32)
        return ((c > gs.upper) | (c < gs.lower)) & static.alive

    def cost(self, static, gs, agg):
        c = agg.replica_count.astype(jnp.float32)
        return jnp.sum(jnp.where(static.alive, imbalance(c, gs.lower, gs.upper), 0.0))

    def acceptance(self, static, gs, agg, act: ActionBatch):
        is_move = act.kind == KIND_MOVE
        src_after = (agg.replica_count[act.src] - 1).astype(jnp.float32)
        dst_after = (agg.replica_count[act.dst] + 1).astype(jnp.float32)
        ok = ((src_after >= gs.lower) | static.dead[act.src]) & (dst_after <= gs.upper)
        return ~is_move | ok

    def action_score(self, static, gs, agg, act: ActionBatch):
        is_move = act.kind == KIND_MOVE
        c_src = agg.replica_count[act.src].astype(jnp.float32)
        c_dst = agg.replica_count[act.dst].astype(jnp.float32)
        score = distribution_score(
            c_src, c_dst, c_src - 1.0, c_dst + 1.0, gs.lower, gs.upper,
            tiebreak=(c_src - c_dst) * 1e-2,
        )
        return jnp.where(is_move, score, 0.0)

    def dst_preference(self, static, gs, agg):
        return -agg.replica_count.astype(jnp.float32)

    def src_rank(self, static, gs, agg):
        return jnp.where(
            static.alive, agg.replica_count.astype(jnp.float32), -jnp.inf
        )

    def drain_contrib(self, static, gs, agg):
        # any replica rebalances the count; prefer the cheapest to move
        from cruise_control_tpu.common.resources import PartMetric

        disk = static.part_load[:, PartMetric.DISK]
        return jnp.broadcast_to(-disk[:, None], agg.assignment.shape)

    def bulk_counts(self, static, gs, agg):
        c = agg.replica_count.astype(jnp.float32)
        surplus = jnp.where(static.dead, c, jnp.maximum(0.0, c - gs.upper))
        deficit = jnp.maximum(0.0, gs.lower - c)
        headroom = gs.upper - c
        dst_key = jnp.where(
            static.replica_dst_ok & (headroom > 0.0),
            deficit * 1e3 + headroom, -jnp.inf,
        )
        return BulkCounts(surplus=surplus, dst_key=dst_key)

    def contribute_acceptance(self, static, gs, tables):
        return tables._replace(
            hi_rep=jnp.minimum(tables.hi_rep, gs.upper),
            lo_rep=jnp.maximum(tables.lo_rep, gs.lower),
        )


class LeaderReplicaDistributionGoal(Goal):
    """Leader count per broker within the balance window
    (cc/analyzer/goals/LeaderReplicaDistributionGoal.java)."""

    name = "LeaderReplicaDistributionGoal"
    uses_leadership = True
    rotate_drain_candidates = True
    count_family = True

    def prepare(self, static, agg, dims):
        n_alive = jnp.maximum(jnp.sum(static.alive.astype(jnp.float32)), 1.0)
        avg = jnp.sum(agg.leader_count).astype(jnp.float32) / n_alive
        lower, upper = balance_limits(avg, static.leader_replica_balance_pct)
        return WindowState(lower=jnp.floor(lower), upper=jnp.ceil(upper),
                           active=jnp.asarray(True))

    def broker_violation(self, static, gs, agg):
        c = agg.leader_count.astype(jnp.float32)
        return ((c > gs.upper) | (c < gs.lower)) & static.alive

    def cost(self, static, gs, agg):
        c = agg.leader_count.astype(jnp.float32)
        return jnp.sum(jnp.where(static.alive, imbalance(c, gs.lower, gs.upper), 0.0))

    def acceptance(self, static, gs, agg, act: ActionBatch):
        transfers = act.dleader > 0
        src_after = (agg.leader_count[act.src] - 1).astype(jnp.float32)
        dst_after = (agg.leader_count[act.dst] + 1).astype(jnp.float32)
        ok = ((src_after >= gs.lower) | static.dead[act.src]) & (dst_after <= gs.upper)
        return ~transfers | ok

    def action_score(self, static, gs, agg, act: ActionBatch):
        transfers = act.dleader > 0
        c_src = agg.leader_count[act.src].astype(jnp.float32)
        c_dst = agg.leader_count[act.dst].astype(jnp.float32)
        score = distribution_score(
            c_src, c_dst, c_src - 1.0, c_dst + 1.0, gs.lower, gs.upper,
            tiebreak=(c_src - c_dst) * 1e-2,
        )
        return jnp.where(transfers, score, 0.0)

    def dst_preference(self, static, gs, agg):
        return -agg.leader_count.astype(jnp.float32)

    def src_rank(self, static, gs, agg):
        return jnp.where(
            static.alive, agg.leader_count.astype(jnp.float32), -jnp.inf
        )

    def drain_contrib(self, static, gs, agg):
        # only leader replicas shift leader counts: moving one (or promoting
        # one of its followers via the leadership family) rebalances; the
        # disk tiebreak prefers the cheapest physical move
        from cruise_control_tpu.common.resources import PartMetric

        disk = static.part_load[:, PartMetric.DISK]
        r = agg.assignment.shape[1]
        is_leader = (jnp.arange(r) == 0)[None, :]
        return jnp.where(is_leader, 1.0 - 1e-9 * disk[:, None], -jnp.inf)

    def bulk_counts(self, static, gs, agg):
        c = agg.leader_count.astype(jnp.float32)
        surplus = jnp.where(static.dead, c, jnp.maximum(0.0, c - gs.upper))
        deficit = jnp.maximum(0.0, gs.lower - c)
        headroom = gs.upper - c
        # moves relocate a whole leader replica; promotions (the dominant
        # family) have assignment-fixed destinations that bypass this key
        dst_key = jnp.where(
            static.replica_dst_ok & static.leadership_dst_ok & (headroom > 0.0),
            deficit * 1e3 + headroom, -jnp.inf,
        )
        return BulkCounts(surplus=surplus, dst_key=dst_key)

    def contribute_acceptance(self, static, gs, tables):
        return tables._replace(
            hi_lead=jnp.minimum(tables.hi_lead, gs.upper),
            lo_lead=jnp.maximum(tables.lo_lead, gs.lower),
        )


class TopicWindowState(NamedTuple):
    lower: jax.Array  # f32[T]
    upper: jax.Array  # f32[T]


class TopicReplicaDistributionGoal(Goal):
    """Per-topic replicas spread evenly across brokers
    (cc/analyzer/goals/TopicReplicaDistributionGoal.java:53)."""

    name = "TopicReplicaDistributionGoal"
    #: drain (topic, broker) surplus pairs
    #: (analyzer.drain.make_pair_drain_round) with round-rotated, band-aware
    #: destination lists, plus a similar-load SWAP fallback when moves are
    #: frozen by the prior goals' bands — per-broker replica picks starve
    #: this goal (a broker's top candidates are mostly replicas of the same
    #: over topic)
    pair_drain = True
    #: the pair rounds are the per-topic×broker form of the bulk count
    #: planner's surplus/deficit kernel; count_family makes them run in
    #: greedy parity mode too (the round-by-round [P, R, K] grid needs ~one
    #: round per unit of topic surplus — ~14k rounds at the 520-broker
    #: parity scale — while a pair round drains one unit off EVERY surplus
    #: broker per wave)
    count_family = True

    def prepare(self, static, agg, dims):
        n_alive = jnp.maximum(jnp.sum(static.alive.astype(jnp.float32)), 1.0)
        per_topic = jnp.sum(agg.topic_replica_count, axis=1).astype(jnp.float32)
        avg = per_topic / n_alive  # f32[T]
        lower, upper = balance_limits(avg, static.topic_replica_balance_pct)
        return TopicWindowState(lower=jnp.floor(lower), upper=jnp.ceil(upper))

    def broker_violation(self, static, gs, agg):
        c = agg.topic_replica_count.astype(jnp.float32)  # [T, B]
        out = (c > gs.upper[:, None]) | (c < gs.lower[:, None])
        return jnp.any(out, axis=0) & static.alive

    def cost(self, static, gs, agg):
        c = agg.topic_replica_count.astype(jnp.float32)
        dist = imbalance(c, gs.lower[:, None], gs.upper[:, None])
        return jnp.sum(jnp.where(static.alive[None, :], dist, 0.0))

    def acceptance(self, static, gs, agg, act: ActionBatch):
        is_move = act.kind == KIND_MOVE
        t = static.topic_id[act.p]
        src_after = (agg.topic_replica_count[t, act.src] - 1).astype(jnp.float32)
        dst_after = (agg.topic_replica_count[t, act.dst] + 1).astype(jnp.float32)
        ok = ((src_after >= gs.lower[t]) | static.dead[act.src]) & (dst_after <= gs.upper[t])
        return ~is_move | ok

    def action_score(self, static, gs, agg, act: ActionBatch):
        is_move = act.kind == KIND_MOVE
        t = static.topic_id[act.p]
        c_src = agg.topic_replica_count[t, act.src].astype(jnp.float32)
        c_dst = agg.topic_replica_count[t, act.dst].astype(jnp.float32)
        score = distribution_score(
            c_src, c_dst, c_src - 1.0, c_dst + 1.0, gs.lower[t], gs.upper[t],
            tiebreak=(c_src - c_dst) * 1e-2,
        )
        return jnp.where(is_move, score, 0.0)

    def src_rank(self, static, gs, agg):
        c = agg.topic_replica_count.astype(jnp.float32)  # [T, B]
        excess = jnp.sum(jnp.maximum(0.0, c - gs.upper[:, None]), axis=0)
        return jnp.where(static.alive & (excess > 0.0), excess, -jnp.inf)

    def drain_contrib(self, static, gs, agg):
        # a replica's priority = how over-count its (topic, broker) pair is;
        # replicas of topics already within bounds on their broker are not
        # drain candidates for this goal
        from cruise_control_tpu.common.resources import PartMetric

        t = static.topic_id  # [P]
        b = jnp.where(agg.assignment >= 0, agg.assignment, 0)  # [P, R]
        cnt = agg.topic_replica_count[t[:, None], b].astype(jnp.float32)
        over = cnt - gs.upper[t][:, None]
        disk = static.part_load[:, PartMetric.DISK]
        return jnp.where(over > 0.0, over - 1e-9 * disk[:, None], -jnp.inf)

    def contribute_acceptance(self, static, gs, tables):
        return tables._replace(
            hi_topic=jnp.minimum(tables.hi_topic, gs.upper),
            lo_topic=jnp.maximum(tables.lo_topic, gs.lower),
        )


class PotentialNwOutGoal(Goal):
    """Even if every replica on a broker became leader, its NW_OUT stays under
    the capacity threshold (cc/analyzer/goals/PotentialNwOutGoal.java:35-40)."""

    name = "PotentialNwOutGoal"

    def prepare(self, static, agg, dims):
        return WindowState(
            lower=jnp.float32(0.0),
            upper=jnp.float32(0.0),  # unused; limit is per-broker capacity
            active=jnp.asarray(True),
        )

    def _limit(self, static):
        return static.capacity_limit[:, Resource.NW_OUT]

    def broker_violation(self, static, gs, agg):
        return (agg.potential_nw_out > self._limit(static)) & static.alive

    def cost(self, static, gs, agg):
        excess = jnp.maximum(0.0, agg.potential_nw_out - self._limit(static))
        return jnp.sum(jnp.where(static.alive, excess, 0.0))

    def acceptance(self, static, gs, agg, act: ActionBatch):
        after = agg.potential_nw_out[act.dst] + act.dpnw
        return (act.dpnw <= 0.0) | (after <= self._limit(static)[act.dst])

    def action_score(self, static, gs, agg, act: ActionBatch):
        src_over = agg.potential_nw_out[act.src] > self._limit(static)[act.src]
        return jnp.where(src_over & (act.dpnw > SCORE_EPS), act.dpnw, 0.0)

    def dst_preference(self, static, gs, agg):
        return self._limit(static) - agg.potential_nw_out

    def src_rank(self, static, gs, agg):
        excess = agg.potential_nw_out - self._limit(static)
        return jnp.where(static.alive & (excess > 0.0), excess, -jnp.inf)

    def drain_contrib(self, static, gs, agg):
        # every replica contributes its partition's leader NW_OUT to the
        # broker's potential outbound load, leaders and followers alike
        from cruise_control_tpu.common.resources import PartMetric

        pnw = static.part_load[:, PartMetric.NW_OUT_LEADER]
        return jnp.broadcast_to(pnw[:, None], agg.assignment.shape)

    def contribute_acceptance(self, static, gs, tables):
        return tables._replace(hi_pnw=jnp.minimum(tables.hi_pnw, self._limit(static)))


class LeaderBytesInDistributionGoal(Goal):
    """Leader bytes-in per broker near the cluster mean
    (cc/analyzer/goals/LeaderBytesInDistributionGoal.java:39)."""

    name = "LeaderBytesInDistributionGoal"
    uses_leadership = True
    rotate_drain_candidates = True
    #: count-like leadership phase: surplus is the broker's excess leader
    #: bytes-in, normalized to approximate leadership-transfer units by the
    #: mean leader weight so the bulk planner's wave budget is meaningful
    count_family = True
    #: stall fallback: paired leadership transfers — heavy off the over-
    #: broker, light off its destination (drain.make_leadership_relay_round).
    #: Near convergence the leader-count bounds veto every +-1 promotion and
    #: the usage bands veto the full transfer, but the relay's NET effect
    #: passes both; the second leg may land anywhere (the pure-swap case is
    #: the e == b slice of the grid)
    leadership_swap = True

    def prepare(self, static, agg, dims):
        n_alive = jnp.maximum(jnp.sum(static.alive.astype(jnp.float32)), 1.0)
        mean = jnp.sum(agg.leader_nw_in) / n_alive
        lower, upper = balance_limits(mean, static.resource_balance_pct[Resource.NW_IN])
        # only the upper bound matters: the goal caps hot leaders
        # (LeaderBytesInDistributionGoal balances by moving leadership off
        # brokers above the mean; brokers below the mean are fine).
        return WindowState(lower=jnp.float32(0.0), upper=upper, active=jnp.asarray(True))

    def broker_violation(self, static, gs, agg):
        return (agg.leader_nw_in > gs.upper) & static.alive

    def cost(self, static, gs, agg):
        excess = jnp.maximum(0.0, agg.leader_nw_in - gs.upper)
        return jnp.sum(jnp.where(static.alive, excess, 0.0))

    def acceptance(self, static, gs, agg, act: ActionBatch):
        transfers = act.dleader_nw_in > 0.0
        after = agg.leader_nw_in[act.dst] + act.dleader_nw_in
        return ~transfers | (after <= gs.upper) | static.dead[act.src]

    def action_score(self, static, gs, agg, act: ActionBatch):
        b_src = agg.leader_nw_in[act.src]
        b_dst = agg.leader_nw_in[act.dst]
        d = act.dleader_nw_in
        score = distribution_score(
            b_src, b_dst, b_src - d, b_dst + d, gs.lower, gs.upper,
            tiebreak=(b_src - b_dst) * 1e-6,
        )
        return jnp.where(d > 0.0, score, 0.0)

    def dst_preference(self, static, gs, agg):
        return -agg.leader_nw_in

    def src_rank(self, static, gs, agg):
        over = agg.leader_nw_in > gs.upper
        return jnp.where(static.alive & over, agg.leader_nw_in, -jnp.inf)

    def drain_contrib(self, static, gs, agg):
        # only leadership carries leader bytes-in: drain the hottest leader
        # replicas (moving one, or promoting a follower, sheds its NW_IN)
        from cruise_control_tpu.common.resources import PartMetric

        nw_in = static.part_load[:, PartMetric.NW_IN_LEADER]
        r = agg.assignment.shape[1]
        is_leader = (jnp.arange(r) == 0)[None, :]
        return jnp.where(is_leader, nw_in[:, None], -jnp.inf)

    def bulk_counts(self, static, gs, agg):
        from cruise_control_tpu.common.resources import PartMetric

        lnw = agg.leader_nw_in
        # mean over REAL partitions: the padded axis length would shrink the
        # unit with the shape bucket and change the planner's wave budget vs
        # the exact-shape run (padding rows carry zero load, so only the
        # denominator needs care)
        mean_w = jnp.sum(static.part_load[:, PartMetric.NW_IN_LEADER]) / jnp.maximum(
            1.0, static.num_valid_partitions
        )
        unit = jnp.maximum(mean_w, 1e-6)
        surplus = jnp.where(
            static.dead,
            agg.leader_count.astype(jnp.float32),
            jnp.maximum(0.0, lnw - gs.upper) / unit,
        )
        headroom = gs.upper - lnw
        dst_key = jnp.where(
            static.leadership_dst_ok & (headroom > 0.0), headroom, -jnp.inf
        )
        return BulkCounts(surplus=surplus, dst_key=dst_key)

    def contribute_acceptance(self, static, gs, tables):
        return tables._replace(
            hi_lnw=jnp.minimum(tables.hi_lnw, gs.upper),
            hi_lnw_waive_dead=jnp.asarray(True),
        )
