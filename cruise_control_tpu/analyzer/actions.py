"""Balancing actions as broadcast-friendly array batches.

The reference's `BalancingAction` (cc/analyzer/BalancingAction.java:17) is one
(topic-partition, source, destination, type) object; `AbstractGoal` walks them
one at a time. Here a *batch* of candidate actions is a struct of arrays with
mutually broadcastable shapes, so a [P, R, K] grid of (partition, slot,
destination) move candidates or a [P, R-1] grid of leadership candidates is
scored by one fused kernel — the "hot loop" of
`AbstractGoal.maybeApplyBalancingAction` (cc/analyzer/goals/AbstractGoal.java:186)
becomes data parallelism.

Action kinds mirror cc/analyzer/ActionType.java:24 (swaps are expressed as two
coupled moves by the optimizer rather than a third kind).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from cruise_control_tpu.common.resources import PartMetric, Resource

KIND_MOVE = 0
KIND_LEADERSHIP = 1

#: Score bonus that makes dead-broker evacuation dominate any balance score:
#: every goal must first ensure no replica remains on a dead broker
#: (GoalUtils.ensureNoReplicaOnDeadBrokers semantics).
DEAD_EVACUATION_BONUS = 1.0e6


class ActionBatch(NamedTuple):
    """A batch of candidate actions; all fields broadcast to a common shape.

    kind  : i32[...]  KIND_MOVE or KIND_LEADERSHIP
    p     : i32[...]  partition index
    slot  : i32[...]  replica slot being moved (move) or promoted (leadership)
    src   : i32[...]  broker losing load (current holder / current leader)
    dst   : i32[...]  broker gaining load (move target / new leader)
    valid : bool[...] structurally valid candidate (slot populated, src != dst, ...)
    dload : f32[..., 4] per-Resource load transferred src -> dst (may have
            negative components for leadership when follower NW_IN > leader NW_IN)
    drep  : i32[...]  replica-count change at dst (+1 for moves)
    dleader : i32[...] leader-count change at dst (1 when leadership transfers)
    dpnw  : f32[...]  potential-NW_OUT transferred (moves only)
    dleader_nw_in : f32[...] leader bytes-in transferred (leadership transfers)
    """

    kind: jax.Array
    p: jax.Array
    slot: jax.Array
    src: jax.Array
    dst: jax.Array
    valid: jax.Array
    dload: jax.Array
    drep: jax.Array
    dleader: jax.Array
    dpnw: jax.Array
    dleader_nw_in: jax.Array


def _leader_vec(part_load: jax.Array, p: jax.Array) -> jax.Array:
    """f32[..., 4] load the partitions `p` place on their leader."""
    pl = part_load[p]  # [..., M]
    return jnp.stack(
        [
            pl[..., PartMetric.CPU_LEADER],
            pl[..., PartMetric.NW_IN_LEADER],
            pl[..., PartMetric.NW_OUT_LEADER],
            pl[..., PartMetric.DISK],
        ],
        axis=-1,
    )


def _follower_vec(part_load: jax.Array, p: jax.Array) -> jax.Array:
    pl = part_load[p]
    zero = jnp.zeros_like(pl[..., 0])
    return jnp.stack(
        [
            pl[..., PartMetric.CPU_FOLLOWER],
            pl[..., PartMetric.NW_IN_FOLLOWER],
            zero,
            pl[..., PartMetric.DISK],
        ],
        axis=-1,
    )


def slot_contrib(part_load: jax.Array, assignment: jax.Array, res: int) -> jax.Array:
    """f32[P, R]: per-slot load contribution for one Resource (leader slots
    carry the leader variant, followers the follower variant)."""
    lead = {
        Resource.CPU: part_load[:, PartMetric.CPU_LEADER],
        Resource.NW_IN: part_load[:, PartMetric.NW_IN_LEADER],
        Resource.NW_OUT: part_load[:, PartMetric.NW_OUT_LEADER],
        Resource.DISK: part_load[:, PartMetric.DISK],
    }[Resource(res)]
    foll = {
        Resource.CPU: part_load[:, PartMetric.CPU_FOLLOWER],
        Resource.NW_IN: part_load[:, PartMetric.NW_IN_FOLLOWER],
        Resource.NW_OUT: jnp.zeros_like(lead),
        Resource.DISK: part_load[:, PartMetric.DISK],
    }[Resource(res)]
    r = assignment.shape[1]
    is_leader = (jnp.arange(r) == 0)[None, :]
    return jnp.where(is_leader, lead[:, None], foll[:, None])


def make_move_batch(
    part_load: jax.Array,
    assignment: jax.Array,
    dst_cands: jax.Array,
) -> ActionBatch:
    """Candidate grid: every replica slot x every destination candidate.

    Shapes broadcast to [P, R, K] (fields are kept at their minimal broadcast
    shape; no [P, R, K] materialization happens here).
    """
    p_count, r = assignment.shape
    p = jnp.arange(p_count, dtype=jnp.int32)[:, None, None]  # [P,1,1]
    slot = jnp.arange(r, dtype=jnp.int32)[None, :, None]  # [1,R,1]
    src = assignment[:, :, None]  # [P,R,1]
    dst = dst_cands[None, None, :]  # [1,1,K]

    is_leader_slot = slot == 0
    lead = _leader_vec(part_load, p)  # [P,1,1,4]
    foll = _follower_vec(part_load, p)
    dload = jnp.where(is_leader_slot[..., None], lead, foll)  # [P,R,1,4]

    pl = part_load[p]  # [P,1,1,M]
    valid = (src >= 0) & (src != dst)
    return ActionBatch(
        kind=jnp.full((1, 1, 1), KIND_MOVE, dtype=jnp.int32),
        p=p,
        slot=slot,
        src=src,
        dst=dst,
        valid=valid,
        dload=dload,
        drep=jnp.ones((1, 1, 1), dtype=jnp.int32),
        dleader=is_leader_slot.astype(jnp.int32),
        dpnw=pl[..., PartMetric.NW_OUT_LEADER],
        dleader_nw_in=jnp.where(
            is_leader_slot, pl[..., PartMetric.NW_IN_LEADER], 0.0
        ),
    )


def make_leadership_batch(part_load: jax.Array, assignment: jax.Array) -> ActionBatch:
    """Candidate grid [P, R-1]: promote the replica in slot s (s >= 1) to leader.

    The model mutation is a slot swap (flat_model.relocate_leadership); the load
    delta is leader_vec - follower_vec moving from the old leader to the new,
    mirroring ClusterModel.relocateLeadership (cc/model/ClusterModel.java:307).
    """
    p_count, r = assignment.shape
    if r < 2:
        raise ValueError("leadership batch requires max replication factor >= 2")
    p = jnp.arange(p_count, dtype=jnp.int32)[:, None]  # [P,1]
    slot = jnp.arange(1, r, dtype=jnp.int32)[None, :]  # [1,R-1]
    src = assignment[:, 0:1]  # [P,1] current leader
    dst = assignment[:, 1:]  # [P,R-1] new leader

    lead = _leader_vec(part_load, p)  # [P,1,4]
    foll = _follower_vec(part_load, p)
    dload = lead - foll  # [P,1,4]

    pl = part_load[p]  # [P,1,M]
    valid = (dst >= 0) & (src >= 0)
    return ActionBatch(
        kind=jnp.full((1, 1), KIND_LEADERSHIP, dtype=jnp.int32),
        p=p,
        slot=slot,
        src=src,
        dst=dst,
        valid=valid,
        dload=dload,
        drep=jnp.zeros((1, 1), dtype=jnp.int32),
        dleader=jnp.ones((1, 1), dtype=jnp.int32),
        dpnw=jnp.zeros((1, 1), dtype=jnp.float32),
        dleader_nw_in=pl[..., PartMetric.NW_IN_LEADER],
    )


def build_selected(part_load: jax.Array, assignment: jax.Array, p, kind, slot, dst) -> ActionBatch:
    """Materialize concrete actions from (partition, kind, slot, dst) picks.

    Shared by the optimizer's shortlist apply and the swap kernel; `p`,
    `kind`, `slot`, `dst` may be scalars or index arrays of a common shape.
    """
    a = assignment
    is_move = kind == KIND_MOVE
    src = jnp.where(is_move, a[p, slot], a[p, 0])
    pl = part_load[p]
    lead = _leader_vec(part_load, p)
    foll = _follower_vec(part_load, p)
    move_load = jnp.where((slot == 0)[..., None], lead, foll)
    dload = jnp.where(is_move[..., None], move_load, lead - foll)
    leader_transfer = (~is_move) | (slot == 0)
    return ActionBatch(
        kind=kind,
        p=p,
        slot=slot,
        src=src,
        dst=dst,
        valid=(src >= 0) & (dst >= 0) & (src != dst),
        dload=dload,
        drep=is_move.astype(jnp.int32),
        dleader=leader_transfer.astype(jnp.int32),
        dpnw=jnp.where(is_move, pl[..., PartMetric.NW_OUT_LEADER], 0.0),
        dleader_nw_in=jnp.where(leader_transfer, pl[..., PartMetric.NW_IN_LEADER], 0.0),
    )


def gather_actions(batch: ActionBatch, *idx) -> ActionBatch:
    """Pick concrete actions out of a broadcast grid by index arrays.

    `idx` has one index array per grid axis; fields are broadcast (a view
    under XLA) then gathered, so the full grid is never materialized.
    """
    shape = jnp.broadcast_shapes(*(f.shape for f in (batch.kind, batch.p, batch.slot, batch.src, batch.dst, batch.valid)))

    def pick(field):
        return jnp.broadcast_to(field, shape)[idx]

    def pick_vec(field):  # trailing per-Resource axis
        return jnp.broadcast_to(field, shape + (field.shape[-1],))[idx]

    return ActionBatch(
        kind=pick(batch.kind),
        p=pick(batch.p),
        slot=pick(batch.slot),
        src=pick(batch.src),
        dst=pick(batch.dst),
        valid=pick(batch.valid),
        dload=pick_vec(batch.dload),
        drep=pick(batch.drep),
        dleader=pick(batch.dleader),
        dpnw=pick(batch.dpnw),
        dleader_nw_in=pick(batch.dleader_nw_in),
    )


@dataclasses.dataclass(frozen=True)
class BalancingAction:
    """Host-side rendering of one applied action, the analog of
    cc/analyzer/BalancingAction.java:17 (for logs, REST responses, tests)."""

    partition: int
    slot: int
    source_broker: int
    destination_broker: int
    kind: int  # KIND_MOVE | KIND_LEADERSHIP

    @property
    def action_type(self) -> str:
        return (
            "INTER_BROKER_REPLICA_MOVEMENT" if self.kind == KIND_MOVE else "LEADERSHIP_MOVEMENT"
        )
