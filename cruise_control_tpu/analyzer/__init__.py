"""Analyzer: goal kernels + the batched-greedy optimizer.

The TPU-native re-design of the reference's analyzer subsystem
(cc/analyzer/: GoalOptimizer, Goal SPI, AbstractGoal greedy engine). Goals are
pure vectorized functions over the FlatClusterModel; the optimizer scores
candidate actions in batch with vmap/top-k and applies shortlisted actions via
a sequentially re-validated lax.scan, preserving the reference's
goal-priority semantics while replacing its one-action-at-a-time greedy.
"""

from cruise_control_tpu.analyzer.stats import ClusterModelStats, compute_stats
from cruise_control_tpu.analyzer.actions import ActionBatch, BalancingAction
from cruise_control_tpu.analyzer.goals import GOAL_REGISTRY, get_goal, goals_by_priority
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer, OptimizerResult
from cruise_control_tpu.analyzer.proposals import ExecutionProposal, proposal_diff

__all__ = [
    "ClusterModelStats",
    "compute_stats",
    "ActionBatch",
    "BalancingAction",
    "GOAL_REGISTRY",
    "get_goal",
    "goals_by_priority",
    "GoalOptimizer",
    "OptimizerResult",
    "ExecutionProposal",
    "proposal_diff",
]
