# cclint: kernel-module
"""Bulk count-rebalance planner: the surplus/deficit wave kernel for
count-distribution goals.

The count-family goals (ReplicaDistribution, LeaderReplicaDistribution,
ReplicaCapacity, LeaderBytesIn's leadership phase — and
TopicReplicaDistribution through its pair-drain engine) move ~one unit of
goal cost per action, so a round-by-round greedy spends thousands of serial
rounds applying moves a closed-form target already determines: every broker's
distance to the floor/ceil balance window is known up front (the
assignment-problem view of count balancing — "On Efficiently Partitioning a
Topic in Apache Kafka", arxiv 2205.09415 — rather than an iterative search).
This kernel computes per-broker surplus/deficit against those targets in one
vectorized pass and emits the whole move set in conflict-free waves:

  1. surplus/deficit: `goal.bulk_counts` -> units each broker must shed
     (dead brokers: everything — evacuation precedes balance) and a
     deficit-first destination rank key;
  2. candidates: each surplus broker's top-K drain replicas by the goal's
     own drain priority (the shared sort-free segment passes,
     drain.broker_top_replicas);
  3. matching: the i-th surplus broker pairs with the (i + wave)-th-ranked
     deficit destination (context.rank_paired_destinations — the
     sorted-by-sorted matching; rotation retries failed pairs on later
     waves), plus, for leadership goals, each candidate's R-1 promotion
     cells whose destinations are fixed by the assignment;
  4. waves: every nomination is scored EXACTLY (structural legality + merged
     prior-goal tables + this goal's acceptance and improvement criterion),
     a broker/host/partition-disjoint subset applies at once
     (context.wave_select contract), and applied candidates retire.

The schedule is adaptive at every level, so the planner only pays off where
it wins and hands off where it can't:

  - the whole round is SKIPPED when no broker owes a full unit (the
    per-round engines' precision-tail regime);
  - the wave budget per round is ceil(max per-broker surplus), capped;
  - waves continue only while they deliver bulk-scale progress (at least
    1/8 of the surplus set per wave) — a dribbling wave means the remaining
    surplus is blocked-pair precision work, which the per-round engines'
    richer candidate sets handle at the same per-action cost.

Every applied action is individually legal and improving at application
time, so a bulk round composes exactly like a sequence of reference-legal
greedy steps (AbstractGoal.java:67-101): the one-action-at-a-time acceptance
semantics of the reference are preserved — only the search order changes.
The per-round engines (the exhaustive [P, R, K] grid in greedy parity mode,
the drain/fill rounds in batched mode) remain as the precision tail: the
goal loop falls back to them whenever the planner finds nothing, so the
final converged state is at least as good as without the planner.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.actions import (
    KIND_LEADERSHIP,
    KIND_MOVE,
    build_selected,
)
from cruise_control_tpu.analyzer.acceptance import score_batch
from cruise_control_tpu.analyzer.context import (
    Aggregates,
    StaticCtx,
    apply_actions_batch,
    make_touch_tag,
    rank_paired_destinations,
    replicas_on_dead,
    wave_select,
)
from cruise_control_tpu.analyzer.drain import broker_top_replicas


def make_bulk_count_round(goal, dims, k_cand: int, max_waves: int):
    """Build bulk_round(static, agg, tables, gs, contrib, rnd) ->
    (agg2, applied) for a count-family goal (goal.count_family).

    `contrib` is the goal's drain_contrib for the entry aggregates (shared
    with the drain/swap engines); candidate picks are fixed at round start
    and re-validated every wave, with applied candidates retired so later
    waves consume the next ones. `rnd` offsets the destination rotation so
    consecutive rounds retry blocked pairs against different destinations.
    """
    p_count, r = dims.num_partitions, dims.max_rf
    b_count = dims.num_brokers
    k = max(1, min(k_cand, p_count))
    use_leadership = goal.uses_leadership and r >= 2
    # cells per candidate: the paired move plus, for leadership goals, one
    # promotion per follower slot (whose destination the assignment fixes)
    fam = r if use_leadership else 1

    def bulk_round(static: StaticCtx, agg: Aggregates, tables, gs, contrib,
                   rnd=jnp.int32(0)):
        # adaptive wave budget: each wave sheds at most one unit per surplus
        # broker (wave disjointness), so ceil(max surplus) waves suffice
        # under perfect matching
        c0 = goal.bulk_counts(static, gs, agg)
        waves_dyn = jnp.clip(
            jnp.ceil(jnp.max(c0.surplus)).astype(jnp.int32), 1, max_waves
        )
        rows = jnp.arange(b_count, dtype=jnp.int32)

        def run(agg_in):
            # every replica on a dead broker is a candidate regardless of
            # the goal's own priorities
            # (GoalUtils.ensureNoReplicaOnDeadBrokers)
            contrib_r = jnp.where(
                replicas_on_dead(static, agg_in.assignment),
                jnp.float32(1e9), contrib,
            )
            cand_p, cand_s, cand_ok = broker_top_replicas(
                static, agg_in, contrib_r, k, b_count
            )  # [B, K]

            def cond(c):
                _, _, w, go, _ = c
                return go & (w < waves_dyn)

            def body(c):
                agg_c, applied_any, w, _, done = c
                counts = goal.bulk_counts(static, gs, agg_c)
                valid_src = counts.surplus > 0.0
                n_valid = jnp.sum(valid_src.astype(jnp.int32))
                paired = rank_paired_destinations(
                    valid_src, counts.dst_key, w + rnd
                )
                a = agg_c.assignment
                live = cand_ok & ~done & valid_src[:, None]
                mv = build_selected(
                    static.part_load, a, cand_p, jnp.int32(KIND_MOVE),
                    cand_s, paired[:, None],
                )
                s_mv = jnp.where(
                    live, score_batch(static, agg_c, mv, goal, gs, tables),
                    -jnp.inf,
                )  # [B, K]
                if use_leadership:
                    slots = jnp.arange(1, r, dtype=jnp.int32)[None, None, :]
                    p3 = cand_p[:, :, None]
                    ld = build_selected(
                        static.part_load, a, p3, jnp.int32(KIND_LEADERSHIP),
                        slots, a[p3, slots],
                    )
                    s_ld = jnp.where(
                        live[:, :, None],
                        score_batch(static, agg_c, ld, goal, gs, tables),
                        -jnp.inf,
                    )  # [B, K, R-1]
                    cells = jnp.concatenate([s_mv[:, :, None], s_ld], axis=2)
                    cells = cells.reshape(b_count, k * fam)
                else:
                    cells = s_mv
                # one nomination per source broker: its best cell
                j = jnp.argmax(cells, axis=1).astype(jnp.int32)
                best = jnp.take_along_axis(cells, j[:, None], axis=1)[:, 0]
                k_i = j // fam
                f_i = j % fam
                p_i = cand_p[rows, k_i]
                s_i = jnp.where(f_i == 0, cand_s[rows, k_i], f_i)
                kind_i = jnp.where(
                    f_i == 0, jnp.int32(KIND_MOVE), jnp.int32(KIND_LEADERSHIP)
                )
                dst_i = jnp.where(f_i == 0, paired, a[p_i, jnp.maximum(f_i, 0)])
                act = build_selected(
                    static.part_load, a, p_i, kind_i, s_i, dst_i
                )
                w_sel = wave_select(
                    best, act.src, act.dst, static.broker_host[act.dst],
                    jnp.isfinite(best), b_count, dims.num_hosts,
                    parts=(act.p,), num_partitions=p_count,
                )
                agg_c = apply_actions_batch(
                    static, agg_c, act, w_sel, tag=make_touch_tag(rnd, w)
                )
                # an applied row's candidate left its source (or its
                # leadership moved): retire it so later waves consume the
                # next candidate
                done = done.at[rows, k_i].set(done[rows, k_i] | w_sel)
                n_applied = jnp.sum(w_sel.astype(jnp.int32))
                # adaptive handoff: continue only while waves deliver
                # BULK-scale progress (>= 1/8 of the surplus set). A
                # dribbling wave means the remaining surplus is a precision
                # problem — blocked pairs, rare legal destinations — which
                # the per-round engine's richer candidate sets handle at
                # the same per-action cost; burning the full wave budget on
                # it stacked planner cost on engine cost without reducing
                # rounds (measured +22% on the 2,600-broker bench before
                # this gate).
                go = n_applied >= jnp.maximum(1, n_valid // 8)
                return (agg_c, applied_any | (n_applied > 0), w + 1, go, done)

            init = (
                agg_in, jnp.asarray(False), jnp.int32(0), jnp.asarray(True),
                jnp.zeros((b_count, k), dtype=bool),
            )
            agg2, applied_any, _, _, _ = jax.lax.while_loop(cond, body, init)
            return agg2, applied_any

        # no broker owes a whole unit (and dead brokers, whose surplus is
        # their full holding, are empty): the remaining work is the
        # per-round engines' precision tail — skip the planner's fixed
        # per-round cost (candidate segment passes + one probe wave)
        return jax.lax.cond(
            jnp.max(c0.surplus) >= 1.0,
            run,
            lambda a: (a, jnp.asarray(False)),
            agg,
        )

    def named_bulk_round(static, agg, tables, gs, contrib, rnd=jnp.int32(0)):
        # named_scope at trace time: the planner's kernels carry this name in
        # xplane op metadata, so profiler captures separate bulk waves from
        # the per-round engines (docs/OBSERVABILITY.md correlation)
        with jax.named_scope(f"cc-bulk-{goal.name}"):
            return bulk_round(static, agg, tables, gs, contrib, rnd)

    return named_bulk_round
