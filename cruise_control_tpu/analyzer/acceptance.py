"""Shared acceptance tables: the whole prior-goal chain as ONE kernel.

The reference re-checks every previously-optimized goal's `actionAcceptance`
per candidate action (AbstractGoal.maybeApplyBalancingAction,
cc/analyzer/goals/AbstractGoal.java:186-227 via AnalyzerUtils
.isProposalAcceptableForOptimizedGoals). Round 1 translated that as a Python
loop over prior goals inside every jitted goal step — correct, but each
goal's XLA program inlined every prior's kernel over the full candidate
grid, growing the compiled program O(goals^2) across the stack.

The TPU-native fix exploits that every goal's acceptance predicate is a
box constraint on the post-action value of a small set of per-broker (or
per-topic / per-host) aggregates:

  RackAwareGoal                 dst rack must not already host the partition
  ReplicaCapacityGoal           replica_count[dst]' <= max
  CapacityGoal(res)             broker_load[dst, res]' <= cap limit (+ host CPU)
  ReplicaDistributionGoal       count' within [lo, hi] (src lo waived if dead)
  LeaderReplicaDistributionGoal leader_count' within [lo, hi]
  ResourceDistributionGoal(res) util' within [lo, hi]  (== raw load within
                                [lo*cap_b, hi*cap_b] per broker)
  TopicReplicaDistributionGoal  topic_replica_count[t, ·]' within [lo_t, hi_t]
  PotentialNwOutGoal            potential_nw_out[dst]' <= cap limit
  LeaderBytesInDistributionGoal leader_nw_in[dst]' <= hi (waived if src dead)

So each optimized goal *contributes* its bounds into an `AcceptanceTables`
(elementwise min of uppers / max of lowers), and a single fixed-size kernel
`tables_acceptance` checks any candidate batch against the merged tables.
Per-goal program size no longer depends on how many goals ran before it.

Uniform conventions (matching the per-goal kernels they replace):
- every upper-bound check is exempt when the action does not increase the
  tracked quantity at dst (delta <= 0);
- every lower-bound check applies at src and is waived when src is dead
  (self-healing: load must leave dead brokers no matter what);
- `hi_lnw_waive_dead` reproduces LeaderBytesInDistributionGoal's dst-side
  dead-source waiver.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.actions import ActionBatch
from cruise_control_tpu.analyzer.context import Aggregates, StaticCtx
from cruise_control_tpu.common.resources import Resource

_INF = jnp.float32(jnp.inf)


class AcceptanceTables(NamedTuple):
    """Merged box constraints of all previously-optimized goals.

    All bounds are in raw aggregate units (loads, counts); +/-inf disables.
    """

    hi_load: jax.Array  # f32[B, 4]
    lo_load: jax.Array  # f32[B, 4]
    hi_rep: jax.Array  # f32[B]
    lo_rep: jax.Array  # f32[B]
    hi_lead: jax.Array  # f32[B]
    lo_lead: jax.Array  # f32[B]
    hi_pnw: jax.Array  # f32[B]
    hi_lnw: jax.Array  # f32[B]
    hi_lnw_waive_dead: jax.Array  # bool[]
    hi_topic: jax.Array  # f32[T]
    lo_topic: jax.Array  # f32[T]
    hi_host_cpu: jax.Array  # f32[H]
    rack_enabled: jax.Array  # bool[]


def empty_tables(dims) -> AcceptanceTables:
    b, t, h = dims.num_brokers, dims.num_topics, dims.num_hosts
    return AcceptanceTables(
        hi_load=jnp.full((b, 4), _INF),
        lo_load=jnp.full((b, 4), -_INF),
        hi_rep=jnp.full((b,), _INF),
        lo_rep=jnp.full((b,), -_INF),
        hi_lead=jnp.full((b,), _INF),
        lo_lead=jnp.full((b,), -_INF),
        hi_pnw=jnp.full((b,), _INF),
        hi_lnw=jnp.full((b,), _INF),
        hi_lnw_waive_dead=jnp.asarray(False),
        hi_topic=jnp.full((t,), _INF),
        lo_topic=jnp.full((t,), -_INF),
        hi_host_cpu=jnp.full((h,), _INF),
        rack_enabled=jnp.asarray(False),
    )


def build_tables(
    priors: Sequence, static: StaticCtx, agg: Aggregates, dims
) -> AcceptanceTables:
    """Merge every prior goal's bounds (thresholds from round-start `agg`,
    exactly when the per-goal `prepare`/initGoalState ran before)."""
    tables = empty_tables(dims)
    for g in priors:
        gs = g.prepare(static, agg, dims)
        tables = g.contribute_acceptance(static, gs, tables)
    return tables


def tables_acceptance(
    static: StaticCtx, tables: AcceptanceTables, agg: Aggregates, act: ActionBatch
) -> jax.Array:
    """bool[...]: does the action satisfy EVERY merged bound?

    Values are read from the *current* aggregates (they may be mid-apply-scan);
    the bounds were fixed at round start — the same split the per-goal chain
    had (thresholds from initGoalState, values from the live model).
    """
    src, dst = act.src, act.dst
    dead_src = static.dead[src]

    # per-resource broker load
    d = act.dload  # [..., 4]
    load_dst_after = agg.broker_load[dst] + d
    load_src_after = agg.broker_load[src] - d
    inc = d > 0.0
    ok = jnp.all(~inc | (load_dst_after <= tables.hi_load[dst]), axis=-1)
    ok &= dead_src | jnp.all(
        ~inc | (load_src_after >= tables.lo_load[src]), axis=-1
    )

    # replica count
    drep = act.drep.astype(jnp.float32)
    rep_inc = drep > 0
    ok &= ~rep_inc | (agg.replica_count[dst] + drep <= tables.hi_rep[dst])
    ok &= ~rep_inc | dead_src | (agg.replica_count[src] - drep >= tables.lo_rep[src])

    # leader count
    dlead = act.dleader.astype(jnp.float32)
    lead_inc = dlead > 0
    ok &= ~lead_inc | (agg.leader_count[dst] + dlead <= tables.hi_lead[dst])
    ok &= ~lead_inc | dead_src | (agg.leader_count[src] - dlead >= tables.lo_lead[src])

    # potential NW_OUT
    pnw_inc = act.dpnw > 0.0
    ok &= ~pnw_inc | (agg.potential_nw_out[dst] + act.dpnw <= tables.hi_pnw[dst])

    # leader bytes-in (dead-source waiver flag per LeaderBytesInDistributionGoal)
    lnw_inc = act.dleader_nw_in > 0.0
    lnw_ok = agg.leader_nw_in[dst] + act.dleader_nw_in <= tables.hi_lnw[dst]
    ok &= ~lnw_inc | lnw_ok | (tables.hi_lnw_waive_dead & dead_src)

    # per-topic replica count (replica moves only: drep carries the indicator)
    topic = static.topic_id[act.p]
    ok &= ~rep_inc | (
        agg.topic_replica_count[topic, dst] + act.drep <= tables.hi_topic[topic]
    )
    ok &= ~rep_inc | dead_src | (
        agg.topic_replica_count[topic, src] - act.drep >= tables.lo_topic[topic]
    )

    # host-level CPU (CpuCapacityGoal); same-host moves shift nothing
    dcpu = d[..., Resource.CPU]
    host_src = static.broker_host[src]
    host_dst = static.broker_host[dst]
    host_after = agg.host_cpu_load[host_dst] + jnp.where(host_src == host_dst, 0.0, dcpu)
    ok &= (dcpu <= 0.0) | (host_after <= tables.hi_host_cpu[host_dst])

    # rack safety (replica moves only): dst rack must not keep a sibling
    rack_src = static.broker_rack[src]
    rack_dst = static.broker_rack[dst]
    count_dst = agg.rack_replica_count[act.p, rack_dst] - (rack_src == rack_dst)
    ok &= ~(tables.rack_enabled & rep_inc) | (count_dst == 0)

    return ok
