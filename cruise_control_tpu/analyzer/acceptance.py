"""Shared acceptance tables: the whole prior-goal chain as ONE kernel.

The reference re-checks every previously-optimized goal's `actionAcceptance`
per candidate action (AbstractGoal.maybeApplyBalancingAction,
cc/analyzer/goals/AbstractGoal.java:186-227 via AnalyzerUtils
.isProposalAcceptableForOptimizedGoals). Round 1 translated that as a Python
loop over prior goals inside every jitted goal step — correct, but each
goal's XLA program inlined every prior's kernel over the full candidate
grid, growing the compiled program O(goals^2) across the stack.

The TPU-native fix exploits that every goal's acceptance predicate is a
box constraint on the post-action value of a small set of per-broker (or
per-topic / per-host) aggregates:

  RackAwareGoal                 dst rack must not already host the partition
  ReplicaCapacityGoal           replica_count[dst]' <= max
  CapacityGoal(res)             broker_load[dst, res]' <= cap limit (+ host CPU)
  ReplicaDistributionGoal       count' within [lo, hi] (src lo waived if dead)
  LeaderReplicaDistributionGoal leader_count' within [lo, hi]
  ResourceDistributionGoal(res) util' within [lo, hi]  (== raw load within
                                [lo*cap_b, hi*cap_b] per broker)
  TopicReplicaDistributionGoal  topic_replica_count[t, ·]' within [lo_t, hi_t]
  PotentialNwOutGoal            potential_nw_out[dst]' <= cap limit
  LeaderBytesInDistributionGoal leader_nw_in[dst]' <= hi (waived if src dead)

So each optimized goal *contributes* its bounds into an `AcceptanceTables`
(elementwise min of uppers / max of lowers), and a single fixed-size kernel
`tables_acceptance` checks any candidate batch against the merged tables.
Per-goal program size no longer depends on how many goals ran before it.

Uniform conventions (matching the per-goal kernels they replace):
- every upper-bound check is exempt when the action does not increase the
  tracked quantity at dst (delta <= 0);
- every lower-bound check applies at src and is waived when src is dead
  (self-healing: load must leave dead brokers no matter what);
- `hi_lnw_waive_dead` reproduces LeaderBytesInDistributionGoal's dst-side
  dead-source waiver.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.actions import (
    DEAD_EVACUATION_BONUS,
    KIND_MOVE,
    ActionBatch,
)
from cruise_control_tpu.analyzer.context import (
    Aggregates,
    StaticCtx,
    dst_hosts_partition,
)
from cruise_control_tpu.common.resources import Resource

_INF = jnp.float32(jnp.inf)


class AcceptanceTables(NamedTuple):
    """Merged constraints of all previously-optimized goals.

    All bounds are in raw aggregate units (loads, counts); +/-inf disables.
    `hi_load`/`lo_load` are HARD boxes (capacity goals). `band_*` carries the
    usage-distribution goals' balance bands with the reference's TWO-CASE
    acceptance (ResourceDistributionGoal.actionAcceptance :91-133): the box
    applies only when both endpoints currently satisfy their side of the band;
    otherwise any action that strictly shrinks the pairwise load difference is
    acceptable. Collapsing the band into the hard box would freeze the model
    whenever brokers sit outside the band — which is the normal state mid-
    optimization at scale.
    """

    hi_load: jax.Array  # f32[B, 4] hard upper (capacity goals)
    lo_load: jax.Array  # f32[B, 4] hard lower (unused today; kept for symmetry)
    band_hi: jax.Array  # f32[B, 4] distribution band upper
    band_lo: jax.Array  # f32[B, 4] distribution band lower
    band_on: jax.Array  # bool[4]  band contributed for this resource
    hi_rep: jax.Array  # f32[B]
    lo_rep: jax.Array  # f32[B]
    hi_lead: jax.Array  # f32[B]
    lo_lead: jax.Array  # f32[B]
    hi_pnw: jax.Array  # f32[B]
    hi_lnw: jax.Array  # f32[B]
    hi_lnw_waive_dead: jax.Array  # bool[]
    hi_topic: jax.Array  # f32[T]
    lo_topic: jax.Array  # f32[T]
    hi_host_cpu: jax.Array  # f32[H]
    rack_enabled: jax.Array  # bool[]


def empty_tables(dims) -> AcceptanceTables:
    b, t, h = dims.num_brokers, dims.num_topics, dims.num_hosts
    return AcceptanceTables(
        hi_load=jnp.full((b, 4), _INF),
        lo_load=jnp.full((b, 4), -_INF),
        band_hi=jnp.full((b, 4), _INF),
        band_lo=jnp.full((b, 4), -_INF),
        band_on=jnp.zeros((4,), dtype=bool),
        hi_rep=jnp.full((b,), _INF),
        lo_rep=jnp.full((b,), -_INF),
        hi_lead=jnp.full((b,), _INF),
        lo_lead=jnp.full((b,), -_INF),
        hi_pnw=jnp.full((b,), _INF),
        hi_lnw=jnp.full((b,), _INF),
        hi_lnw_waive_dead=jnp.asarray(False),
        hi_topic=jnp.full((t,), _INF),
        lo_topic=jnp.full((t,), -_INF),
        hi_host_cpu=jnp.full((h,), _INF),
        rack_enabled=jnp.asarray(False),
    )


def band_move_acceptance(tables: AcceptanceTables, agg: Aggregates, src, dst, dload,
                         dead_src) -> jax.Array:
    """bool[...]: the two-case distribution-band check for a (possibly signed)
    per-resource load transfer src -> dst.

    Case 1 (src above its lower bound AND dst under its upper bound — both
    endpoints currently 'fine' for the direction they're changing): the move
    must keep them so. Case 2 (either endpoint already outside): the move
    must strictly shrink |load_src - load_dst| — the reference's
    isGettingMoreBalanced (:866), which is what lets optimization continue in
    tight states. Source-side bounds are waived for dead sources.
    """
    s = agg.broker_load[src]  # [..., 4]
    d = agg.broker_load[dst]
    lo_s = tables.band_lo[src]
    hi_s = tables.band_hi[src]
    lo_d = tables.band_lo[dst]
    hi_d = tables.band_hi[dst]
    dead = dead_src[..., None]
    pos = dload >= 0.0
    # the endpoint losing load must sit above its lower bound, the one gaining
    # must sit under its upper bound — roles depend on the transfer's sign
    case1 = jnp.where(pos, (s >= lo_s) & (d <= hi_d), (d >= lo_d) & (s <= hi_s))
    acc1_pos = (d + dload <= hi_d) & ((s - dload >= lo_s) | dead)
    acc1_neg = (s - dload <= hi_s) & (d + dload >= lo_d)
    acc1 = jnp.where(pos, acc1_pos, acc1_neg)
    prev = s - d
    acc2 = jnp.abs(prev - 2.0 * dload) < jnp.abs(prev)
    ok = jnp.where(case1, acc1, acc2 | dead)
    ok = ok | (dload == 0.0) | ~tables.band_on
    return jnp.all(ok, axis=-1)


def build_tables(
    priors: Sequence, static: StaticCtx, agg: Aggregates, dims
) -> AcceptanceTables:
    """Merge the given goals' bounds from the current aggregates.

    The fused stack program (analyzer.optimizer._make_stack_step) accumulates
    tables incrementally via `contribute_acceptance` as each goal finishes;
    this helper builds the same tables in one shot for tests/analysis."""
    tables = empty_tables(dims)
    for g in priors:
        gs = g.prepare(static, agg, dims)
        tables = g.contribute_acceptance(static, gs, tables)
    return tables


def tables_acceptance(
    static: StaticCtx, tables: AcceptanceTables, agg: Aggregates, act: ActionBatch
) -> jax.Array:
    """bool[...]: does the action satisfy EVERY merged bound?

    Values are read from the *current* aggregates (they may be mid-apply-scan);
    the bounds were fixed at round start — the same split the per-goal chain
    had (thresholds from initGoalState, values from the live model).
    """
    src, dst = act.src, act.dst
    dead_src = static.dead[src]

    # per-resource broker load: hard capacity box ...
    d = act.dload  # [..., 4]
    load_dst_after = agg.broker_load[dst] + d
    load_src_after = agg.broker_load[src] - d
    inc = d > 0.0
    ok = jnp.all(~inc | (load_dst_after <= tables.hi_load[dst]), axis=-1)
    ok &= dead_src | jnp.all(
        ~inc | (load_src_after >= tables.lo_load[src]), axis=-1
    )
    # ... and the usage-distribution goals' two-case band
    ok &= band_move_acceptance(tables, agg, src, dst, d, dead_src)

    # replica count
    drep = act.drep.astype(jnp.float32)
    rep_inc = drep > 0
    ok &= ~rep_inc | (agg.replica_count[dst] + drep <= tables.hi_rep[dst])
    ok &= ~rep_inc | dead_src | (agg.replica_count[src] - drep >= tables.lo_rep[src])

    # leader count
    dlead = act.dleader.astype(jnp.float32)
    lead_inc = dlead > 0
    ok &= ~lead_inc | (agg.leader_count[dst] + dlead <= tables.hi_lead[dst])
    ok &= ~lead_inc | dead_src | (agg.leader_count[src] - dlead >= tables.lo_lead[src])

    # potential NW_OUT
    pnw_inc = act.dpnw > 0.0
    ok &= ~pnw_inc | (agg.potential_nw_out[dst] + act.dpnw <= tables.hi_pnw[dst])

    # leader bytes-in (dead-source waiver flag per LeaderBytesInDistributionGoal)
    lnw_inc = act.dleader_nw_in > 0.0
    lnw_ok = agg.leader_nw_in[dst] + act.dleader_nw_in <= tables.hi_lnw[dst]
    ok &= ~lnw_inc | lnw_ok | (tables.hi_lnw_waive_dead & dead_src)

    # per-topic replica count (replica moves only: drep carries the indicator)
    topic = static.topic_id[act.p]
    ok &= ~rep_inc | (
        agg.topic_replica_count[topic, dst] + act.drep <= tables.hi_topic[topic]
    )
    ok &= ~rep_inc | dead_src | (
        agg.topic_replica_count[topic, src] - act.drep >= tables.lo_topic[topic]
    )

    # host-level CPU (CpuCapacityGoal); same-host moves shift nothing
    dcpu = d[..., Resource.CPU]
    host_src = static.broker_host[src]
    host_dst = static.broker_host[dst]
    host_after = agg.host_cpu_load[host_dst] + jnp.where(host_src == host_dst, 0.0, dcpu)
    ok &= (dcpu <= 0.0) | (host_after <= tables.hi_host_cpu[host_dst])

    # rack safety (replica moves only): dst rack must not keep a sibling
    rack_src = static.broker_rack[src]
    rack_dst = static.broker_rack[dst]
    count_dst = agg.rack_replica_count[act.p, rack_dst] - (rack_src == rack_dst)
    ok &= ~(tables.rack_enabled & rep_inc) | (count_dst == 0)

    return ok


def swap_tables_acceptance(
    static: StaticCtx, tables: AcceptanceTables, agg: Aggregates, mv1, mv2
) -> jax.Array:
    """bool[...]: does a SWAP satisfy every merged bound, evaluated on its
    NET effect?

    `mv1` moves a replica hot -> cold, `mv2` moves one cold -> hot (the
    optimizer's two-coupled-moves encoding of INTER_BROKER_REPLICA_SWAP).
    The reference evaluates actionAcceptance on the swap action atomically
    (AbstractGoal.maybeApplySwapAction :240); checking each leg alone against
    the merged tables is stricter — near a bound it vetoes swaps whose net
    load change is tiny, which is the entire point of a swap. Load-like
    quantities (per-resource load, leader count, potential NW_OUT, leader
    bytes-in, host CPU) are therefore checked on the net delta per broker;
    per-topic counts stay per-leg (their deltas are +-1 regardless), skipped
    when both replicas share a topic (net zero); replica counts don't change.
    """
    hot, cold = mv1.src, mv2.src
    d = mv1.dload - mv2.dload  # [..., 4] net load cold gains (hot loses)

    def box(broker, delta):
        inc = delta > 0.0
        after = agg.broker_load[broker] + delta
        up = jnp.all(~inc | (after <= tables.hi_load[broker]), axis=-1)
        lo = jnp.all(inc | (after >= tables.lo_load[broker]), axis=-1)
        return up & lo

    ok = box(cold, d) & box(hot, -d)
    # distribution bands, two-case on the swap's net transfer hot -> cold
    # (ResourceDistributionGoal swap acceptance :96-121: box only when both
    # brokers currently satisfy the relevant side of the band, otherwise the
    # swap must shrink |load_hot - load_cold|)
    not_dead = jnp.zeros(jnp.broadcast_shapes(hot.shape, cold.shape), dtype=bool)
    ok &= band_move_acceptance(tables, agg, hot, cold, d, not_dead)

    # leader count (a swap can carry a leader slot across)
    dl = (mv1.dleader - mv2.dleader).astype(jnp.float32)
    ok &= (dl <= 0) | (
        (agg.leader_count[cold] + dl <= tables.hi_lead[cold])
        & (agg.leader_count[hot] - dl >= tables.lo_lead[hot])
    )
    ok &= (dl >= 0) | (
        (agg.leader_count[hot] - dl <= tables.hi_lead[hot])
        & (agg.leader_count[cold] + dl >= tables.lo_lead[cold])
    )

    # potential NW_OUT and leader bytes-in, net per broker
    dpnw = mv1.dpnw - mv2.dpnw
    ok &= (dpnw <= 0.0) | (agg.potential_nw_out[cold] + dpnw <= tables.hi_pnw[cold])
    ok &= (dpnw >= 0.0) | (agg.potential_nw_out[hot] - dpnw <= tables.hi_pnw[hot])
    dlnw = mv1.dleader_nw_in - mv2.dleader_nw_in
    ok &= (dlnw <= 0.0) | (agg.leader_nw_in[cold] + dlnw <= tables.hi_lnw[cold])
    ok &= (dlnw >= 0.0) | (agg.leader_nw_in[hot] - dlnw <= tables.hi_lnw[hot])

    # per-topic counts, per-leg (+-1), inert when both replicas share a topic
    t1 = static.topic_id[mv1.p]
    t2 = static.topic_id[mv2.p]
    diff_topic = t1 != t2
    topic_ok = (
        (agg.topic_replica_count[t1, cold] + 1 <= tables.hi_topic[t1])
        & (agg.topic_replica_count[t1, hot] - 1 >= tables.lo_topic[t1])
        & (agg.topic_replica_count[t2, hot] + 1 <= tables.hi_topic[t2])
        & (agg.topic_replica_count[t2, cold] - 1 >= tables.lo_topic[t2])
    )
    ok &= ~diff_topic | topic_ok

    # host-level CPU, net (same-host swaps shift nothing between hosts)
    dcpu = d[..., Resource.CPU]
    host_hot = static.broker_host[hot]
    host_cold = static.broker_host[cold]
    same_host = host_hot == host_cold
    ok &= same_host | (dcpu <= 0.0) | (
        agg.host_cpu_load[host_cold] + dcpu <= tables.hi_host_cpu[host_cold]
    )
    ok &= same_host | (dcpu >= 0.0) | (
        agg.host_cpu_load[host_hot] - dcpu <= tables.hi_host_cpu[host_hot]
    )
    return ok


def structural_mask(static: StaticCtx, agg: Aggregates, act: ActionBatch):
    """Checks every action must pass regardless of goals: the dense analog of
    GoalUtils.legitMove + OptimizationOptions filtering."""
    is_move = act.kind == KIND_MOVE
    ok = act.valid & static.movable_partition[act.p]
    ok = ok & jnp.where(
        is_move, static.replica_dst_ok[act.dst], static.leadership_dst_ok[act.dst]
    )
    ok = ok & ~(is_move & dst_hosts_partition(agg, act.p, act.dst))
    ok = ok & ((~static.only_move_immigrants) | static.dead[act.src])
    return ok


from cruise_control_tpu.analyzer.goals.base import SCORE_EPS as _SCORE_EPS  # noqa: E402


def score_batch(static: StaticCtx, agg: Aggregates, act: ActionBatch, goal, gs, tables):
    """f32[...]: masked score of each candidate (-inf where unacceptable).

    All prior goals' acceptance is enforced by the merged `tables` in one
    fixed-size kernel — the program does not grow with the number of
    previously-optimized goals."""
    mask = structural_mask(static, agg, act)
    mask = mask & tables_acceptance(static, tables, agg, act)
    mask = mask & goal.acceptance(static, gs, agg, act)
    score = goal.action_score(static, gs, agg, act)
    # Evacuating dead brokers dominates any balance improvement: every goal
    # must first clear replicas/leadership off dead brokers
    # (GoalUtils.ensureNoReplicaOnDeadBrokers semantics).
    evac = static.dead[act.src] & ((act.kind == KIND_MOVE) | (act.dleader > 0))
    score = score + jnp.where(evac, DEAD_EVACUATION_BONUS, 0.0)
    return jnp.where(mask & (score > _SCORE_EPS), score, -jnp.inf)
