"""Decision provenance: the per-move attribution ledger.

PRs 2 and 7 made the service observable in *time* (spans, histograms,
device telemetry); this module makes it observable in *decision*: for every
accepted replica move / leadership change of an optimization run, WHICH goal
proposed it, under WHICH engine (grid/drain/bulk/polish), in WHICH round and
apply wave, and what the goal's violated-count / cost deltas were — the
TPU-native analog of the reference's per-proposal balancing-action reasons
(cc/analyzer/BalancingAction + the proposal summaries attached to every
OptimizerResult).

Collection is sync-free by design: the engines stamp a packed (round, wave)
tag into `Aggregates.touch_tag` alongside every assignment write
(context.apply_actions_batch), the fused stack / chunked goal machine
snapshot the assignment + tag arrays once per goal phase INSIDE the compiled
program, and the whole snapshot stack leaves the device in the one batched
`device_get` the optimizer already performs at its span boundary. No
per-move host sync exists to lose when the round loop fuses into a single
`lax.while_loop` (ROADMAP item 2) — the attribution rides the device state.

Host-side, `build_run_ledger` diffs consecutive phase snapshots into
`MoveRecord`s (NET accepted moves per goal phase: a cell moved and moved
back inside one phase cancels, matching proposal semantics), and the bounded
thread-safe `MoveLedger` registry retains recent `RunLedger`s for
GET `/explain`, `scripts/diff_runs.py`, and the bench's provenance digests.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from cruise_control_tpu.common.sensors import REGISTRY

#: touch-tag packing width — mirrors context.TAG_WAVE_BASE (kept literal so
#: recorded ledger JSON stays decodable without importing the kernels)
TAG_WAVE_BASE = 1024


def decode_tag(tag: int) -> tuple:
    """(round, wave) from a packed touch tag; (-1, -1) = untagged."""
    tag = int(tag)
    if tag == -1:
        return -1, -1
    rnd, wave = divmod(tag, TAG_WAVE_BASE)
    return rnd, wave


class MoveRecord(NamedTuple):
    """One accepted assignment-cell change, fully attributed.

    A NamedTuple, not a dataclass: ledger builds construct one record per
    accepted move and a frozen dataclass pays object.__setattr__ per field —
    measured 2-3x the whole build budget at bench scale."""

    partition: int
    slot: int
    kind: str  # "move" | "leadership"
    src: int
    dst: int
    goal: str
    engine: str
    phase: str  # "main" | "polish"
    goal_index: int  # phase index in the run's phase order
    round: int  # within-goal round of the last accepted touch (-1 = unknown)
    wave: int  # apply-wave index inside that round (-1 = unknown)

    def key(self) -> tuple:
        """Canonical alignment key (diff_runs pairs moves on this)."""
        return (self.goal_index, self.round, self.wave, self.partition, self.slot)

    def decision(self) -> tuple:
        """The decision itself, engine label excluded: two runs under
        different settings legitimately label the same goal's engine
        differently (`drain` vs `drain+polish`) — that is presentation, not
        a divergent decision. Digests and diff_runs compare on this."""
        return (
            self.goal_index, self.round, self.wave, self.partition, self.slot,
            self.kind, self.src, self.dst, self.goal, self.phase,
        )

    def to_dict(self) -> Dict:
        return {
            "partition": self.partition,
            "slot": self.slot,
            "kind": self.kind,
            "src": self.src,
            "dst": self.dst,
            "goal": self.goal,
            "engine": self.engine,
            "phase": self.phase,
            "goalIndex": self.goal_index,
            "round": self.round,
            "wave": self.wave,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "MoveRecord":
        return cls(
            partition=int(d["partition"]), slot=int(d["slot"]),
            kind=str(d["kind"]), src=int(d["src"]), dst=int(d["dst"]),
            goal=str(d["goal"]), engine=str(d.get("engine", "")),
            phase=str(d.get("phase", "main")),
            goal_index=int(d.get("goalIndex", -1)),
            round=int(d.get("round", -1)), wave=int(d.get("wave", -1)),
        )


@dataclasses.dataclass(frozen=True)
class GoalSegment:
    """One goal phase of a run: the per-goal acceptance outcome the moves of
    that phase were admitted under."""

    goal: str
    engine: str
    phase: str  # "main" | "polish"
    index: int  # phase index in the run's phase order
    cost_before: float
    cost_after: float
    violated_before: int
    violated_after: int
    rounds: int
    converged: bool
    num_moves: int
    num_leadership: int

    @property
    def cost_delta(self) -> float:
        return self.cost_after - self.cost_before

    def to_dict(self) -> Dict:
        return {
            "goal": self.goal, "engine": self.engine, "phase": self.phase,
            "index": self.index,
            "costBefore": round(self.cost_before, 6),
            "costAfter": round(self.cost_after, 6),
            "costDelta": round(self.cost_delta, 6),
            "violatedBefore": self.violated_before,
            "violatedAfter": self.violated_after,
            "rounds": self.rounds, "converged": self.converged,
            "numMoves": self.num_moves, "numLeadership": self.num_leadership,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "GoalSegment":
        return cls(
            goal=str(d["goal"]), engine=str(d.get("engine", "")),
            phase=str(d.get("phase", "main")), index=int(d.get("index", -1)),
            cost_before=float(d.get("costBefore", 0.0)),
            cost_after=float(d.get("costAfter", 0.0)),
            violated_before=int(d.get("violatedBefore", 0)),
            violated_after=int(d.get("violatedAfter", 0)),
            rounds=int(d.get("rounds", 0)),
            converged=bool(d.get("converged", False)),
            num_moves=int(d.get("numMoves", 0)),
            num_leadership=int(d.get("numLeadership", 0)),
        )


class RunLedger:
    """All attribution of one optimization run (immutable once built)."""

    def __init__(
        self,
        run_id: str,
        segments: Sequence[GoalSegment],
        moves: Sequence[MoveRecord],
        meta: Optional[Dict] = None,
        created_at: Optional[float] = None,
    ):
        self.run_id = run_id
        self.segments: List[GoalSegment] = list(segments)
        self.moves: List[MoveRecord] = list(moves)
        self.meta: Dict = dict(meta or {})
        self.created_at = time.time() if created_at is None else created_at

    # -- queries ---------------------------------------------------------------

    def query(
        self,
        partition: Optional[int] = None,
        broker: Optional[int] = None,
        goal: Optional[str] = None,
        round: Optional[int] = None,
        kind: Optional[str] = None,
        phase: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> List[MoveRecord]:
        """Move-level view: records filtered by any combination of axes
        (`broker` matches either endpoint)."""
        out = []
        for m in self.moves:
            if partition is not None and m.partition != partition:
                continue
            if broker is not None and m.src != broker and m.dst != broker:
                continue
            if goal is not None and m.goal != goal:
                continue
            if round is not None and m.round != round:
                continue
            if kind is not None and m.kind != kind:
                continue
            if phase is not None and m.phase != phase:
                continue
            out.append(m)
            if limit is not None and len(out) >= limit:
                break
        return out

    def proposal_view(self, partition: Optional[int] = None) -> List[Dict]:
        """Proposal-level view: moves grouped by partition — the answer to
        'why does partition p appear in this OptimizerResult'."""
        groups: "OrderedDict[int, List[MoveRecord]]" = OrderedDict()
        for m in self.moves:
            if partition is not None and m.partition != partition:
                continue
            groups.setdefault(m.partition, []).append(m)
        return [
            {
                "partition": p,
                "provenanceId": f"{self.run_id}/p{p}",
                "goals": sorted({m.goal for m in ms}),
                "moves": [m.to_dict() for m in ms],
            }
            for p, ms in groups.items()
        ]

    # -- digests ---------------------------------------------------------------

    def digest(self, goals: Optional[Sequence[str]] = None) -> Dict:
        """Per-goal move counts + cost-delta checksum, plus a short hash of
        the full canonical move list — two runs with equal digests made the
        same decisions; a mismatch at equal parity is silent decision drift
        (scripts/perf_gate.py's distinct exit path).

        `goals`: restrict the digest to moves ON these goals (the
        incremental lane's unaffected-goal contract, analyzer/incremental.py:
        an incremental re-solve and a from-scratch solve must agree on every
        goal the sensitivity map marks unaffected). A goal-scoped digest
        hashes move decisions only — per-goal cost deltas are EXCLUDED,
        because a goal-scoped run never measures goals outside its subset
        and the comparison must not depend on what one side didn't run."""
        if goals is not None:
            keep = set(goals)
            moves = [m for m in self.moves if m.goal in keep]
        else:
            moves = self.moves
        by_goal: Dict[str, int] = {}
        for m in moves:
            by_goal[m.goal] = by_goal.get(m.goal, 0) + 1
        cost_delta = {
            s.goal: round(s.cost_delta, 6)
            for s in self.segments
            if s.phase == "main"
        } if goals is None else {}
        h = hashlib.sha256()
        for m in sorted(moves, key=MoveRecord.key):
            h.update("|".join(map(str, m.decision())).encode())
        for g in sorted(cost_delta):
            h.update(f"{g}={cost_delta[g]}".encode())
        return {
            "moves": len(moves),
            "byGoal": by_goal,
            **({"costDelta": cost_delta} if goals is None else {"goals": sorted(keep)}),
            "checksum": h.hexdigest()[:16],
        }

    def summary(self) -> Dict:
        moves = sum(1 for m in self.moves if m.kind == "move")
        return {
            "runId": self.run_id,
            "createdAt": self.created_at,
            "numMoves": moves,
            "numLeadership": len(self.moves) - moves,
            "segments": [s.to_dict() for s in self.segments],
            "digest": self.digest(),
            **({"meta": self.meta} if self.meta else {}),
        }

    # -- persistence (scripts/diff_runs.py reads these files) ------------------

    def to_dict(self, include_moves: bool = True) -> Dict:
        out = {
            "runId": self.run_id,
            "createdAt": self.created_at,
            "meta": self.meta,
            "digest": self.digest(),
            "segments": [s.to_dict() for s in self.segments],
        }
        if include_moves:
            out["moves"] = [m.to_dict() for m in self.moves]
        return out

    @classmethod
    def from_dict(cls, d: Dict) -> "RunLedger":
        return cls(
            run_id=str(d.get("runId", "?")),
            segments=[GoalSegment.from_dict(s) for s in d.get("segments", [])],
            moves=[MoveRecord.from_dict(m) for m in d.get("moves", [])],
            meta=d.get("meta") or {},
            created_at=d.get("createdAt"),
        )


# -- host-side builder ---------------------------------------------------------


def build_run_ledger(
    run_id: str,
    phases: Sequence[Dict],
    init_assignment: np.ndarray,
    snap_assignment: np.ndarray,
    snap_tag: np.ndarray,
    valid_partitions: Optional[int] = None,
    meta: Optional[Dict] = None,
) -> RunLedger:
    """Diff consecutive phase snapshots into an attributed RunLedger.

    `phases[i]` describes snapshot row i: {goal, engine, phase, costBefore,
    costAfter, violatedBefore, violatedAfter, rounds, converged}. Arrays are
    host numpy: init [P, R], snapshots [n_phases, P, R] (assignment + packed
    touch tags). `valid_partitions` drops shape-bucket padding rows. The
    diff touches only changed cells (np.nonzero prefilter), so build cost
    scales with moves made, not partitions examined — the <2% overhead
    contract's load-bearing property (tests/test_provenance.py).
    """
    t0 = time.monotonic()
    init = np.asarray(init_assignment)
    snaps = np.asarray(snap_assignment)
    tags = np.asarray(snap_tag)
    if valid_partitions is not None:
        init = init[:valid_partitions]
        snaps = snaps[:, :valid_partitions]
        tags = tags[:, :valid_partitions]
    segments: List[GoalSegment] = []
    moves: List[MoveRecord] = []
    prev = init
    for i, ph in enumerate(phases):
        cur = snaps[i]
        tag = tags[i]
        p_idx, s_idx = np.nonzero(prev != cur)
        n_moves = 0
        n_lead = 0
        if p_idx.size:
            src_v = prev[p_idx, s_idx]
            dst_v = cur[p_idx, s_idx]
            # a leadership change re-homes an existing replica between slots
            # (apply semantics: slot 0 and slot s swap); a move introduces a
            # broker absent from the row before
            is_lead = (prev[p_idx] == dst_v[:, None]).any(axis=1)
            tag_v = tag[p_idx, s_idx].astype(np.int64)
            # exact -1 is the untagged sentinel; -1 % base would read 1023
            rnd_v = np.where(tag_v == -1, -1, tag_v // TAG_WAVE_BASE)
            wave_v = np.where(tag_v == -1, -1, tag_v % TAG_WAVE_BASE)
            goal = str(ph["goal"])
            engine = str(ph.get("engine", ""))
            phase = str(ph.get("phase", "main"))
            n_lead = int(is_lead.sum())
            n_moves = int(p_idx.size) - n_lead
            moves.extend(
                MoveRecord(
                    partition=int(p), slot=int(s),
                    kind="leadership" if lead else "move",
                    src=int(sv), dst=int(dv),
                    goal=goal, engine=engine, phase=phase, goal_index=i,
                    round=int(rv), wave=int(wv),
                )
                for p, s, sv, dv, lead, rv, wv in zip(
                    p_idx, s_idx, src_v, dst_v, is_lead, rnd_v, wave_v
                )
            )
        segments.append(
            GoalSegment(
                goal=str(ph["goal"]), engine=str(ph.get("engine", "")),
                phase=str(ph.get("phase", "main")), index=i,
                cost_before=float(ph.get("costBefore", 0.0)),
                cost_after=float(ph.get("costAfter", 0.0)),
                violated_before=int(ph.get("violatedBefore", 0)),
                violated_after=int(ph.get("violatedAfter", 0)),
                rounds=int(ph.get("rounds", 0)),
                converged=bool(ph.get("converged", False)),
                num_moves=n_moves, num_leadership=n_lead,
            )
        )
        prev = cur
    ledger = RunLedger(run_id, segments, moves, meta=meta)
    build_s = time.monotonic() - t0
    REGISTRY.histogram("MoveLedger.build-timer").record(build_s)
    return ledger


# -- the bounded process registry ----------------------------------------------

_run_counter = itertools.count(1)


def new_run_id() -> str:
    """Process-unique, time-ordered run id (joins proposals, executor tasks,
    and ledger rows: provenance id = `<run_id>/p<partition>`)."""
    return f"run-{next(_run_counter)}-{uuid.uuid4().hex[:8]}"


class MoveLedger:
    """Bounded, thread-safe registry of recent RunLedgers.

    The optimizer records every ledger-enabled run here; GET `/explain` and
    `scripts/dump_metrics.py` read it. Bounds: `max_runs` retained runs
    (oldest evicted) and `max_moves_per_run` move rows per run (excess rows
    drop with a `truncatedMoves` marker — counts and digests are computed
    before truncation, so nothing is silently lost)."""

    def __init__(self, max_runs: int = 8, max_moves_per_run: int = 500_000):
        self._lock = threading.Lock()
        self._runs: "OrderedDict[str, RunLedger]" = OrderedDict()  #: guarded_by(_lock)
        self._max_runs = max_runs  #: guarded_by(_lock)
        self._max_moves = max_moves_per_run  #: guarded_by(_lock)
        self._total_recorded = 0  #: guarded_by(_lock)

    def configure(self, max_runs: Optional[int] = None,
                  max_moves_per_run: Optional[int] = None) -> None:
        with self._lock:
            if max_runs is not None:
                self._max_runs = max(1, int(max_runs))
            if max_moves_per_run is not None:
                self._max_moves = max(1, int(max_moves_per_run))
            while len(self._runs) > self._max_runs:
                self._runs.popitem(last=False)

    def record(self, ledger: RunLedger) -> None:
        n_moves = len(ledger.moves)
        with self._lock:
            if n_moves > self._max_moves:
                # digest/summary were computed over the full list by callers;
                # mark the truncation visibly rather than dropping silently
                ledger.meta["truncatedMoves"] = n_moves - self._max_moves
                ledger.moves = ledger.moves[: self._max_moves]
            self._runs[ledger.run_id] = ledger
            self._runs.move_to_end(ledger.run_id)
            self._total_recorded += 1
            while len(self._runs) > self._max_runs:
                self._runs.popitem(last=False)
        REGISTRY.meter("MoveLedger.runs-recorded").mark()
        REGISTRY.meter("MoveLedger.moves-recorded").mark(n_moves)

    def get(self, run_id: str) -> Optional[RunLedger]:
        with self._lock:
            return self._runs.get(run_id)

    def latest(self) -> Optional[RunLedger]:
        with self._lock:
            if not self._runs:
                return None
            return next(reversed(self._runs.values()))

    def run_ids(self) -> List[str]:
        with self._lock:
            return list(self._runs)

    def state(self) -> Dict:
        with self._lock:
            runs = list(self._runs.values())
            total = self._total_recorded
            cap = self._max_runs
        return {
            "runs": [
                {
                    "runId": l.run_id,
                    "createdAt": l.created_at,
                    "numMoves": len(l.moves),
                    "numSegments": len(l.segments),
                }
                for l in runs
            ],
            "totalRecorded": total,
            "capacity": cap,
        }

    def clear(self) -> None:
        with self._lock:
            self._runs.clear()


#: process-wide ledger registry (the /explain surface)
LEDGER = MoveLedger()

REGISTRY.gauge("MoveLedger.runs-retained", lambda: len(LEDGER.run_ids()))


# -- run-pair diffing (scripts/diff_runs.py core) ------------------------------


def diff_ledgers(a: RunLedger, b: RunLedger) -> Dict:
    """Align two recorded ledgers and report the FIRST divergent move with
    both sides' attribution — the tool that turns 'config 3's parity
    knife-edges by Δ0.193' from prose into a pinpointed decision.

    Moves are compared in canonical (goal_index, round, wave, partition,
    slot) order; the first position where the sequences disagree (different
    cell, different destination, or one side exhausted) is the divergence
    point. Segment-level deltas are reported for every goal so the reader
    sees where costs split even when the move streams stay aligned longer.
    """
    sa = sorted(a.moves, key=MoveRecord.key)
    sb = sorted(b.moves, key=MoveRecord.key)
    seg_deltas = []
    by_goal_b = {(s.goal, s.phase): s for s in b.segments}
    for s in a.segments:
        t = by_goal_b.get((s.goal, s.phase))
        if t is None:
            continue
        seg_deltas.append(
            {
                "goal": s.goal,
                "phase": s.phase,
                "movesA": s.num_moves + s.num_leadership,
                "movesB": t.num_moves + t.num_leadership,
                "costAfterA": round(s.cost_after, 6),
                "costAfterB": round(t.cost_after, 6),
                "costAfterDelta": round(s.cost_after - t.cost_after, 6),
            }
        )
    first = None
    index = None
    for i, (ma, mb) in enumerate(zip(sa, sb)):
        if ma.decision() != mb.decision():
            first, index = (ma, mb), i
            break
    if first is None and len(sa) != len(sb):
        i = min(len(sa), len(sb))
        first = (sa[i] if i < len(sa) else None, sb[i] if i < len(sb) else None)
        index = i
    diverged = first is not None
    out = {
        "runA": a.run_id,
        "runB": b.run_id,
        "movesA": len(sa),
        "movesB": len(sb),
        "digestA": a.digest(),
        "digestB": b.digest(),
        "identical": not diverged,
        "segments": seg_deltas,
    }
    if diverged:
        ma, mb = first
        out["firstDivergence"] = {
            "index": index,
            "a": ma.to_dict() if ma is not None else None,
            "b": mb.to_dict() if mb is not None else None,
        }
        # the human-readable one-liner reports the earliest attributable
        # decision split; a one-sided record means one run simply kept going
        who = ma or mb
        out["firstDivergenceGoal"] = who.goal
        out["firstDivergencePhase"] = who.phase
    return out
