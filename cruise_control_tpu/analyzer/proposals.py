"""Execution proposals: the diff between two replica placements.

The analog of AnalyzerUtils.getDiff (cc/analyzer/AnalyzerUtils.java:54,:70)
producing ExecutionProposal records (cc/executor/ExecutionProposal.java:
old/new replica lists, replicasToAdd/Remove :156-163, dataToMoveInMB :184).
Host-side NumPy: proposals leave the device exactly once, at the end of an
optimization run.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from cruise_control_tpu.common.resources import PartMetric


@dataclasses.dataclass(frozen=True)
class ExecutionProposal:
    """One partition's reassignment. new_replicas[0] is the new leader
    (matching Partition semantics: cc/model/Partition.java:95)."""

    partition: int
    old_replicas: Tuple[int, ...]
    new_replicas: Tuple[int, ...]
    data_to_move_mb: float = 0.0
    topic_partition: Optional[str] = None  # "topic-3" rendering when metadata given

    @property
    def old_leader(self) -> int:
        return self.old_replicas[0] if self.old_replicas else -1

    @property
    def new_leader(self) -> int:
        return self.new_replicas[0] if self.new_replicas else -1

    @property
    def replicas_to_add(self) -> Tuple[int, ...]:
        return tuple(sorted(set(self.new_replicas) - set(self.old_replicas)))

    @property
    def replicas_to_remove(self) -> Tuple[int, ...]:
        return tuple(sorted(set(self.old_replicas) - set(self.new_replicas)))

    @property
    def has_replica_action(self) -> bool:
        return bool(self.replicas_to_add or self.replicas_to_remove)

    @property
    def has_leader_action(self) -> bool:
        return self.old_leader != self.new_leader

    def is_completed(self, current_replicas: Tuple[int, ...]) -> bool:
        """Replica-set completion predicate (ExecutionProposal.isCompleted)."""
        return tuple(current_replicas) == self.new_replicas

    def to_dict(self) -> dict:
        return {
            "partition": self.partition,
            "topicPartition": self.topic_partition,
            "oldLeader": self.old_leader,
            "oldReplicas": list(self.old_replicas),
            "newReplicas": list(self.new_replicas),
            "dataToMoveMB": round(self.data_to_move_mb, 3),
        }


def proposal_diff(
    init_assignment: np.ndarray,
    final_assignment: np.ndarray,
    part_load: Optional[np.ndarray] = None,
    metadata=None,
) -> List[ExecutionProposal]:
    """Diff two i32[P, R] placements into proposals, vectorized prefilter.

    A partition yields a proposal when its replica *set* or its leader (slot 0)
    changed — same contract as AnalyzerUtils.getDiff.
    """
    init = np.asarray(init_assignment)
    final = np.asarray(final_assignment)
    if init.shape != final.shape:
        raise ValueError("assignment shapes differ")
    changed = np.nonzero((init != final).any(axis=1))[0]
    proposals: List[ExecutionProposal] = []
    for p in changed:
        old = tuple(int(x) for x in init[p] if x >= 0)
        new = tuple(int(x) for x in final[p] if x >= 0)
        if set(old) == set(new) and (not old or old[0] == new[0]):
            continue  # slot shuffle without semantic change
        added = set(new) - set(old)
        mb = 0.0
        if part_load is not None and added:
            mb = float(part_load[p, PartMetric.DISK]) * len(added)
        proposals.append(
            ExecutionProposal(
                partition=int(p),
                old_replicas=old,
                new_replicas=new,
                data_to_move_mb=mb,
                topic_partition=metadata.topic_partition(int(p)) if metadata else None,
            )
        )
    return proposals
