# cclint: kernel-module
"""Online incremental rebalancing: in-place model deltas + goal-scoped re-solve.

Every proposal in the base pipeline rebuilds the cluster model from scratch
and re-solves all goals from zero — tens of seconds exactly when the cluster
is degraded and the detector's `ProposalDriftAnomaly` recompute is queued.
This module is the recovery lane that avoids the rebuild:

  1. `derive_deltas` diffs the monitor's fresh model against the model the
     last full solve ran on and emits a typed `ModelDelta` stream (broker
     death/revival, topic delete, partition add, load spike). Structural
     changes a row-scatter cannot express (capacity edits, dense shifts
     after a topic delete, axis growth past the shape bucket) become
     fallback reasons instead of deltas.
  2. `apply_delta_batch` scatters the batch INTO the device-resident padded
     `StaticCtx` captured through the `GoalOptimizer._prep_cache` seam —
     masked `.at[].set(mode="drop")` updates into the flat arrays, no
     rebuild, no host round-trip per delta, and no recompile as long as the
     shape bucket holds. The scatter recomputes exactly the state-derived
     rows `build_static_ctx` derives (alive/dead/new/demoted and the
     destination-eligibility masks), so the updated context is bitwise
     equal to a from-scratch build on the perturbed model — that identity
     is what makes the digest contract below checkable.
  3. `SENSITIVITY` classifies which goals each delta kind can actually
     violate (a pure load spike cannot violate Rack/ReplicaCount goals), so
     `IncrementalLane.propose` re-solves only the affected goal subset —
     riding the full-stack machine's runtime enabled mask
     (`_machine_goal_plan`), seeded from the surviving placement.

Correctness contract (machine-checked in tests/test_incremental.py and
gated by scripts/perf_gate.py): for any goal subset the sensitivity map
marks unaffected, the incremental solve makes ZERO moves — and a scoped
solve of the affected subset is provenance-digest-equal (PR-8 ledger) to a
from-scratch solve of the same subset on the same perturbed model, because
both run literally the same `_solve_prepared` code on bit-identical inputs.

The lane NEVER guesses: any delta it cannot express in place (or a stale
generation, or an unarmed lane) is a typed fallback reason, and the facade
falls back to the full re-solve when `optimizer.incremental.fallback.full`
is on (docs/RESILIENCE.md failure matrix).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer.context import OptimizationOptions, StaticCtx
from cruise_control_tpu.common.resources import BrokerState
from cruise_control_tpu.common.sensors import REGISTRY
from cruise_control_tpu.common.tracing import TRACER
from cruise_control_tpu.models.flat_model import FlatClusterModel

# -- delta vocabulary ----------------------------------------------------------

#: host-level delta kinds (the typed stream `derive_deltas` emits)
DELTA_BROKER_DEATH = "broker_death"
DELTA_BROKER_REVIVAL = "broker_revival"
DELTA_BROKER_STATE = "broker_state"  # NEW/DEMOTED transitions
DELTA_LOAD_SPIKE = "load_spike"
DELTA_PART_ADD = "part_add"
DELTA_TOPIC_DELETE = "topic_delete"

DELTA_KINDS = (
    DELTA_BROKER_DEATH,
    DELTA_BROKER_REVIVAL,
    DELTA_BROKER_STATE,
    DELTA_LOAD_SPIKE,
    DELTA_PART_ADD,
    DELTA_TOPIC_DELETE,
)

#: kernel kind codes (DeltaBatch.kind); every broker-state transition shares
#: one code — the scatter recomputes all state-derived rows regardless
KIND_NOOP = 0
KIND_STATE = 1
KIND_LOAD = 2
KIND_PART_ADD = 3

_KERNEL_KIND = {
    DELTA_BROKER_DEATH: KIND_STATE,
    DELTA_BROKER_REVIVAL: KIND_STATE,
    DELTA_BROKER_STATE: KIND_STATE,
    DELTA_LOAD_SPIKE: KIND_LOAD,
    DELTA_PART_ADD: KIND_PART_ADD,
}


@dataclasses.dataclass(frozen=True)
class ModelDelta:
    """One typed model change, derived from monitor sample generations.

    Field use by kind: broker-state kinds carry (broker, state); load spikes
    carry (row, load) — the fresh model's EXACT f32 row, a replacement
    rather than a multiplier so the scattered row is bitwise equal to a
    from-scratch build; part adds carry (row, topic, load) and activate a
    padded row; topic deletes carry only the kind (never applied in place —
    the dense shift breaks row identity, see SENSITIVITY)."""

    kind: str
    broker: int = -1
    state: int = -1
    row: int = -1
    topic: int = -1
    load: Optional[np.ndarray] = None  # f32[M]

    def __post_init__(self):
        if self.kind not in DELTA_KINDS:
            raise ValueError(f"unknown delta kind {self.kind!r}")


class DeltaBatch(NamedTuple):
    """Fixed-shape device form of a delta list: padded to `max_deltas` rows
    with KIND_NOOP so every batch size shares ONE compiled scatter kernel."""

    kind: jax.Array  # i32[D]
    broker: jax.Array  # i32[D]
    state: jax.Array  # i32[D]
    row: jax.Array  # i32[D]
    topic: jax.Array  # i32[D]
    load: jax.Array  # f32[D, M]


def build_delta_batch(
    deltas: Sequence[ModelDelta], max_deltas: int, num_metrics: int
) -> DeltaBatch:
    """Pack host deltas into the fixed-shape batch (NOOP-padded)."""
    d = max_deltas
    kind = np.zeros(d, np.int32)
    broker = np.zeros(d, np.int32)
    state = np.zeros(d, np.int32)
    row = np.zeros(d, np.int32)
    topic = np.zeros(d, np.int32)
    load = np.zeros((d, num_metrics), np.float32)
    for i, dl in enumerate(deltas):
        kind[i] = _KERNEL_KIND[dl.kind]
        broker[i] = dl.broker
        state[i] = dl.state
        row[i] = dl.row
        topic[i] = dl.topic
        if dl.load is not None:
            load[i] = np.asarray(dl.load, dtype=np.float32)  # cclint: disable=tpu-host-sync -- host-side batch packing of ModelDelta payloads (pure numpy in, jnp out at the return)
    return DeltaBatch(
        kind=jnp.asarray(kind),
        broker=jnp.asarray(broker),
        state=jnp.asarray(state),
        row=jnp.asarray(row),
        topic=jnp.asarray(topic),
        load=jnp.asarray(load),
    )


# -- the in-place scatter kernel -----------------------------------------------


def apply_delta_batch(
    static: StaticCtx,
    batch: DeltaBatch,
    base_replica_dst: jax.Array,
    base_leadership_dst: jax.Array,
) -> StaticCtx:
    """Scatter a delta batch into the device-resident StaticCtx.

    Bit-identity contract with `build_static_ctx` (context.py): for every
    delta kind this kernel applies, the returned context equals — array for
    array, bit for bit — a from-scratch build on the equivalently-perturbed
    host model. The state-derived rows are recomputed with the SAME
    expressions build_static_ctx uses (`alive = (state != DEAD) & valid`,
    destination masks `alive & base`), where `base_replica_dst` /
    `base_leadership_dst` are the state-INDEPENDENT factors of the
    destination masks (valid & not-excluded [& requested]) the lane
    precomputes at arm time. Capacity, rack/host topology, and the
    constraint scalars never change under these kinds (structural edits are
    fallbacks), so every other field passes through untouched — and stays
    resident on device.

    Writes are routed out of bounds for non-matching kinds and dropped
    (`mode="drop"`), so one fixed-shape program serves every batch. No
    donation: the input arrays are shared with the optimizer's prep cache.
    """
    b = static.broker_state.shape[0]
    p = static.part_load.shape[0]
    is_state = batch.kind == KIND_STATE
    is_load = (batch.kind == KIND_LOAD) | (batch.kind == KIND_PART_ADD)
    is_add = batch.kind == KIND_PART_ADD

    b_idx = jnp.where(is_state, batch.broker, b)
    state = static.broker_state.at[b_idx].set(batch.state, mode="drop")
    valid = static.broker_valid
    alive = (state != BrokerState.DEAD) & valid
    demoted = (state == BrokerState.DEMOTED) & valid

    r_idx = jnp.where(is_load, batch.row, p)
    part_load = static.part_load.at[r_idx].set(batch.load, mode="drop")
    t_idx = jnp.where(is_add, batch.row, p)
    topic_id = static.topic_id.at[t_idx].set(batch.topic, mode="drop")
    # f32 addition of small integer counts is exact, so this matches
    # build_static_ctx's jnp.float32(valid_partitions) bit for bit
    nvp = static.num_valid_partitions + jnp.sum(is_add).astype(jnp.float32)

    return static._replace(
        broker_state=state,
        alive=alive,
        dead=(state == BrokerState.DEAD) & valid,
        new=(state == BrokerState.NEW) & valid,
        demoted=demoted,
        replica_dst_ok=alive & base_replica_dst,
        leadership_dst_ok=alive & ~demoted & base_leadership_dst,
        part_load=part_load,
        topic_id=topic_id,
        num_valid_partitions=nvp,
    )


#: module-level so the compiled scatter survives across lane instances
_jit_apply_delta_batch = jax.jit(apply_delta_batch)


# -- delta derivation ----------------------------------------------------------

#: fallback reason vocabulary (docs/RESILIENCE.md failure matrix)
FALLBACK_DISABLED = "DISABLED"
FALLBACK_NOT_ARMED = "NOT_ARMED"
FALLBACK_STALE_GENERATION = "STALE_GENERATION"
FALLBACK_SHAPE_RF = "SHAPE_RF"
FALLBACK_SHAPE_BROKERS = "SHAPE_BROKERS"
FALLBACK_SHAPE_BUCKET = "SHAPE_BUCKET"
FALLBACK_SHAPE_TOPICS = "SHAPE_TOPICS"
FALLBACK_STRUCTURAL = "STRUCTURAL"
FALLBACK_STRUCTURAL_SHIFT = "STRUCTURAL_SHIFT"
FALLBACK_TOO_MANY_DELTAS = "TOO_MANY_DELTAS"
FALLBACK_SENSITIVITY_ALL = "SENSITIVITY_ALL"
FALLBACK_OPTIONS = "OPTIONS"
FALLBACK_NO_DELTAS = "NO_DELTAS"


def derive_deltas(
    old: FlatClusterModel, new: FlatClusterModel
) -> Tuple[List[ModelDelta], Optional[str]]:
    """Diff two UNPADDED monitor models into a typed delta stream.

    Returns (deltas, fallback_reason): a non-None reason means the change
    cannot be expressed as in-place row scatters (shape or structural
    drift) and the caller must fall back to the full re-solve. Host-side
    numpy; the models are the monitor's host builds, not device arrays.
    `TopologyFingerprint.diff` (executor/validation.py) classifies the same
    drifts for the dispatch guard — this is the model-array-level twin."""
    if new.max_replication_factor != old.max_replication_factor:
        return [], FALLBACK_SHAPE_RF
    if new.num_brokers != old.num_brokers:
        return [], FALLBACK_SHAPE_BROKERS
    cap_o = np.asarray(old.broker_capacity)  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
    cap_n = np.asarray(new.broker_capacity)  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
    if (
        not np.array_equal(cap_o, cap_n)
        or not np.array_equal(np.asarray(old.broker_rack), np.asarray(new.broker_rack))  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
        or not np.array_equal(np.asarray(old.broker_host), np.asarray(new.broker_host))  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
    ):
        return [], FALLBACK_STRUCTURAL

    p_old, p_new = old.num_partitions, new.num_partitions
    if p_new < p_old:
        # a topic delete dense-shifts every later partition row: row
        # identity is gone, no scatter can express it. Emit the typed
        # marker; SENSITIVITY maps it to "all" and the lane falls back.
        return [ModelDelta(kind=DELTA_TOPIC_DELETE)], None
    tid_o = np.asarray(old.topic_id)  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
    tid_n = np.asarray(new.topic_id)  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
    if not np.array_equal(tid_o, tid_n[:p_old]):
        return [], FALLBACK_STRUCTURAL_SHIFT

    deltas: List[ModelDelta] = []
    st_o = np.asarray(old.broker_state)  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
    st_n = np.asarray(new.broker_state)  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
    for b in np.nonzero(st_o != st_n)[0]:
        ns = int(st_n[b])  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
        if ns == BrokerState.DEAD:
            kind = DELTA_BROKER_DEATH
        elif int(st_o[b]) == BrokerState.DEAD:  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
            kind = DELTA_BROKER_REVIVAL
        else:
            kind = DELTA_BROKER_STATE
        deltas.append(ModelDelta(kind=kind, broker=int(b), state=ns))

    pl_o = np.asarray(old.part_load)  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
    pl_n = np.asarray(new.part_load)  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
    # row replacement, not a multiplier: `old * (new/old)` is not bitwise
    # `new` in f32, and the digest contract needs bitwise
    for r in np.nonzero(np.any(pl_o != pl_n[:p_old], axis=1))[0]:
        deltas.append(ModelDelta(kind=DELTA_LOAD_SPIKE, row=int(r), load=pl_n[r]))
    for r in range(p_old, p_new):
        deltas.append(
            ModelDelta(
                kind=DELTA_PART_ADD, row=r, topic=int(tid_n[r]), load=pl_n[r]  # cclint: disable=tpu-host-sync -- derive_deltas diffs HOST monitor models by documented contract; no device array reaches it
            )
        )
    return deltas, None


# -- goal sensitivity ----------------------------------------------------------

#: sentinel: the delta cannot be scoped (or expressed) — fall back to full
ALL = "all"

_COUNT_GOALS = frozenset(
    (
        "RackAwareGoal",
        "ReplicaCapacityGoal",
        "ReplicaDistributionGoal",
        "TopicReplicaDistributionGoal",
        "LeaderReplicaDistributionGoal",
    )
)
_LOAD_GOALS = frozenset(
    (
        "DiskCapacityGoal",
        "NetworkInboundCapacityGoal",
        "NetworkOutboundCapacityGoal",
        "CpuCapacityGoal",
        "PotentialNwOutGoal",
        "DiskUsageDistributionGoal",
        "NetworkInboundUsageDistributionGoal",
        "NetworkOutboundUsageDistributionGoal",
        "CpuUsageDistributionGoal",
        "LeaderBytesInDistributionGoal",
    )
)


def _sensitivity_map() -> Dict[str, object]:
    from cruise_control_tpu.analyzer.goals import HARD_GOAL_NAMES, GOAL_REGISTRY

    all_names = frozenset(GOAL_REGISTRY)
    return {
        # a pure load change moves no replica and kills no broker: the five
        # count/placement goals (rack spread, replica counts) see the exact
        # same assignment and cannot become violated
        DELTA_LOAD_SPIKE: _LOAD_GOALS,
        # a broker death strands replicas: every goal window changes (the
        # dead broker leaves `alive`), so the whole armed stack re-solves —
        # still IN-LANE (warm program + surviving placement), just unscoped
        DELTA_BROKER_DEATH: all_names,
        DELTA_BROKER_STATE: all_names,
        # a revived broker re-enters empty-handed: it cannot push any HARD
        # goal into violation (capacity/rack checks only bind brokers that
        # HOLD replicas); only the soft distribution goals want to use it
        DELTA_BROKER_REVIVAL: all_names - frozenset(HARD_GOAL_NAMES),
        # an added partition lands with observed load already accounted in
        # its LOAD row (derive_deltas emits part_add rows with the fresh
        # load); the new row changes counts and placement windows
        DELTA_PART_ADD: _COUNT_GOALS,
        # not expressible in place (dense row shift) — forces the fallback
        DELTA_TOPIC_DELETE: ALL,
    }


SENSITIVITY: Dict[str, object] = _sensitivity_map()


def affected_goals(
    deltas: Sequence[ModelDelta], armed_goal_names: Sequence[str]
) -> Optional[Tuple[str, ...]]:
    """The armed-order goal subset this batch can violate; None = ALL
    (sensitivity cannot scope the batch — fall back)."""
    union: set = set()
    for d in deltas:
        sens = SENSITIVITY[d.kind]
        if sens == ALL:
            return None
        union |= set(sens)
    return tuple(n for n in armed_goal_names if n in union)


# -- configuration -------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class IncrementalConfig:
    """`optimizer.incremental.*` knobs (config/cruise_config.py)."""

    enabled: bool = True
    max_deltas: int = 64
    fallback_full: bool = True

    @classmethod
    def from_config(cls, config) -> "IncrementalConfig":
        return cls(
            enabled=config.get_boolean("optimizer.incremental.enabled"),
            max_deltas=config.get_int("optimizer.incremental.max.deltas"),
            fallback_full=config.get_boolean("optimizer.incremental.fallback.full"),
        )


# -- outcome + lane ------------------------------------------------------------


@dataclasses.dataclass
class IncrementalOutcome:
    """One propose() attempt: either a scoped OptimizerResult or a typed
    fallback reason the facade routes to the full re-solve."""

    result: Optional[object]  # OptimizerResult
    deltas: List[ModelDelta]
    affected: Tuple[str, ...]
    goals_skipped: int
    fallback_reason: Optional[str]
    duration_s: float

    @property
    def ok(self) -> bool:
        return self.result is not None

    def summary(self) -> Dict:
        by_kind: Dict[str, int] = {}
        for d in self.deltas:
            by_kind[d.kind] = by_kind.get(d.kind, 0) + 1
        return {
            "ok": self.ok,
            "deltas": len(self.deltas),
            "deltasByKind": by_kind,
            "affectedGoals": list(self.affected),
            "goalsSkipped": self.goals_skipped,
            "fallbackReason": self.fallback_reason,
            "durationS": round(self.duration_s, 4),
        }


@dataclasses.dataclass
class _ArmedState:
    """What the lane captured from the last stamped full solve."""

    model: FlatClusterModel  # the UNPADDED host model that solve ran on
    options: OptimizationOptions
    goal_names: Tuple[str, ...]
    generation: Optional[int]
    p_valid: int  # real partitions (grows with part_add deltas)
    pmodel: FlatClusterModel  # padded HOST copy, kept delta-consistent
    dims: object
    static: StaticCtx  # device-resident (mesh-placed when sharded)
    static_canon: StaticCtx  # unsharded canonical copy the kernel updates
    bucketed: Dict
    base_replica_dst: np.ndarray  # bool[B] state-independent dst factor
    base_leadership_dst: np.ndarray  # bool[B]


class IncrementalLane:
    """The incremental re-proposal lane over one GoalOptimizer.

    `arm()` after every stamped full solve captures the prep-cache entry of
    that solve (padded model + device StaticCtx + bucket record);
    `propose()` then turns a fresh monitor model into a scoped re-solve in
    milliseconds-to-one-device-call instead of a full rebuild. Thread-safe
    the same way the facade's proposal cache is (one lock, short critical
    sections; the solve itself runs outside the lock on the optimizer's own
    locking discipline)."""

    def __init__(self, optimizer, config: IncrementalConfig = IncrementalConfig()):
        self._optimizer = optimizer
        self._config = config
        self._lock = threading.Lock()
        self._armed: Optional[_ArmedState] = None
        self._last: Optional[IncrementalOutcome] = None
        self._goals_skipped = 0
        REGISTRY.gauge("Incremental.goals-skipped", lambda: self._goals_skipped)

    @property
    def config(self) -> IncrementalConfig:
        return self._config

    # -- arming ----------------------------------------------------------------

    def arm(
        self,
        model: FlatClusterModel,
        options: OptimizationOptions,
        goal_names: Sequence[str],
        generation: Optional[int] = None,
    ) -> bool:
        """Capture the prep-cache entry of a just-completed full solve.

        Must be called with the SAME (model, options) objects that solve
        used — the prep cache keys by identity. Returns False (lane stays
        unarmed/previous) when disabled or when the entry was evicted."""
        if not self._config.enabled:
            return False
        prepared_entry = getattr(self._optimizer, "prepared_entry", None)
        if prepared_entry is None:
            # Optimizer without a prep cache (e.g. a test double): the lane
            # simply never arms and every propose() falls back to a full solve.
            return False
        entry = prepared_entry(model, options)
        if entry is None:
            return False
        p_orig, pmodel, dims, static, static_canon, bucketed = entry
        b = dims.num_brokers
        valid = np.arange(b) < model.num_brokers

        def padded(mask):
            if mask is None:
                return None
            m = np.asarray(mask, dtype=bool)  # cclint: disable=tpu-host-sync -- arm-time mask padding over HOST option arrays (off the proposal hot path)
            return np.concatenate([m, np.zeros(b - m.shape[0], dtype=bool)])

        base_replica = valid.copy()
        excl_rep = padded(options.excluded_brokers_for_replica_move)
        if excl_rep is not None:
            base_replica &= ~excl_rep
        req = padded(options.requested_destination_brokers)
        if req is not None:
            base_replica &= req
        base_lead = valid.copy()
        excl_lead = padded(options.excluded_brokers_for_leadership)
        if excl_lead is not None:
            base_lead &= ~excl_lead

        host_pmodel = FlatClusterModel(*(np.asarray(f) for f in pmodel))  # cclint: disable=tpu-host-sync -- deliberate one-time d2h at arm time: the lane keeps a host twin of the padded model
        with self._lock:
            self._armed = _ArmedState(
                model=model,
                options=options,
                goal_names=tuple(goal_names),
                generation=generation,
                p_valid=p_orig,
                pmodel=host_pmodel,
                dims=dims,
                static=static,
                static_canon=static_canon,
                bucketed=dict(bucketed),
                base_replica_dst=base_replica,
                base_leadership_dst=base_lead,
            )
        REGISTRY.meter("Incremental.lane-armed").mark()
        return True

    # -- proposing -------------------------------------------------------------

    def propose(
        self,
        new_model: FlatClusterModel,
        generation: Optional[int] = None,
        progress=None,
    ) -> IncrementalOutcome:
        """Derive deltas vs the armed model, scatter them in place, and
        re-solve the sensitivity-affected goal subset. Never raises on a
        lane miss — every ineligibility is a typed fallback outcome."""
        t0 = time.monotonic()
        if not self._config.enabled:
            return self._fallback([], FALLBACK_DISABLED, t0)
        with self._lock:
            armed = self._armed
        if armed is None:
            return self._fallback([], FALLBACK_NOT_ARMED, t0)
        if (
            generation is not None
            and armed.generation is not None
            and generation < armed.generation
        ):
            return self._fallback([], FALLBACK_STALE_GENERATION, t0)

        deltas, reason = derive_deltas(armed.model, new_model)
        if reason is not None:
            return self._fallback(deltas, reason, t0)
        if not deltas:
            return self._fallback(deltas, FALLBACK_NO_DELTAS, t0)
        if len(deltas) > self._config.max_deltas:
            return self._fallback(deltas, FALLBACK_TOO_MANY_DELTAS, t0)
        reason = self._eligibility(armed, deltas, new_model)
        if reason is not None:
            return self._fallback(deltas, reason, t0)
        affected = affected_goals(deltas, armed.goal_names)
        if affected is None:
            return self._fallback(deltas, FALLBACK_SENSITIVITY_ALL, t0)

        with TRACER.span(
            "incremental-delta-apply", kind="incremental",
            deltas=len(deltas), goals=len(affected),
        ):
            dims = armed.dims
            batch = build_delta_batch(
                deltas, self._config.max_deltas, armed.pmodel.part_load.shape[1]
            )
            new_canon = _jit_apply_delta_batch(
                armed.static_canon, batch,
                jnp.asarray(armed.base_replica_dst),
                jnp.asarray(armed.base_leadership_dst),
            )
            if self._optimizer._mesh is not None:
                from cruise_control_tpu.parallel.sharding import place_static

                new_static = place_static(new_canon, self._optimizer._mesh)
            else:
                new_static = new_canon
            pmodel = self._updated_pmodel(armed, deltas, new_model)
        p_valid = new_model.num_partitions

        result = self._optimizer.incremental_optimizations(
            pmodel, dims, new_static, new_canon,
            dict(armed.bucketed, incremental=True),
            p_orig=p_valid, goal_names=affected,
            raise_on_hard_failure=False, progress=progress,
        )

        skipped = len(armed.goal_names) - len(affected)
        with self._lock:
            self._armed = dataclasses.replace(
                armed,
                model=new_model,
                generation=generation if generation is not None else armed.generation,
                p_valid=p_valid,
                pmodel=pmodel,
                static=new_static,
                static_canon=new_canon,
            )
            self._goals_skipped = skipped
        REGISTRY.meter("Incremental.deltas-applied").mark(len(deltas))
        for d in deltas:
            REGISTRY.meter(f"Incremental.deltas-applied.{d.kind}").mark()
        duration = time.monotonic() - t0
        REGISTRY.histogram("Incremental.reproposal-timer").record(duration)
        outcome = IncrementalOutcome(
            result=result,
            deltas=deltas,
            affected=affected,
            goals_skipped=skipped,
            fallback_reason=None,
            duration_s=duration,
        )
        with self._lock:
            self._last = outcome
        return outcome

    def _eligibility(
        self, armed: _ArmedState, deltas: Sequence[ModelDelta],
        new_model: FlatClusterModel,
    ) -> Optional[str]:
        """Shape-bucket + options checks the padded context imposes."""
        dims = armed.dims
        for d in deltas:
            if d.kind == DELTA_PART_ADD:
                if d.row >= dims.num_partitions:
                    return FALLBACK_SHAPE_BUCKET
                if d.topic >= dims.num_topics:
                    return FALLBACK_SHAPE_TOPICS
                if armed.options.excluded_partitions is not None:
                    # the padded exclusion mask marked pad rows excluded; an
                    # activated pad row would need a mask rebuild
                    return FALLBACK_OPTIONS
        return None

    def _updated_pmodel(
        self, armed: _ArmedState, deltas: Sequence[ModelDelta],
        new_model: FlatClusterModel,
    ) -> FlatClusterModel:
        """Host twin of the device scatter: the padded model copy the solve
        computes stats/proposals from, kept bit-consistent with the kernel
        by applying the SAME row writes (plus the fresh assignment, which
        is always taken whole — the solve seeds from the live placement)."""
        pm = armed.pmodel
        part_load = pm.part_load.copy()
        topic_id = pm.topic_id.copy()
        broker_state = pm.broker_state.copy()
        for d in deltas:
            code = _KERNEL_KIND[d.kind]
            if code == KIND_STATE:
                broker_state[d.broker] = d.state
            elif code in (KIND_LOAD, KIND_PART_ADD):
                part_load[d.row] = np.asarray(d.load, dtype=np.float32)  # cclint: disable=tpu-host-sync -- host twin of the device scatter by design (see docstring); pure numpy rows
                if code == KIND_PART_ADD:
                    topic_id[d.row] = d.topic
        target_p, rf = pm.assignment.shape
        fresh = np.asarray(new_model.assignment)  # cclint: disable=tpu-host-sync -- host twin of the device scatter by design (see docstring); pure numpy rows
        assignment = np.concatenate(
            [fresh, np.full((target_p - fresh.shape[0], rf), -1, dtype=fresh.dtype)]
        )
        return pm._replace(
            assignment=assignment,
            part_load=part_load,
            topic_id=topic_id,
            broker_state=broker_state,
        )

    def _fallback(
        self, deltas: List[ModelDelta], reason: str, t0: float
    ) -> IncrementalOutcome:
        REGISTRY.meter("Incremental.fallback-to-full").mark()
        REGISTRY.meter(f"Incremental.fallback-to-full.{reason}").mark()
        outcome = IncrementalOutcome(
            result=None,
            deltas=deltas,
            affected=(),
            goals_skipped=0,
            fallback_reason=reason,
            duration_s=time.monotonic() - t0,
        )
        with self._lock:
            self._last = outcome
        return outcome

    # -- introspection ---------------------------------------------------------

    def state(self) -> Dict:
        """The `/state` IncrementalState block (facade.state())."""
        with self._lock:
            armed = self._armed
            last = self._last
        return {
            "enabled": self._config.enabled,
            "maxDeltas": self._config.max_deltas,
            "fallbackFull": self._config.fallback_full,
            "armed": armed is not None,
            **(
                {
                    "generation": armed.generation,
                    "goals": list(armed.goal_names),
                    "bucket": armed.bucketed.get("bucket"),
                    "validPartitions": armed.p_valid,
                }
                if armed is not None
                else {}
            ),
            "lastOutcome": last.summary() if last is not None else None,
        }
